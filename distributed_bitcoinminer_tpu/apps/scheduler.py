"""The scheduler: shard nonce ranges over an elastic miner pool, merge argmins.

Faithful state machine of the reference coordinator
(ref: bitcoin/server/server.go:19-403), as one asyncio actor instead of
channel-coupled goroutines:

- FIFO request queue, ONE request in flight at a time (deliberate reference
  simplification — no pipeline parallelism).
- ``load_balance``: bounds become exclusive (``upper += 1``); even split
  ``total // num_miners`` with the remainder given to the FIRST miner; when
  there are more miners than nonces, only ``total`` miners get 1-nonce chunks
  (ref: server.go:165-205).
- Bound quirk preserved for bit parity: chunks are sent with EXCLUSIVE upper
  bounds but the miner treats ``Upper`` as inclusive (ref: miner.go:51-52),
  so each chunk scans one extra nonce and the system as a whole scans
  ``[0, maxNonce+1]``.
- Request striping (ISSUE 4, ``DBM_STRIPE``; no reference analog): each
  miner's even-split share may be subdivided into up to
  ``StripeParams.depth`` contiguous chunks sized at
  ``StripeParams.chunk_s`` seconds of work from its throughput EWMA, so
  the miner's pending FIFO is deep enough for its dispatch pipeline
  (``DBM_PIPELINE``, apps/miner.py) to overlap chunk k+1's device work
  with chunk k's result fetch/serialize — and a blown lease or dead miner
  forfeits one stripe chunk, not the whole share. Chunk indices still
  ascend with nonce range globally and boundaries stay contiguous, so the
  merge rules below (strict-less arg-min, difficulty prefix release) are
  untouched; a cold pool (no EWMA yet) or ``DBM_STRIPE=0`` reproduces the
  reference one-chunk-per-miner split bit-for-bit.
- Result merge: strict ``<`` on the uint64 hash; barrier releases the Result
  to the client when every chunk of the request has been answered
  (ref: server.go:257-325).
- Difficulty extension (no reference analog; BASELINE config 5): a Request
  carrying ``Target`` fans out with the target on every chunk, miners
  early-exit at their chunk's first ``hash < target`` nonce, and the merge
  answers the lowest-nonce qualifying response — the globally first
  qualifying nonce when every miner speaks the extension (chunks ascend
  and each reports its chunk-first hit; a stock Target-dropping miner
  reports a chunk arg-min instead, weakening its chunk to "a qualifying
  nonce" — detected via the Result's target echo and surfaced in logs,
  see ``Request.weak``). No hit anywhere degrades to the exact arg-min,
  and stock Requests (``Target`` absent = 0) take the reference path
  byte-for-byte.
- Difficulty prefix release (VERDICT r4): chunks cover ascending disjoint
  ranges, so once some chunk ``c`` reports a qualifying hit and every chunk
  ``< c`` has answered without one, no later answer can beat it — the
  Result is released IMMEDIATELY, without waiting for the full barrier.
  The released job's remaining chunks are cancelled exactly like a
  client-drop (miners free, their late Results pop as stale via the
  job_id/FIFO machinery), so a tight target's time-to-first-hit is the
  winning chunk's scan, not the slowest full scan. Stock arg-min requests
  keep the reference's full barrier untouched (ref: server.go:309-324).
- Miner drop: reassign its unanswered chunks to available miners, else park
  them; parked chunks are re-issued when a miner joins or frees up
  (ref: server.go:326-376, 222-244, 285-304).
- Client drop: the in-flight request is cancelled immediately — miners are
  freed, parked chunks cleared, the next queued request starts.
- Robustness plane (no reference analog; PNPCoin-style lease discipline,
  PAPERS.md arxiv 2208.12628): every assigned chunk carries a LEASE whose
  deadline derives from its nonce-range size and an EWMA of the assigned
  miner's observed per-chunk throughput (pool-wide EWMA, then a flat grace,
  when unobserved). The reference's only fault trigger is the LSP
  epoch-limit drop; a miner whose transport still heartbeats but whose
  compute is wedged (hung device dispatch, stalled worker thread) passes
  that check forever. On lease expiry the chunk is speculatively RE-ISSUED
  to an available miner — first Result wins; the loser's late Result pops
  from its FIFO as answered/stale and is dropped by the existing
  ``job_id``/``answered[idx]`` machinery. A miner that blows
  ``quarantine_after`` consecutive leases is QUARANTINED: excluded from new
  assignments until it answers again (any Result pop lifts it). Leases and
  quarantine change scheduling latency under faults only — never the
  answer: re-issued chunks scan the same range, so the merge is idempotent.
- Position-aware leases (ISSUE 3, closes the ROADMAP "lease-aware FIFO
  depth" item): a miner computes its pending FIFO strictly in order, so a
  chunk assigned BEHIND other entries (e.g. behind the cancelled chunk of
  a dropped client that the miner is still grinding) cannot start until
  they pop. Its initial deadline therefore BUDGETS the work ahead — the
  latest predecessor expiry plus its own lease — and is re-stamped to the
  tight single-chunk lease when the chunk actually reaches the FIFO head
  (which also re-stamps ``assigned_at``, keeping the throughput EWMA
  honest). A deep-but-healthy FIFO no longer blows leases spuriously,
  while a FIFO wedged at its head still expires once the budget runs out
  (never deferring forever — the flaw a pure start-at-head clock has).
  ``LeaseParams.fifo_aware=False`` restores the at-assignment clock; with
  it off, a lease that blows while entries sit ahead of the chunk is
  counted in ``leases_blown_spurious`` (the before/after evidence).
- Desperation dispatch (ISSUE 3, closes the ROADMAP open item): when the
  ENTIRE pool is quarantined, waiting for an answer that may never come
  serves nobody — a queued request is dispatched to the least-bad
  available quarantined miner (lowest blown-lease streak, then highest
  observed throughput) as a last resort, counted in
  ``desperation_dispatch`` and logged as a structured warning. Gated by
  ``LeaseParams.desperation``; any non-quarantined miner disables it.

Fair-share QoS dispatch plane (ISSUE 5, ``DBM_QOS``; no reference
analog): the reference's one-request-in-flight FIFO lets a 2^40-range
elephant park every later request until its last chunk merges, and
nothing bounds intake. With QoS on, every request is keyed to a TENANT
(its client conn id — no wire change) and dispatch runs through
``apps/qos.py``:

- Requests whose estimated scan exceeds ``QosParams.wholesale_s`` are
  CHUNKED: split into pool-EWMA-sized chunks (``chunk_s`` seconds each,
  at most ``max_chunks``) held centrally and granted to miners
  incrementally — each miner's live FIFO capped at ``QosParams.depth``
  so the rest of the pool stays grantable. Multiple requests are then in
  flight CONCURRENTLY, their chunks interleaved across the miner pool by
  deficit-round-robin over tenants (grant share converges to the
  configured weights; DRR's quantum guarantee means no tenant starves).
  Chunk indices still ascend with nonce range per request and every
  merge rule — strict-less arg-min barrier, difficulty prefix release,
  speculative re-issue dedup — is per-request and untouched, so answers
  are bit-identical to the FIFO scheduler's.
- Smaller requests (and any request on a COLD pool) dispatch WHOLESALE
  through the stock path below, and a wholesale request in flight blocks
  later starts exactly like the reference — so single-tenant traffic,
  the conformance/parity shape, and everything with ``DBM_QOS=0``
  reproduce today's FIFO dispatch order bit-for-bit.
- Admission + shedding: a per-tenant token bucket (``rate``/``burst``)
  sheds at arrival when drained; a total ``max_queued`` bound sheds the
  OLDEST queued request (cancelled through the trace/cancel path, conn
  closed) so ``submit_with_retry`` clients back off and resubmit instead
  of hanging into their wire deadline. ResultCache replays are answered
  BEFORE admission and are never shed — a retry storm of already-
  answered requests burns no quota.
- Coalescing grant hint (ISSUE 9, ``DBM_COALESCE``): within one QoS
  pump pass, once a SMALL chunk (argmin mode, <=
  ``CoalesceParams.max_nonces``) is granted to a miner, further small
  grants — typically other tenants' mice, per DRR — may target the
  same miner's COALESCING WINDOW, up to ``lanes`` chunks sharing one
  ``coalesce_id``. Windowed chunks count as ONE live chunk against the
  per-miner ``QosParams.depth`` cap (they will share one device
  launch on the miner: apps/miner.py's coalescer drains them from its
  local queue into a single batched dispatch), while per-tenant DRR
  deficits, admission debits, in-flight accounting, leases, and every
  merge rule stay per chunk, unchanged. The hint is what actually
  lands N small chunks in one miner's queue at once — without it the
  depth cap trickles mice out one-per-free-slot and the miner-side
  coalescer has nothing to batch. ``DBM_COALESCE=0`` never opens a
  window: grants and live accounting are bit-identical to stock.

Observability plane (ISSUE 3): every counter that used to live in the
ad-hoc ``stats`` dict is now a series in a per-scheduler metrics
:class:`~..utils.metrics.Registry`, mounted into the process registry under
``sched.`` so the periodic emitter and ``bench.py`` snapshots include it;
``Scheduler.stats`` remains as a read-only dict view for tests/operators.
Queue depth, queue-age and lease-wait histograms, per-miner throughput
EWMA gauges, lease-remaining gauges, and the cache hit ratio ride the same
registry. Each request additionally records a TRACE — an ordered span of
enqueue -> dispatch -> assign/result/merge -> reply events keyed by its
``job_id`` (no wire-format change) — retrievable via
:meth:`Scheduler.trace` and dumped wholesale when a queue-age or in-flight
age alarm fires, so a stalled request names the miner that wedged it and
the re-issue that rescued it.

Bookkeeping divergence from the reference (deliberate): the reference tracks
one recorded chunk per miner plus a positional ``responsibleMiners`` list,
which deadlocks or double-counts in several reachable states — a parked chunk
whose client drops stalls every later request (server.go:377-400 never
releases the barrier); a freed miner re-assigned before flushing its previous
Result leaks that stale Result into the new request; an idle miner dropping
reassigns a stale chunk from an older request (server.go:339-370). Here every
Request written to a miner pushes a full chunk record onto that miner's
pending FIFO; since miners answer sequentially over in-order exactly-once
LSP, each arriving Result pops exactly the chunk it answers, so stale Results
are identified precisely, and a dead miner's unanswered chunks are recovered
individually. The observable contract (assignment order, chunk boundaries,
merge rule, one-in-flight FIFO scheduling) is unchanged.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from dataclasses import dataclass, field
from typing import Optional

from ..bitcoin.hash import MAX_U64
from ..bitcoin.message import Message, MsgType, new_request, new_result
from ..lsp.errors import LspError
from ..lsp.server import AsyncServer
from ..utils import sanitize as _sanitize
from ..utils import trace as _tracing
from ..utils.config import CacheParams, CoalesceParams, LeaseParams, \
    QosParams, StripeParams, coalesce_from_env, qos_from_env, \
    stripe_from_env
from ..utils.metrics import (LATENCY_BUCKETS_S, OCCUPANCY_BUCKETS, Registry,
                             RequestTrace, TraceBuffer, ensure_emitter,
                             registry as process_registry)
from .qos import QosPlane

logger = logging.getLogger("dbm.scheduler")

#: Every monotonic counter the scheduler keeps (the old ``stats`` dict keys
#: plus the ISSUE 3 additions). ``Scheduler.stats`` is a dict view of these.
STAT_COUNTERS = (
    "results_sent", "dup_results", "leases_blown", "reissues",
    "quarantines", "cache_hits", "cache_misses", "cache_stores",
    "queue_alarms", "inflight_alarms", "no_eligible_miner",
    "desperation_dispatch", "leases_blown_spurious", "chunks_striped",
    "qos_grants", "qos_shed", "qos_window_grants",
)


class ResultCache:
    """Bounded LRU of finished Results, keyed on the full request
    identity ``(data, lower, upper, target)``.

    submit_with_retry re-submits the identical request after a lost
    Result; without memoization every retry re-ran the whole search. A
    hit replays the recorded answer in O(1) — sound because the answer
    is a pure function of the key: the arg-min (and the
    first-qualifying-nonce difficulty answer) of a fixed range is
    deterministic. The one non-deterministic case — a WEAK difficulty
    merge, where a stock Target-dropping miner answered a chunk — is
    never stored (see Scheduler._finish).
    """

    def __init__(self, size: int):
        self.size = size
        self._d: dict = {}     # insertion order == LRU order (py3.7+)

    def get(self, key):
        hit = self._d.pop(key, None)
        if hit is not None:
            self._d[key] = hit          # refresh recency
        return hit

    def put(self, key, value) -> None:
        self._d.pop(key, None)
        self._d[key] = value
        while len(self._d) > self.size:
            self._d.pop(next(iter(self._d)))

    def __len__(self):
        return len(self._d)


@dataclass
class Chunk:
    job_id: int
    data: str
    lower: int
    upper: int              # exclusive end, as sent on the wire
    target: int = 0         # difficulty target; rides every (re)assignment
    idx: int = 0            # position in the request's ascending chunk order
    # Set when the requesting client drops: the chunk stays in the miner's
    # pending FIFO (its Result must still pop in order) but no longer
    # counts against the miner's availability.
    cancelled: bool = False
    # Lease plane. Each FIFO entry is one ASSIGNMENT: a speculative
    # re-issue pushes a fresh Chunk object (same job/idx/range) onto the
    # takeover miner's FIFO with its own lease, while the blown original
    # stays in its miner's FIFO awaiting the in-order pop.
    assigned_at: float = 0.0   # monotonic stamp; reset when the lease starts
    deadline: float = 0.0      # lease expiry (monotonic)
    # Position-aware lease clock (fifo_aware): False until the chunk
    # reaches the head of its miner's FIFO. Until then the deadline is a
    # BUDGET covering the predecessors too; at the head it is re-stamped
    # to the tight single-chunk lease.
    lease_started: bool = False
    lease_blown: bool = False  # expiry observed (counted once per entry)
    reissued: bool = False     # a speculative copy is already in flight
    # Coalescing grant hint (ISSUE 9): chunks sharing a coalesce_id were
    # granted into one miner's coalescing window — they may share a
    # device launch, and they count as ONE live chunk against the QoS
    # depth cap (_miner_live). None = stock accounting. A speculative
    # re-issue copy never inherits the id (fresh Chunk): the takeover
    # miner runs it solo.
    coalesce_id: Optional[int] = None

    @property
    def size(self) -> int:
        """Nonce count the miner actually scans (``Upper`` read inclusive —
        the reference bound quirk, see module docstring)."""
        return self.upper - self.lower + 1


@dataclass
class MinerState:
    conn_id: int
    # Every Request written to this miner, in write order (see module doc).
    pending: list = field(default_factory=list)
    # Lease plane: observed per-chunk throughput (nonces/sec EWMA; None
    # until the first Result), consecutive blown leases, and the
    # quarantine latch (set at quarantine_after blown leases, cleared by
    # any Result pop from this miner).
    rate_ewma: Optional[float] = None
    blown_streak: int = 0
    quarantined: bool = False
    # Windowed throughput sampling (ISSUE 5; see _observe_result): the
    # wall-clock window currently accumulating answered nonces. Per-pop
    # size/elapsed sampling is a lie under the pipelined miner — a
    # prefetched chunk's Result lands ~1ms after its lease re-stamp and
    # reads as 10^9 nonces/s.
    win_t0: float = 0.0
    win_nonces: int = 0

    @property
    def available(self) -> bool:
        """Derived, not stored (ADVICE r2): a miner is available iff it has
        no LIVE pending chunk. Cancelled chunks still occupy the FIFO (their
        stale Results pop in order) without blocking new assignments."""
        return not any(not c.cancelled for c in self.pending)


@dataclass
class Request:
    conn_id: int
    data: str
    lower: int
    upper: int              # inclusive on arrival; +1 at load_balance
    target: int = 0         # difficulty target; 0 = exact arg-min (stock)
    job_id: int = 0
    num_chunks: int = 0
    min_hash: int = MAX_U64
    min_nonce: int = 0
    # Difficulty merge plane, per-chunk (VERDICT r4 prefix release).
    # Chunks cover ascending disjoint sub-ranges and each until-speaking
    # miner reports its chunk-FIRST qualifying (hash < target) nonce, so
    # the lowest-INDEX qualifying chunk holds the globally first
    # qualifying nonce — final as soon as every earlier chunk has
    # answered without a hit, regardless of chunks still in flight.
    # (A stock Target-dropping miner reports its chunk ARG-MIN, which may
    # qualify later than its chunk's first hit, weakening the answer to
    # "a qualifying nonce" — see client.submit_until docstring.)
    answered: list = field(default_factory=list)   # bool per chunk idx
    chunk_q: dict = field(default_factory=dict)    # idx -> (nonce, hash)
    # True once any responder answered a target chunk without echoing the
    # target (stock miner in the pool): the merged answer is then only
    # guaranteed qualifying, not guaranteed globally first (ADVICE r4 —
    # surfaced in logs, invisible on the reference-shaped wire).
    weak: bool = False
    started: float = 0.0           # set at dispatch (load_balance)
    # Memoization / observability plane.
    cache_key: Optional[tuple] = None  # (data, lower, upper, target) as received
    queued_at: float = 0.0         # monotonic stamp set at _on_request
    last_alarm: float = 0.0        # last queue-age warning for this request
    # Separate stamp for the in-flight age alarm: a request that alarmed
    # while QUEUED must not have its first in-flight alarm suppressed for
    # a full extra bound after dispatch.
    last_inflight_alarm: float = 0.0
    trace: object = None           # RequestTrace (utils/metrics.py)
    # QoS dispatch plane (ISSUE 5). ``qos_mode`` is "" until dispatch,
    # then "wholesale" (stock path: every chunk assigned at dispatch) or
    # "chunked" (chunk plan held centrally, granted incrementally).
    qos_mode: str = ""
    chunk_bounds: list = None      # chunked mode: [(lo, up_excl), ...]
    next_chunk: int = 0            # chunked mode: first ungranted idx
    granted_chunks: int = 0        # chunks handed to miners so far

    def __post_init__(self):
        # Every Request carries a trace from birth, even when constructed
        # directly (tests, programmatic drivers) rather than via
        # _on_request — the scheduler records events unconditionally.
        if self.trace is None:
            self.trace = RequestTrace(data=self.data, lower=self.lower,
                                      upper=self.upper, target=self.target,
                                      client=self.conn_id)


class Scheduler:
    """Single-actor scheduler over an :class:`AsyncServer`."""

    def __init__(self, server: AsyncServer,
                 lease: Optional[LeaseParams] = None,
                 cache: Optional[CacheParams] = None,
                 stripe: Optional[StripeParams] = None,
                 qos: Optional[QosParams] = None,
                 coalesce: Optional[CoalesceParams] = None,
                 clock=None):
        self.server = server
        self.lease = lease if lease is not None else LeaseParams()
        self.cache = cache if cache is not None else CacheParams()
        # Env-defaulted (unlike lease/cache) so the tier-1 knob-off matrix
        # leg (DBM_STRIPE=0) exercises the Go-parity split through every
        # existing harness without threading a parameter into each test.
        self.stripe = stripe if stripe is not None else stripe_from_env()
        # Env-defaulted like stripe: DBM_QOS=0 pins the stock FIFO path
        # through every existing harness (the tier-1 matrix leg).
        self.qos = qos if qos is not None else qos_from_env()
        # Env-defaulted like stripe/qos: DBM_COALESCE=0 pins stock grant
        # accounting (no windows, no shared live slots) bit-for-bit.
        self.coalesce = (coalesce if coalesce is not None
                         else coalesce_from_env())
        self._next_coalesce_id = 0
        self.results: Optional[ResultCache] = (
            ResultCache(self.cache.size) if self.cache.enabled else None)
        self.miners: list[MinerState] = []      # join order, like minersArray
        self.parked: list[Chunk] = []           # chunks of dropped miners
        self.queue: list[Request] = []
        # In-flight requests by job_id, oldest first (dict preserves
        # insertion order). The stock FIFO path keeps AT MOST ONE entry
        # — the reference's one-request-in-flight invariant — while the
        # QoS plane runs several concurrently; ``current`` (below) stays
        # the single-request view every existing caller reads.
        self._inflight: dict[int, Request] = {}
        self._next_job_id = 0
        self._pool_rate: Optional[float] = None   # pool-wide throughput EWMA
        self._dispatching = False                 # _maybe_dispatch guard
        self._starved = False                     # no-eligible-miner latch
        # Observability plane (ISSUE 3): a per-scheduler registry (so unit
        # tests see exactly THIS instance's counts), mounted into the
        # process registry under "sched." for the emitter/bench snapshot.
        # The prefix is FIXED and latest-wins by design: production runs
        # one scheduler per process, and a stable key set is what keeps
        # emitter lines and BENCH snapshots diffable across restarts. A
        # process deliberately embedding several live schedulers should
        # read each instance's own `.metrics`/`.stats` — only the newest
        # is visible through the process snapshot. Never drives behavior.
        self.metrics = Registry()
        process_registry().mount("sched", self.metrics)
        ensure_emitter()
        # Runtime sanitizer (ISSUE 7, DBM_SANITIZE=1): installs the
        # process slow-callback watchdog and pins the hot dispatch
        # structures (miners/queue/_inflight and everything reachable
        # from the event handlers) to the actor's own thread. None when
        # the knob is off — the guard below is then one attribute test.
        self._owner = (_sanitize.ThreadOwner(
            "Scheduler hot state (miners/queue/_inflight)")
            if _sanitize.ensure_sanitizer() else None)
        self._counters = {n: self.metrics.counter(n) for n in STAT_COUNTERS}
        self._queue_depth = self.metrics.gauge("queue_depth")
        self._pool_size = self.metrics.gauge("pool_size")
        self._pool_quarantined = self.metrics.gauge("pool_quarantined")
        self._cache_hit_ratio = self.metrics.gauge("cache_hit_ratio")
        self._lease_min_remaining = self.metrics.gauge(
            "lease_min_remaining_s")
        self._queue_wait = self.metrics.histogram("queue_wait_s",
                                                  LATENCY_BUCKETS_S)
        self._lease_wait = self.metrics.histogram("lease_wait_s",
                                                  LATENCY_BUCKETS_S)
        # Striping plane (dispatch pipeline): chunks per miner share.
        self._stripe_depth = self.metrics.histogram("stripe_chunks_per_share",
                                                    OCCUPANCY_BUCKETS)
        self.traces = TraceBuffer()
        self._cache_trace_seq = 0
        # Cross-process tracing plane (ISSUE 10, DBM_TRACE=1 default):
        # miner-side chunk spans arriving on the Result's Span extension
        # are stitched into the request's trace, and the Perfetto export
        # draws one track per miner/tenant. Track identity lives in a
        # TrackSet under the same cardinality discipline as labeled
        # metric series — registered on first sight, RETIRED on miner
        # drop / tenant GC so conn churn cannot grow the export without
        # bound. DBM_TRACE=0 turns every hook into one boolean check.
        self._trace_on = _tracing.ensure_tracer()
        self._tracks = _tracing.TrackSet()
        # Fair-share QoS plane (ISSUE 5): always constructed (tenant
        # accounting is a few dicts), consulted only when qos.enabled.
        # ``clock`` (ISSUE 8) feeds the admission token buckets: the
        # deterministic-schedule explorer (analysis/schedcheck) injects
        # its virtual clock here so bucket refills are a function of the
        # explored schedule, not of wall time. Note the scheduler's own
        # lease/trace stamps read ``time.monotonic`` directly — the
        # explorer patches that; this parameter exists because the
        # bucket CAPTURES its clock at construction.
        self.qos_plane = QosPlane(
            self.metrics, clock=clock if clock is not None
            else time.monotonic)
        self._tenant_weights: dict = {}    # programmatic overrides

    # ---------------------------------------------------------- public view

    @property
    def current(self) -> Optional[Request]:
        """The OLDEST in-flight request, or None. Under the stock FIFO
        path this is the reference's single in-flight request; under QoS
        several may be in flight — callers that need them all read
        :attr:`inflight`."""
        return next(iter(self._inflight.values()), None)

    @property
    def inflight(self) -> dict:
        """Read-only view of every in-flight request by job id."""
        return dict(self._inflight)

    # ------------------------------------------------------- stats / metrics

    @property
    def stats(self) -> dict:
        """Read-only dict view of every counter (the pre-ISSUE-3 ``stats``
        dict surface, now backed by the registry)."""
        return {n: c.value for n, c in self._counters.items()}

    def _count(self, name: str, n: int = 1) -> None:
        self._counters[name].inc(n)

    def _update_pool_gauges(self) -> None:
        self._pool_size.set(len(self.miners))
        self._pool_quarantined.set(
            sum(1 for m in self.miners if m.quarantined))

    def _cache_lookup(self, key, count_miss: bool = True):
        """ResultCache get + hit/miss/ratio accounting in one place.

        ``count_miss=False`` for the dispatch-time RE-check of a key that
        already missed at enqueue: counting it again would charge every
        normally-dispatched request two misses and skew the hit ratio."""
        hit = self.results.get(key)
        if hit is not None:
            self._count("cache_hits")
        elif count_miss:
            self._count("cache_misses")
        hits = self._counters["cache_hits"].value
        total = hits + self._counters["cache_misses"].value
        self._cache_hit_ratio.set(hits / total if total else 0.0)
        return hit

    def trace(self, request_id: int):
        """The recorded :class:`RequestTrace` for a job id (or a
        ``cache:N`` replay key); None when unknown or evicted."""
        return self.traces.get(request_id)

    def _dump_trace(self, why: str, trace) -> None:
        """Structured single-line JSON dump of one request trace — the
        queue-age alarm's "a stalled request explains itself" payload."""
        if trace is None:
            return
        logger.warning("trace dump (%s): %s", why,
                       json.dumps(trace.to_dict(), sort_keys=True,
                                  default=str))

    def _fold_span(self, trace, conn_id: int, chunk: Chunk,
                   span: Optional[dict]) -> None:
        """Stitch one miner-side chunk span (the Result's Span wire
        extension) into the request's trace as a ``miner_span`` event
        (ISSUE 10). The span vocabulary is whitelisted (a hostile peer
        cannot inject arbitrary keys into dumps), the DOMINANT phase is
        named inline so a stalled request's dump reads "force stalled on
        miner 7" without arithmetic, and the owning miner's export track
        is registered (retired again on miner drop)."""
        if span is None or trace is None or not self._trace_on:
            return
        clean = {}
        for key in _tracing.SPAN_PHASES + _tracing.SPAN_EXTRAS:
            v = span.get(key)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                clean[key] = v
        if not clean:
            return
        self._tracks.track("trace_track", miner=str(conn_id))
        slow = _tracing.slow_phase(clean)
        if slow is not None:
            clean["slow"] = slow
        trace.event("miner_span", miner=conn_id, idx=chunk.idx, **clean)

    def export_trace(self, path: Optional[str] = None) -> dict:
        """Chrome trace-event JSON (Perfetto-loadable) of every retained
        request trace: one track per tenant (scheduler process) and per
        miner, request slices + instant fault events + the stitched
        miner-side phase spans (``scripts/dbmtrace.py`` is the CLI
        wrapper). Returns the document; ``path`` also writes it."""
        dicts = []
        for _key, t in self.traces.items():
            d = t.to_dict()
            d["t0"] = t.t0
            dicts.append(d)
        tenant_tracks, miner_tracks = {}, {}
        for labels, tid in self._tracks.items("trace_track"):
            labels = dict(labels)
            if "tenant" in labels:
                tenant_tracks[labels["tenant"]] = tid
            if "miner" in labels:
                miner_tracks[labels["miner"]] = tid
        doc = _tracing.to_chrome_trace(dicts, tenant_tracks=tenant_tracks,
                                       miner_tracks=miner_tracks)
        if path:
            with open(path, "w", encoding="utf-8") as fh:
                json.dump(doc, fh, sort_keys=True)
        return doc

    def _track_tenant(self, conn_id: int) -> None:
        if self._trace_on:
            self._tracks.track("trace_track", tenant=str(conn_id))

    # ------------------------------------------------------------- main loop

    async def run(self) -> None:
        """Serve until the LSP server is closed."""
        # The sweep runs even with leases disabled: the queue-age alarm
        # (an observability plane, not a scheduling one) rides it.
        lease_task = asyncio.get_running_loop().create_task(
            self._lease_loop())
        try:
            while True:
                try:
                    conn_id, payload = await self.server.read()
                except LspError:
                    return
                if isinstance(payload, Exception):
                    self._on_drop(conn_id)
                    continue
                try:
                    msg = Message.from_json(payload)
                except ValueError:
                    continue
                if msg.type == MsgType.JOIN:
                    self._on_join(conn_id)
                elif msg.type == MsgType.REQUEST:
                    self._on_request(conn_id, msg)
                elif msg.type == MsgType.RESULT:
                    self._on_result(conn_id, msg)
        finally:
            if lease_task is not None:
                lease_task.cancel()

    async def _lease_loop(self) -> None:
        """Periodic sweep; the only timer the scheduler owns. Checks
        chunk leases (when enabled) and the queued-request age alarm."""
        while True:
            await asyncio.sleep(self.lease.tick_s)
            try:
                if self.lease.enabled:
                    self._check_leases()
                self._check_queue_age()
                if self.qos.enabled:
                    # Idle-tenant GC: a tenant with no queued or in-flight
                    # work, nothing granted outstanding, and a full
                    # admission bucket carries no state worth keeping —
                    # dropping it frees its metric series so conn churn
                    # stays bounded over a long server life. Tenants the
                    # GC forgets also lose their export track (ISSUE 10):
                    # the track registry obeys the same churn rule.
                    before = set(self.qos_plane.tenants)
                    self.qos_plane.gc(
                        {r.conn_id for r in self.queue}
                        | {r.conn_id for r in self._inflight.values()})
                    for tenant in before - set(self.qos_plane.tenants):
                        self._tracks.retire("trace_track",
                                            tenant=str(tenant))
            except Exception:   # noqa: BLE001 — the sweep must never die
                logger.exception("lease sweep failed; continuing")

    # ---------------------------------------------------------------- events

    def _on_request(self, conn_id: int, msg: Message) -> None:
        if self._owner is not None:
            self._owner.assert_here()
        key = (msg.data, msg.lower, msg.upper, msg.target)
        if self.results is not None:
            hit = self._cache_lookup(key)
            if hit is not None:
                # O(1) replay: a retried/resubmitted request after a lost
                # Result answers from the memo without touching the pool
                # (and without queueing behind the in-flight request).
                h, nonce = hit
                self._write(conn_id, new_result(h, nonce))
                self._count("results_sent")
                self._trace_cache_replay(conn_id, key, h, nonce)
                logger.info("request %r [%d, %d] target=%d answered from "
                            "the result cache", msg.data, msg.lower,
                            msg.upper, msg.target)
                return
        request = Request(conn_id=conn_id, data=msg.data,
                          lower=msg.lower, upper=msg.upper,
                          target=msg.target, cache_key=key,
                          queued_at=time.monotonic())
        if self.qos.enabled:
            # Admission (cache replays above never reach here — an
            # already-answered retry must not burn quota, ISSUE 5
            # satellite). A drained bucket sheds the NEW request;
            # overload sheds the OLDEST queued one (their client is
            # nearest its own deadline; shedding it now gives its
            # backed-off resubmission the best chance of landing in a
            # drained queue).
            self.qos_plane.tenant(conn_id, self._weight_for(conn_id),
                                  self.qos.rate, self.qos.burst)
            if not self.qos_plane.admit(conn_id):
                self._shed(request, "admission")
                return
        request.trace.event("enqueue", queue_depth=len(self.queue))
        self.queue.append(request)
        self._queue_depth.set(len(self.queue))
        if self.qos.enabled and self.qos.max_queued > 0:
            while len(self.queue) > self.qos.max_queued:
                self._shed(self.queue.pop(0), "overload")
            self._queue_depth.set(len(self.queue))
        self._maybe_dispatch()

    def _trace_cache_replay(self, conn_id: int, key, h: int,
                            nonce: int) -> None:
        """An at-enqueue memo replay never builds a Request (and never
        gets a job id): trace it under a synthetic ``cache:N`` key so
        trace completeness still holds. (A replay at DISPATCH time reuses
        the queued Request's own trace instead — its enqueue stamp and
        queue wait are real history that must not be discarded.)"""
        self._cache_trace_seq += 1
        trace = self.traces.new(data=key[0], lower=key[1], upper=key[2],
                                target=key[3], client=conn_id)
        trace.event("enqueue", queue_depth=len(self.queue))
        trace.event("cache_hit", at="request")
        trace.event("reply", hash=h, nonce=nonce, cached=True)
        self.traces.register(f"cache:{self._cache_trace_seq}", trace)
        self._track_tenant(conn_id)

    def _on_join(self, conn_id: int) -> None:
        if self._owner is not None:
            self._owner.assert_here()
        miner = MinerState(conn_id=conn_id)
        # A joining miner immediately absorbs one parked chunk, if any
        # (ref: server.go:222-244).
        chunk = self._next_parked()
        if chunk is not None:
            self._assign_chunk(miner, chunk, kind="parked")
        self.miners.append(miner)
        self._update_pool_gauges()
        self._maybe_dispatch()

    def _on_result(self, conn_id: int, msg: Message) -> None:
        if self._owner is not None:
            self._owner.assert_here()
        miner = self._find_miner(conn_id)
        if miner is None or not miner.pending:
            return
        chunk = miner.pending.pop(0)   # the Result answers the oldest Request
        self._observe_result(miner, chunk)
        # Position-aware leases: the next FIFO entry is what the miner
        # computes now — start its clock (no-op when already started, i.e.
        # fifo_aware off or it was assigned to an empty FIFO).
        if miner.pending and not miner.pending[0].lease_started:
            self._start_lease(miner, miner.pending[0])
        # A freed miner immediately absorbs one parked chunk
        # (ref: server.go:285-304) — BEFORE the stale-Result return, so a
        # miner freed by a stale answer still rescues parked work. The
        # just-popped (job, idx) is excluded: this very Result is about to
        # answer it, so a parked speculative copy of it is garbage — not
        # work to hand back to the miner that just did it.
        if self.parked and miner.available:
            parked = self._next_parked(skip_key=(chunk.job_id, chunk.idx))
            if parked is not None:
                self._assign_chunk(miner, parked, kind="parked")
        curr = self._inflight.get(chunk.job_id)
        if curr is None:
            stale = self.traces.get(chunk.job_id)
            if stale is not None:
                stale.event("stale_result", miner=conn_id, idx=chunk.idx)
                # A wedged/slow miner's span arrives LATE by definition
                # (its chunk was re-issued and the request already
                # replied): stitching it into the closed trace is what
                # names the miner-side phase that stalled.
                self._fold_span(stale, conn_id, chunk, msg.span)
            # A freed miner may unblock a queued/ungranted chunk.
            if self.qos.enabled:
                self._maybe_dispatch()
            return  # stale Result for a cancelled/finished request
        if curr.answered[chunk.idx]:
            # Loser of a speculative re-issue race: another assignment of
            # this same (job, idx) already merged. Re-issued copies scan
            # the identical range, so dropping the duplicate changes
            # nothing but the stats.
            self._count("dup_results")
            self._fold_span(curr.trace, conn_id, chunk, msg.span)
            curr.trace.event("result", miner=conn_id, idx=chunk.idx,
                             duplicate=True)
            logger.info("duplicate Result for job %d chunk %d from miner %d "
                        "(speculation loser)", curr.job_id, chunk.idx,
                        conn_id)
            if self.qos.enabled:
                # The duplicate still freed a live-FIFO slot on this miner.
                self._maybe_dispatch()
            return
        if msg.hash < curr.min_hash:
            curr.min_hash = msg.hash
            curr.min_nonce = msg.nonce
        curr.answered[chunk.idx] = True
        if self.qos.enabled:
            self.qos_plane.on_chunk_answered(curr.conn_id)
        self._fold_span(curr.trace, conn_id, chunk, msg.span)
        curr.trace.event("result", miner=conn_id, idx=chunk.idx)
        curr.trace.event("merge", idx=chunk.idx,
                         answered=sum(curr.answered))
        if curr.target and msg.target != curr.target and not curr.weak:
            curr.weak = True
            logger.info(
                "difficulty request %d: miner %d answered without the "
                "target extension; the merged result is guaranteed "
                "qualifying, not guaranteed globally first",
                curr.job_id, conn_id)
        if curr.target and msg.hash < curr.target:
            curr.chunk_q[chunk.idx] = (msg.nonce, msg.hash)
        # Prefix release (difficulty only): the lowest-index qualifying
        # chunk is final once every earlier chunk has answered clean —
        # later chunks cover strictly higher nonces and cannot beat it.
        if curr.chunk_q:
            c = min(curr.chunk_q)
            if all(curr.answered[:c]):
                nonce, q_hash = curr.chunk_q[c]
                self._finish(curr, q_hash, nonce, early=True)
                return
        if curr.answered and all(curr.answered):
            # Full barrier: stock request, or target missed everywhere —
            # the exact arg-min. (A difficulty hit always releases above:
            # at the barrier, its qualifying prefix is trivially complete.)
            self._finish(curr, curr.min_hash, curr.min_nonce)
        elif self.qos.enabled:
            # The answering miner freed a live-FIFO slot: grant the next
            # chunk (this request's or another tenant's, per DRR).
            self._maybe_dispatch()

    def _on_drop(self, conn_id: int) -> None:
        if self._owner is not None:
            self._owner.assert_here()
        miner = self._find_miner(conn_id)
        if miner is not None:
            logger.info("miner %d dropped", conn_id)
            self.miners.remove(miner)
            self._update_pool_gauges()
            # Retire the dead conn-id's labeled series: stale values must
            # not linger in snapshots, and reconnect churn (every rejoin
            # is a fresh conn id) must not exhaust the family cardinality
            # bound over a long server life.
            self.metrics.remove("miner_rate_nps", miner=str(conn_id))
            self.metrics.remove("lease_remaining_s", miner=str(conn_id))
            # Export-track retirement (ISSUE 10): same churn rule as the
            # labeled series above — a dead conn id's track must free
            # its slot under the cardinality bound.
            self._tracks.retire("trace_track", miner=str(conn_id))
            _tracing.flight("miner_drop", miner=conn_id)
            if not self._inflight:
                return
            for req in self._inflight.values():
                req.trace.event("miner_drop", miner=conn_id)
            # Recover every unanswered chunk of each in-flight request
            # (ref: server.go:326-376, single-chunk version; the stock
            # FIFO path has exactly one). Chunks whose idx already merged
            # (speculation winner landed first) and chunks with a live
            # speculative copy in another FIFO need no recovery — the
            # copy is tracked independently.
            for chunk in miner.pending:
                req = self._inflight.get(chunk.job_id)
                if req is None or chunk.cancelled:
                    continue
                if req.answered[chunk.idx] or chunk.reissued:
                    continue
                takeover = next((m for m in self._eligible()), None)
                if takeover is not None:
                    self._assign_chunk(takeover, chunk, kind="recovered")
                else:
                    self.parked.append(chunk)
                    req.trace.event("park", idx=chunk.idx)
        else:
            logger.info("client %d dropped", conn_id)
            # Purge the dead client's queued requests FIRST so cancelling its
            # in-flight request can't promote another of its own requests.
            for req in self.queue:
                if req.conn_id == conn_id:
                    req.trace.event("cancel", reason="client_drop")
            self.queue = [r for r in self.queue if r.conn_id != conn_id]
            self._queue_depth.set(len(self.queue))
            self._tracks.retire("trace_track", tenant=str(conn_id))
            if self.qos.enabled:
                self.qos_plane.forget(conn_id)
            for req in [r for r in self._inflight.values()
                        if r.conn_id == conn_id]:
                # Cancel immediately (divergence, see module docstring).
                req.trace.event("cancel", reason="client_drop")
                self._retire(req)

    # -------------------------------------------------------------- internal

    def _finish(self, curr: Request, h: int, nonce: int,
                early: bool = False) -> None:
        """Answer the client and retire the request. ``early`` = prefix
        release: the job's other chunks are still in flight."""
        self._write(curr.conn_id, new_result(h, nonce))
        self._count("results_sent")
        if self.results is not None and curr.cache_key is not None \
                and not curr.weak:
            # Weak merges excluded: "a qualifying nonce" from a stock
            # miner is not a deterministic function of the key.
            self.results.put(curr.cache_key, (h, nonce))
            self._count("cache_stores")
        elapsed = time.monotonic() - curr.started
        curr.trace.event("reply", hash=h, nonce=nonce, early=early,
                         weak=curr.weak, elapsed_s=round(elapsed, 6))
        if self._trace_on:
            _tracing.flight("reply", job=curr.job_id, tenant=curr.conn_id,
                            elapsed_s=round(elapsed, 6))
        logger.info(
            "request %d served in %.3fs: [%d, %d) over %d chunks%s%s",
            curr.job_id, elapsed,
            curr.lower, curr.upper, curr.num_chunks,
            " (prefix release)" if early else "",
            " (weak merge)" if curr.weak else "")
        self._retire(curr)

    def _retire(self, curr: Request) -> None:
        """Retire one in-flight request and pump the queue.

        Any still-pending chunks of the retiring job (prefix release,
        client drop, or the unanswered losers of speculative re-issues at
        a full-barrier finish) are marked cancelled: the pool frees
        immediately (availability is derived), the FIFO pop discipline for
        their late Results is preserved (they drop at the job_id check),
        and the job's parked chunks are discarded. Under QoS the tenant's
        in-flight slots for granted-but-unanswered chunks are released
        and any UNGRANTED chunks simply evaporate (a difficulty prefix
        release on a chunked elephant skips their scans entirely)."""
        for m in self.miners:
            for c in m.pending:
                if c.job_id == curr.job_id:
                    c.cancelled = True
        self.parked = [c for c in self.parked if c.job_id != curr.job_id]
        self._inflight.pop(curr.job_id, None)
        if self.qos.enabled:
            self.qos_plane.release(
                curr.conn_id, curr.granted_chunks - sum(curr.answered))
        if not self._inflight:
            # No live leases remain: clear the remaining-lease gauges so
            # an idle system's snapshot doesn't keep reporting the
            # retired job's last sweep values as work in flight.
            for m in self.miners:
                self.metrics.remove("lease_remaining_s",
                                    miner=str(m.conn_id))
            self._lease_min_remaining.set(0.0)
        self._maybe_dispatch()

    def _find_miner(self, conn_id: int) -> Optional[MinerState]:
        for m in self.miners:
            if m.conn_id == conn_id:
                return m
        return None

    def _next_parked(self, skip_key=None) -> Optional[Chunk]:
        """Pop the next parked chunk that still NEEDS executing, discarding
        stale ones: a parked chunk whose idx was meanwhile answered by a
        speculation winner (its copy blew a lease, was re-issued, and the
        re-issue landed first) — or whose ``(job_id, idx)`` matches
        ``skip_key``, the assignment the caller is answering right now —
        would only burn a full scan to pop as a duplicate."""
        while self.parked:
            chunk = self.parked.pop(0)
            req = self._inflight.get(chunk.job_id)
            if req is None or req.answered[chunk.idx]:
                continue
            if skip_key is not None and \
                    (chunk.job_id, chunk.idx) == skip_key:
                continue
            return chunk
        return None

    def _eligible(self) -> list[MinerState]:
        """Miners that may take new work: available and not quarantined."""
        return [m for m in self.miners
                if m.available and not m.quarantined]

    def _desperation_pool(self) -> list[MinerState]:
        """Last-resort pool when the WHOLE pool is quarantined: the
        least-bad available quarantined miner (lowest blown streak, then
        highest observed throughput), or nothing. Any non-quarantined
        miner — even a busy one that will free up — disables desperation:
        waiting for a healthy miner beats feeding a known-bad one."""
        if not self.lease.desperation or not self.miners:
            return []
        if not all(m.quarantined for m in self.miners):
            return []
        avail = [m for m in self.miners if m.available]
        if not avail:
            return []
        return [min(avail, key=lambda m: (m.blown_streak,
                                          -(m.rate_ewma or 0.0)))]

    def _maybe_dispatch(self) -> None:
        """Start queued work when the pool can take it: the stock FIFO
        pump (one wholesale request at a time), or the QoS grant pump.

        Re-entrancy guard: an empty-range request finishes INSIDE its own
        dispatch (_load_balance -> _finish -> _retire -> here), so without
        the guard a burst of empty-range requests would recurse one stack
        frame set per request and overflow; with it, the inner call
        returns immediately and the OUTER pump loop drains the queue
        iteratively."""
        if self._owner is not None:
            self._owner.assert_here()
        if self._dispatching:
            return
        self._dispatching = True
        try:
            if self.qos.enabled:
                self._qos_pump()
            else:
                self._fifo_pump()
        finally:
            self._dispatching = False
        if not self._inflight and self.queue and not self._eligible():
            # A dispatch pass found work but no taker: latch so the
            # condition logs once per starvation episode (every later
            # event re-enters here until a miner joins/frees/answers),
            # while the sweep's queue-age alarm keeps counting time.
            if not self._starved:
                self._starved = True
                self._count("no_eligible_miner")
                quarantined = sum(1 for m in self.miners if m.quarantined)
                logger.warning(
                    "no eligible miner for %d queued request(s): pool=%d "
                    "quarantined=%d busy=%d — queue is stalled until a "
                    "miner joins, frees, or answers",
                    len(self.queue), len(self.miners), quarantined,
                    sum(1 for m in self.miners
                        if not m.available and not m.quarantined))
        elif not self.queue:
            self._starved = False

    def _fifo_pump(self) -> None:
        """The stock dispatch loop: pop the queue head whenever nothing
        is in flight — the reference's FIFO order, bit-for-bit."""
        while not self._inflight and self.queue:
            pool = self._eligible()
            desperate = False
            if not pool:
                pool = self._desperation_pool()
                if not pool:
                    break
                desperate = True
            req = self.queue.pop(0)
            self._queue_depth.set(len(self.queue))
            if self._replay_at_dispatch(req):
                continue
            self._load_balance(req, pool, desperate=desperate)
            self._starved = False

    def _replay_at_dispatch(self, req: Request) -> bool:
        """Dispatch-time memo re-check: a duplicate that queued BEHIND
        its original (retry raced the still-in-flight first copy) replays
        at pop time — the original finished and stored while this one
        waited. The request's OWN trace is completed and registered
        (under a cache:N key — it never gets a job id) so the real queue
        wait stays on record. True = replayed (the caller drops it)."""
        if self.results is None or req.cache_key is None:
            return False
        hit = self._cache_lookup(req.cache_key, count_miss=False)
        if hit is None:
            return False
        self._write(req.conn_id, new_result(*hit))
        self._count("results_sent")
        self._queue_wait.observe(time.monotonic() - req.queued_at)
        req.trace.event("cache_hit", at="dispatch")
        req.trace.event("reply", hash=hit[0], nonce=hit[1], cached=True)
        self._cache_trace_seq += 1
        self.traces.register(f"cache:{self._cache_trace_seq}", req.trace)
        self._track_tenant(req.conn_id)
        logger.info(
            "queued request %r [%d, %d] answered from "
            "the result cache at dispatch", req.data,
            req.lower, req.upper)
        return True

    # ------------------------------------------------------------ QoS plane

    def _tenant(self, conn_id):
        """The QoS tenant state for a conn, created with the configured
        weight and admission bucket on first sight."""
        return self.qos_plane.tenant(conn_id, self._weight_for(conn_id),
                                     self.qos.rate, self.qos.burst)

    def _weight_for(self, tenant) -> float:
        w = self._tenant_weights.get(tenant)
        return w if w is not None else self.qos.weight_for(tenant)

    def set_tenant_weight(self, tenant, weight: float) -> None:
        """Programmatic per-tenant DRR weight override (tests and
        embedded drivers; the env path is ``DBM_QOS_WEIGHTS``)."""
        self._tenant_weights[tenant] = max(weight, 1e-3)
        self.qos_plane.set_weight(tenant, weight)

    def _miner_live(self, miner: MinerState) -> int:
        """Live (non-cancelled) chunks in a miner's pending FIFO, with
        a coalescing window's chunks counting as ONE (they share one
        device launch on the miner — ISSUE 9): the QoS depth cap bounds
        launches in flight, not rows per launch."""
        n = 0
        groups = set()
        for c in miner.pending:
            if c.cancelled:
                continue
            if c.coalesce_id is None:
                n += 1
            else:
                groups.add(c.coalesce_id)
        return n + len(groups)

    def _qos_capacity_pool(self) -> list[MinerState]:
        """Miners that may take an incremental QoS chunk: not
        quarantined, below the per-miner live-FIFO cap, and not sitting
        on a blown-lease chunk (a wedged miner's blown original stays
        live in its FIFO awaiting the in-order pop — the stock path's
        ``available`` never feeds such a miner either, and a mouse
        granted behind it would stall a full lease period), least-loaded
        first (ties keep join order — the reference's assignment
        order)."""
        depth = self.qos.depth
        pool = [m for m in self.miners
                if not m.quarantined and self._miner_live(m) < depth
                and not any(c.lease_blown and not c.cancelled
                            for c in m.pending)]
        pool.sort(key=self._miner_live)
        return pool

    def _qos_est_s(self, req: Request) -> Optional[float]:
        """Estimated pool-seconds to scan ``req``; None on a cold pool."""
        total = req.upper - req.lower + 1    # still inclusive pre-dispatch
        if total <= 0:
            return 0.0
        if self._pool_rate is None or self._pool_rate <= 0:
            return None
        n = max(1, len(self._eligible()) or len(self.miners) or 1)
        return total / (self._pool_rate * n)

    def _qos_small(self, req: Request) -> bool:
        """Small enough for the stock wholesale dispatch: the estimated
        scan fits ``wholesale_s``, or the pool is cold (no throughput
        observed — wholesale preserves reference parity for first
        requests, exactly like the striping plane's cold fallback)."""
        est = self._qos_est_s(req)
        return est is None or est <= self.qos.wholesale_s

    def _qos_chunk_plan(self, total: int, pool_n: int) -> tuple[int, int]:
        """``(n_chunks, first_chunk_size)`` for a chunked activation of
        ``total`` nonces: chunks sized at ``chunk_s`` seconds of one
        miner's pool-EWMA work, capped at ``max_chunks`` (a request too
        large for the cap gets proportionally larger chunks); an even
        split over ``pool_n`` when cold. Shared by the activation (the
        actual plan) and the DRR head cost (what one grant will debit) —
        the two MUST agree, or a chunked start banks the whole request's
        cost as unearned deficit and starves every other tenant."""
        rate = self._pool_rate if self._pool_rate else 0.0
        if rate > 0:
            n = -(-total // max(1, int(rate * self.qos.chunk_s)))
        else:
            n = max(1, pool_n)
        n = max(1, min(self.qos.max_chunks, n, total))
        return n, total // n + (1 if total % n else 0)

    def _qos_heads(self) -> dict:
        """Each tenant's next grantable work item:
        ``{tenant: (kind, request, cost_nonces)}``.

        - ``("chunk", req, n)`` — the next ungranted chunk of the
          tenant's oldest chunked in-flight request.
        - ``("start", req, n)`` — the tenant's oldest queued request
          (tenants serve their own requests FIFO; fairness is across
          tenants). Starts are withheld while a WHOLESALE request is in
          flight — that is the stock one-at-a-time order, which keeps
          single-tenant and small-request traffic bit-identical to the
          FIFO scheduler — but flow freely alongside chunked requests.

        Tenants at their ``max_inflight`` cap are skipped.
        """
        heads: dict = {}
        cap = self.qos.max_inflight
        any_chunked = any(r.qos_mode == "chunked"
                          for r in self._inflight.values())
        for req in self._inflight.values():     # oldest first
            if req.qos_mode != "chunked" or \
                    req.next_chunk >= req.num_chunks:
                continue
            t = req.conn_id
            if t in heads:
                continue
            if cap > 0 and self._tenant(t).inflight >= cap:
                continue
            lo, up = req.chunk_bounds[req.next_chunk]
            heads[t] = ("chunk", req, up - lo)
        busy = {r.conn_id for r in self._inflight.values()}
        for req in self.queue:
            if self._inflight and not any_chunked:
                break               # wholesale in flight: stock FIFO wait
            t = req.conn_id
            if t in heads or t in busy:
                continue
            if cap > 0 and self._tenant(t).inflight >= cap:
                continue
            # The head COST is what granting it will actually DEBIT —
            # the same branch the pump executes: the whole range for a
            # start that will dispatch wholesale (nothing in flight and
            # small — every chunk is assigned at dispatch), but only the
            # FIRST planned chunk for one that will activate chunked.
            # Pricing a to-be-chunked start at its full 2^40 range banks
            # the difference as unearned deficit, and quantum (the max
            # candidate cost) balloons with it — one mispriced start
            # then outbids every tenant for the rest of its life.
            total = max(1, req.upper - req.lower + 1)
            if not self._inflight and self._qos_small(req):
                cost = total
            else:
                _, cost = self._qos_chunk_plan(
                    total, len(self.miners) or 1)
            heads[t] = ("start", req, cost)
        return heads

    def _coalescible_cost(self, req: Request, cost: int) -> bool:
        """May a grant of ``cost`` nonces for ``req`` enter a coalescing
        window? Argmin mode only, and SMALL twice over: an absolute
        nonce bound (``max_nonces``) and an estimated-seconds bound at
        the pool rate (``small_s``) — only a chunk whose scan is
        launch-overhead-scale belongs in a shared launch; an absolute
        bound alone would misclassify a slow pool's rate-scaled
        elephant chunks as mice and serialize the elephant onto one
        miner's window."""
        if not self.coalesce.enabled or req.target \
                or cost > self.coalesce.max_nonces:
            return False
        rate = self._pool_rate
        if rate is not None and rate > 0:
            return cost <= rate * self.coalesce.small_s
        return True

    def _window_slot(self, window: dict, job_id: int):
        """The first open coalescing-window slot that can take a chunk
        of ``job_id``: a free lane, NOT already holding this job
        (windows batch across requests; stacking one request's own
        chunks would just re-merge what the chunk planner split), on a
        live non-quarantined miner. Returns ``(miner, slot)`` or
        ``(None, None)``. ONE definition shared by pump candidacy
        (:meth:`_window_room`) and the grant itself (:meth:`_qos_grant`)
        — if the two drifted, the pump could admit a candidate the
        grant cannot place and spin (code review)."""
        for conn_id, slot in window.items():
            if slot[1] >= self.coalesce.lanes or job_id in slot[2]:
                continue
            m = self._find_miner(conn_id)
            if m is not None and not m.quarantined:
                return m, slot
        return None, None

    def _window_room(self, window: dict, job_id: int = 0) -> bool:
        """Any joinable window for ``job_id``? (See
        :meth:`_window_slot`.)"""
        if not window:
            return False
        return self._window_slot(window, job_id)[0] is not None

    def _qos_pump(self) -> None:
        """The QoS grant loop: while grantable work and pool capacity
        exist, pick the next tenant by deficit-round-robin and execute
        ONE grant — an incremental chunk, a chunked activation, or a
        stock wholesale dispatch for small/cold requests.

        The pass carries a COALESCING WINDOW map (ISSUE 9): miner conn
        id -> ``[coalesce_id, lanes_used, {job_ids}]``. A small grant
        may land in an open window even when the capacity pool is empty
        (the window counts as one live slot however many lanes it
        holds), which is what batches N mice onto one miner within a
        single pump pass. Windows live for ONE pass only — the next
        pump starts fresh, so a window can never span a lease sweep or
        quarantine event."""
        plane = self.qos_plane
        # Classic DRR: a tenant whose backlog empties forfeits its
        # accumulated deficit — idle time must not bank credit. Backlog =
        # a queued request or an in-flight chunked request with ungranted
        # chunks (NOT merely capacity-blocked tenants, which keep theirs).
        backlogged = {r.conn_id for r in self.queue} | {
            r.conn_id for r in self._inflight.values()
            if r.qos_mode == "chunked" and r.next_chunk < r.num_chunks}
        for t, st in plane.tenants.items():
            if t not in backlogged:
                st.deficit = 0.0
        window: dict = {}
        while True:
            heads = self._qos_heads()
            if not heads:
                break
            eligible = self._eligible()
            cap_pool = self._qos_capacity_pool()
            candidates = {}
            for t, (kind, req, cost) in heads.items():
                joinable = (self._coalescible_cost(req, cost)
                            and self._window_room(window, req.job_id))
                if kind == "chunk":
                    if cap_pool or joinable:
                        candidates[t] = cost
                elif not self._inflight and self._qos_small(req):
                    # Wholesale start: needs the stock eligibility (or
                    # the desperation fallback), exactly like the FIFO
                    # pump.
                    if eligible or self._desperation_pool():
                        candidates[t] = cost
                elif cap_pool or joinable:
                    candidates[t] = cost
            if not candidates:
                break
            t = plane.pick(candidates)
            kind, req, cost = heads[t]
            if kind == "chunk":
                self._qos_grant(req, cap_pool, window)
                continue
            self.queue.remove(req)
            self._queue_depth.set(len(self.queue))
            if self._replay_at_dispatch(req):
                continue
            if not self._inflight and self._qos_small(req):
                pool, desperate = self._eligible(), False
                if not pool:
                    pool, desperate = self._desperation_pool(), True
                self._load_balance(req, pool, desperate=desperate)
            else:
                self._qos_activate(req, cap_pool, window)
            self._starved = False

    def _qos_activate(self, req: Request, pool: list[MinerState],
                      window: Optional[dict] = None) -> None:
        """Activate a request in CHUNKED mode: plan contiguous ascending
        chunks sized at ``chunk_s`` seconds of pool-EWMA work (capped at
        ``max_chunks``; an even split over the capacity pool when cold)
        and grant the first one. Later chunks are granted by subsequent
        pump turns, so concurrent tenants' chunks interleave."""
        self._next_job_id += 1
        req.job_id = self._next_job_id
        req.qos_mode = "chunked"
        req.started = time.monotonic()
        self._queue_wait.observe(req.started - req.queued_at)
        self.traces.register(req.job_id, req.trace)
        self._track_tenant(req.conn_id)
        self._inflight[req.job_id] = req
        req.upper += 1  # inclusive -> exclusive
        total = req.upper - req.lower
        req.trace.event("dispatch", job=req.job_id, mode="chunked",
                        miners=[m.conn_id for m in pool])
        if self._trace_on:
            _tracing.flight("dispatch", job=req.job_id, mode="chunked",
                            tenant=req.conn_id)
        if total <= 0:
            # Empty/inverted range, same answer as the wholesale path.
            self._finish(req, MAX_U64, 0)
            return
        # Cold-pool fallback sized over the WHOLE pool, exactly like the
        # DRR head pricing in _qos_heads — the activation may now run
        # with an EMPTY capacity pool (the window-joinable path), and
        # len(pool)=0 on a cold rate would plan ONE whole-request chunk
        # that diverges from the priced head cost (code review).
        n, _ = self._qos_chunk_plan(total, len(self.miners) or 1)
        bounds = []
        base = req.lower
        size, rem = divmod(total, n)
        for i in range(n):
            step = size + (1 if i < rem else 0)
            bounds.append((base, base + step))
            base += step
        req.chunk_bounds = bounds
        req.num_chunks = n
        req.answered = [False] * n
        req.next_chunk = 0
        self._qos_grant(req, pool, window)

    def _qos_grant(self, req: Request, pool: list[MinerState],
                   window: Optional[dict] = None) -> None:
        """Hand the request's next planned chunk to the least-loaded
        capacity miner and account the grant with the DRR plane.

        Coalescing (ISSUE 9): a SMALL chunk first tries to join an open
        window in ``window`` (sharing that window's ``coalesce_id`` —
        one live slot, one future shared launch); failing that it goes
        to the least-loaded capacity miner and, still being small,
        OPENS a window there for later grants of this pump pass. Large
        or difficulty chunks never touch windows. Accounting (DRR
        debit, tenant in-flight, lease) is identical either way."""
        idx = req.next_chunk
        lo, up = req.chunk_bounds[idx]
        miner = None
        cid = None
        small = self._coalescible_cost(req, up - lo)
        if small and window:
            miner, slot = self._window_slot(window, req.job_id)
            if miner is not None:
                cid = slot[0]
                slot[1] += 1
                slot[2].add(req.job_id)
                self._count("qos_window_grants")
        if miner is None:
            if not pool:
                return    # window gone and no capacity: next pump turn
            miner = pool[0]
            if small and window is not None \
                    and miner.conn_id not in window:
                self._next_coalesce_id += 1
                cid = self._next_coalesce_id
                window[miner.conn_id] = [cid, 1, {req.job_id}]
        req.next_chunk += 1
        req.granted_chunks += 1
        self._count("qos_grants")
        self.qos_plane.on_grant(req.conn_id, up - lo)
        self._assign_chunk(
            miner, Chunk(req.job_id, req.data, lo, up,
                         target=req.target, idx=idx, coalesce_id=cid),
            kind="qos")

    def _shed(self, req: Request, reason: str) -> None:
        """Shed one request under admission/overload pressure: cancel it
        through the trace/cancel path and CLOSE its conn. Classic LSP has
        no reject message, so the conn close is the signal — the client's
        transport declares the conn dead within its epoch window and
        ``submit_with_retry`` backs off and resubmits, instead of hanging
        into its wire deadline. The tenant's other QUEUED requests ride
        the same dying conn and are purged with it (in-flight work
        finishes; its reply write fails harmlessly)."""
        victims = [req] + [r for r in self.queue
                           if r.conn_id == req.conn_id and r is not req]
        self.queue = [r for r in self.queue if r.conn_id != req.conn_id]
        self._queue_depth.set(len(self.queue))
        for i, victim in enumerate(victims):
            self._count("qos_shed")
            self.qos_plane.on_shed(victim.conn_id,
                                   reason if i == 0 else "conn")
            victim.trace.event("cancel", reason="shed", shed_reason=reason)
            self._cache_trace_seq += 1
            self.traces.register(f"shed:{self._cache_trace_seq}",
                                 victim.trace)
            self._track_tenant(victim.conn_id)
            if self._trace_on:
                _tracing.flight("shed", tenant=victim.conn_id,
                                reason=reason)
        logger.warning(
            "QoS shed (%s): request %r [%d, %d] from tenant %d "
            "(+%d queued sibling(s)); closing its conn so the client "
            "backs off and resubmits", reason, req.data, req.lower,
            req.upper, req.conn_id, len(victims) - 1)
        close = getattr(self.server, "close_conn", None)
        if close is not None:
            try:
                close(req.conn_id)
            except Exception:  # noqa: BLE001 — conn may already be gone
                logger.info("shed: conn %d already closed", req.conn_id)

    def _load_balance(self, request: Request, pool: list[MinerState],
                      desperate: bool = False) -> None:
        """Split the range over ``pool`` (the eligible miners, or the
        single-miner desperation pool).

        Without faults this is ALL miners (the reference invariant: one
        request in flight, so every miner is free at dispatch); quarantined
        or still-busy miners (wedged compute holding a live lease-blown
        chunk) are excluded."""
        self._next_job_id += 1
        request.job_id = self._next_job_id
        request.qos_mode = "wholesale"
        self._inflight[request.job_id] = request
        request.started = time.monotonic()
        self._queue_wait.observe(request.started - request.queued_at)
        self.traces.register(request.job_id, request.trace)
        self._track_tenant(request.conn_id)
        request.trace.event("dispatch", job=request.job_id,
                            miners=[m.conn_id for m in pool],
                            desperate=desperate)
        if self._trace_on:
            _tracing.flight("dispatch", job=request.job_id,
                            mode="wholesale", tenant=request.conn_id)
        if desperate:
            self._count("desperation_dispatch")
            m = pool[0]
            logger.warning(
                "DESPERATION dispatch: entire pool (%d miner(s)) is "
                "quarantined; assigning request %r [%d, %d] to least-bad "
                "miner %d (blown streak %d, rate %s) as a last resort",
                len(self.miners), request.data, request.lower,
                request.upper, m.conn_id, m.blown_streak,
                f"{m.rate_ewma:.0f}/s" if m.rate_ewma else "unknown")
        num = len(pool)
        request.upper += 1  # inclusive -> exclusive
        total = request.upper - request.lower
        if total <= 0:
            # Empty/inverted range: answer like an empty scan (the reference
            # would wrap negative totals through uint64 and wedge the pool).
            self._finish(request, MAX_U64, 0)
            return
        individual = total // num
        leftover = total - individual * num
        if individual == 0:  # more miners than nonces
            individual, leftover, num = 1, 0, total
        # Striping (dispatch pipeline, ISSUE 4): each miner's even-split
        # share may be cut into several contiguous chunks so its pending
        # FIFO is deep enough for the miner-side pipeline to overlap.
        # The full chunk plan is built FIRST — chunk indices must ascend
        # with nonce range globally (the difficulty prefix-release merge
        # depends on it) and ``answered`` must be sized before the first
        # assignment records a trace event against it.
        plan: list[tuple[MinerState, int, int]] = []
        start = request.lower
        for i in range(num):
            end = start + individual + (leftover if i == 0 else 0)
            share = end - start
            n_i = self._stripe_chunks(pool[i], share)
            self._stripe_depth.observe(n_i)
            base = start
            for j in range(n_i):
                size = share // n_i + (1 if j < share % n_i else 0)
                plan.append((pool[i], base, base + size))
                base += size
            start = end
        if len(plan) > num:
            self._count("chunks_striped", len(plan) - num)
        request.num_chunks = len(plan)
        request.answered = [False] * len(plan)
        request.granted_chunks = len(plan)
        if self.qos.enabled:
            # Wholesale chunks count against the tenant's in-flight cap
            # and grant share like incremental ones — an elephant that
            # slipped through wholesale (cold pool) still pays its DRR
            # deficit, so later contended rounds stay fair.
            self._tenant(request.conn_id)
            for _, lo, up in plan:
                self.qos_plane.on_grant(request.conn_id, up - lo)
        for idx, (miner, lo, up) in enumerate(plan):
            self._assign_chunk(
                miner,
                Chunk(request.job_id, request.data, lo, up,
                      target=request.target, idx=idx))

    def _stripe_chunks(self, miner: MinerState, share: int) -> int:
        """Chunk count for one miner's share: ``ceil(share / (rate *
        chunk_s))`` capped at ``stripe.depth``. 1 (the stock even split)
        when striping is off, the share is trivial, or no throughput has
        been observed yet — a cold pool's first request is always
        bit-identical to the reference split, so the parity/conformance
        shape needs no knob to reproduce."""
        if not self.stripe.enabled or share <= 1:
            return 1
        rate = miner.rate_ewma if miner.rate_ewma is not None \
            else self._pool_rate
        if rate is None or rate <= 0:
            return 1
        target = max(1, int(rate * self.stripe.chunk_s))
        return max(1, min(self.stripe.depth, -(-share // target)))

    def _assign_chunk(self, miner: MinerState, chunk: Chunk,
                      kind: str = "initial") -> None:
        chunk.assigned_at = time.monotonic()
        chunk.lease_blown = False
        chunk.reissued = False
        chunk.lease_started = False
        chunk.deadline = 0.0
        miner.pending.append(chunk)
        # Position-aware lease clock (see module docstring): a chunk at
        # the FIFO head starts its tight lease now; one assigned behind
        # other entries gets a BUDGET deadline (latest predecessor expiry
        # + its own lease) that is tightened when it reaches the head
        # (_on_result) — so a deep healthy FIFO never blows spuriously,
        # but a FIFO wedged at its head still expires. fifo_aware=False
        # restores the at-assignment clock unconditionally.
        if not self.lease.fifo_aware or len(miner.pending) == 1:
            self._start_lease(miner, chunk)
        else:
            now = chunk.assigned_at
            ahead = max((c.deadline for c in miner.pending[:-1]),
                        default=now)
            chunk.deadline = max(now, ahead) + self._lease_for(miner, chunk)
        trace = self.traces.get(chunk.job_id)
        if trace is not None:
            trace.event("assign", miner=miner.conn_id, idx=chunk.idx,
                        lower=chunk.lower, upper=chunk.upper, kind=kind,
                        fifo_pos=len(miner.pending) - 1,
                        lease_started=chunk.lease_started)
        if self._trace_on:
            _tracing.flight("assign", job=chunk.job_id, idx=chunk.idx,
                            miner=miner.conn_id, kind=kind)
        self._write(miner.conn_id,
                    new_request(chunk.data, chunk.lower, chunk.upper,
                                chunk.target))

    # ---------------------------------------------------------- lease plane

    def _start_lease(self, miner: MinerState, chunk: Chunk) -> None:
        """Start the lease clock: the miner is (about to be) computing this
        chunk. ``assigned_at`` is re-stamped so both the expiry log and the
        throughput sample measure actual compute time, not FIFO wait."""
        now = time.monotonic()
        if chunk.assigned_at:
            self._lease_wait.observe(now - chunk.assigned_at)
        chunk.assigned_at = now
        chunk.deadline = now + self._lease_for(miner, chunk)
        chunk.lease_started = True

    #: Wall-clock span one throughput sample must cover (window-union
    #: accounting, the scheduler-side analog of the miner's
    #: _ThroughputWindow from ISSUE 4).
    RATE_WINDOW_S = 0.5

    def _observe_result(self, miner: MinerState, chunk: Chunk) -> None:
        """Per-pop bookkeeping: throughput sampling, streak reset,
        quarantine lift. Runs for EVERY pop — stale and cancelled chunks
        were computed too, so they are valid throughput samples, and an
        answer is an answer for quarantine purposes ("until it answers
        again").

        Throughput is sampled over a WALL-CLOCK WINDOW per miner, not per
        pop: the pipelined miner computes chunk k+1 while k's result is
        in flight, so k+1's Result arrives milliseconds after its lease
        re-stamp and a per-pop size/elapsed sample reads as 10^9
        nonces/s — which then poisons every consumer (stripe plans grow
        one-giant-chunk, the QoS wholesale gate misclassifies elephants,
        leases collapse to the floor). Accumulating answered nonces until
        ``RATE_WINDOW_S`` of wall clock has passed measures the miner's
        true OUTPUT rate regardless of internal overlap."""
        alpha = self.lease.ewma_alpha
        now = time.monotonic()
        if chunk.assigned_at and not chunk.lease_blown and not chunk.target:
            # Two exclusions keep the sample set honest (they also RESET
            # the window below). Blown-lease answers: a wedged miner's
            # eventual 60s "sample" would inflate its (and the pool's)
            # lease to minutes and blunt re-wedge detection. Difficulty
            # chunks: an in-kernel early exit may scan 1% of the range,
            # so size/elapsed would overestimate throughput ~100x and
            # starve every later stock chunk's lease.
            if miner.win_nonces == 0 \
                    or now - miner.win_t0 > 4 * self.RATE_WINDOW_S:
                # Fresh (or stale — an idle gap must not deflate the
                # sample) window, anchored at this chunk's lease start.
                miner.win_t0 = chunk.assigned_at or now
                miner.win_nonces = 0
            miner.win_nonces += chunk.size
            elapsed = now - miner.win_t0
            if elapsed >= self.RATE_WINDOW_S:
                rate = miner.win_nonces / elapsed
                miner.win_t0, miner.win_nonces = now, 0
                miner.rate_ewma = rate if miner.rate_ewma is None else \
                    alpha * rate + (1 - alpha) * miner.rate_ewma
                self._pool_rate = rate if self._pool_rate is None else \
                    alpha * rate + (1 - alpha) * self._pool_rate
                self.metrics.gauge(
                    "miner_rate_nps",
                    miner=str(miner.conn_id)).set(miner.rate_ewma)
                self.metrics.gauge("pool_rate_nps").set(self._pool_rate)
        else:
            miner.win_t0, miner.win_nonces = 0.0, 0
        miner.blown_streak = 0
        if miner.quarantined:
            miner.quarantined = False
            self._update_pool_gauges()
            logger.info("miner %d answered; quarantine lifted",
                        miner.conn_id)
            self._maybe_dispatch()

    def _lease_for(self, miner: MinerState, chunk: Chunk) -> float:
        """Lease duration for assigning ``chunk`` to ``miner``: headroom
        over the EWMA-predicted scan time, clamped below; a flat grace when
        nothing has been observed yet (cold pool)."""
        if not self.lease.enabled:
            return float("inf")
        rate = miner.rate_ewma if miner.rate_ewma is not None \
            else self._pool_rate
        if rate is None or rate <= 0:
            return self.lease.grace_s
        return max(self.lease.floor_s, chunk.size / rate * self.lease.factor)

    def _check_queue_age(self) -> None:
        """Age alarms (ROADMAP open item + ISSUE 3; per-tenant since
        ISSUE 5): the OLDEST queued request of each TENANT past
        ``lease.queue_alarm_s`` — and any request still IN FLIGHT past the
        same bound — emits a structured warning, once per bound interval
        per request, plus a full trace dump so the stall explains itself
        (a queued request's stall is usually an in-flight request's wedged
        miner, so the oldest in-flight trace is dumped alongside).

        The alarm and its dump carry the tenant's cumulative GRANT SHARE,
        so a starved mouse (near-zero share despite backlog) is
        distinguishable from a busy elephant (large share, long queue by
        its own volume). Observability only: never changes scheduling."""
        bound = self.lease.queue_alarm_s
        if bound <= 0:
            return
        now = time.monotonic()
        curr = self.current
        queue_alarmed = False
        # Oldest queued request per tenant (queue is FIFO: first seen
        # wins). Under the stock FIFO path every tenant still alarms on
        # its own oldest request — the pre-ISSUE-5 behavior alarmed on
        # every over-age request; per-tenant-oldest is strictly the more
        # readable subset (later same-tenant requests are queued behind
        # the alarmed one by definition).
        oldest: dict = {}
        for req in self.queue:
            oldest.setdefault(req.conn_id, req)
        for req in oldest.values():
            age = now - req.queued_at
            if age < bound or now - req.last_alarm < bound:
                continue
            req.last_alarm = now
            queue_alarmed = True
            share = self.qos_plane.grant_share(req.conn_id)
            self._count("queue_alarms")
            logger.warning(
                "tenant %d: oldest request %r [%d, %d] queued for %.1fs "
                "(bound %.1fs): grant_share=%.3f pool=%d eligible=%d "
                "in_flight=%d",
                req.conn_id, req.data, req.lower, req.upper, age, bound,
                share, len(self.miners), len(self._eligible()),
                len(self._inflight))
            req.trace.event("queue_alarm", age_s=round(age, 3),
                            tenant=req.conn_id,
                            grant_share=round(share, 4))
            self._dump_trace("queue-age alarm: stalled request", req.trace)
        inflight_due = [
            r for r in self._inflight.values()
            if now - r.started >= bound
            and now - r.last_inflight_alarm >= bound]
        if queue_alarmed and curr is not None and curr not in inflight_due:
            # An in-flight request is the usual culprit; the oldest one's
            # trace is the same document for every stalled request, so
            # dump it once per sweep — and not at all when the in-flight
            # alarm below dumps the identical document anyway.
            self._dump_trace("queue-age alarm: request in flight "
                             "ahead of the stalled one", curr.trace)
        for req in inflight_due:
            age = now - req.started
            req.last_inflight_alarm = now
            share = self.qos_plane.grant_share(req.conn_id)
            self._count("inflight_alarms")
            logger.warning(
                "request %d (tenant %d) in flight for %.1fs (bound %.1fs): "
                "%d/%d chunks answered, %d granted, grant_share=%.3f",
                req.job_id, req.conn_id, age, bound, sum(req.answered),
                req.num_chunks, req.granted_chunks, share)
            req.trace.event("inflight_alarm", age_s=round(age, 3),
                            tenant=req.conn_id,
                            grant_share=round(share, 4))
            self._dump_trace("in-flight age alarm", req.trace)
        if self._trace_on and (queue_alarmed or inflight_due):
            # Flight-recorder post-mortem (ISSUE 10): the alarm's trace
            # dump explains ONE request; the ring shows what the whole
            # control plane did around the stall. Once per sweep even
            # when both alarm kinds fired — the ring is one document.
            _tracing.flight_dump("queue-age / in-flight alarm")

    def _check_leases(self) -> None:
        """One lease sweep: blow expired leases (quarantining repeat
        offenders) and speculatively re-issue each blown chunk to an
        eligible miner — first Result wins, the loser pops as a duplicate
        (``_on_result``). A blown chunk with no taker stays watched and is
        re-issued on a later sweep once a miner frees up or joins.

        Every in-flight job is swept: the stock FIFO path has at most one,
        but the QoS plane (ISSUE 5) runs several concurrently — a wedged
        miner holding a mouse's chunk must blow even while an elephant's
        chunks are also live."""
        if self._owner is not None:
            self._owner.assert_here()
        if not self._inflight:
            return
        now = time.monotonic()
        # Per-miner MINIMUM remaining lease (a deep budgeted chunk must not
        # mask the head chunk's imminent expiry), set after the sweep.
        per_miner_remaining: dict[int, float] = {}
        for miner in list(self.miners):
            for chunk in list(miner.pending):
                if chunk.cancelled:
                    continue
                curr = self._inflight.get(chunk.job_id)
                if curr is None or curr.answered[chunk.idx]:
                    continue
                if not chunk.lease_blown:
                    if now < chunk.deadline:
                        remaining = chunk.deadline - now
                        prev = per_miner_remaining.get(miner.conn_id)
                        if prev is None or remaining < prev:
                            per_miner_remaining[miner.conn_id] = remaining
                        continue
                    chunk.lease_blown = True
                    self._count("leases_blown")
                    # With the at-assignment clock (fifo_aware=False) a
                    # chunk can blow while entries still sit AHEAD of it —
                    # the miner never even reached it. Counted so the
                    # position-aware fix has before/after evidence. (With
                    # fifo_aware, a pre-head blow means the budgeted
                    # deadline covering the predecessors ALSO ran out —
                    # the whole pipeline is overdue, not spurious.)
                    spurious = (not self.lease.fifo_aware
                                and miner.pending[0] is not chunk)
                    if spurious:
                        self._count("leases_blown_spurious")
                    miner.blown_streak += 1
                    curr.trace.event("lease_blown", miner=miner.conn_id,
                                     idx=chunk.idx,
                                     streak=miner.blown_streak,
                                     spurious=spurious)
                    if self._trace_on:
                        _tracing.flight("lease_blown", job=chunk.job_id,
                                        idx=chunk.idx,
                                        miner=miner.conn_id,
                                        streak=miner.blown_streak)
                    logger.warning(
                        "miner %d blew the lease on job %d chunk %d "
                        "[%d, %d) after %.2fs (streak %d)%s",
                        miner.conn_id, chunk.job_id, chunk.idx,
                        chunk.lower, chunk.upper, now - chunk.assigned_at,
                        miner.blown_streak,
                        " [spurious: miner had not reached this chunk]"
                        if spurious else "")
                    if (miner.blown_streak >= self.lease.quarantine_after
                            and not miner.quarantined):
                        miner.quarantined = True
                        self._count("quarantines")
                        self._update_pool_gauges()
                        curr.trace.event("quarantine",
                                         miner=miner.conn_id)
                        logger.warning(
                            "miner %d quarantined after %d consecutive "
                            "blown leases; no new assignments until it "
                            "answers", miner.conn_id, miner.blown_streak)
                if chunk.reissued:
                    continue
                takeover = next(
                    (m for m in self._eligible() if m is not miner), None)
                if takeover is None:
                    continue   # retry next sweep
                chunk.reissued = True
                self._count("reissues")
                curr.trace.event("reissue", idx=chunk.idx,
                                 from_miner=miner.conn_id,
                                 to_miner=takeover.conn_id)
                if self._trace_on:
                    _tracing.flight("reissue", job=chunk.job_id,
                                    idx=chunk.idx,
                                    from_miner=miner.conn_id,
                                    to_miner=takeover.conn_id)
                logger.warning(
                    "speculatively re-issuing job %d chunk %d [%d, %d) "
                    "from miner %d to miner %d",
                    chunk.job_id, chunk.idx, chunk.lower, chunk.upper,
                    miner.conn_id, takeover.conn_id)
                self._assign_chunk(
                    takeover,
                    Chunk(chunk.job_id, chunk.data, chunk.lower,
                          chunk.upper, target=chunk.target, idx=chunk.idx),
                    kind="reissue")
        # Miners with no live unexpired lease this sweep (blown, answered,
        # or idle) lose their series: a stale positive "remaining" on a
        # blown lease would read as healthy headroom.
        for m in self.miners:
            if m.conn_id not in per_miner_remaining:
                self.metrics.remove("lease_remaining_s",
                                    miner=str(m.conn_id))
        for conn_id, remaining in per_miner_remaining.items():
            self.metrics.gauge("lease_remaining_s",
                               miner=str(conn_id)).set(remaining)
        self._lease_min_remaining.set(
            min(per_miner_remaining.values()) if per_miner_remaining
            else 0.0)

    def _write(self, conn_id: int, msg: Message) -> None:
        try:
            self.server.write(conn_id, msg.to_json())
        except LspError:
            # The drop event for this connection is already in flight; the
            # drop handler will repair the assignment.
            logger.info("write to %d failed; awaiting drop event", conn_id)
