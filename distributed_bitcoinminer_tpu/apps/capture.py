"""Workload capture & replay: measured traffic becomes the test suite.

The observability stack can *see* everything (metrics, spans, flight
rings) and the load harness can *synthesize* storms
(``apps/loadharness.WORKLOADS``), but until this module nothing
converted what the system actually served into a workload it can serve
again. The capture plane closes that gap (ISSUE 15, the second half of
the ROADMAP self-tuning item):

- **Capture** (``DBM_CAPTURE``, default 0 = bit-for-bit stock — with
  the knob off no capture object exists anywhere and every scheduler
  hook is one attribute test, the ``DBM_TRACE`` discipline): the
  scheduler's existing arrival/reply/shed/cancel/re-issue/span hooks
  append one compact JSON line each to a versioned *workload trace*
  (:data:`CAPTURE_VERSION`): per-request arrival stamp (relative to the
  capture epoch), HASHED tenant key (salted SHA-256 — identities stay
  distinct, never recoverable), request geometry (range size, argmin vs
  difficulty mode, pow2 data-size class), shed/retry/cancel events, and
  a periodic pool-composition snapshot (miner count, rate EWMAs, queue
  depth) riding the sweep. The file is DISK-BOUNDED: past
  ``DBM_CAPTURE_LINES`` lines it rotates (current file renamed to
  ``<path>.1``, previous ``.1`` unlinked — at most ~two windows on
  disk, the spool-cache rotation discipline), and each rotated-in file
  restarts with its own header so any window is independently
  loadable.
- **Replay** (:func:`load_capture` / :func:`replay_plan` /
  ``apps/loadharness.run_replay``): a capture re-drives through the
  detnet harness (or ``--procs`` real UDP), preserving the
  inter-arrival process per hashed tenant and the geometry mix, with
  ``DBM_REPLAY_SPEED`` time-warping the arrival clock. The dbmcheck
  ``replayed_storm`` scenario converts a capture (or the checked-in
  fixture) into a deterministic scenario, so interleaving exploration
  runs over *measured* traffic shapes under the full invariant pack.
- **Fidelity** (:func:`capture_baseline` / :func:`fidelity`): every
  replay emits a side-by-side report — admitted/s, shed rate, p50/p99,
  per-phase span medians — against the capture's OWN numbers, with
  stated bounds (:data:`FIDELITY_BOUNDS`); ``within`` is the gate that
  says the replay reproduced the shape (``bench.py detail.replay``,
  the tier-1 replay leg).

Record vocabulary (one JSON object per line; short keys keep a
million-request capture in tens of MB):

- ``{"k": "hdr", "v": 1, "t0": <epoch seconds>, "snap_s": ...}`` —
  every file (including rotated-in ones) starts with this; readers
  REFUSE unknown versions.
- ``{"k": "cfg", ...}`` — scheduler attach: the workload-shape knobs a
  replay should reproduce (queue bound, wholesale threshold).
- ``{"k": "req", "t": ..., "ten": "<hash>", "n": <range size>,
  "mode": "argmin"|"diff", "dc": <pow2 data-size class>}``
- ``{"k": "rep", "t": ..., "ten": ..., "el": <reply latency>}``
  (``"cached": true`` for ResultCache replays)
- ``{"k": "shed", "t": ..., "ten": ..., "why": ...}`` /
  ``{"k": "cancel", "t": ..., "ten": ..., "n": ...}`` /
  ``{"k": "reissue", "t": ...}``
- ``{"k": "span", "t": ..., "queue_s": ..., "force_s": ..., ...}`` —
  miner-side chunk span phases as they fold at the scheduler.
- ``{"k": "pool", "t": ..., "miners": N, "rates": [...],
  "queued": ..., "inflight": ...}`` — periodic composition snapshot.

Knobs (all via utils/_env; catalog in utils/config.py): ``DBM_CAPTURE``
(default 0), ``DBM_CAPTURE_PATH``, ``DBM_CAPTURE_LINES``,
``DBM_CAPTURE_SNAP_S``, ``DBM_REPLAY_SPEED``.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from statistics import median
from typing import Dict, List, Optional

from ..utils import metrics as _metrics
from ..utils._env import float_env as _float_env, int_env as _int_env, \
    str_env as _str_env
from ..utils.trace import SPAN_PHASES

__all__ = ["WorkloadCapture", "Capture", "enabled", "ensure_from_env",
           "close_active", "load_capture", "capture_baseline",
           "replay_plan", "fidelity", "replay_speed",
           "CAPTURE_VERSION", "FIDELITY_BOUNDS"]

#: Capture record-schema version; bumped on any incompatible change.
#: :func:`load_capture` refuses files whose header carries a different
#: version — a replay of a misread geometry would "pass" fidelity on
#: the wrong workload, which is worse than failing loudly.
CAPTURE_VERSION = 1

#: Stated fidelity bounds (the ``within`` gate): a replay on the SAME
#: harness class must land inside these vs the capture's own numbers.
#: Deliberately generous — the gate catches a SHAPE failure (half the
#: arrivals missing, a shed storm that did not reproduce, an
#: order-of-magnitude latency departure), not scheduler jitter on a
#: loaded 2-core box. ``admitted_ratio``/``p99_ratio`` are
#: replay-over-capture ratios (admitted rescaled by the replay speed);
#: ``shed_delta`` is an absolute shed-rate difference.
FIDELITY_BOUNDS = {
    "admitted_ratio": (0.4, 2.5),
    "p99_ratio": (0.2, 5.0),
    "shed_delta": 0.25,
}


def enabled() -> bool:
    """True when the capture plane is on (``DBM_CAPTURE``, default 0).

    Read per call (the ``trace.enabled`` contract) so tests and
    embedded drivers can toggle the knob around constructions. Default
    OFF: capture writes disk per request — an operator opts in per
    incident/soak, and the knob-off matrix leg pins the stock shape.
    """
    return _int_env("DBM_CAPTURE", 0) != 0


def replay_speed() -> float:
    """``DBM_REPLAY_SPEED`` (default 1.0): replay time-warp factor —
    captured inter-arrival gaps are divided by it, so 4.0 re-drives a
    real hour in fifteen minutes. Fidelity p99 comparison is only
    asserted at 1.0 (service latency does not scale with arrivals)."""
    v = _float_env("DBM_REPLAY_SPEED", 1.0)
    return v if v > 0 else 1.0


def _pow2_class(n: int) -> int:
    """pow2 size class of a byte/char count (0 for empty)."""
    return max(0, int(n)).bit_length()


class WorkloadCapture:
    """Appending side of the capture plane (scheduler-resident).

    One instance per capture file; :func:`ensure_from_env` hands every
    scheduler in the process the same instance (the in-process replica
    tier must interleave into ONE trace with one epoch). ``record()``
    cost is a dict → one ``json.dumps`` → one buffered file write under
    a lock; flushes ride the pool snapshot cadence and close().
    """

    def __init__(self, path: Optional[str] = None,
                 max_lines: Optional[int] = None,
                 snap_s: Optional[float] = None):
        self.path = path if path is not None else _str_env(
            "DBM_CAPTURE_PATH", "dbm_capture.jsonl")
        self.max_lines = max_lines if max_lines is not None else max(
            1024, _int_env("DBM_CAPTURE_LINES", 200_000))
        self.snap_s = snap_s if snap_s is not None else _float_env(
            "DBM_CAPTURE_SNAP_S", 5.0)
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        # Tenant keys are salted per capture: identities stay DISTINCT
        # inside one trace (the replay needs the per-tenant arrival
        # process) but unlinkable across captures and unrecoverable
        # from the file.
        self._salt = os.urandom(8).hex()
        self._keys: Dict[object, str] = {}     # conn -> hashed tenant key
        self._cfg: dict = {}     # last attach config, re-emitted on rotation
        self._lines = 0          # lines in the CURRENT file
        self._total = 0          # lines over the capture's lifetime
        self._rotations = 0
        self.closed = False
        self._last_snap = float("-inf")
        reg = _metrics.registry()
        self._rec_counter = reg.counter("capture.records")
        self._rot_counter = reg.counter("capture.rotations")
        self._drop_counter = reg.counter("capture.write_errors")
        # LINE-buffered: every record reaches the OS as it is written,
        # so a SIGTERM'd/killed process loses nothing (atexit does not
        # run on SIGTERM — a live 3-process drive lost every record
        # between the last snapshot flush and the kill). One syscall
        # per record is the spool-cache discipline: peers there consume
        # complete lines for the same reason.
        self._fh = open(self.path, "w", encoding="utf-8", buffering=1)
        self._write_header()
        # Crash artifacts name the active workload (ISSUE 15 satellite):
        # flight-recorder dumps and the atexit metrics snapshot read
        # this slot, so a post-mortem points at the trace that produced
        # it. The bound method is pinned ONCE — clear_capture_info
        # compares by identity, and attribute access would mint a fresh
        # method object every time.
        self._info_fn = self.info
        _metrics.set_capture_info(self._info_fn)

    # ------------------------------------------------------------- writing

    def _write_header(self) -> None:
        self._fh.write(json.dumps(
            {"k": "hdr", "v": CAPTURE_VERSION,
             "t0": round(time.time(), 3), "snap_s": self.snap_s},
            sort_keys=True) + "\n")
        self._lines += 1
        self._total += 1

    def _rotate_locked(self) -> None:
        """Disk bound: rename current → ``.1`` (unlinking the previous
        ``.1``), reopen fresh with its own header — at most ~two
        windows on disk, any window independently loadable. The attach
        config is re-emitted too: a rotated-in window replayed alone
        must keep the workload-shape knobs AND the transport tag (a
        missing transport would mis-gate a real-LSP capture's latency
        fidelity as same-transport — code review)."""
        self._fh.close()
        try:
            os.replace(self.path, self.path + ".1")
        except OSError:
            pass
        self._fh = open(self.path, "w", encoding="utf-8", buffering=1)
        self._lines = 0
        self._rotations += 1
        self._rot_counter.inc()
        self._write_header()
        if self._cfg:
            rec = {"k": "cfg", "t": self._t()}
            rec.update(self._cfg)
            self._fh.write(json.dumps(rec, sort_keys=True) + "\n")
            self._lines += 1
            self._total += 1

    def _w(self, rec: dict) -> None:
        if self.closed:
            return
        line = json.dumps(rec, sort_keys=True)
        with self._lock:
            if self.closed:
                return
            try:
                self._fh.write(line + "\n")
                self._lines += 1
                self._total += 1
                if self._lines >= self.max_lines:
                    self._rotate_locked()
            except (OSError, ValueError):
                # A full disk / closed handle must never take the
                # scheduler down — capture is observability-only.
                self._drop_counter.inc()
                return
        self._rec_counter.inc()

    def _t(self) -> float:
        return round(time.monotonic() - self._t0, 6)

    def info(self) -> dict:
        """``{"path", "lines", "rotations"}`` — what crash artifacts
        embed so they name the workload that produced them."""
        return {"path": self.path, "lines": self._lines,
                "rotations": self._rotations}

    def tenant_key(self, conn_id) -> str:
        """Salted tenant hash, memoized per conn — every request pays
        at least two key lookups (arrival + reply) and the hash is
        constant per connection (code review). The memo is bounded by
        a hard clear, not an LRU: under conn churn the keys stay
        derivable, so dropping the whole map only costs re-hashing."""
        key = self._keys.get(conn_id)
        if key is None:
            if len(self._keys) >= 65536:
                self._keys.clear()
            key = self._keys[conn_id] = hashlib.sha256(
                f"{self._salt}:{conn_id}".encode()).hexdigest()[:10]
        return key

    # ------------------------------------------------------------ the hooks

    def config(self, **kw) -> None:
        """Scheduler attach: workload-shape knobs a replay reproduces
        (kept for re-emission into every rotated-in window)."""
        self._cfg.update(kw)
        rec = {"k": "cfg", "t": self._t()}
        rec.update(kw)
        self._w(rec)

    def request(self, conn_id, data_len: int, nonces: int,
                difficulty: bool) -> None:
        self._w({"k": "req", "t": self._t(),
                 "ten": self.tenant_key(conn_id), "n": int(nonces),
                 "mode": "diff" if difficulty else "argmin",
                 "dc": _pow2_class(data_len)})

    def reply(self, conn_id, elapsed_s: float,
              cached: bool = False) -> None:
        rec = {"k": "rep", "t": self._t(),
               "ten": self.tenant_key(conn_id),
               "el": round(elapsed_s, 6)}
        if cached:
            rec["cached"] = True
        self._w(rec)

    def shed(self, conn_id, reason: str) -> None:
        self._w({"k": "shed", "t": self._t(),
                 "ten": self.tenant_key(conn_id), "why": reason})

    def cancel(self, conn_id, n: int = 1) -> None:
        self._w({"k": "cancel", "t": self._t(),
                 "ten": self.tenant_key(conn_id), "n": int(n)})

    def reissue(self) -> None:
        self._w({"k": "reissue", "t": self._t()})

    def span(self, span: dict) -> None:
        """One miner-side chunk span as it folds at the scheduler —
        only the fixed phase vocabulary survives (same whitelist rule
        as the trace fold: a hostile peer cannot inject keys)."""
        rec = {"k": "span", "t": self._t()}
        for key in SPAN_PHASES:
            v = span.get(key)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                rec[key] = round(float(v), 6)
        if len(rec) > 2:
            self._w(rec)

    def maybe_snapshot(self, miners: int, rates: List[float],
                       queued: int, inflight: int) -> None:
        """Pool-composition snapshot, at most once per ``snap_s``
        (rides the scheduler sweep). Doubles as the flush cadence."""
        now = time.monotonic()
        if now - self._last_snap < self.snap_s:
            return
        self._last_snap = now
        self._w({"k": "pool", "t": self._t(), "miners": int(miners),
                 "rates": [round(float(r), 1) for r in rates],
                 "queued": int(queued), "inflight": int(inflight)})
        self.flush()

    # ----------------------------------------------------------- lifecycle

    def flush(self) -> None:
        with self._lock:
            if not self.closed:
                try:
                    self._fh.flush()
                except (OSError, ValueError):
                    self._drop_counter.inc()

    def close(self) -> None:
        with self._lock:
            if self.closed:
                return
            self.closed = True
            try:
                self._fh.close()
            except OSError:
                pass
        _metrics.clear_capture_info(self._info_fn)


_active: Optional[WorkloadCapture] = None
_active_lock = threading.Lock()
_atexit_registered = False


def ensure_from_env() -> Optional[WorkloadCapture]:
    """The process capture, or None when ``DBM_CAPTURE=0`` (default).

    The ensure_tracer/ensure_sanitizer shape: every scheduler calls
    this at construction; with the knob off it returns None and NO
    capture state exists anywhere (the parity contract). With it on,
    every scheduler in the process shares ONE capture (the in-process
    replica tier interleaves into one trace with one epoch), closed —
    flushed — at interpreter exit like the metrics emitter's final
    dump."""
    if not enabled():
        return None
    global _active, _atexit_registered
    with _active_lock:
        if _active is None or _active.closed:
            _active = WorkloadCapture()
            if not _atexit_registered:
                import atexit
                atexit.register(close_active)
                _atexit_registered = True
        return _active


def close_active() -> None:
    """Flush + close the process capture (tests, CLI teardown)."""
    global _active
    with _active_lock:
        cap, _active = _active, None
    if cap is not None:
        cap.close()


# ------------------------------------------------------------------ reading


class Capture:
    """Parsed view of one capture file (the replay side's input)."""

    def __init__(self, header: dict):
        self.header = header
        self.cfg: dict = {}
        self.reqs: List[dict] = []
        self.reps: List[dict] = []
        self.sheds: List[dict] = []
        self.cancels: List[dict] = []
        self.reissues: int = 0
        self.spans: List[dict] = []
        self.pools: List[dict] = []

    def pool_rates(self) -> List[float]:
        """Per-miner rate EWMAs from the LAST pool snapshot (newest
        composition wins — that is the pool a replay should model)."""
        for rec in reversed(self.pools):
            rates = [float(r) for r in rec.get("rates", ())
                     if isinstance(r, (int, float)) and r > 0]
            if rates:
                return rates
        return []


def load_capture(path: str) -> Capture:
    """Parse one capture file; raises ``ValueError`` on a missing or
    unknown-version header. A torn tail line (crash mid-write) is
    skipped like the spool cache's ingest skips incomplete lines; a
    rotated capture's ``.1`` window is NOT read implicitly — each file
    is self-contained."""
    cap: Optional[Capture] = None
    with open(path, encoding="utf-8") as fh:
        for raw in fh:
            raw = raw.strip()
            if not raw:
                continue
            try:
                rec = json.loads(raw)
            except ValueError:
                continue       # torn tail / foreign line
            if not isinstance(rec, dict):
                continue
            kind = rec.get("k")
            if cap is None:
                if kind != "hdr":
                    raise ValueError(
                        f"{path}: not a workload capture (first record "
                        f"is {kind!r}, expected a 'hdr' header)")
                if rec.get("v") != CAPTURE_VERSION:
                    raise ValueError(
                        f"{path}: unsupported capture version "
                        f"{rec.get('v')!r} (this reader speaks "
                        f"{CAPTURE_VERSION}); refusing to replay a "
                        f"schema it might misread")
                cap = Capture(rec)
                continue
            if kind == "hdr":
                # A rotation boundary inside one file cannot happen
                # (rotation renames); a concatenation of windows is
                # fine as long as versions agree.
                if rec.get("v") != CAPTURE_VERSION:
                    raise ValueError(
                        f"{path}: mixed capture versions "
                        f"({rec.get('v')!r} after {CAPTURE_VERSION})")
            elif kind == "cfg":
                cap.cfg.update({k: v for k, v in rec.items()
                                if k not in ("k", "t")})
            elif kind == "req":
                cap.reqs.append(rec)
            elif kind == "rep":
                cap.reps.append(rec)
            elif kind == "shed":
                cap.sheds.append(rec)
            elif kind == "cancel":
                cap.cancels.append(rec)
            elif kind == "reissue":
                cap.reissues += 1
            elif kind == "span":
                cap.spans.append(rec)
            elif kind == "pool":
                cap.pools.append(rec)
            # Unknown SAME-version record kinds are skipped (forward-
            # compatible additions); unknown versions were refused.
    if cap is None:
        raise ValueError(f"{path}: empty capture (no header)")
    return cap


def capture_baseline(cap: Capture,
                     tenants: Optional[set] = None) -> dict:
    """The capture's OWN numbers — the fidelity report's left column.

    Same shape as a harness leg: requests/completed/shed counts,
    admitted/s over the capture's active window, reply p50/p99, and
    per-phase span medians. ``tenants`` restricts the tenant-keyed
    records to one hashed-key subset — the ``max_tenants``-truncated
    replay must compare against the SAME window's baseline, not the
    full capture's (code review; spans carry no tenant key and always
    feed the phase medians)."""
    if tenants is not None:
        cap = _restrict(cap, tenants)
    # Cached replays (el=0.0 by construction) are excluded from the
    # latency percentiles: the replay harness runs with the result
    # cache OFF and recomputes every request, so folding the capture's
    # cache hits in would deflate the baseline p50/p99 and fail a
    # faithful replay spuriously (code review; the summarize CLI
    # applies the same rule). They still count as completed — the
    # replay answers those arrivals too.
    lats = sorted(float(r.get("el", 0.0)) for r in cap.reps
                  if not r.get("cached"))
    stamps = ([r["t"] for r in cap.reqs]
              + [r["t"] for r in cap.reps] + [r["t"] for r in cap.sheds])
    makespan = (max(stamps) - min(stamps)) if stamps else 0.0
    completed = len(cap.reps)
    total = len(cap.reqs)

    def pct(q: float):
        if not lats:
            return None
        return round(lats[min(len(lats) - 1, int(q * len(lats)))], 6)

    out = {
        "requests": total,
        "completed": completed,
        "shed_requests": len(cap.sheds),
        "shed_rate": round(len(cap.sheds) / total, 4) if total else 0.0,
        "makespan_s": round(makespan, 3),
        "admitted_per_s": round(completed / makespan, 1)
        if makespan > 0 else None,
        "p50_s": pct(0.50),
        "p99_s": pct(0.99),
    }
    phases: Dict[str, list] = {}
    for rec in cap.spans:
        for ph in SPAN_PHASES:
            v = rec.get(ph)
            if isinstance(v, (int, float)):
                phases.setdefault(ph, []).append(float(v))
    trace = {"spans": len(cap.spans)}
    for ph, xs in sorted(phases.items()):
        trace[f"miner_{ph}_p50"] = round(median(xs), 6)
    out["trace"] = trace
    return out


def _restrict(cap: Capture, tenants: set) -> Capture:
    """A view of ``cap`` with tenant-keyed records filtered to
    ``tenants`` (hashed keys); spans/pools/cfg pass through."""
    out = Capture(cap.header)
    out.cfg = cap.cfg
    out.reqs = [r for r in cap.reqs if str(r.get("ten")) in tenants]
    out.reps = [r for r in cap.reps if str(r.get("ten")) in tenants]
    out.sheds = [r for r in cap.sheds if str(r.get("ten")) in tenants]
    out.cancels = [r for r in cap.cancels
                   if str(r.get("ten")) in tenants]
    out.reissues = cap.reissues
    out.spans = cap.spans
    out.pools = cap.pools
    return out


def replay_plan(cap: Capture, max_tenants: Optional[int] = None) -> list:
    """Deterministic tenant/request schedule from a capture.

    ``[{"name", "start", "reqs": [(offset_s, nonces, mode, dc), ...]},
    ...]`` — tenants in first-arrival order (``r0``, ``r1``, ...),
    ``start`` relative to the first captured arrival, per-request
    offsets relative to the tenant's own start. The same capture always
    yields the same plan (the round-trip determinism contract); the
    replay driver owns the speed warp and the transport."""
    by_tenant: Dict[str, List[dict]] = {}
    order: List[str] = []
    for rec in cap.reqs:
        ten = str(rec.get("ten"))
        if ten not in by_tenant:
            by_tenant[ten] = []
            order.append(ten)
        by_tenant[ten].append(rec)
    if max_tenants is not None:
        order = order[:max_tenants]
    t_first = min((r["t"] for r in cap.reqs), default=0.0)
    plan = []
    for i, ten in enumerate(order):
        recs = by_tenant[ten]
        start = recs[0]["t"] - t_first
        plan.append({
            "name": f"r{i}",
            "ten": ten,        # source hashed key (baseline restriction)
            "start": round(start, 6),
            "reqs": [(round(r["t"] - recs[0]["t"], 6),
                      max(1, int(r.get("n", 1))),
                      str(r.get("mode", "argmin")),
                      int(r.get("dc", 3))) for r in recs],
        })
    return plan


def fidelity(base: dict, rep: dict, speed: float = 1.0,
             bounds: Optional[dict] = None) -> dict:
    """Side-by-side fidelity verdict: replay ``rep`` vs capture
    ``base`` (both the harness measurement shape). ``admitted_ratio``
    is rescaled by ``speed`` (a 4x time-warp legitimately admits 4x/s);
    the p99 bound only applies at speed 1.0 (service latency does not
    follow the arrival clock)."""
    bounds = dict(FIDELITY_BOUNDS, **(bounds or {}))
    out: dict = {"speed": speed}
    violations: List[str] = []
    # Truthiness on the REPLAY side would skip the gate exactly when
    # it matters most — a near-dead replay's admitted/s rounds to 0.0
    # (code review); only a missing or zero BASELINE (nothing to
    # divide by) skips a ratio.
    b_adm, r_adm = base.get("admitted_per_s"), rep.get("admitted_per_s")
    if b_adm and r_adm is not None:
        ratio = (r_adm / speed) / b_adm
        out["admitted_ratio"] = round(ratio, 3)
        # A bound of None reports the ratio without gating it — the
        # cross-transport case (detnet capture replayed over --procs
        # real UDP) where service latency legitimately diverges.
        if bounds["admitted_ratio"] is not None:
            lo, hi = bounds["admitted_ratio"]
            if not lo <= ratio <= hi:
                violations.append(
                    f"admitted/s ratio {ratio:.3f} outside [{lo}, {hi}]")
    b_p99, r_p99 = base.get("p99_s"), rep.get("p99_s")
    if b_p99 and r_p99 is not None:
        ratio = r_p99 / b_p99
        out["p99_ratio"] = round(ratio, 3)
        if bounds["p99_ratio"] is not None and speed == 1.0:
            lo, hi = bounds["p99_ratio"]
            if not lo <= ratio <= hi:
                violations.append(
                    f"p99 ratio {ratio:.3f} outside [{lo}, {hi}]")
    b_shed = base.get("shed_rate") or 0.0
    r_shed = rep.get("shed_rate") or 0.0
    delta = abs(r_shed - b_shed)
    out["shed_delta"] = round(delta, 4)
    if bounds["shed_delta"] is not None and delta > bounds["shed_delta"]:
        violations.append(
            f"shed-rate delta {delta:.3f} over {bounds['shed_delta']}")
    if base.get("requests") and rep.get("requests") is not None \
            and rep["requests"] != base["requests"]:
        violations.append(
            f"replay drove {rep['requests']} requests for "
            f"{base['requests']} captured arrivals")
    out["within"] = not violations
    out["violations"] = violations
    return out
