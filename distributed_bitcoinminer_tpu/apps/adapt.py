"""Self-tuning control plane: setpoint controllers for the dispatch
knobs (ISSUE 13, ``DBM_ADAPT``).

Every performance knob in the dispatch plane is a static env var —
``DBM_QOS_CHUNK_S`` / ``DBM_STRIPE_CHUNK_S`` (seconds of work per
chunk), ``DBM_COALESCE_SMALL_S`` (the coalescing-window smallness
bound), ``DBM_QOS_RATE`` (a fixed admission token rate) — yet the spans
and metrics the control plane already collects (ISSUE 10) measure
exactly the quantities those knobs should track. A substrate serving
"millions of users" (PNPCoin's framing, arXiv 2208.12628) cannot ship
hand-tuned constants per deployment; this module closes the loop with
three small, clock-injectable setpoint controllers the scheduler mounts
under one master knob:

- :class:`ChunkSizeController` — drives the QoS grant-chunk seconds
  AND the stripe-chunk seconds (one value: both knobs mean "seconds of
  work per dispatch unit") toward a per-chunk FORCE-LATENCY setpoint
  (``DBM_ADAPT_FORCE_S``), from the per-chunk service time the lease
  plane already stamps and the miner-side ``force_s`` span when one
  rides the Result. AIMD with a hysteresis dead-band: additive increase
  while measured latency sits below the band, multiplicative decrease
  above it — and an unconditional decrease when the observed
  LEASE-MARGIN fraction collapses (chunks finishing just under their
  lease are one stall away from a blow/re-issue storm). Hard
  floors/ceilings bound the value so chunk-size churn can never walk
  into recompile-storm territory (the jit-static lint and the
  CompileObserver police that boundary; the clamps keep the controller
  out of it by construction).
- :class:`CoalesceWindowController` — widens the coalescing-window
  bound (``small_s``) when the SMALL-request arrival rate shows a mouse
  flood deep enough that a wider window would actually stack rows
  (arrivals/s x window >= ~2) while queue wait is non-trivial, and
  COLLAPSES it multiplicatively when the miner-side ``gap_s`` spans
  show pipeline bubbles (idle executor time means batching is starving
  the device, not feeding it).
- :class:`AdmissionController` — congestion-style admission replacing
  the fixed token rate: a scheduler-wide token bucket whose rate is
  AIMD-controlled on the QUEUE-AGE SLOPE — additive increase while the
  oldest queued request's age falls (or the queue is empty), multiplicative
  decrease while it rises — so the shed rate tracks the pool's ACTUAL
  service capacity across replica counts instead of a constant. The
  controller starts OPEN (rate at the ceiling — it never sheds until
  congestion is observed) unless ``DBM_ADAPT_RATE0`` pins a starting
  rate. Per-tenant admission buckets (``DBM_QOS_RATE``), when
  configured, still apply in front for fairness; this bucket is the
  capacity governor behind them.

Every controller observes only ALREADY-COLLECTED signals (lease
stamps, Result spans, queue stamps — no new per-nonce instrumentation),
exposes its value as a gauge (``adapt_chunk_s`` / ``adapt_small_s`` /
``adapt_admit_rate``) plus per-controller adjustment counters and
flight-recorder events, and keeps a bounded value HISTORY the dbmcheck
``adaptive_control`` scenario audits for stability: values clamped to
their floors/ceilings always, and no REPEATED post-transient swing
wider than a bounded peak/trough ratio (:func:`oscillation_ratios`) —
AIMD's sawtooth is bounded by one multiplicative step plus the
dead-band, one wide swing is a congestion episode riding out a load
change, and two is a controller fighting its own measurement.

``DBM_ADAPT=0`` (the default for this PR's soak) is bit-for-bit stock:
the scheduler constructs NO plane and every hook is one ``is None``
test — pinned by the tier-1 knob-off matrix leg and
``tests/test_adapt.py``.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from ..utils import trace as _tracing
from ..utils.config import AdaptParams
from ..utils.metrics import Registry
from .qos import TokenBucket

__all__ = ["AdaptPlane", "AimdValue", "ChunkSizeController",
           "CoalesceWindowController", "AdmissionController",
           "oscillation_ratio", "oscillation_ratios"]

#: Bounded per-controller value history (enough for a whole dbmcheck
#: schedule or a bench leg at 10 Hz; old entries roll off).
HISTORY = 512


def oscillation_ratios(history) -> List[float]:
    """Peak/trough ratios of the POST-TRANSIENT swings of one
    controller's ``[(t, value), ...]`` history.

    The initial monotone run (e.g. the admission controller descending
    from its open ceiling to the observed capacity) is a transient, not
    an oscillation — it is skipped up to and including the FIRST
    direction reversal. After that, every adjacent local-extremum
    pair's ``hi / lo`` ratio is one swing's amplitude (the history's
    final value closes the last swing). For a healthy AIMD loop each
    swing is bounded by ~``(1/mul) * (1 + band)`` — one multiplicative
    step plus the dead-band the capped probe crosses.

    The stability audit (dbmcheck ``adaptive_control``) tolerates ONE
    swing over its amplitude bound per history — a congestion episode
    is exactly that shape (an anchored multiplicative descent, then
    the recovery ramp back toward open, which this function's endpoint
    rule counts as the episode's second half) — and fails on TWO: a
    loop that repeatedly swings wide is fighting its own measurement
    (limit cycle), not riding out one load change.
    """
    values = [v for _t, v in history]
    if len(values) < 3:
        return []
    # Local extrema of the piecewise-monotone value series.
    extrema: List[float] = []
    direction = 0
    for prev, curr in zip(values, values[1:]):
        if curr == prev:
            continue
        d = 1 if curr > prev else -1
        if direction and d != direction:
            extrema.append(prev)
        direction = d
    extrema.append(values[-1])
    if len(extrema) < 3:
        return []           # at most the transient + its end: no swing
    # extrema[0] ends the initial transient; ratios start after it.
    out: List[float] = []
    for a, b in zip(extrema[1:], extrema[2:]):
        hi, lo = max(a, b), max(min(a, b), 1e-12)
        out.append(hi / lo)
    return out


def oscillation_ratio(history) -> float:
    """Worst single post-transient swing amplitude (1.0 when the
    history has no closed swing) — see :func:`oscillation_ratios`."""
    return max(oscillation_ratios(history), default=1.0)


class _Ewma:
    """Tiny fixed-alpha EWMA (the metrics registry's EWMA is
    wall-clock-aware; controllers want a plain sample smoother)."""

    __slots__ = ("alpha", "value")

    def __init__(self, alpha: float = 0.3):
        self.alpha = alpha
        self.value: Optional[float] = None

    def observe(self, x: float) -> float:
        self.value = x if self.value is None else \
            self.alpha * x + (1 - self.alpha) * self.value
        return self.value


class AimdValue:
    """One AIMD-governed value with hard floor/ceiling clamps and a
    bounded ``(t, value)`` history.

    ``increase()`` adds ``max(add, add_frac * value)`` (a bounded
    proportional probe — pure constant-additive would take minutes to
    recover a rate that was halved from 10^4) CAPPED at a 2x growth
    ratio per step: near the floor a constant step is a huge RELATIVE
    move (0.05 -> 0.30 is 6x — the dbmcheck sweep caught exactly that
    as an oscillation-amplitude violation), and the cap is what keeps
    the sawtooth's peak/trough ratio bounded at every value scale.
    ``decrease()`` multiplies by ``mul``. Both clamp and both record
    history only when the value actually moved — the clamps are HARD:
    no sequence of observations can push the value outside
    ``[floor, ceil]``, which is the no-recompile-storm /
    no-starvation safety argument.
    """

    __slots__ = ("value", "floor", "ceil", "add", "add_frac", "mul",
                 "history", "adjustments", "_clock")

    def __init__(self, value: float, floor: float, ceil: float,
                 add: float, mul: float = 0.5, add_frac: float = 0.0,
                 clock: Callable[[], float] = time.monotonic):
        self.floor = floor
        self.ceil = ceil
        self.add = add
        self.add_frac = add_frac
        self.mul = mul
        self._clock = clock
        self.value = min(ceil, max(floor, value))
        self.adjustments = 0
        self.history: deque = deque([(clock(), self.value)],
                                    maxlen=HISTORY)

    def _set(self, v: float) -> bool:
        v = min(self.ceil, max(self.floor, v))
        if v == self.value:
            return False
        self.value = v
        self.adjustments += 1
        self.history.append((self._clock(), v))
        return True

    def increase(self) -> bool:
        step = max(self.add, self.add_frac * self.value)
        return self._set(min(self.value + step, 2.0 * self.value))

    def decrease(self) -> bool:
        return self._set(self.value * self.mul)

    def decrease_floored(self, floor: Optional[float]) -> bool:
        """Multiplicative decrease that never lands below ``floor`` —
        and HOLDS (no change) when the value already sits at or under
        it: a decrease signal at a value the anchor says is sustainable
        is backlog drain, not fresh congestion."""
        if floor is not None and self.value <= floor:
            return False
        v = self.value * self.mul
        if floor is not None:
            v = max(v, floor)
        return self._set(v)


class ChunkSizeController:
    """Drive the chunk/stripe seconds-of-work knob toward a per-chunk
    force-latency setpoint (module docstring, controller 1).

    Per-miner mode (ISSUE 14 satellite, ``DBM_ADAPT_PER_MINER``,
    default off): in a HETEROGENEOUS pool a 100x rate skew means one
    pool-wide seconds-of-work value cannot hit both tiers' setpoints —
    the mesh miner's chunks force in milliseconds while the host tier's
    force in seconds, and the blended EWMA tunes for neither. With
    ``per_miner`` the controller ALSO keys force-latency samples by
    miner conn, and once the pool's rate EWMAs diverge past
    ``PER_MINER_RATIO`` (:meth:`note_rate_ratio` — fed from the miner
    plane's own EWMAs each tick) it forks a per-miner AIMD value
    (seeded from the pool-wide value) per sampled miner and runs the
    identical setpoint/settle logic per miner
    (:meth:`tick_miners`). The per-miner values drive the STRIPE
    planner through ``MinerPlane.chunk_s_overrides``; the pool-wide
    value keeps driving the (miner-agnostic) QoS chunk plan. While the
    pool is NOT diverged the per-miner state only accumulates samples
    — one knob is enough, and forking it would just add noise."""

    #: Rate-EWMA max/min ratio past which per-miner setpoints fork.
    PER_MINER_RATIO = 4.0

    #: Hard clamps on seconds-of-work per chunk. The floor keeps a
    #: mispriced pool from shattering requests into confetti (and the
    #: resulting fresh jit signatures from storming the compile cache);
    #: the ceiling bounds how much work one lease can put at risk.
    FLOOR_S = 0.05
    CEIL_S = 10.0
    #: Additive step per adjustment interval, seconds.
    ADD_S = 0.25
    #: Observed lease-margin fraction below which the controller
    #: decreases REGARDLESS of the latency error: chunks finishing with
    #: <25% of their lease left are one stall away from a blow.
    MARGIN_FLOOR = 0.25

    def __init__(self, value: float, setpoint_s: float, band: float,
                 clock: Callable[[], float] = time.monotonic,
                 per_miner: bool = False):
        self.setpoint_s = setpoint_s
        self.band = band
        self._clock = clock
        self.per_miner = per_miner
        self.aimd = AimdValue(value, self.FLOOR_S, self.CEIL_S,
                              self.ADD_S, clock=clock)
        self._latency = _Ewma()
        self._min_margin: Optional[float] = None
        self._samples = 0
        self._settle = False
        self._miners: Dict[int, dict] = {}
        self._diverged = False
        self._unfork_pending = False

    def observe(self, service_s: Optional[float],
                margin_frac: Optional[float],
                force_s: Optional[float] = None,
                miner: Optional[int] = None) -> None:
        """One answered chunk: miner-side ``force_s`` span when it rode
        the Result, else the scheduler-side service time the lease plane
        stamped; plus the chunk's remaining-lease fraction. ``miner``
        (the answering conn id) keys the per-miner sample stream when
        per-miner mode is on."""
        lat = force_s if force_s is not None else service_s
        if lat is not None and lat >= 0:
            self._latency.observe(lat)
            self._samples += 1
        if margin_frac is not None:
            self._min_margin = margin_frac if self._min_margin is None \
                else min(self._min_margin, margin_frac)
        if self.per_miner and miner is not None:
            st = self._miners.get(miner)
            if st is None:
                st = self._miners[miner] = {
                    "lat": _Ewma(), "n": 0, "margin": None,
                    "aimd": None, "settle": False}
            if lat is not None and lat >= 0:
                st["lat"].observe(lat)
                st["n"] += 1
            if margin_frac is not None:
                st["margin"] = margin_frac if st["margin"] is None \
                    else min(st["margin"], margin_frac)

    def note_rate_ratio(self, ratio: Optional[float]) -> None:
        """Current pool rate-EWMA max/min ratio (None when fewer than
        two measured miners): the divergence gate for per-miner
        forking."""
        if self.per_miner:
            self._diverged = (ratio is not None
                              and ratio > self.PER_MINER_RATIO)

    def forget_miner(self, miner: int) -> None:
        """Retire a dropped miner's sample stream + forked value (conn
        churn must not grow the map without bound)."""
        self._miners.pop(miner, None)

    def unfork_pending(self) -> bool:
        """True ONCE after the pool re-converges with forked values
        live: the caller must clear its per-miner overrides so the
        pool-wide knob governs again (a stale fork would shadow it
        forever — code review)."""
        out = self._unfork_pending
        self._unfork_pending = False
        return out

    def tick_miners(self) -> Dict[int, float]:
        """Per-miner adjustment pass: ``{conn: new_chunk_s}`` for every
        miner whose forked value moved this tick; empty while the pool
        is not diverged (pool-wide value governs alone). Same AIMD +
        hysteresis + margin guard + SETTLE-tick logic as the pool-wide
        :meth:`tick`, per miner. While NOT diverged, each tick DRAINS
        the per-miner sample accumulators (a later fork must decide
        from fresh post-divergence samples, not latency/margin history
        taken under long-gone chunk sizes — the same stale-sample rule
        the pool-wide settle tick enforces) and retires any forked
        values (flagging :meth:`unfork_pending`)."""
        if not (self.per_miner and self._diverged):
            for st in self._miners.values():
                if st["n"] or st["margin"] is not None:
                    st["lat"] = _Ewma()
                    st["n"] = 0
                    st["margin"] = None
                if st["aimd"] is not None:
                    st["aimd"] = None
                    st["settle"] = False
                    self._unfork_pending = True
            return {}
        out: Dict[int, float] = {}
        for conn, st in self._miners.items():
            if not st["n"]:
                continue
            lat = st["lat"].value
            margin = st["margin"]
            st["n"] = 0
            st["margin"] = None
            if st["settle"]:
                st["settle"] = False
                st["lat"] = _Ewma()
                continue
            if st["aimd"] is None:
                # Forked at first divergence, seeded from the pool-wide
                # value so the per-miner walk starts where the pool is.
                st["aimd"] = AimdValue(self.aimd.value, self.FLOOR_S,
                                       self.CEIL_S, self.ADD_S,
                                       clock=self._clock)
            changed = None
            if (margin is not None and margin < self.MARGIN_FLOOR) or \
                    lat > self.setpoint_s * (1 + self.band):
                if st["aimd"].decrease():
                    changed = st["aimd"].value
            elif lat < self.setpoint_s * (1 - self.band):
                if st["aimd"].increase():
                    changed = st["aimd"].value
            if changed is not None:
                st["settle"] = True
                st["lat"] = _Ewma()
                out[conn] = changed
        return out

    def tick(self) -> Optional[float]:
        """One adjustment interval; returns the new value or None.

        After every adjustment the controller takes one SETTLE tick —
        it drains (and discards) the samples still arriving from
        chunks granted at the OLD size, and resets the latency EWMA so
        the next decision measures only post-change chunks. Without
        this, measurement lag turns one honest decrease into a
        multiplicative cascade (stale large-chunk samples keep the
        EWMA above the band for several ticks) followed by the mirror
        overshoot on the way back up — the exact bounded-amplitude
        violation the dbmcheck ``adaptive_control`` sweep caught.
        """
        if not self._samples:
            return None
        lat = self._latency.value
        margin = self._min_margin
        self._samples = 0
        self._min_margin = None
        if self._settle:
            self._settle = False
            self._latency = _Ewma()
            return None
        changed = None
        if (margin is not None and margin < self.MARGIN_FLOOR) or \
                lat > self.setpoint_s * (1 + self.band):
            if self.aimd.decrease():
                changed = self.aimd.value
        elif lat < self.setpoint_s * (1 - self.band):
            if self.aimd.increase():
                changed = self.aimd.value
        if changed is not None:
            self._settle = True
            self._latency = _Ewma()
        return changed


class CoalesceWindowController:
    """Widen/collapse the coalescing-window smallness bound (module
    docstring, controller 2)."""

    FLOOR_S = 0.05
    CEIL_S = 2.0
    ADD_S = 0.05
    #: A wider window only helps when it would actually stack rows:
    #: small arrivals per window >= this many.
    FLOOD_ROWS = 2.0
    #: Queue wait (EWMA) below this is an unloaded system — no widening.
    WAIT_MIN_S = 0.05
    #: Executor bubbles: a gap EWMA above this fraction of the window
    #: means batching is starving the device — collapse.
    GAP_FRAC = 0.5

    def __init__(self, value: float, band: float,
                 clock: Callable[[], float] = time.monotonic):
        self.band = band
        self.aimd = AimdValue(value, self.FLOOR_S, self.CEIL_S,
                              self.ADD_S, clock=clock)
        self._clock = clock
        self._small_arrivals = 0
        self._last_tick = clock()
        self._wait = _Ewma()
        self._gap = _Ewma()
        self._gap_samples = 0

    def observe_arrival(self, small: bool) -> None:
        if small:
            self._small_arrivals += 1

    def observe_wait(self, wait_s: float) -> None:
        if wait_s >= 0:
            self._wait.observe(wait_s)

    def observe_gap(self, gap_s: float) -> None:
        # gap_s is "idle executor time before this chunk", UNBOUNDED:
        # the first chunk after a traffic lull carries the whole lull.
        # A gap larger than any possible window is a lull, not a
        # pipeline bubble — batching cannot have caused it, so it must
        # not feed the collapse signal (one 60s lull would seed the
        # EWMA at 60 and pin the window to its floor).
        if 0 <= gap_s <= self.CEIL_S:
            self._gap.observe(gap_s)
            self._gap_samples += 1

    def tick(self) -> Optional[float]:
        now = self._clock()
        dt = max(1e-9, now - self._last_tick)
        self._last_tick = now
        rate = self._small_arrivals / dt
        self._small_arrivals = 0
        gap_fresh = self._gap_samples > 0
        self._gap_samples = 0
        gap = self._gap.value or 0.0
        changed = None
        if gap_fresh and gap > self.GAP_FRAC * self.aimd.value:
            # Collapse only on FRESH bubble evidence — a stale EWMA
            # with zero new samples this interval is yesterday's
            # traffic, and repeatedly acting on it would walk the
            # window to its floor during exactly the lull before the
            # next flood.
            if self.aimd.decrease():
                changed = self.aimd.value
        elif (rate * self.aimd.value >= self.FLOOD_ROWS
                and (self._wait.value or 0.0) >= self.WAIT_MIN_S):
            if self.aimd.increase():
                changed = self.aimd.value
        if changed is not None:
            self._gap = _Ewma()     # measure the NEW window fresh
        return changed


class AdmissionController:
    """Congestion-style admission on the queue-age slope (module
    docstring, controller 3). Owns the scheduler-wide token bucket."""

    RATE_FLOOR = 1.0
    RATE_CEIL = 1e5
    #: Additive step (requests/s) and bounded proportional term — see
    #: AimdValue docstring for why the probe is not purely constant.
    ADD_RATE = 8.0
    ADD_FRAC = 0.1
    #: Multiplicative decrease: gentler than the 0.5 the latency
    #: controllers use — the feedback here (queue-age jitter) is far
    #: noisier than a latency EWMA, and halving on every wiggle was
    #: measured to park the rate ~25% under capacity (utilization
    #: loss), shedding work an honest controller would have served.
    MUL = 0.7
    #: Age-slope dead zone (seconds of age change per tick), and the
    #: HEALTHY-QUEUE age floor: below it the system is underloaded
    #: whatever the slope says — keep probing up; only a queue already
    #: older than this with a RISING age is congestion. The floor is
    #: also the knee the equilibrium queue age oscillates around, i.e.
    #: the latency the controller trades for full utilization.
    SLOPE_EPS = 0.02
    MIN_AGE_S = 0.3
    #: Bucket burst as seconds of the controlled rate (an arrival burst
    #: shorter than this rides through without shedding).
    BURST_S = 0.25

    #: Service-rate anchors (the capacity signal is the scheduler's own
    #: ``results_sent`` counter — already collected). The MD result is
    #: floored at ``SRV_FLOOR_FRAC x`` the measured service rate: under
    #: sustained overload the HEAD AGE keeps rising through the whole
    #: drain of an old backlog (its entries arrived faster than the
    #: pool serves), and an unanchored MD cascade was measured parking
    #: the rate at ~20% of capacity. The congestion QUEUE BOUND is the
    #: depth at which the backlog itself costs ~``MIN_AGE_S`` of wait
    #: (``srv_rate x MIN_AGE_S``, floored at ``QUEUE_MIN``): beyond it
    #: the OLDEST requests shed through the stock overload path, so a
    #: descent transient's backlog cannot dominate every later
    #: request's latency — this depth-at-capacity trim is exactly how
    #: "shed rate tracks actual service capacity".
    SRV_FLOOR_FRAC = 0.7
    QUEUE_MIN = 8

    def __init__(self, rate0: float,
                 clock: Callable[[], float] = time.monotonic):
        start = rate0 if rate0 > 0 else self.RATE_CEIL
        self.aimd = AimdValue(start, self.RATE_FLOOR, self.RATE_CEIL,
                              self.ADD_RATE, mul=self.MUL,
                              add_frac=self.ADD_FRAC, clock=clock)
        self.bucket = TokenBucket(self.aimd.value,
                                  self._burst(self.aimd.value), clock)
        self._prev_age: Optional[float] = None
        self._srv = _Ewma()
        self._settle = False
        self.shed = 0

    def _burst(self, rate: float) -> float:
        return max(8.0, rate * self.BURST_S)

    def admit(self) -> bool:
        ok = self.bucket.take(1.0)
        if not ok:
            self.shed += 1
        return ok

    def observe_service_rate(self, served_per_s: float) -> None:
        """One tick's measured completion rate (requests/s)."""
        if served_per_s >= 0:
            self._srv.observe(served_per_s)

    def queue_bound(self) -> Optional[int]:
        """Congestion queue-depth bound (class docstring), or None
        before any service rate has been observed."""
        srv = self._srv.value
        if srv is None or srv <= 0:
            return None
        return max(self.QUEUE_MIN, int(srv * self.MIN_AGE_S))

    def tick(self, queue_age_s: float) -> Optional[float]:
        prev, self._prev_age = self._prev_age, queue_age_s
        if prev is None:
            return None
        if self._settle:
            # One settle tick after every adjustment: the queue age
            # needs a tick to respond to the new rate before the slope
            # means anything (same lag rule as the chunk controller).
            self._settle = False
            return None
        slope = queue_age_s - prev
        changed = None
        if queue_age_s < self.MIN_AGE_S or slope < -self.SLOPE_EPS:
            if self.aimd.increase():
                changed = self.aimd.value
        elif slope > self.SLOPE_EPS:
            srv = self._srv.value
            floor = srv * self.SRV_FLOOR_FRAC if srv else None
            if self.aimd.decrease_floored(floor):
                changed = self.aimd.value
        if changed is not None:
            self._settle = True
            self.bucket.set_rate(changed, self._burst(changed))
        return changed


class AdaptPlane:
    """The scheduler-mounted bundle of enabled controllers.

    Constructed only when ``AdaptParams.enabled`` — with the knob off
    the scheduler holds ``None`` and every hook is one attribute test
    (the bit-for-bit stock contract). The ``clock`` is injectable for
    dbmcheck's virtual time and the unit tests' scripted series; the
    initial values are the live param blocks' statics, so an adaptive
    run STARTS at the static configuration and departs from it only on
    evidence.
    """

    def __init__(self, params: AdaptParams, metrics: Registry,
                 clock: Optional[Callable[[], float]] = None,
                 *, chunk_s: float = 1.0, small_s: float = 0.25,
                 trace_on: bool = False):
        clock = clock if clock is not None else time.monotonic
        self.params = params
        self._clock = clock
        self._trace_on = trace_on
        self._last_apply = clock()
        self._served_prev: Optional[int] = None
        # A statically DISABLED plane (chunk_s/small_s <= 0 is the repo
        # 0-disables convention) stays disabled: the controllers tune
        # live knobs, they never re-enable what an operator turned off.
        self.chunk = (ChunkSizeController(
            chunk_s, params.force_s, params.band, clock,
            per_miner=params.per_miner)
            if params.chunk and chunk_s > 0 else None)
        self.window = (CoalesceWindowController(
            small_s, params.band, clock)
            if params.coalesce and small_s > 0 else None)
        self.admission = (AdmissionController(params.rate0, clock)
                          if params.admit else None)
        # Series exist only for MOUNTED controllers: registering a
        # gauge creates it in the snapshot, and a permanent
        # adapt_admit_rate=0.0 for an admission controller that does
        # not exist reads as "admission fully closed" to an operator.
        self._g_chunk = self._g_small = self._g_rate = None
        self._c_adjust: Dict[str, object] = {}
        self._c_shed = None
        if self.chunk is not None:
            self._g_chunk = metrics.gauge("adapt_chunk_s")
            self._c_adjust["chunk"] = metrics.counter(
                "adapt_adjust_chunk")
            self._g_chunk.set(self.chunk.aimd.value)
        if self.window is not None:
            self._g_small = metrics.gauge("adapt_small_s")
            self._c_adjust["window"] = metrics.counter(
                "adapt_adjust_window")
            self._g_small.set(self.window.aimd.value)
        if self.admission is not None:
            self._g_rate = metrics.gauge("adapt_admit_rate")
            self._c_adjust["admit"] = metrics.counter(
                "adapt_adjust_admit")
            self._c_shed = metrics.counter("adapt_admit_shed")
            self._g_rate.set(self.admission.aimd.value)

    # ------------------------------------------------------ observations

    def observe_chunk(self, service_s: Optional[float],
                      margin_frac: Optional[float],
                      span: Optional[dict] = None,
                      sized: bool = True,
                      miner: Optional[int] = None) -> None:
        """One popped chunk: scheduler-side service/margin plus the
        Result's span extension when it carried one (force_s feeds the
        chunk controller, gap_s the window controller). Span values are
        whitelisted numerics exactly like the trace fold.

        ``sized`` marks a chunk whose size was actually DERIVED from
        the controlled seconds-of-work knob (a chunked-mode grant):
        only those feed the sizing loop — a mouse's wholesale split is
        small because the REQUEST is small, and letting its
        milliseconds-scale latency into the EWMA walked the chunk size
        to its ceiling under pure mouse traffic, handing the next
        elephant a transient of maximal chunks (measured in the
        adversarial A/B). Gap spans feed the window controller from
        every pop either way."""
        force_s = gap_s = None
        if isinstance(span, dict):
            v = span.get("force_s")
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                force_s = float(v)
            v = span.get("gap_s")
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                gap_s = float(v)
        if self.chunk is not None and sized:
            self.chunk.observe(service_s, margin_frac, force_s,
                               miner=miner)
        if self.window is not None and gap_s is not None:
            self.window.observe_gap(gap_s)

    def forget_miner(self, miner: int) -> None:
        """Miner dropped: retire its per-miner controller state."""
        if self.chunk is not None:
            self.chunk.forget_miner(miner)

    def observe_arrival(self, small: bool) -> None:
        if self.window is not None:
            self.window.observe_arrival(small)

    def observe_wait(self, wait_s: float) -> None:
        if self.window is not None:
            self.window.observe_wait(wait_s)

    def admit(self) -> bool:
        """Congestion-admission gate at arrival; True when no admission
        controller is mounted."""
        if self.admission is None:
            return True
        ok = self.admission.admit()
        if not ok:
            self._c_shed.inc()
        return ok

    def effective_max_queued(self, static_bound: int) -> int:
        """The tighter of the static overload bound and the admission
        controller's congestion depth (capacity x age knee): what the
        scheduler's oldest-first overload shed trims to. The static
        bound's 0-means-unbounded convention is preserved when no
        congestion bound exists yet."""
        if self.admission is None:
            return static_bound
        bound = self.admission.queue_bound()
        if bound is None:
            return static_bound
        return min(static_bound, bound) if static_bound > 0 else bound

    # ------------------------------------------------------------- ticks

    def tick(self, queue_age_s: float,
             served_total: Optional[int] = None,
             rate_ratio: Optional[float] = None):
        """One sweep tick: rate-limited to ``params.tick_s``; returns
        the changed knob values for the scheduler to apply (empty dict
        = nothing moved). ``served_total`` is the scheduler's
        cumulative ``results_sent`` counter — the plane differentiates
        it into the service-rate anchor the admission controller
        floors itself on. ``rate_ratio`` is the pool's rate-EWMA
        max/min ratio (None below two measured miners) — the per-miner
        chunk controller's divergence gate; per-miner changes come
        back under the ``chunk_s_miner`` key as ``{conn: value}``."""
        now = self._clock()
        if now - self._last_apply < self.params.tick_s:
            return {}
        dt = max(1e-9, now - self._last_apply)
        self._last_apply = now
        if served_total is not None and self.admission is not None:
            if self._served_prev is not None:
                self.admission.observe_service_rate(
                    (served_total - self._served_prev) / dt)
            self._served_prev = served_total
        out: Dict[str, object] = {}
        if self.chunk is not None:
            self.chunk.note_rate_ratio(rate_ratio)
            v = self.chunk.tick()
            if v is not None:
                out["chunk_s"] = v
                self._g_chunk.set(v)
                self._c_adjust["chunk"].inc()
            per = self.chunk.tick_miners()
            if per:
                out["chunk_s_miner"] = per
                self._c_adjust["chunk"].inc(len(per))
            if self.chunk.unfork_pending():
                out["chunk_s_miner_clear"] = True
        if self.window is not None:
            v = self.window.tick()
            if v is not None:
                out["small_s"] = v
                self._g_small.set(v)
                self._c_adjust["window"].inc()
        if self.admission is not None:
            v = self.admission.tick(queue_age_s)
            if v is not None:
                self._g_rate.set(v)
                self._c_adjust["admit"].inc()
                out["admit_rate"] = v   # informational: applied in-plane
        if out and self._trace_on:
            _tracing.flight("adapt", **{
                k: (round(v, 6) if isinstance(v, float)
                    else {m: round(x, 6) for m, x in v.items()}
                    if isinstance(v, dict) else v)
                for k, v in out.items()})
        return out

    # ----------------------------------------------------------- queries

    def histories(self) -> Dict[str, Tuple[float, float, list]]:
        """``{controller: (floor, ceil, [(t, value), ...])}`` — the
        dbmcheck stability audit's view."""
        out: Dict[str, Tuple[float, float, list]] = {}
        for name, ctl in (("chunk", self.chunk), ("window", self.window),
                          ("admit", self.admission)):
            if ctl is not None:
                a = ctl.aimd
                out[name] = (a.floor, a.ceil, list(a.history))
        return out

    def state(self) -> dict:
        """Current values + adjustment counts (bench/harness echo)."""
        out: dict = {}
        if self.chunk is not None:
            out["chunk_s"] = round(self.chunk.aimd.value, 6)
            out["chunk_adjustments"] = self.chunk.aimd.adjustments
        if self.window is not None:
            out["small_s"] = round(self.window.aimd.value, 6)
            out["window_adjustments"] = self.window.aimd.adjustments
        if self.admission is not None:
            out["admit_rate"] = round(self.admission.aimd.value, 3)
            out["admit_adjustments"] = self.admission.aimd.adjustments
            out["admit_shed"] = self.admission.shed
        return out
