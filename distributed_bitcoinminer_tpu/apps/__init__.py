"""The three-role distributed application: scheduler, miner worker, client.

TPU-first split of the reference Part B (ref: bitcoin/server, bitcoin/miner,
bitcoin/client): the scheduler and wire protocol are host-side asyncio actors
speaking byte-compatible LSP; the miner's hot loop is the mesh-sharded JAX
search program from ``models``/``parallel``. Scheduling semantics (FIFO queue,
one request in flight, even split with remainder-to-first, argmin merge,
miner-drop reassignment, client-drop cancellation) match the reference
exactly — including its inclusive/exclusive bound quirk, see ``scheduler.py``.
"""

from .client import printable_result, submit
from .miner import MinerWorker
from .scheduler import Scheduler

__all__ = ["Scheduler", "MinerWorker", "submit", "printable_result"]
