"""The TPU miner worker: an LSP client wrapped around the device search.

Replaces the reference worker's scalar hot loop (ref: bitcoin/miner/miner.go)
with the chunk-scheduled JAX program from ``models``: Join, then serve
Requests, exiting silently on transport errors exactly like the reference
(miner.go:40-44, 63-66).

Two serving shapes (``DBM_PIPELINE``, default on):

- **Pipelined** (ISSUE 4): a reader task lands incoming Requests in a
  bounded local queue while a compute executor overlaps chunk k+1's device
  DISPATCH with chunk k's result force + JSON serialize + LSP write — the
  dispatch/finalize split the model layer already exposes (the identical
  dispatch measured 420M nonces/s on chip where finalize-blocking ran
  229M), fed by the scheduler's request striping (``DBM_STRIPE``) which
  keeps the FIFO deep enough to overlap. Results are written strictly in
  request order, so the scheduler's in-order FIFO pop contract — and
  therefore every merge rule — is untouched. Difficulty-target chunks and
  searchers without the dispatch/finalize split degrade to the blocking
  shape per chunk, still in order.
- **Serial** (``DBM_PIPELINE=0``): the stock read -> blocking search ->
  write loop, preserved verbatim for Go-parity conformance and replay.

Cross-request batched dispatch (ISSUE 9, ``DBM_COALESCE``, default on,
pipelined shape only): a 2^14 "mouse" chunk pays a full device dispatch
+ force + serialize round-trip for ~1ms of compute, so at
millions-of-users mice traffic the miner drowns in launch overhead, not
hashing. The pipelined executor therefore COALESCES: after pulling a
chunk from its local queue, it opportunistically drains further
compatible small chunks (argmin mode, size <= ``DBM_COALESCE_MAX``, up
to ``DBM_COALESCE_LANES``) — possibly from different requests/tenants;
the scheduler's QoS grant hint deliberately stacks such chunks on one
miner — and dispatches them as ONE batched device launch
(models.NonceSearcher.dispatch_batch: per-row plans, a per-request
segment-min on device), then scatters the per-request Results out of a
single force, still written strictly in request order, so the
scheduler's FIFO pop contract and every merge rule are untouched.
``DBM_COALESCE=0`` never drains: each chunk takes the stock
one-chunk-one-dispatch path bit-for-bit (the tier-1 matrix leg pins
it). Batches the searcher cannot serve (no batch API, gated pallas
tier, mixed incompatible searchers) degrade to the per-chunk path, in
order.

Either way the compute runs in worker threads so the asyncio loop keeps
serving LSP heartbeats/acks while the device is busy; JAX dispatch is
thread-safe.

Bound parity: the received ``Upper`` is treated as INCLUSIVE even though the
scheduler computed it as an exclusive end — the reference miner does the same
(miner.go:51-52), so each chunk scans one extra nonce.
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import OrderedDict
from typing import Callable, Optional

from ..bitcoin.hash import MAX_U64
from ..bitcoin.message import Message, MsgType, new_join, new_result
from ..lsp.client import AsyncClient, new_async_client
from ..lsp.errors import LspError
from ..lsp.params import Params
from ..utils import sanitize as _sanitize
from ..utils import trace as _trace
from ..utils._env import int_env as _int_env
from ..utils.metrics import (OCCUPANCY_BUCKETS, ensure_emitter,
                             registry as _registry)

logger = logging.getLogger("dbm.miner")

# Process-wide miner compute metrics (utils/metrics.py): per-chunk compute
# latency, scanned-nonce totals, and a nonces/s EWMA — the miner-side
# ground truth the scheduler's lease EWMA estimates from the outside.
_M = _registry()
_MET_CHUNK_S = _M.histogram("miner.chunk_seconds")
_MET_NONCES = _M.counter("miner.nonces_scanned")
_MET_CHUNKS = _M.counter("miner.chunks_served")
_MET_RATE = _M.ewma("miner.nonces_per_s", tau_s=30.0)
_MET_FAILURES = _M.counter("miner.search_failures")
# Dispatch-pipeline plane (ISSUE 4): local queue depth at executor pickup,
# busy-time fraction of the worker's life, and the overlap ratio (what
# fraction of summed chunk time was hidden under another chunk).
_MET_QDEPTH = _M.histogram("miner.dispatch_queue_depth", OCCUPANCY_BUCKETS)
_MET_OCCUPANCY = _M.gauge("miner.pipeline_occupancy")
_MET_OVERLAP = _M.gauge("miner.pipeline_overlap_ratio")
_MET_TWO_PHASE = _M.counter("miner.chunks_two_phase")
# Batched-dispatch plane (ISSUE 9): coalesced dispatches, the chunks
# that rode them, and the width distribution (chunks per shared launch).
_MET_COAL_DISPATCHES = _M.counter("miner.coalesced_dispatches")
_MET_COAL_CHUNKS = _M.counter("miner.chunks_coalesced")
_MET_COAL_WIDTH = _M.histogram("miner.coalesce_width", OCCUPANCY_BUCKETS)
# Tracing plane (ISSUE 10): the compile observer's fresh-signature
# counter, read around each dispatch so a span can report how many jit
# compiles it paid (same registry series utils/trace.py increments).
_MET_JITC = _M.counter("trace.jit_compiles")


class _ThroughputWindow:
    """Windowed wall-clock nonces/s accounting, overlap-safe (ISSUE 4
    satellite).

    The old per-chunk ``scanned / elapsed`` EWMA double-counted wall clock
    under the dispatch pipeline: chunk k+1's elapsed window overlaps chunk
    k's, so per-chunk rates summed to more throughput than the wall clock
    delivered — and the scheduler's lease EWMA (fed indirectly by result
    pacing) would have sized leases off an inflated figure. This
    accumulator instead UNIONS the chunk intervals ``[t0, t1]``
    (completions arrive in FIFO order with nondecreasing t0, so the union
    is a single frontier sweep) and observes ``nonces / busy_union`` once
    at least ``min_window_s`` of busy time has accumulated. Serial
    execution degenerates to the old numbers (union == sum); overlapped
    execution reports true wall-clock throughput. Difficulty chunks are
    excluded exactly as before: their in-kernel early exit makes
    ``scanned`` an upper bound.
    """

    def __init__(self, ewma=_MET_RATE, min_window_s: float = 0.5):
        self._ewma = ewma
        self._min_window_s = min_window_s
        self._born: Optional[float] = None   # first chunk's t0
        self._frontier = 0.0                 # union sweep frontier
        self._busy_s = 0.0                   # lifetime union of intervals
        self._sum_s = 0.0                    # lifetime sum of durations
        self._win_busy = 0.0
        self._win_nonces = 0

    def observe(self, t0: float, t1: float, scanned: int) -> None:
        if self._born is None:
            self._born = t0
            self._frontier = t0
        busy = max(0.0, t1 - max(t0, self._frontier))
        self._frontier = max(self._frontier, t1)
        self._busy_s += busy
        self._sum_s += max(0.0, t1 - t0)
        if self._sum_s > 0.0:
            _MET_OVERLAP.set(1.0 - self._busy_s / self._sum_s)
        _MET_OCCUPANCY.set(
            self._busy_s / max(time.monotonic() - self._born, 1e-9))
        self._win_busy += busy
        self._win_nonces += scanned
        if self._win_busy >= self._min_window_s:
            self._ewma.observe(self._win_nonces / self._win_busy)
            self._win_busy, self._win_nonces = 0.0, 0


class HostSearcher:
    """Device-free fallback: the native C++ scan (SHA-NI where the CPU has
    it, all cores for large ranges), or the pure-Python oracle when no
    toolchain is present. ``threads``: 0 = auto, 1 = single-threaded,
    N = pinned worker count.

    Exposes the same two-phase ``dispatch``/``finalize`` shape as the
    device searchers (ISSUE 4): ``dispatch`` starts the native scan on a
    dedicated worker thread and returns immediately, ``finalize`` joins
    it — so the host compute tier pipelines through the miner executor
    exactly like the device tiers (the scan of chunk k+1 overlaps chunk
    k's serialize + LSP write; the native scan manages its own core
    fan-out, so one extra in-flight scan only deepens the OS scheduler's
    queue, it does not over-subscribe a pinned ``threads`` count).
    """

    def __init__(self, data: str, threads: int = 0):
        self.data = data
        self.threads = threads
        self._pool = None

    def _executor(self):
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor
            # 2 workers: one scan finishing while the next starts — the
            # same double-buffer depth as the device-tier pipeline.
            self._pool = ThreadPoolExecutor(max_workers=2,
                                            thread_name_prefix="host-scan")
        return self._pool

    def search(self, lower: int, upper: int):
        from .. import native
        return native.scan_min_native(self.data, lower, upper,
                                      threads=self.threads)

    def search_until(self, lower: int, upper: int, target: int):
        from .. import native
        return native.scan_until_native(self.data, lower, upper, target,
                                        threads=self.threads)

    def dispatch(self, lower: int, upper: int):
        """Start the scan without blocking; returns a handle for
        :meth:`finalize` (same contract as NonceSearcher.dispatch)."""
        if lower > upper:
            raise ValueError("empty range")
        return self._executor().submit(self.search, lower, upper)

    def finalize(self, handle, lower: int):
        """Join a dispatched scan -> exact (min_hash, argmin_nonce)."""
        return handle.result()

    def dispatch_batch(self, entries: list):
        """Batched-dispatch contract (same as
        ``NonceSearcher.dispatch_batch``): start every job's scan on its
        searcher's own worker pool. The host tier has no per-launch
        device overhead to amortize, but serving the API keeps the
        miner's coalescer uniform — a coalesced batch pipelines through
        one finalize instead of degrading to N blocking chunks."""
        if not all(isinstance(s, HostSearcher) for s, _lo, _up in entries):
            return None
        return [s.dispatch(lower, upper) for s, lower, upper in entries]

    def finalize_batch(self, handle) -> list:
        """Join a batched dispatch -> one (hash, nonce) pair per entry."""
        return [f.result() for f in handle]


def default_searcher_factory(data: str, batch: Optional[int] = None,
                             tier: Optional[str] = None):
    """Pick the widest available compute plane for ``data``.

    Multi-device -> the ISSUE 14 mesh plane (carry-chained whole-mesh
    spans, one host pair per span; ``DBM_MESH=0`` restores the round-3
    sharded model — per-sub partials, stock local-device sharding
    byte-for-byte); single device -> plain chunked scan;
    ``DBM_COMPUTE=host`` -> pure-host scan (no JAX), for boxes without
    accelerators and for process-level tests. ``tier`` pins the device
    kernel (jnp | pallas); None reads the environment default.
    """
    from ..utils._env import str_env

    if str_env("DBM_COMPUTE", "").lower() == "host":
        return HostSearcher(data)

    import jax

    from ..models import (MeshNonceSearcher, NonceSearcher,
                          ShardedNonceSearcher)
    from ..parallel import make_mesh
    from ..utils.config import apply_jax_platform_env, jax_devices_robust

    apply_jax_platform_env()
    devices = jax_devices_robust()
    if batch is None:
        batch = (1 << 20) if devices[0].platform != "cpu" else (1 << 12)
    if len(devices) > 1:
        cls = (MeshNonceSearcher if _int_env("DBM_MESH", 1) != 0
               else ShardedNonceSearcher)
        return cls(data, batch=batch, mesh=make_mesh(), tier=tier)
    return NonceSearcher(data, batch=batch, tier=tier)


class MinerWorker:
    """One miner process: joins the scheduler and serves search requests."""

    # Searchers kept per message string; LRU-bounded so a stream of distinct
    # messages can't grow device/midstate caches without bound.
    SEARCHER_CACHE_SIZE = 4

    #: Cross-thread ownership table (dbmlint: thread-state). Attributes
    #: listed here are touched from BOTH the event loop and compute
    #: worker threads by design, with the serialization argument on
    #: record; the analyzer fails any cross-thread attribute that is
    #: neither declared here nor mutated under a lock.
    THREAD_SHARED = {
        "_searchers": "compute-executor-serialized: at most one dispatch "
                      "or blocking-search worker runs at a time (a single "
                      "dtask is in flight, and the degraded path drains "
                      "it before running), so the LRU is never touched "
                      "concurrently even though the touching thread "
                      "changes per chunk.",
    }

    def __init__(self, hostport: str, params: Optional[Params] = None,
                 searcher_factory: Callable = default_searcher_factory,
                 batch: Optional[int] = None,
                 pipeline: Optional[bool] = None,
                 pipeline_depth: Optional[int] = None,
                 coalesce: Optional[bool] = None,
                 coalesce_lanes: Optional[int] = None,
                 coalesce_max: Optional[int] = None,
                 rate_hint: Optional[float] = None):
        self.hostport = hostport
        self.params = params
        self.searcher_factory = searcher_factory
        self.batch = batch
        self._searchers: OrderedDict[str, object] = OrderedDict()
        self.client: Optional[AsyncClient] = None
        self.jobs_done = 0
        # Dispatch pipeline (ISSUE 4): env-defaulted like the scheduler's
        # stripe knob so the tier-1 DBM_PIPELINE=0 matrix leg exercises
        # the stock serial loop through every existing harness.
        self.pipeline = (pipeline if pipeline is not None
                         else _int_env("DBM_PIPELINE", 1) != 0)
        self.pipeline_depth = max(1, pipeline_depth if pipeline_depth
                                  is not None
                                  else _int_env("DBM_PIPELINE_DEPTH", 8))
        # Cross-request batched dispatch (ISSUE 9): env-defaulted like
        # the pipeline so the DBM_COALESCE=0 matrix leg pins the stock
        # one-chunk-one-dispatch path through every existing harness.
        self.coalesce = (coalesce if coalesce is not None
                         else _int_env("DBM_COALESCE", 1) != 0)
        self.coalesce_lanes = max(2, coalesce_lanes
                                  if coalesce_lanes is not None
                                  else _int_env("DBM_COALESCE_LANES", 8))
        self.coalesce_max = (coalesce_max if coalesce_max is not None
                             else _int_env("DBM_COALESCE_MAX", 1 << 20))
        if self.coalesce_max <= 0:
            self.coalesce = False    # repo 0-disables convention
        # Rate-hint JOIN (ISSUE 14): a measured nonces/s figure sent on
        # the Join so the scheduler's per-miner rate EWMA starts warm —
        # a cold 1B-nps mesh must not warm up through mouse-sized
        # chunks. None/0 = no hint (stock Join bytes). _run_miner
        # resolves DBM_RATE_HINT (a number, or "probe" for a measured
        # startup probe) and passes the value here.
        self.rate_hint = max(0.0, rate_hint or 0.0)
        self._window = _ThroughputWindow()
        ensure_emitter()   # DBM_METRICS_INTERVAL_S-driven; 0 = no-op
        # Runtime sanitizer (ISSUE 7): DBM_SANITIZE=1 installs the
        # slow-callback watchdog and arms the off-loop assertions on the
        # compute entry points below.
        self._sanitize = _sanitize.ensure_sanitizer()
        # Tracing plane (ISSUE 10): DBM_TRACE=1 (default) records one
        # span per served chunk — reader-queue wait, dispatch, pipeline
        # wait, force, bubble gap, shared-launch membership — shipped
        # back on the Result's Span extension for the scheduler to
        # stitch; 0 leaves every Result byte-identical to stock and the
        # hooks below are single boolean checks.
        self._trace = _trace.ensure_tracer()
        self._trace_launch = 0        # per-miner shared-launch id seq
        self._trace_last_done = 0.0   # previous chunk's finish stamp

    async def join(self) -> None:
        """Connect and send Join (ref: miner.go:24-34). With a rate
        hint the Join carries the Rate extension; hint-less Joins keep
        reference-identical bytes (wire-compat pin: tests/test_mesh)."""
        self.client = await new_async_client(self.hostport, self.params)
        self.client.write(new_join(rate=int(self.rate_hint)).to_json())

    async def run(self) -> None:
        """Serve Requests until the connection dies (silent exit, like
        ref). ``DBM_PIPELINE`` selects the overlapped executor; 0 the
        stock serial loop."""
        if self.client is None:
            await self.join()
        if self.pipeline:
            await self._run_pipelined()
        else:
            await self._run_serial()

    async def _run_serial(self) -> None:
        """The stock loop: read Request -> blocking search -> write Result
        (Go-parity path, preserved verbatim under ``DBM_PIPELINE=0``)."""
        while True:
            try:
                payload = await self.client.read()
            except LspError:
                return
            try:
                msg = Message.from_json(payload)
            except ValueError:
                continue
            if msg.type != MsgType.REQUEST:
                continue
            if not await self._serve_blocking(msg):
                return

    async def _run_pipelined(self) -> None:
        """Overlapped executor: a reader task lands Requests in a bounded
        queue; this loop dispatches chunk k+1's device work BEFORE forcing
        chunk k's results, then writes Results strictly in request order.

        The overlap window is two concurrent worker threads per loop
        body: the next chunk's dispatch (async device enqueue — or a full
        jit trace+compile on a cold signature) runs as its own task WHILE
        the previous chunk's finalize (force + serialize + LSP write)
        proceeds, so a multi-second compile can never hold an
        already-computed Result hostage past its head-of-FIFO lease
        (chunk sizes drift with the rate EWMA, so fresh signatures happen
        in steady state, not just on new data). The Result write still
        lands before the next chunk enters finalize — strictly in request
        order. Chunks that cannot split into dispatch/finalize —
        difficulty targets (their early-exit pipelining lives inside
        search_until), inverted ranges, searchers without the two-phase
        API — drain the in-flight chunk first and run blocking, which
        keeps every Result in FIFO order.

        Searcher RESOLUTION also happens on the dispatch worker thread,
        never on the event loop: a cache-miss construction triggers JAX
        backend init, which a wedged accelerator tunnel can hang for
        minutes (see utils/config._pin_platform_if_backend_wedged) — on
        the loop that would starve LSP heartbeats and get this miner
        declared dead mid-init (the serial loop has always resolved
        inside ``asyncio.to_thread`` via ``_search`` for the same
        reason).
        """
        queue: asyncio.Queue = asyncio.Queue(
            maxsize=max(1, self.pipeline_depth))
        _STOP = object()
        client = self.client

        async def reader():
            while True:
                try:
                    payload = await client.read()
                except LspError:
                    await queue.put(_STOP)
                    return
                try:
                    msg = Message.from_json(payload)
                except ValueError:
                    continue
                if msg.type != MsgType.REQUEST:
                    continue
                if self._trace:
                    # Span anchor: the reader-queue wait phase starts
                    # here (the stamp rides the Message object — local
                    # bookkeeping, never serialized back out).
                    msg._recv_t = time.monotonic()
                # A full queue backpressures here; the LSP engine keeps
                # acking/heartbeating underneath regardless.
                await queue.put(msg)

        reader_task = asyncio.create_task(reader())
        _IDLE = object()
        inflight = None  # (msg[s], searcher, handle, t0, dispatch_s, span[s])
        carry = None        # drained-but-incompatible msg (or _STOP)
        try:
            while True:
                if carry is not None:
                    msg, carry = carry, None
                elif inflight is None:
                    msg = await queue.get()
                else:
                    try:
                        msg = queue.get_nowait()
                    except asyncio.QueueEmpty:
                        msg = _IDLE
                if msg is _STOP:
                    return   # transport died; nothing can be written
                if msg is not _IDLE:
                    _MET_QDEPTH.observe(queue.qsize())
                # Cross-request coalescing (ISSUE 9): opportunistically
                # drain further compatible small chunks already sitting
                # in the local queue — consecutive FIFO entries, so
                # batching them into one launch and writing their
                # Results in drain order preserves strict request
                # order. Never waits: an empty queue means the batch is
                # whatever arrived, keeping single-chunk latency
                # untouched. DBM_COALESCE=0 skips the drain entirely —
                # the stock one-chunk path below is then bit-for-bit.
                msgs = None
                if self.coalesce and msg is not _IDLE \
                        and self._coalescible(msg):
                    msgs = [msg]
                    while len(msgs) < self.coalesce_lanes:
                        try:
                            nxt = queue.get_nowait()
                        except asyncio.QueueEmpty:
                            break
                        if nxt is _STOP or not self._coalescible(nxt):
                            carry = nxt
                            break
                        msgs.append(nxt)
                    if len(msgs) == 1:
                        msgs = None      # solo: stock path, bit-for-bit
                # Start the new chunk's dispatch on its own worker thread
                # BEFORE draining the previous chunk — this concurrency
                # is the overlap window, and it also means a dispatch
                # stuck in jit trace+compile (fresh signature) cannot
                # delay the in-flight chunk's Result write.
                dtask = t0 = None
                if msgs is not None:
                    t0 = time.monotonic()
                    dtask = asyncio.create_task(asyncio.to_thread(
                        self._resolve_and_dispatch_batch, msgs))
                elif msg is not _IDLE and msg.target == 0 \
                        and msg.lower <= msg.upper:
                    t0 = time.monotonic()
                    dtask = asyncio.create_task(asyncio.to_thread(
                        self._resolve_and_dispatch, msg))
                if inflight is not None:
                    fin = (self._finalize_and_reply_batch
                           if isinstance(inflight[0], list)
                           else self._finalize_and_reply)
                    if not await fin(*inflight):
                        if dtask is not None:
                            # Transport died with a dispatch possibly
                            # mid-compile on its thread: reap it quietly
                            # (the thread itself cannot be interrupted).
                            dtask.cancel()
                            dtask.add_done_callback(
                                lambda t: t.cancelled() or t.exception())
                        return
                    inflight = None
                if msg is _IDLE:
                    continue
                if dtask is not None:
                    try:
                        searcher, handle, dispatch_s, sp = await dtask
                    except Exception:
                        await self._exit_broken(
                            msgs[0] if msgs is not None else msg)
                        return
                    if handle is not None and msgs is not None:
                        inflight = (msgs, searcher, handle, t0,
                                    dispatch_s, sp)
                        _MET_TWO_PHASE.inc(len(msgs))
                    elif handle is not None:
                        inflight = (msg, searcher, handle, t0,
                                    dispatch_s, sp)
                        _MET_TWO_PHASE.inc()
                    elif msgs is not None:
                        # No batch API (or gated tier): degrade to the
                        # stock per-chunk two-phase path, in drain order
                        # (sequential — the rare path loses overlap,
                        # never order or answers).
                        for m in msgs:
                            if not await self._serve_two_phase(m):
                                return
                    elif not await self._serve_blocking(msg):
                        return   # no two-phase API: degraded, in order
                elif not await self._serve_blocking(msg):
                    return
        finally:
            reader_task.cancel()

    def _coalescible(self, msg) -> bool:
        """May this Request share a coalesced launch? Argmin mode only
        (difficulty chunks keep their early-exit pipelining), non-empty
        range, and small enough that batching it cannot meaningfully
        delay its own first result (``DBM_COALESCE_MAX`` nonces)."""
        return (msg.target == 0 and msg.lower <= msg.upper
                and msg.upper - msg.lower + 1 <= self.coalesce_max)

    def _span_open(self, msg) -> Optional[dict]:
        """Span skeleton at dispatch-worker entry: the reader-queue wait
        phase closes here, and the compile-counter base is stamped so
        the span can report fresh-signature compiles it paid. None when
        tracing is off (the entire span path is then dead)."""
        if not self._trace:
            return None
        now = time.monotonic()
        return {"queue_s": round(max(0.0, now - getattr(
            msg, "_recv_t", now)), 6), "_c0": _MET_JITC.value}

    @staticmethod
    def _span_dispatched(span: Optional[dict], dispatch_s: float) -> None:
        """Close the dispatch phase (worker thread, right after the
        device enqueue returned)."""
        if span is None:
            return
        span["dispatch_s"] = round(dispatch_s, 6)
        span["_d_end"] = time.monotonic()
        compiles = _MET_JITC.value - span.pop("_c0", 0)
        if compiles:
            span["compiles"] = compiles

    def _span_close(self, span: Optional[dict], t0: float, t2: float,
                    t3: float) -> Optional[dict]:
        """Finish a span at reply time: pipeline wait (dispatch done →
        force start), force, and the executor bubble gap BEFORE this
        chunk (idle time since the previous chunk's finish — the
        pipeline's lost overlap, visible per chunk instead of only in
        the aggregate occupancy gauge). Internal keys are stripped; the
        returned dict is exactly what rides the wire."""
        if span is None:
            return None
        d_end = span.pop("_d_end", t2)
        span.pop("_c0", None)
        span["wait_s"] = round(max(0.0, t2 - d_end), 6)
        span["force_s"] = round(max(0.0, t3 - t2), 6)
        if self._trace_last_done:
            span["gap_s"] = round(max(0.0, t0 - self._trace_last_done), 6)
        return span

    def _resolve_and_dispatch(self, msg):
        """Worker-thread half of a two-phase chunk: resolve the searcher
        — possibly CONSTRUCTING it, which on first touch runs JAX backend
        init and must therefore never happen on the event loop — and
        start its dispatch. Returns ``(searcher, handle, dispatch_s,
        span)``; ``handle`` is None when the searcher lacks the two-phase
        API (caller degrades to the blocking path, which finds the
        searcher cached). ``dispatch_s`` is the dispatch phase's own
        elapsed time, so the chunk-latency histogram can report busy time
        (dispatch + finalize) rather than wall time — a pipelined chunk's
        wall span includes head-of-line wait behind the previous chunk's
        finalize+write, which would read as a latency regression in
        BENCH artifact diffs whenever the knob toggles. ``span`` is the
        chunk's trace-span skeleton (None with ``DBM_TRACE=0``)."""
        if self._sanitize:
            _sanitize.assert_off_loop("miner searcher resolution/dispatch")
        span = self._span_open(msg)
        t0 = time.monotonic()
        searcher = self._get_searcher(msg.data)
        if hasattr(searcher, "dispatch") and hasattr(searcher, "finalize"):
            handle = searcher.dispatch(msg.lower, msg.upper)
            dispatch_s = time.monotonic() - t0
            self._span_dispatched(span, dispatch_s)
            # Devloop spans (ISSUE 19) collapse the per-sub launch chain
            # into one in-kernel loop; the span carries the loop's sub
            # count so the trace stays honest about work done per launch.
            subs = getattr(searcher, "last_dispatch_subs", None)
            if span is not None and subs is not None:
                span["subs"] = subs
            return searcher, handle, dispatch_s, span
        return searcher, None, 0.0, span

    async def _finalize_and_reply(self, msg, searcher, handle, t0: float,
                                  dispatch_s: float,
                                  span: Optional[dict] = None) -> bool:
        """Force a dispatched chunk's results and write its Result; False
        ends the serve loop (transport death or broken compute)."""
        t2 = time.monotonic()
        try:
            best_hash, best_nonce = await asyncio.to_thread(
                searcher.finalize, handle, msg.lower)
        except Exception:
            await self._exit_broken(msg)
            return False
        t3 = time.monotonic()
        busy_s = dispatch_s + (t3 - t2)
        return self._reply(msg, best_hash, best_nonce, 0, t0,
                           busy_s=busy_s,
                           span=self._span_close(span, t0, t2, t3))

    def _resolve_and_dispatch_batch(self, msgs: list):
        """Worker-thread half of a COALESCED chunk set (ISSUE 9):
        resolve every chunk's searcher (cache-miss construction runs
        JAX backend init — same off-loop rule as the single-chunk path)
        and start ONE batched dispatch through the first searcher's
        ``dispatch_batch``. Returns ``(searcher, handle, dispatch_s,
        spans)``; ``handle`` is None when the searchers cannot serve a
        batch (no batch API, incompatible mix, gated pallas tier) — the
        caller then degrades to per-chunk serving, still in order.
        ``spans`` is one trace-span skeleton per chunk (each with its
        OWN reader-queue wait; dispatch/force phases are the shared
        launch's, stamped batch-wide)."""
        if self._sanitize:
            _sanitize.assert_off_loop("miner batched resolution/dispatch")
        spans = [self._span_open(m) for m in msgs]
        t0 = time.monotonic()
        searchers = [self._get_searcher(m.data) for m in msgs]
        s0 = searchers[0]
        if hasattr(s0, "dispatch_batch") and hasattr(s0, "finalize_batch"):
            handle = s0.dispatch_batch(
                [(s, m.lower, m.upper)
                 for s, m in zip(searchers, msgs)])
            if handle is not None:
                dispatch_s = time.monotonic() - t0
                for span in spans:
                    self._span_dispatched(span, dispatch_s)
                return s0, handle, dispatch_s, spans
        return s0, None, 0.0, spans

    async def _finalize_and_reply_batch(self, msgs: list, searcher,
                                        handle, t0: float,
                                        dispatch_s: float,
                                        spans: Optional[list] = None
                                        ) -> bool:
        """Force a coalesced dispatch with ONE fetch and scatter the
        per-request Results in request order; False ends the serve
        loop."""
        t2 = time.monotonic()
        try:
            results = await asyncio.to_thread(searcher.finalize_batch,
                                              handle)
        except Exception:
            await self._exit_broken(msgs[0])
            return False
        t3 = time.monotonic()
        busy_s = dispatch_s + (t3 - t2)
        if spans is not None:
            spans = [self._span_close(s, t0, t2, t3) for s in spans]
        return self._reply_batch(msgs, results, t0, busy_s, spans=spans)

    def _reply_batch(self, msgs: list, results: list, t0: float,
                     busy_s: float, spans: Optional[list] = None) -> bool:
        """Batch-aware accounting + in-order Result scatter (ISSUE 9
        satellite): busy time is attributed ONCE per shared launch —
        observing the same interval per chunk would hand the
        chunk-latency histogram N copies of the full batch latency, and
        nonces are split per request so the throughput window (and the
        scheduler's windowed rate EWMA downstream of the Result pacing)
        measures real work over real wall clock, not N chunks each
        claiming the whole launch."""
        t1 = time.monotonic()
        _MET_CHUNK_S.observe(max(busy_s, 1e-9))
        _MET_COAL_DISPATCHES.inc()
        _MET_COAL_CHUNKS.inc(len(msgs))
        _MET_COAL_WIDTH.observe(len(msgs))
        total = sum(m.upper - m.lower + 1 for m in msgs
                    if m.upper >= m.lower)
        if total:
            self._window.observe(t0, t1, total)
        launch_id = None
        if spans is not None and any(s is not None for s in spans):
            # One shared-launch id per coalesced dispatch: every lane's
            # span carries it, so the stitched traces of N different
            # requests show the SAME launch — the cross-request batching
            # made visible per request.
            self._trace_launch += 1
            launch_id = self._trace_launch
        for i, (msg, (best_hash, best_nonce)) in enumerate(
                zip(msgs, results)):
            _MET_CHUNKS.inc()
            if msg.upper >= msg.lower:
                _MET_NONCES.inc(msg.upper - msg.lower + 1)
            span = spans[i] if spans is not None else None
            if span is not None:
                span["launch"] = launch_id
                span["lanes"] = len(msgs)
            try:
                self.client.write(
                    new_result(best_hash, best_nonce, 0,
                               span=span).to_json())
            except LspError:
                return False
            self.jobs_done += 1
        if self._trace:
            self._trace_last_done = t1
            _trace.flight("chunk_batch_done", lanes=len(msgs),
                          busy_s=round(busy_s, 6), launch=launch_id)
        return True

    async def _serve_two_phase(self, msg) -> bool:
        """One chunk through the stock single-chunk two-phase machinery
        (resolve+dispatch off-loop, then finalize+reply), degrading to
        the blocking path when the searcher lacks the split. Used by
        the coalescer's no-batch-API degrade path — the chunks were
        already drained from the queue, so they cannot re-enter the
        overlapped main loop; serving them here keeps order and
        per-chunk accounting identical to the stock path."""
        t0 = time.monotonic()
        try:
            searcher, handle, dispatch_s, span = await asyncio.to_thread(
                self._resolve_and_dispatch, msg)
        except Exception:
            await self._exit_broken(msg)
            return False
        if handle is None:
            return await self._serve_blocking(msg)
        _MET_TWO_PHASE.inc()
        return await self._finalize_and_reply(msg, searcher, handle, t0,
                                              dispatch_s, span)

    async def _serve_blocking(self, msg) -> bool:
        """One chunk through the stock blocking search; False ends the
        serve loop. Shared by the serial loop and the pipelined
        executor's degraded (target / no-two-phase-API) path."""
        # Compute off-loop so LSP heartbeats keep flowing mid-search.
        span = self._span_open(msg)
        if span is not None:
            # Blocking chunk: the whole search is one force-like phase
            # (there is no dispatch/finalize split to attribute).
            span["serial"] = 1
        t0 = time.monotonic()
        try:
            best_hash, best_nonce, echo_target = await asyncio.to_thread(
                self._search, msg.data, msg.lower, msg.upper, msg.target)
        except Exception:
            # A broken worker must LEAVE the pool — exit so the
            # scheduler declares the connection lost and reassigns
            # this exact chunk (ref: the Go miner exits silently on
            # any failure, miner.go:44-50; recovery = chunk
            # re-execution, SURVEY §3.4). Round 3 replaced the old
            # answer-with-sentinel behavior here: a fabricated
            # (MAX_U64, 0) Result is indistinguishable from a real
            # empty scan and handed single-miner clients garbage (the
            # e2e caught exactly that when the device backend failed
            # to init in the miner process).
            await self._exit_broken(msg)
            return False
        return self._reply(msg, best_hash, best_nonce, echo_target, t0,
                           span=self._span_close(span, t0, t0,
                                                 time.monotonic()))

    async def _exit_broken(self, msg) -> None:
        """Compute-failure exit path (must be called from an except
        block: it logs the active exception)."""
        _MET_FAILURES.inc()
        logger.exception("search failed for %r [%d, %d]; exiting",
                         msg.data, msg.lower, msg.upper)
        await self.client.close()

    def _reply(self, msg, best_hash: int, best_nonce: int,
               echo_target: int, t0: float,
               busy_s: Optional[float] = None,
               span: Optional[dict] = None) -> bool:
        """Per-chunk accounting + in-order Result write; False on
        transport death. ``busy_s`` (pipelined two-phase chunks) keeps
        the chunk-latency histogram on compute time — dispatch +
        finalize, excluding head-of-line wait — so its semantics match
        the serial path's; the throughput window still gets the wall
        interval ``[t0, t1]`` (its union sweep subtracts overlap
        itself)."""
        t1 = time.monotonic()
        _MET_CHUNK_S.observe(max(busy_s if busy_s is not None
                                 else t1 - t0, 1e-9))
        _MET_CHUNKS.inc()
        if msg.upper >= msg.lower:
            # Upper is read inclusive (reference bound quirk). A
            # difficulty early-exit may scan less than `scanned`, so
            # difficulty chunks are excluded from the throughput window —
            # same caveat as the scheduler-side lease EWMA.
            scanned = msg.upper - msg.lower + 1
            _MET_NONCES.inc(scanned)
            if not msg.target:
                self._window.observe(t0, t1, scanned)
        try:
            self.client.write(
                new_result(best_hash, best_nonce, echo_target,
                           span=span).to_json())
        except LspError:
            return False
        self.jobs_done += 1
        if self._trace:
            self._trace_last_done = t1
        return True

    def _get_searcher(self, data: str):
        """Per-message searcher from the LRU cache (builds on miss)."""
        searcher = self._searchers.get(data)
        if searcher is None:
            searcher = self.searcher_factory(data, self.batch)
            self._searchers[data] = searcher
            while len(self._searchers) > self.SEARCHER_CACHE_SIZE:
                self._searchers.popitem(last=False)
        else:
            self._searchers.move_to_end(data)
        return searcher

    def _search(self, data: str, lower: int, upper: int,
                target: int = 0) -> tuple[int, int, int]:
        """(hash, nonce, echo_target) — echo_target is the request's
        target when the until mode actually ran (the Result then reports
        the chunk-FIRST qualifying nonce), 0 when this miner behaved like
        a stock full scan; the scheduler uses the echo to grade its merge
        guarantee (ADVICE r4)."""
        if self._sanitize:
            _sanitize.assert_off_loop("miner blocking search")
        if lower > upper:
            # The Go miner's loop body never runs for an inverted range and
            # it reports (maxUint, 0) (ref: miner.go:46-59); match that
            # instead of letting the searcher raise.
            return (MAX_U64, 0, 0)
        searcher = self._get_searcher(data)
        if target:
            # Difficulty-target Request (wire extension, message.py): run
            # the early-exiting search. The Result carries the qualifying
            # (hash, nonce) when one exists — the scheduler/client detect
            # success by hash < target — else the exact chunk arg-min.
            # A searcher without the mode (user-supplied factory) degrades
            # to the full scan, exactly like a stock Go miner that dropped
            # the unknown Target key.
            until = getattr(searcher, "search_until", None)
            if until is not None:
                best_hash, best_nonce, _found = until(lower, upper, target)
                return best_hash, best_nonce, target
        return (*searcher.search(lower, upper), 0)

    async def close(self) -> None:
        if self.client is not None:
            await self.client.close()


def _pin_platform_if_backend_wedged(compute: str = "auto") -> bool:
    """Deadlined accelerator probe before the first in-process backend
    touch; pin CPU when it cannot come up.

    A dead or flapping accelerator tunnel HANGS backend init for minutes
    (observed live in round 5: bare miners wedged in axon init while the
    chip endpoint was down, so the pool served nothing — the ambient
    image env pins JAX_PLATFORMS=axon, so inheriting the environment IS
    the hang case). The probe runs in a subprocess with a deadline (the
    bench/chip_e2e mechanism, utils.config.probe_backend); on failure
    this process is pinned to CPU — a slow miner beats a silent hang.
    Skipped for an explicit CPU pin (nothing to probe), the host compute
    tier (the native scan never touches a JAX backend), pod mode
    (platform choice there is the deployment's concern, and an
    asymmetric CPU fallback would desync the pod), or with
    DBM_MINER_PROBE_TIMEOUT_S=0.

    Returns True iff the CPU pin was applied here — i.e. the process
    WOULD have wedged; the caller may then also swap an ``auto`` compute
    config to the faster host tier (see :func:`_cpu_fallback_config`).
    """
    import os

    from ..utils._env import float_env, str_env
    from ..utils.config import probe_backend
    if compute == "host" or str_env("DBM_COORDINATOR") or \
            os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        return False
    timeout_s = float_env("DBM_MINER_PROBE_TIMEOUT_S", 120.0)
    if timeout_s <= 0:
        return False
    probe = probe_backend(timeout_s)
    if "error" in probe:
        logger.warning("accelerator probe failed (%s); pinning this miner "
                       "to CPU", probe["error"])
        os.environ["JAX_PLATFORMS"] = "cpu"
        return True
    return False


def _cpu_fallback_config(cfg):
    """On a CPU-pinned fallback, swap an ``auto`` compute config to the
    native host tier when it exists: "auto" means the widest AVAILABLE
    plane, and with the accelerator unreachable that is the SHA-NI scan
    (~1.5x the jnp CPU tier, BASELINE.md), not XLA:CPU. ``available()``
    may g++-build the scan once (cached .so thereafter) — a cost the
    first chunk would pay anyway, paid here before joining the pool
    instead. Explicit tier pins are respected unchanged."""
    if cfg.compute != "auto":
        return cfg
    from .. import native
    if not native.available():
        return cfg
    import dataclasses
    logger.warning("CPU fallback: serving with the native host compute tier")
    return dataclasses.replace(cfg, compute="host")


def _probe_and_pin(cfg):
    """Blocking startup half of :func:`_run_miner`: the deadlined
    accelerator probe (a subprocess join of up to
    ``DBM_MINER_PROBE_TIMEOUT_S``) and, on a pin, the native-tier
    fallback (which may g++-build the scan once). Runs on a worker
    thread via ``asyncio.to_thread`` — executed inline it held the
    event loop for the probe's whole deadline, so the LSP client
    created right after started life up to 120s behind on its own
    epoch timers (dbmlint: loop-block)."""
    if _pin_platform_if_backend_wedged(cfg.compute):
        return _cpu_fallback_config(cfg)
    return cfg


def measure_rate_hint(searcher, probe_nonces: int = 1 << 17) -> float:
    """Measured startup throughput probe (nonces/s) for the rate-hint
    JOIN: one warm pass (pays compile + midstate build), one timed pass
    over an adjacent same-pow2 window (same jit signature). Both
    windows sit inside one aligned 10^9 block so the geometry matches
    steady-state serving. Returns 0.0 on any failure — no hint beats a
    made-up one."""
    base = 100_000_000
    try:
        searcher.search(base, base + probe_nonces - 1)
        t0 = time.monotonic()
        searcher.search(base + probe_nonces, base + 2 * probe_nonces - 1)
        return probe_nonces / max(time.monotonic() - t0, 1e-6)
    except Exception:
        logger.exception("rate-hint probe failed; joining without a hint")
        return 0.0


def _resolve_rate_hint(factory, batch) -> float:
    """``DBM_RATE_HINT`` semantics: unset/0 = no hint; a number = the
    operator's measured figure (e.g. a chip-chain BENCH artifact);
    ``probe`` = measure here with :func:`measure_rate_hint` (runs on
    the caller's worker thread — searcher construction touches JAX
    backend init, the loop-block class)."""
    from ..utils._env import str_env
    raw = str_env("DBM_RATE_HINT", "0")
    if raw.strip().lower() == "probe":
        return measure_rate_hint(factory("dbm rate probe", batch))
    try:
        return max(0.0, float(raw))
    except ValueError:
        return 0.0


async def _run_miner(hostport: str) -> int:
    from ..utils import from_env
    from ..utils.config import apply_jax_platform_env
    cfg = from_env()
    cfg = await asyncio.to_thread(_probe_and_pin, cfg)

    # Pod mode (north star: a whole multi-host pod joins as ONE miner).
    # DBM_COORDINATOR et al. select it; unset means plain single-host.
    from ..parallel.multihost import (PodSearcher, broadcast_stop,
                                      initialize_multihost, is_lsp_owner,
                                      run_follower)
    apply_jax_platform_env()
    multihost = initialize_multihost()
    if multihost and not is_lsp_owner():
        # Follower hosts never touch LSP: they execute broadcast jobs in
        # lockstep with the owner until it releases them.
        jobs = await asyncio.to_thread(run_follower, cfg.batch)
        logger.info("follower done after %d jobs", jobs)
        return 0

    if multihost:
        factory = lambda data, batch: PodSearcher(data, batch)  # noqa: E731
    else:
        factory = lambda data, batch: cfg.make_searcher(data)   # noqa: E731
    # Rate-hint JOIN (ISSUE 14): resolved off-loop — the "probe" mode
    # constructs a searcher (JAX backend init) and runs two timed spans.
    rate_hint = await asyncio.to_thread(_resolve_rate_hint, factory,
                                        cfg.batch)
    worker = MinerWorker(hostport, params=cfg.params,
                         searcher_factory=factory, batch=cfg.batch,
                         rate_hint=rate_hint)
    try:
        try:
            await worker.join()
        except LspError as exc:
            print("Failed to join with server:", exc)
            return 1
        await worker.run()
        return 0
    finally:
        # Release the followers on EVERY exit path — including a failed
        # join — and even if the LSP teardown raises: a stuck broadcast
        # partner is worse than an unflushed socket (review r3).
        try:
            await worker.close()
        finally:
            if multihost:
                broadcast_stop()


def main(argv=None) -> int:
    """CLI contract of the reference binary (ref: miner.go:70-77):
    ``miner <hostport>``; exits silently when the connection dies."""
    import sys
    argv = sys.argv if argv is None else argv
    if len(argv) != 2:
        print(f"Usage: ./{argv[0]} <hostport>", end="")
        return 1
    return asyncio.run(_run_miner(argv[1]))


if __name__ == "__main__":
    import sys
    sys.exit(main())
