"""The TPU miner worker: an LSP client wrapped around the device search.

Replaces the reference worker's scalar hot loop (ref: bitcoin/miner/miner.go)
with the chunk-scheduled JAX program from ``models``: Join, then loop
{read Request -> device arg-min search -> write Result}, exiting silently on
transport errors exactly like the reference (miner.go:40-44, 63-66).

The device search runs in a worker thread so the asyncio loop keeps serving
LSP heartbeats/acks while the TPU is busy; JAX dispatch is thread-safe.

Bound parity: the received ``Upper`` is treated as INCLUSIVE even though the
scheduler computed it as an exclusive end — the reference miner does the same
(miner.go:51-52), so each chunk scans one extra nonce.
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import OrderedDict
from typing import Callable, Optional

from ..bitcoin.hash import MAX_U64
from ..bitcoin.message import Message, MsgType, new_join, new_result
from ..lsp.client import AsyncClient, new_async_client
from ..lsp.errors import LspError
from ..lsp.params import Params
from ..utils.metrics import ensure_emitter, registry as _registry

logger = logging.getLogger("dbm.miner")

# Process-wide miner compute metrics (utils/metrics.py): per-chunk compute
# latency, scanned-nonce totals, and a nonces/s EWMA — the miner-side
# ground truth the scheduler's lease EWMA estimates from the outside.
_M = _registry()
_MET_CHUNK_S = _M.histogram("miner.chunk_seconds")
_MET_NONCES = _M.counter("miner.nonces_scanned")
_MET_CHUNKS = _M.counter("miner.chunks_served")
_MET_RATE = _M.ewma("miner.nonces_per_s", tau_s=30.0)
_MET_FAILURES = _M.counter("miner.search_failures")


class HostSearcher:
    """Device-free fallback: the native C++ scan (SHA-NI where the CPU has
    it, all cores for large ranges), or the pure-Python oracle when no
    toolchain is present. ``threads``: 0 = auto, 1 = single-threaded,
    N = pinned worker count."""

    def __init__(self, data: str, threads: int = 0):
        self.data = data
        self.threads = threads

    def search(self, lower: int, upper: int):
        from .. import native
        return native.scan_min_native(self.data, lower, upper,
                                      threads=self.threads)

    def search_until(self, lower: int, upper: int, target: int):
        from .. import native
        return native.scan_until_native(self.data, lower, upper, target,
                                        threads=self.threads)


def default_searcher_factory(data: str, batch: Optional[int] = None,
                             tier: Optional[str] = None):
    """Pick the widest available compute plane for ``data``.

    Multi-device -> mesh-sharded search; single device -> plain chunked scan;
    ``DBM_COMPUTE=host`` -> pure-host scan (no JAX), for boxes without
    accelerators and for process-level tests. ``tier`` pins the device
    kernel (jnp | pallas); None reads the environment default.
    """
    import os

    if os.environ.get("DBM_COMPUTE", "").lower() == "host":
        return HostSearcher(data)

    import jax

    from ..models import NonceSearcher, ShardedNonceSearcher
    from ..parallel import make_mesh
    from ..utils.config import apply_jax_platform_env, jax_devices_robust

    apply_jax_platform_env()
    devices = jax_devices_robust()
    if batch is None:
        batch = (1 << 20) if devices[0].platform != "cpu" else (1 << 12)
    if len(devices) > 1:
        return ShardedNonceSearcher(data, batch=batch, mesh=make_mesh(),
                                    tier=tier)
    return NonceSearcher(data, batch=batch, tier=tier)


class MinerWorker:
    """One miner process: joins the scheduler and serves search requests."""

    # Searchers kept per message string; LRU-bounded so a stream of distinct
    # messages can't grow device/midstate caches without bound.
    SEARCHER_CACHE_SIZE = 4

    def __init__(self, hostport: str, params: Optional[Params] = None,
                 searcher_factory: Callable = default_searcher_factory,
                 batch: Optional[int] = None):
        self.hostport = hostport
        self.params = params
        self.searcher_factory = searcher_factory
        self.batch = batch
        self._searchers: OrderedDict[str, object] = OrderedDict()
        self.client: Optional[AsyncClient] = None
        self.jobs_done = 0
        ensure_emitter()   # DBM_METRICS_INTERVAL_S-driven; 0 = no-op

    async def join(self) -> None:
        """Connect and send Join (ref: miner.go:24-34)."""
        self.client = await new_async_client(self.hostport, self.params)
        self.client.write(new_join().to_json())

    async def run(self) -> None:
        """Serve Requests until the connection dies (silent exit, like ref)."""
        if self.client is None:
            await self.join()
        while True:
            try:
                payload = await self.client.read()
            except LspError:
                return
            try:
                msg = Message.from_json(payload)
            except ValueError:
                continue
            if msg.type != MsgType.REQUEST:
                continue
            # Compute off-loop so LSP heartbeats keep flowing mid-search.
            t0 = time.monotonic()
            try:
                best_hash, best_nonce, echo_target = await asyncio.to_thread(
                    self._search, msg.data, msg.lower, msg.upper, msg.target)
            except Exception:
                _MET_FAILURES.inc()
                # A broken worker must LEAVE the pool — exit so the
                # scheduler declares the connection lost and reassigns
                # this exact chunk (ref: the Go miner exits silently on
                # any failure, miner.go:44-50; recovery = chunk
                # re-execution, SURVEY §3.4). Round 3 replaced the old
                # answer-with-sentinel behavior here: a fabricated
                # (MAX_U64, 0) Result is indistinguishable from a real
                # empty scan and handed single-miner clients garbage (the
                # e2e caught exactly that when the device backend failed
                # to init in the miner process).
                logger.exception("search failed for %r [%d, %d]; exiting",
                                 msg.data, msg.lower, msg.upper)
                await self.client.close()
                return
            elapsed = max(time.monotonic() - t0, 1e-9)
            _MET_CHUNK_S.observe(elapsed)
            _MET_CHUNKS.inc()
            if msg.upper >= msg.lower:
                # Upper is read inclusive (reference bound quirk). A
                # difficulty early-exit may scan less than `scanned`, so
                # the EWMA is an upper bound there — same caveat as the
                # scheduler-side lease EWMA, which excludes target chunks.
                scanned = msg.upper - msg.lower + 1
                _MET_NONCES.inc(scanned)
                if not msg.target:
                    _MET_RATE.observe(scanned / elapsed)
            try:
                self.client.write(
                    new_result(best_hash, best_nonce, echo_target).to_json())
            except LspError:
                return
            self.jobs_done += 1

    def _search(self, data: str, lower: int, upper: int,
                target: int = 0) -> tuple[int, int, int]:
        """(hash, nonce, echo_target) — echo_target is the request's
        target when the until mode actually ran (the Result then reports
        the chunk-FIRST qualifying nonce), 0 when this miner behaved like
        a stock full scan; the scheduler uses the echo to grade its merge
        guarantee (ADVICE r4)."""
        if lower > upper:
            # The Go miner's loop body never runs for an inverted range and
            # it reports (maxUint, 0) (ref: miner.go:46-59); match that
            # instead of letting the searcher raise.
            return (MAX_U64, 0, 0)
        searcher = self._searchers.get(data)
        if searcher is None:
            searcher = self.searcher_factory(data, self.batch)
            self._searchers[data] = searcher
            while len(self._searchers) > self.SEARCHER_CACHE_SIZE:
                self._searchers.popitem(last=False)
        else:
            self._searchers.move_to_end(data)
        if target:
            # Difficulty-target Request (wire extension, message.py): run
            # the early-exiting search. The Result carries the qualifying
            # (hash, nonce) when one exists — the scheduler/client detect
            # success by hash < target — else the exact chunk arg-min.
            # A searcher without the mode (user-supplied factory) degrades
            # to the full scan, exactly like a stock Go miner that dropped
            # the unknown Target key.
            until = getattr(searcher, "search_until", None)
            if until is not None:
                best_hash, best_nonce, _found = until(lower, upper, target)
                return best_hash, best_nonce, target
        return (*searcher.search(lower, upper), 0)

    async def close(self) -> None:
        if self.client is not None:
            await self.client.close()


def _pin_platform_if_backend_wedged(compute: str = "auto") -> bool:
    """Deadlined accelerator probe before the first in-process backend
    touch; pin CPU when it cannot come up.

    A dead or flapping accelerator tunnel HANGS backend init for minutes
    (observed live in round 5: bare miners wedged in axon init while the
    chip endpoint was down, so the pool served nothing — the ambient
    image env pins JAX_PLATFORMS=axon, so inheriting the environment IS
    the hang case). The probe runs in a subprocess with a deadline (the
    bench/chip_e2e mechanism, utils.config.probe_backend); on failure
    this process is pinned to CPU — a slow miner beats a silent hang.
    Skipped for an explicit CPU pin (nothing to probe), the host compute
    tier (the native scan never touches a JAX backend), pod mode
    (platform choice there is the deployment's concern, and an
    asymmetric CPU fallback would desync the pod), or with
    DBM_MINER_PROBE_TIMEOUT_S=0.

    Returns True iff the CPU pin was applied here — i.e. the process
    WOULD have wedged; the caller may then also swap an ``auto`` compute
    config to the faster host tier (see :func:`_cpu_fallback_config`).
    """
    import os

    from ..utils.config import probe_backend
    if compute == "host" or os.environ.get("DBM_COORDINATOR") or \
            os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        return False
    timeout_s = float(os.environ.get("DBM_MINER_PROBE_TIMEOUT_S", "120"))
    if timeout_s <= 0:
        return False
    probe = probe_backend(timeout_s)
    if "error" in probe:
        logger.warning("accelerator probe failed (%s); pinning this miner "
                       "to CPU", probe["error"])
        os.environ["JAX_PLATFORMS"] = "cpu"
        return True
    return False


def _cpu_fallback_config(cfg):
    """On a CPU-pinned fallback, swap an ``auto`` compute config to the
    native host tier when it exists: "auto" means the widest AVAILABLE
    plane, and with the accelerator unreachable that is the SHA-NI scan
    (~1.5x the jnp CPU tier, BASELINE.md), not XLA:CPU. ``available()``
    may g++-build the scan once (cached .so thereafter) — a cost the
    first chunk would pay anyway, paid here before joining the pool
    instead. Explicit tier pins are respected unchanged."""
    if cfg.compute != "auto":
        return cfg
    from .. import native
    if not native.available():
        return cfg
    import dataclasses
    logger.warning("CPU fallback: serving with the native host compute tier")
    return dataclasses.replace(cfg, compute="host")


async def _run_miner(hostport: str) -> int:
    from ..utils import from_env
    from ..utils.config import apply_jax_platform_env
    cfg = from_env()
    if _pin_platform_if_backend_wedged(cfg.compute):
        cfg = _cpu_fallback_config(cfg)

    # Pod mode (north star: a whole multi-host pod joins as ONE miner).
    # DBM_COORDINATOR et al. select it; unset means plain single-host.
    from ..parallel.multihost import (PodSearcher, broadcast_stop,
                                      initialize_multihost, is_lsp_owner,
                                      run_follower)
    apply_jax_platform_env()
    multihost = initialize_multihost()
    if multihost and not is_lsp_owner():
        # Follower hosts never touch LSP: they execute broadcast jobs in
        # lockstep with the owner until it releases them.
        jobs = await asyncio.to_thread(run_follower, cfg.batch)
        logger.info("follower done after %d jobs", jobs)
        return 0

    if multihost:
        factory = lambda data, batch: PodSearcher(data, batch)  # noqa: E731
    else:
        factory = lambda data, batch: cfg.make_searcher(data)   # noqa: E731
    worker = MinerWorker(hostport, params=cfg.params,
                         searcher_factory=factory, batch=cfg.batch)
    try:
        try:
            await worker.join()
        except LspError as exc:
            print("Failed to join with server:", exc)
            return 1
        await worker.run()
        return 0
    finally:
        # Release the followers on EVERY exit path — including a failed
        # join — and even if the LSP teardown raises: a stuck broadcast
        # partner is worse than an unflushed socket (review r3).
        try:
            await worker.close()
        finally:
            if multihost:
                broadcast_stop()


def main(argv=None) -> int:
    """CLI contract of the reference binary (ref: miner.go:70-77):
    ``miner <hostport>``; exits silently when the connection dies."""
    import sys
    argv = sys.argv if argv is None else argv
    if len(argv) != 2:
        print(f"Usage: ./{argv[0]} <hostport>", end="")
        return 1
    return asyncio.run(_run_miner(argv[1]))


if __name__ == "__main__":
    import sys
    sys.exit(main())
