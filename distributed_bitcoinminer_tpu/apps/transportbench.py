"""Transport-datapath bench probe (ISSUE 17): ``detail.transport``.

Measures the real UDP datapath — ``AsyncServer``/``AsyncClient`` over
loopback, the exact code production traffic takes — under an echo storm:
every client keeps a fixed number of round-trips in flight, the server
echoes every payload back, and the probe reports application messages
per second BOTH directions plus the syscall economics the batched
datapath (``DBM_MMSG``) and the allocation-free wire codec
(``DBM_WIRE_FAST``) were built to change:

- ``echo_storm.throughput`` — app msgs/s both directions, fast datapath
  (the tier-1 gated number; ``benchdiff`` classifies the literal key
  ``throughput`` higher-better);
- ``echo_storm.speedup`` — fast vs stock (``DBM_MMSG=0
  DBM_WIRE_FAST=0``) medians over interleaved, order-swapped rounds
  (same noise discipline as the pipeline probe: a 2-core bench box
  swings single legs more than the win itself);
- ``syscalls_per_msg`` — from the ``net.syscalls``/``net.datagrams``
  counter deltas across the timed window (stock truthfully reports
  ~1.0 each direction; the mmsg path amortizes);
- ``bytes_per_msg`` — wire bytes per datagram from ``net.bytes``;
- ``p99_ack_rtt_s`` — send→ack latency from the ``lsp.msg_rtt_s``
  histogram (bucket upper-bound estimate, Karn-filtered samples);
- ``conn_memory`` — resident bytes per live ``ConnCore`` pair at
  10k/50k/100k connections (the flattened slotted-struct + ring-window
  state, measured as VmRSS deltas — no sockets involved).

Each leg runs in a SUBPROCESS (``bench.py --transport-child``) so the
``DBM_MMSG``/``DBM_WIRE_FAST`` knobs bind at import/endpoint-creation
time exactly as they do in production, and so the two legs never share
a warmed allocator or event loop.
"""

from __future__ import annotations

import asyncio
import json
import os
import statistics
import subprocess
import sys
import time
from typing import List, Optional, Tuple

from ..lsp.params import Params
from ..utils._env import float_env as _float_env, int_env as _int_env

#: Child geometry knobs (documented in utils/config.py).
_DEF_CONNS = 32
_DEF_INFLIGHT = 8
_DEF_PAYLOAD = 128
_DEF_MEASURE_S = 1.0
_DEF_WARMUP_S = 0.3
_DEF_WINDOW = 64


# --------------------------------------------------------------- child leg

def _counter(snap: dict, key: str) -> float:
    return float(snap.get("counters", {}).get(key, 0))


def _hist_p99(snap: dict, key: str) -> Optional[float]:
    """Bucket upper-bound p99 estimate of a cumulative-``le`` histogram."""
    h = snap.get("histograms", {}).get(key)
    if not h or not h.get("count"):
        return None
    want = 0.99 * h["count"]
    for bound, cum in zip(h["le"], h["counts"]):
        if cum >= want:
            return float(bound)
    return float("inf")


def _net_stats(snap: dict) -> dict:
    return {
        "sys_recv": _counter(snap, "net.syscalls{dir=recv}"),
        "sys_send": _counter(snap, "net.syscalls{dir=send}"),
        "dg_recv": _counter(snap, "net.datagrams{dir=recv}"),
        "dg_send": _counter(snap, "net.datagrams{dir=send}"),
        "bytes_recv": _counter(snap, "net.bytes{dir=recv}"),
        "bytes_send": _counter(snap, "net.bytes{dir=send}"),
    }


async def _echo_storm() -> dict:
    from ..lsp.client import new_async_client
    from ..lsp.errors import ConnectionClosed
    from ..lsp.server import new_async_server
    from ..utils.metrics import registry

    conns = max(1, _int_env("DBM_BENCH_TRANSPORT_CONNS", _DEF_CONNS))
    inflight = max(1, _int_env("DBM_BENCH_TRANSPORT_INFLIGHT",
                               _DEF_INFLIGHT))
    payload = b"n" * max(1, _int_env("DBM_BENCH_TRANSPORT_PAYLOAD",
                                     _DEF_PAYLOAD))
    measure_s = _float_env("DBM_BENCH_TRANSPORT_SECS", _DEF_MEASURE_S)
    warmup_s = _float_env("DBM_BENCH_TRANSPORT_WARMUP_S", _DEF_WARMUP_S)
    params = Params(window_size=_DEF_WINDOW)

    server = await new_async_server(0, params)

    async def echo() -> None:
        # One awaited read per burst, then drain — the scheduler's
        # batched recv idiom; every inbound payload turns around.
        try:
            item: Optional[Tuple[int, object]] = await server.read()
            while True:
                while item is not None:
                    cid, body = item
                    if isinstance(body, (bytes, bytearray)):
                        try:
                            server.write(cid, bytes(body))
                        except ConnectionClosed:
                            pass
                    item = server.read_nowait()
                item = await server.read()
        except (ConnectionClosed, asyncio.CancelledError):
            return

    echo_task = asyncio.get_running_loop().create_task(echo())
    clients = []
    for _ in range(conns):
        clients.append(await new_async_client(f"127.0.0.1:{server.port}",
                                              params))

    completed = [0]

    async def drive(client) -> None:
        try:
            for _ in range(inflight):
                client.write(payload)
            while True:
                await client.read()
                completed[0] += 1
                client.write(payload)
        except (ConnectionClosed, asyncio.CancelledError):
            return

    tasks = [asyncio.get_running_loop().create_task(drive(c))
             for c in clients]

    await asyncio.sleep(warmup_s)
    snap0 = registry().snapshot()
    n0, t0 = completed[0], time.monotonic()
    await asyncio.sleep(measure_s)
    snap1 = registry().snapshot()
    n1, t1 = completed[0], time.monotonic()

    for task in tasks + [echo_task]:
        task.cancel()
    await asyncio.gather(*tasks, echo_task, return_exceptions=True)

    elapsed = max(t1 - t0, 1e-9)
    roundtrips = n1 - n0
    d0, d1 = _net_stats(snap0), _net_stats(snap1)
    delta = {k: d1[k] - d0[k] for k in d1}
    datagrams = delta["dg_recv"] + delta["dg_send"]
    syscalls = delta["sys_recv"] + delta["sys_send"]
    wire_bytes = delta["bytes_recv"] + delta["bytes_send"]
    return {
        # App msgs/s both directions: each round-trip is one client->
        # server message plus one echo back.
        "throughput": round(2.0 * roundtrips / elapsed, 1),
        "roundtrips": roundtrips,
        "elapsed_s": round(elapsed, 4),
        "conns": conns,
        "inflight": inflight,
        "payload_bytes": len(payload),
        "syscalls_per_msg": (round(syscalls / datagrams, 4)
                             if datagrams else None),
        "bytes_per_msg": (round(wire_bytes / datagrams, 1)
                          if datagrams else None),
        "datagrams_per_s": round(datagrams / elapsed, 1),
        "p99_ack_rtt_s": _hist_p99(snap1, "lsp.msg_rtt_s"),
        "mmsg_active": _int_env("DBM_MMSG", 1) != 0,
        "wire_fast_active": _int_env("DBM_WIRE_FAST", 1) != 0,
    }


def echo_storm_child() -> dict:
    """One echo-storm leg in THIS process (``bench.py --transport-child``);
    the knobs are whatever the environment says."""
    return asyncio.run(_echo_storm())


# -------------------------------------------------------- conn-memory probe

def _vm_rss_bytes() -> Optional[int]:
    try:
        with open("/proc/self/status", encoding="ascii") as fh:
            for line in fh:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    return None


def conn_memory_probe(counts=(10_000, 50_000, 100_000),
                      window: int = 8) -> dict:
    """Resident bytes per live connection: bare ``ConnCore`` pairs (one
    server-side + one client-side core per logical conn — both ends live
    in-process under detnet and the load harness), measured as VmRSS
    growth. No sockets, no loop: this is the flattened conn-table state
    ISSUE 17's slotted structs + ring windows exist to shrink."""
    import gc

    from ..lsp.core import ConnCore

    rss0 = _vm_rss_bytes()
    if rss0 is None:
        return {"error": "VmRSS unavailable"}
    params = Params(window_size=window)
    cores: List[ConnCore] = []
    out = {}
    for target in sorted(counts):
        while len(cores) < 2 * target:
            cid = len(cores) // 2 + 1
            cores.append(ConnCore(params, cid))
            cores.append(ConnCore(params, cid, connect=True))
        gc.collect()
        rss = _vm_rss_bytes()
        if rss is None:
            break
        out[f"rss_per_conn_at_{target}"] = round((rss - rss0) / target, 1)
    out["window"] = window
    return out


# ------------------------------------------------------------ orchestration

_FAST_ENV = {"DBM_MMSG": "1", "DBM_WIRE_FAST": "1"}
_STOCK_ENV = {"DBM_MMSG": "0", "DBM_WIRE_FAST": "0"}


def _run_child(repo_root: str, overrides: dict,
               timeout_s: float = 60.0) -> dict:
    env = dict(os.environ)
    env.update(overrides)
    # The child is a pure transport measurement: keep the metrics
    # emitter and capture planes out of the timed window.
    env.setdefault("DBM_METRICS_INTERVAL_S", "0")
    proc = subprocess.run(
        [sys.executable, os.path.join(repo_root, "bench.py"),
         "--transport-child"],
        capture_output=True, text=True, timeout=timeout_s, env=env,
        cwd=repo_root, check=False)
    for line in reversed(proc.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    raise RuntimeError(
        f"transport child produced no JSON (rc={proc.returncode}): "
        f"{proc.stderr.strip()[-300:]}")


def transport_probe(repo_root: str) -> dict:
    """The full ``detail.transport`` dict: interleaved A/B echo-storm legs
    (fast datapath vs ``DBM_MMSG=0 DBM_WIRE_FAST=0`` stock), medians,
    plus the conn-memory scaling measurement."""
    from ..lsp import _mmsg

    rounds = max(1, _int_env("DBM_BENCH_TRANSPORT_ROUNDS", 3))
    fast_legs: List[dict] = []
    stock_legs: List[dict] = []
    for i in range(rounds):
        # Order swapped each round: kills slow-box order bias.
        order = [(_FAST_ENV, fast_legs), (_STOCK_ENV, stock_legs)]
        if i % 2:
            order.reverse()
        for overrides, legs in order:
            legs.append(_run_child(repo_root, overrides))

    def med(legs: List[dict], key: str) -> Optional[float]:
        vals = [leg[key] for leg in legs if leg.get(key) is not None]
        return round(statistics.median(vals), 4) if vals else None

    fast_tp = med(fast_legs, "throughput") or 0.0
    stock_tp = med(stock_legs, "throughput") or 0.0
    return {
        "schema": "transport_datapath_v1",
        "mmsg_available": _mmsg.available(),
        "rounds": rounds,
        "echo_storm": {
            "throughput": fast_tp,
            "stock_msgs_per_s": stock_tp,
            "speedup": (round(fast_tp / stock_tp, 3) if stock_tp else None),
        },
        "fast": {
            "syscalls_per_msg": med(fast_legs, "syscalls_per_msg"),
            "bytes_per_msg": med(fast_legs, "bytes_per_msg"),
            "p99_ack_rtt_s": med(fast_legs, "p99_ack_rtt_s"),
        },
        "stock": {
            "syscalls_per_msg": med(stock_legs, "syscalls_per_msg"),
            "bytes_per_msg": med(stock_legs, "bytes_per_msg"),
            "p99_ack_rtt_s": med(stock_legs, "p99_ack_rtt_s"),
        },
        "conn_memory": conn_memory_probe(),
        "samples": {"fast": fast_legs, "stock": stock_legs},
    }


def standalone_artifact(repo_root: str) -> dict:
    """The ``bench.py --transport-only`` artifact (the tier-1 transport-
    regression leg's input): the probe dict nested under ``transport``
    so its paths line up with the full BENCH artifact's
    ``detail/transport/...`` leaves for ``benchdiff``."""
    probe = transport_probe(repo_root)
    return {
        "metric": "transport_datapath",
        "value": probe["echo_storm"]["throughput"],
        "unit": "msgs/sec",
        "detail": {"transport": probe},
    }
