"""Multi-process replica tier: one OS process per replica (ISSUE 12).

PR 11 ran N scheduler replicas IN ONE PROCESS behind one socket, with
takeover driven by a method call. This module promotes the topology to
real processes that survive real crashes (SIGKILL, SIGSTOP wedge,
partition), with failure detected by the health plane in
``apps/health.py`` instead of any test hook:

- **Replica process** (:class:`ReplicaProcess`, CLI ``replica``): one
  :class:`~.scheduler.Scheduler` on its OWN LSP socket, heartbeating a
  :class:`~.health.Beat` file every ``DBM_HEALTH_BEAT_S`` seconds and
  watching the published membership for its own fence — a replica that
  finds its ``(rid, incarnation)`` in the fenced ledger STOPS SERVING
  (closes its socket so clients resubmit and miners rejoin) and exits
  with :data:`FENCED_EXIT` for its supervisor to respawn fresh.
- **Router process** (:class:`Router`, CLI ``router``): control-plane
  ONLY — it scans the beat files at the beat cadence, runs the shared
  :func:`~.health.router_tick` detection (a replica whose beat ``seq``
  freezes for ``DBM_HEALTH_MISS_K`` beats is dead), and publishes
  ``membership.json`` with a bumped fencing epoch. It is NOT on the
  data path: clients hash the tenant over the advertised ring
  themselves (client-side ring — see README "Horizontal scale" for the
  justification vs a proxy router), so a router restart never
  interrupts traffic; it only delays the NEXT membership change.
- **Miner agent** (:class:`MinerAgent`, CLI ``miner``): wraps a
  :class:`~.miner.MinerWorker`; joins the live replica with the
  thinnest advertised miner slice and, when its conn dies (replica
  killed or fenced), re-reads the membership and REJOINS a survivor —
  the process-topology analog of PR 11's in-process miner adoption.
- **Gateway agent** (:class:`GatewayAgent`, CLI ``gateway``): one OS
  process holding a whole federated child cluster (ISSUE 20) — an
  inner LSP server + stock :class:`~.scheduler.Scheduler` + N
  in-process child :class:`~.miner.MinerWorker`\\ s + one
  :class:`~.gateway.GatewayMiner` that JOINs the thinnest live replica
  as ONE very wide miner. Owner pick and fence-push mirror the miner
  agent; the process publishes a ``gateway``-role rollup blob so
  ``dbmtop`` shows the federation tier next to the flat one.
- **Replicated cache tier** (:class:`SpoolResultCache`): each replica's
  ResultCache WRITES THROUGH finished results to an append-only
  per-incarnation spool file; every replica ingests its peers' spools
  on the beat cadence, so a tenant re-hashed after a failover replays
  answers the dead replica produced. Lines from a FENCED incarnation
  are dropped at ingest (:meth:`~.health.Membership.writer_fenced`) —
  a declared-dead replica's late writes must not propagate; a missing
  entry only degrades to recompute, never to a wrong or duplicate
  reply. (The alternative — an LSP-served cache process — was
  rejected: a synchronous miss-path RPC from inside the scheduler's
  event handlers is exactly the loop-block class dbmlint polices, an
  asynchronous one gives no stronger guarantee than spool ingest, and
  the extra process is one more thing to health-check; the measured
  cost of the spool tier is one file append per finished request and
  an O(new lines) read per beat.)

Exactly-once across process death is the PR 11 argument re-based on the
client: a killed replica never replied to the requests still queued or
in flight with it (a replied request is no longer in flight), so the
client's retry plane re-serves them through the new ring owner — a
retry of an ALREADY-replied request replays from the replicated cache
(or recomputes the identical pure function of the request key). The
fencing epoch closes the partitioned-but-alive hole: a replica that
was declared dead but keeps serving only ever answers conns its
clients have already abandoned, and its cache writes are refused.

State directory layout (all writes atomic tmp+rename)::

    <statedir>/beat_<rid>.json       one Beat per replica, seq advancing
    <statedir>/membership.json       ring + fencing ledger (router-owned)
    <statedir>/cache_<rid>_<inc>.spool   append-only result spool
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..lsp.errors import LspError
from ..utils._env import float_env as _float_env, int_env as _int_env
from .health import Beat, BeatMonitor, Membership, RouterState, router_tick
from .replicas import HashRing
from .rollup import RollupPublisher, gc_stale_blobs, rollup_enabled
from .scheduler import ResultCache

logger = logging.getLogger("dbm.procs")

__all__ = ["ReplicaProcess", "Router", "MinerAgent", "GatewayAgent",
           "SpoolResultCache", "ProcCluster", "read_membership",
           "resolve_owner", "gc_fenced_spools", "FENCED_EXIT"]

#: Exit code of a replica process that observed its own fence: the
#: supervisor (ProcCluster, or an operator's systemd unit) respawns it
#: with a fresh incarnation, which the router re-admits.
FENCED_EXIT = 3


def health_beat_s() -> float:
    """``DBM_HEALTH_BEAT_S`` (default 0.5): replica heartbeat period and
    router poll cadence."""
    return max(0.01, _float_env("DBM_HEALTH_BEAT_S", 0.5))


def health_miss_k() -> int:
    """``DBM_HEALTH_MISS_K`` (default 3): missed beats before a replica
    is declared dead and fenced."""
    return max(1, _int_env("DBM_HEALTH_MISS_K", 3))


def proc_cache_enabled() -> bool:
    """``DBM_PROC_CACHE`` (default 1): the spool-replicated cache tier;
    0 = per-replica caches only (failover replays degrade to
    recompute)."""
    return _int_env("DBM_PROC_CACHE", 1) != 0


# ------------------------------------------------------------ state files

def write_json_atomic(path: str, obj: dict) -> None:
    """Atomic publish: a reader sees the old or the new document, never
    a torn write (rename is atomic on one filesystem)."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(obj, fh, sort_keys=True)
    os.replace(tmp, path)


def read_json(path: str) -> Optional[dict]:
    try:
        with open(path, encoding="utf-8") as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def beat_path(statedir: str, rid: int) -> str:
    return os.path.join(statedir, f"beat_{rid}.json")


def membership_path(statedir: str) -> str:
    return os.path.join(statedir, "membership.json")


def read_membership(statedir: str) -> Optional[Membership]:
    """The advertised membership, or None while the router has not yet
    published (or mid-restart with no file) — callers back off."""
    d = read_json(membership_path(statedir))
    return Membership.from_dict(d) if d else None


def read_beats(statedir: str) -> List[Beat]:
    out = []
    try:
        names = os.listdir(statedir)
    except OSError:
        return out
    for name in names:
        if not (name.startswith("beat_") and name.endswith(".json")):
            continue
        d = read_json(os.path.join(statedir, name))
        if d is not None:
            try:
                out.append(Beat.from_dict(d))
            except (TypeError, KeyError):
                continue
    return out


def resolve_owner(statedir: str, key) -> Optional[Tuple[int, str]]:
    """Client-side ring: ``(rid, hostport)`` of the replica owning
    ``key``, or None when no membership / no live replica is advertised
    (back off and retry).

    The ring spans SERVING replicas — live AND advertising at least one
    joined miner in their current incarnation's beat — mirroring the
    PR 11 in-process routing rule: a hash owner with an empty miner
    slice would queue the request into the age alarm forever while
    capacity sat idle next door. When no replica holds miners yet, every
    key resolves to the FIRST live replica — exactly where the miner
    agent's thinnest-slice rule lands the first JOIN (min miner count,
    ties by lowest rid), so pre-miner requests wait where capacity will
    first appear."""
    m = read_membership(statedir)
    if m is None or not m.live:
        return None
    counts = {b.rid: b.miners for b in read_beats(statedir)
              if b.rid in m.live
              and b.incarnation == m.live[b.rid]["incarnation"]}
    serving = sorted(r for r in m.live if counts.get(r, 0) > 0)
    ring_ids = serving or [min(m.live)]
    rid = HashRing(ring_ids).owner(key)
    return rid, f"127.0.0.1:{m.live[rid]['port']}"


def pick_thinnest(statedir: str) -> Optional[Tuple[int, str, str]]:
    """``(rid, incarnation, hostport)`` of the live replica advertising
    the thinnest miner slice (ties by lowest rid), or None while no
    membership is published — the JOIN placement rule shared by the
    miner agent and the gateway agent."""
    m = read_membership(statedir)
    if m is None or not m.live:
        return None
    counts = {b.rid: b.miners for b in read_beats(statedir)}
    rid = min(sorted(m.live), key=lambda r: counts.get(r, 0))
    entry = m.live[rid]
    return rid, entry["incarnation"], f"127.0.0.1:{entry['port']}"


# ------------------------------------------------------- replicated cache

class SpoolResultCache(ResultCache):
    """ResultCache with write-through spool replication (module
    docstring). ``put`` appends one JSON line to this incarnation's
    spool; :meth:`ingest` folds peers' new lines into the local LRU,
    dropping lines whose writer incarnation is fenced.

    Disk discipline (code review): the in-memory LRU is bounded by
    ``size`` but an append-only file is not — after
    ``ROTATE_FACTOR * size`` lines the spool ROTATES (the old file is
    unlinked and a fresh ``.<seq>.spool`` starts), so one incarnation
    never holds more than ~one rotation window on disk. Entries a slow
    peer had not yet consumed from an unlinked file are lost — a
    recompute, never a wrong reply (the tier is best-effort by
    contract). Fenced incarnations' leftover spools are unlinked by
    the router (:func:`gc_fenced_spools`)."""

    #: Spool lines per file before rotation, as a multiple of the LRU
    #: bound (entries past ~1 LRU's worth are evictees anyway).
    ROTATE_FACTOR = 4

    def __init__(self, size: int, statedir: str, rid: int,
                 incarnation: str):
        super().__init__(size)
        self.statedir = statedir
        self.rid = rid
        self.incarnation = incarnation
        self._spool_seq = 0
        self._spool_lines = 0
        self._rotate_at = max(1024, self.ROTATE_FACTOR * size)
        self._spool = os.path.join(
            statedir, f"cache_{rid}_{incarnation}.spool")
        self._offsets: Dict[str, int] = {}     # peer spool -> bytes read
        self.spooled = 0
        self.ingested = 0
        self.dropped_fenced = 0

    def put(self, key, value) -> None:
        super().put(key, value)
        line = json.dumps({"rid": self.rid, "inc": self.incarnation,
                           "key": list(key), "h": value[0],
                           "n": value[1]})
        try:
            with open(self._spool, "a", encoding="utf-8") as fh:
                fh.write(line + "\n")
            self.spooled += 1
            self._spool_lines += 1
            if self._spool_lines >= self._rotate_at:
                self._rotate()
        except OSError:
            logger.warning("cache spool append failed; entry stays "
                           "local-only", exc_info=True)

    def _rotate(self) -> None:
        """Unlink the full spool and start a fresh one (class
        docstring). The filename keeps the ``cache_<rid>_<inc>`` stem
        (ingesters parse writer identity from the LINES, the router's
        fence GC from the stem)."""
        try:
            os.unlink(self._spool)
        except OSError:
            pass
        self._spool_seq += 1
        self._spool_lines = 0
        self._spool = os.path.join(
            self.statedir,
            f"cache_{self.rid}_{self.incarnation}"
            f".{self._spool_seq}.spool")

    def ingest(self, membership: Optional[Membership]) -> int:
        """Fold peers' new spool lines into the local cache (best-effort
        replay forwarding). Returns entries ingested this call."""
        got = 0
        try:
            names = os.listdir(self.statedir)
        except OSError:
            return 0
        spools = sorted(n for n in names if n.startswith("cache_")
                        and n.endswith(".spool"))
        # Offsets of rotated/GC'd-away files would otherwise accumulate
        # one entry per dead filename forever.
        for stale in set(self._offsets) - set(spools):
            self._offsets.pop(stale, None)
        for name in spools:
            path = os.path.join(self.statedir, name)
            if path == self._spool:
                continue
            offset = self._offsets.get(name, 0)
            try:
                with open(path, "rb") as fh:
                    fh.seek(offset)
                    data = fh.read()
            except FileNotFoundError:
                # Rotated/GC'd away: drop the stale offset so the
                # tracking map stays bounded by LIVE spool files.
                self._offsets.pop(name, None)
                continue
            except OSError:
                continue
            # Consume only COMPLETE lines: a read racing the writer's
            # append may end mid-line — leave the partial tail for the
            # next pass instead of losing the entry.
            end = data.rfind(b"\n")
            if end < 0:
                continue
            self._offsets[name] = offset + end + 1
            for raw in data[:end].splitlines():
                try:
                    d = json.loads(raw.decode("utf-8"))
                    key = tuple(d["key"])
                    value = (int(d["h"]), int(d["n"]))
                    wrid, winc = int(d["rid"]), str(d["inc"])
                except (ValueError, KeyError, TypeError):
                    continue      # corrupt line = one lost entry = one
                    # recompute, never a wrong reply
                if membership is not None and \
                        membership.writer_fenced(wrid, winc):
                    self.dropped_fenced += 1
                    continue
                ResultCache.put(self, key, value)   # no re-spool
                got += 1
        self.ingested += got
        return got


def gc_fenced_spools(statedir: str, membership: Membership) -> int:
    """Unlink cache spools left behind by FENCED incarnations (their
    lines are refused at ingest anyway — the files are pure disk
    leak). Run by the router; returns files removed."""
    removed = 0
    try:
        names = os.listdir(statedir)
    except OSError:
        return 0
    for name in names:
        if not (name.startswith("cache_") and name.endswith(".spool")):
            continue
        core = name[len("cache_"):-len(".spool")]
        rid_s, _, rest = core.partition("_")
        inc = rest.split(".")[0]     # strip a rotation suffix
        try:
            rid = int(rid_s)
        except ValueError:
            continue
        if membership.writer_fenced(rid, inc):
            try:
                os.unlink(os.path.join(statedir, name))
                removed += 1
            except OSError:
                continue
    return removed


# --------------------------------------------------------- replica process

class ReplicaProcess:
    """One scheduler replica as its own OS process (module docstring).

    Owns: the LSP server on ``port`` (0 = ephemeral, advertised via the
    beat), the Scheduler, the beat task, and the fence watch. ``run()``
    returns ``"fenced"`` when the replica observed its own fence and
    stopped serving, ``"closed"`` on transport close.
    """

    def __init__(self, statedir: str, rid: int, port: int = 0,
                 params=None, lease=None, cache=None, stripe=None,
                 qos=None, beat_s: Optional[float] = None,
                 spool: Optional[bool] = None):
        from ..utils.config import CacheParams
        self.statedir = statedir
        self.rid = rid
        self.port = port
        self.params = params
        self.lease = lease
        self.stripe = stripe
        self.qos = qos
        self.beat_s = beat_s if beat_s is not None else health_beat_s()
        self.incarnation = f"{os.getpid()}-{int(time.time() * 1000)}"
        cache = cache if cache is not None else CacheParams()
        use_spool = spool if spool is not None else proc_cache_enabled()
        self.cache_params = cache
        self.cache: Optional[ResultCache] = None
        if cache.enabled:
            self.cache = (SpoolResultCache(cache.size, statedir, rid,
                                           self.incarnation)
                          if use_spool else ResultCache(cache.size))
        self.server = None
        self.sched = None
        self.fenced = False
        self._seq = 0
        # Rollup plane (ISSUE 18): publish this process's registry
        # snapshot into the state directory every beat. None when the
        # knob is off — no blob, no extra write, bit-for-bit stock.
        self._rollup = (RollupPublisher(statedir, "replica", rid,
                                        self.incarnation,
                                        beat_s=self.beat_s)
                        if rollup_enabled() else None)

    async def run(self) -> str:
        from ..lsp.server import new_async_server
        from .scheduler import Scheduler
        os.makedirs(self.statedir, exist_ok=True)
        self.server = await new_async_server(self.port, self.params)
        self.sched = Scheduler(self.server, lease=self.lease,
                               cache=self.cache_params,
                               stripe=self.stripe, qos=self.qos,
                               result_cache=self.cache)
        print(f"Replica {self.rid} listening on port {self.server.port}",
              flush=True)
        self._write_beat()                 # admit before first request
        if self._rollup is not None:
            self._rollup.publish()
        beat_task = asyncio.get_running_loop().create_task(
            self._beat_loop())
        try:
            await self.sched.run()
            return "fenced" if self.fenced else "closed"
        finally:
            beat_task.cancel()
            self._write_beat(final=True)
            if self._rollup is not None:
                self._rollup.publish(final=True)
            await self.server.close()

    def _write_beat(self, final: bool = False) -> None:
        self._seq += 1
        m = read_membership(self.statedir)
        beat = Beat(
            rid=self.rid, incarnation=self.incarnation, seq=self._seq,
            port=self.server.port if self.server else 0,
            serving=not self.fenced and not final,
            miners=len(self.sched.miners) if self.sched else 0,
            queue_depth=(self.sched.tenant_plane.queue_len()
                         if self.sched else 0),
            epoch_seen=m.epoch if m else 0)
        try:
            write_json_atomic(beat_path(self.statedir, self.rid),
                              beat.to_dict())
        except OSError:
            logger.warning("beat write failed; retrying next tick",
                           exc_info=True)

    async def _beat_loop(self) -> None:
        """Heartbeat + fence watch + cache-spool ingest, one tick per
        ``beat_s``. On observing its own fence the replica stops
        serving: the server closes, every conn dies (clients resubmit
        via the ring, miners rejoin a survivor), and ``run`` returns."""
        while True:
            await asyncio.sleep(self.beat_s)
            m = read_membership(self.statedir)
            if m is not None and m.is_fenced(self.rid, self.incarnation):
                self.fenced = True
                logger.warning(
                    "replica %d (%s) observed its own fence at epoch %d:"
                    " closing the socket and exiting for respawn",
                    self.rid, self.incarnation, m.epoch)
                self._write_beat()
                await self.server.close()
                return
            if isinstance(self.cache, SpoolResultCache):
                self.cache.ingest(m)
            self._write_beat()
            if self._rollup is not None:
                self._rollup.publish(epoch_seen=m.epoch if m else 0)


# ----------------------------------------------------------------- router

class Router:
    """The thin membership/health router (control plane only)."""

    def __init__(self, statedir: str, beat_s: Optional[float] = None,
                 miss_k: Optional[int] = None):
        self.statedir = statedir
        self.beat_s = beat_s if beat_s is not None else health_beat_s()
        self.miss_k = miss_k if miss_k is not None else health_miss_k()
        self.state = RouterState(BeatMonitor(self.beat_s, self.miss_k))
        self.incarnation = f"{os.getpid()}-{int(time.time() * 1000)}"
        self._rollup = (RollupPublisher(statedir, "router", 0,
                                        self.incarnation,
                                        beat_s=self.beat_s)
                        if rollup_enabled() else None)

    async def run(self) -> None:
        os.makedirs(self.statedir, exist_ok=True)
        # Restart continuity: the fencing epoch must never regress, so
        # a restarted router resumes from the published document.
        prior = read_membership(self.statedir)
        if prior is not None:
            self.state.membership = prior
        print(f"Router watching {self.statedir} "
              f"(beat {self.beat_s}s, K={self.miss_k})", flush=True)
        loop = asyncio.get_running_loop()
        published = False
        ticks = 0
        while True:
            changed = router_tick(self.state, read_beats(self.statedir),
                                  loop.time())
            if changed or not published:
                write_json_atomic(membership_path(self.statedir),
                                  self.state.membership.to_dict())
                published = True
                if changed:
                    m = self.state.membership
                    logger.warning(
                        "membership epoch %d: live=%s fenced=%s",
                        m.epoch, sorted(m.live),
                        {r: f["epoch"] for r, f in m.fenced.items()})
            ticks += 1
            if changed or ticks % 64 == 0:
                # Fenced incarnations' leftover spools are a pure disk
                # leak (their lines are refused at ingest): sweep them
                # on every fence and periodically thereafter. Metric
                # blobs get the softer sweep: a fresh corpse stays
                # VISIBLE (flagged stale/fenced by the rollup), only
                # long-dead blobs are litter.
                gc_fenced_spools(self.statedir, self.state.membership)
                gc_stale_blobs(self.statedir)
            if self._rollup is not None:
                self._rollup.publish(
                    epoch_seen=self.state.membership.epoch)
            await asyncio.sleep(self.beat_s)


# ------------------------------------------------------------ miner agent

class MinerAgent:
    """Replica-aware miner wrapper: join the thinnest live slice, rejoin
    a survivor when the conn dies — or, FASTER, when the membership
    fences its owner (module docstring).

    Fence-push (ISSUE 13 satellite): the agent used to discover its
    owner's death only through LSP epoch detection on its own conn
    (``epoch_limit x epoch_millis`` — the measured ~0.8 s of rejoin
    dead air). The router already PUBLISHES the fence in
    ``membership.json`` one missed-beat window after the death; a
    watcher task polls the membership at the beat cadence and, the
    moment the owner rid is gone (or wears a fresh incarnation —
    either way the conn this agent holds is to a fenced incarnation),
    closes the worker's transport so ``run()`` returns and the rejoin
    loop re-picks a survivor immediately. Rejoin dead air drops to
    ~one beat; epoch detection remains the backstop when the router
    itself is down (``owner_gone`` returns False on a missing
    membership — no membership is no evidence).
    """

    def __init__(self, statedir: str, params=None,
                 searcher_factory: Optional[Callable] = None,
                 backoff_s: float = 0.2):
        self.statedir = statedir
        self.params = params
        self.backoff_s = backoff_s
        if searcher_factory is None:
            from .miner import HostSearcher
            searcher_factory = lambda d, b: HostSearcher(d)  # noqa: E731
        self.factory = searcher_factory
        self.joins = 0
        self.fence_pushes = 0
        self._pushed = False
        self.incarnation = f"{os.getpid()}-{int(time.time() * 1000)}"
        # Miner agents have no rid; the pid keys the blob (the rollup's
        # SourceSet bounds + retires churned pids, and the router GCs
        # their long-stale blobs).
        self._rollup = (RollupPublisher(statedir, "miner", os.getpid(),
                                        self.incarnation)
                        if rollup_enabled() else None)

    def _pick(self) -> Optional[Tuple[int, str, str]]:
        """``(rid, incarnation, hostport)`` of the thinnest advertised
        live slice, or None while no membership is published."""
        return pick_thinnest(self.statedir)

    @staticmethod
    def owner_gone(m: Optional[Membership], rid: int,
                   incarnation: str) -> bool:
        """Fence-push predicate: has the owner this agent joined left
        the advertised ring? True when the rid is no longer live OR is
        live under a DIFFERENT incarnation (the joined one was fenced
        and respawned). A missing membership is no evidence — the
        router may be restarting; epoch detection stays the backstop."""
        if m is None:
            return False
        entry = m.live.get(rid)
        return entry is None or entry.get("incarnation") != incarnation

    async def _watch_owner(self, rid: int, incarnation: str,
                           worker) -> None:
        """Poll the membership at the beat cadence; on the owner's
        fence, close the worker's transport so its run loop returns
        NOW instead of after epoch detection."""
        period = min(self.backoff_s, health_beat_s())
        while True:
            await asyncio.sleep(period)
            m = await asyncio.to_thread(read_membership, self.statedir)
            if self.owner_gone(m, rid, incarnation):
                self.fence_pushes += 1
                self._pushed = True
                logger.info(
                    "miner agent: owner rid %d (%s) fenced — closing "
                    "conn for immediate rejoin (fence-push #%d)",
                    rid, incarnation, self.fence_pushes)
                await worker.close()
                return

    async def _publish_loop(self) -> None:
        """Beat-cadence rollup publishing (the agent has no beat file of
        its own — this task is its whole state-plane presence)."""
        period = health_beat_s()
        while True:
            m = await asyncio.to_thread(read_membership, self.statedir)
            self._rollup.publish(epoch_seen=m.epoch if m else 0)
            await asyncio.sleep(period)

    async def run(self) -> None:
        publisher = None
        if self._rollup is not None:
            publisher = asyncio.get_running_loop().create_task(
                self._publish_loop())
        try:
            await self._run_inner()
        finally:
            if publisher is not None:
                publisher.cancel()

    async def _run_inner(self) -> None:
        from .miner import MinerWorker
        while True:
            picked = self._pick()
            if picked is None:
                await asyncio.sleep(self.backoff_s)
                continue
            rid, incarnation, hostport = picked
            worker = MinerWorker(hostport, params=self.params,
                                 searcher_factory=self.factory)
            watcher = None
            try:
                await worker.join()
                self.joins += 1
                logger.info("miner agent joined %s (join #%d)",
                            hostport, self.joins)
                watcher = asyncio.get_running_loop().create_task(
                    self._watch_owner(rid, incarnation, worker))
                await worker.run()     # returns on conn death OR push
            except LspError as exc:
                logger.info("miner agent join/run to %s failed: %s",
                            hostport, exc)
            finally:
                if watcher is not None:
                    watcher.cancel()
                await worker.close()
            if self._pushed:
                # Fence-push exit: the membership ALREADY advertises a
                # survivor — re-pick immediately instead of paying the
                # backoff the push exists to avoid (backoff remains
                # the spin guard for the no-membership/conn-death
                # paths, where _pick returning None still sleeps).
                self._pushed = False
                continue
            await asyncio.sleep(self.backoff_s)


class _InstantSearcher:
    """Fake miner compute for the ``--fake`` agent mode (loadharness
    ``--procs``): answers instantly with a deterministic function of
    (data, lower) — the control plane is the thing being measured."""

    _MIX = 0xBF58476D1CE4E5B9
    _MASK = (1 << 64) - 1

    def __init__(self, data: str):
        self.data = data

    def search(self, lower: int, upper: int):
        h = (hash(self.data) * self._MIX
             + lower * 0x9E3779B97F4A7C15) & self._MASK
        return h, lower


# --------------------------------------------------------- gateway agent

class GatewayAgent:
    """One federated child cluster in one OS process (ISSUE 20): an
    inner LSP server + stock :class:`~.scheduler.Scheduler` + N
    in-process child :class:`~.miner.MinerWorker` loops + one
    :class:`~.gateway.GatewayMiner` that JOINs the replica ring as ONE
    very wide miner.

    Placement and failover mirror :class:`MinerAgent`: each (re)join
    picks the thinnest advertised live slice (:func:`pick_thinnest`),
    and a fence-push watcher closes the parent conn the moment the
    joined owner leaves the ring — the GatewayMiner's ``run_forever``
    loop then re-picks a survivor immediately instead of waiting for
    epoch detection. The children live IN-PROCESS against the inner
    localhost socket, making the process boundary the child cluster's
    fault domain: ``kill -9`` the agent and the parent sees exactly one
    dropped (very wide) miner, recovered by the stock re-issue plane.

    Like the miner agent the process has no beat file — a
    ``gateway``-role rollup blob (pid-keyed, same churn discipline) is
    its whole state-plane presence, so ``dbmtop`` renders the
    federation tier next to the flat one.
    """

    def __init__(self, statedir: str, params=None,
                 searcher_factory: Optional[Callable] = None,
                 children: int = 1, backoff_s: float = 0.2,
                 gateway=None):
        from ..utils.config import gateway_from_env
        self.statedir = statedir
        self.params = params
        self.children = max(1, int(children))
        self.backoff_s = backoff_s
        self.gw_params = gateway if gateway is not None \
            else gateway_from_env()
        if searcher_factory is None:
            from .miner import HostSearcher
            searcher_factory = lambda d, b: HostSearcher(d)  # noqa: E731
        self.factory = searcher_factory
        self.joins = 0
        self.fence_pushes = 0
        self.incarnation = f"{os.getpid()}-{int(time.time() * 1000)}"
        self._owner: Optional[Tuple[int, str]] = None
        self.gw = None                      # set by run()
        self._rollup = (RollupPublisher(statedir, "gateway", os.getpid(),
                                        self.incarnation)
                        if rollup_enabled() else None)

    async def _parent_connect(self):
        """GatewayMiner ``parent_connect`` hook: block until a live
        replica is advertised, then dial the thinnest slice. Raising
        (refused dial) is fine — the rejoin loop backs off and calls
        again."""
        from ..lsp.client import new_async_client
        while True:
            picked = await asyncio.to_thread(pick_thinnest, self.statedir)
            if picked is not None:
                rid, incarnation, hostport = picked
                chan = await new_async_client(hostport, self.params)
                self._owner = (rid, incarnation)
                self.joins += 1
                logger.info("gateway agent dialing parent rid %d at %s "
                            "(join #%d)", rid, hostport, self.joins)
                return chan
            await asyncio.sleep(self.backoff_s)

    async def _watch_loop(self) -> None:
        """Fence-push (the MinerAgent idiom): when the joined owner
        leaves the advertised ring, close the parent conn so the
        GatewayMiner re-picks a survivor NOW instead of after epoch
        detection."""
        period = min(self.backoff_s, health_beat_s())
        while True:
            await asyncio.sleep(period)
            owner = self._owner
            chan = self.gw._parent if self.gw is not None else None
            if owner is None or chan is None:
                continue
            m = await asyncio.to_thread(read_membership, self.statedir)
            if MinerAgent.owner_gone(m, owner[0], owner[1]):
                self.fence_pushes += 1
                self._owner = None
                logger.info(
                    "gateway agent: owner rid %d (%s) fenced — closing "
                    "parent conn for immediate rejoin (fence-push #%d)",
                    owner[0], owner[1], self.fence_pushes)
                try:
                    await chan.close()
                except Exception:  # noqa: BLE001 — conn already dead
                    pass

    async def _child_loop(self, hostport: str) -> None:
        """One stock in-process child miner, rejoining the inner tier
        across shed/close exactly like a remote worker would."""
        from .miner import MinerWorker
        while True:
            worker = MinerWorker(hostport, params=self.params,
                                 searcher_factory=self.factory)
            try:
                await worker.join()
                await worker.run()
            except LspError as exc:
                logger.info("gateway child join/run failed: %s", exc)
            finally:
                await worker.close()
            await asyncio.sleep(self.backoff_s)

    async def _publish_loop(self) -> None:
        period = health_beat_s()
        while True:
            m = await asyncio.to_thread(read_membership, self.statedir)
            self._rollup.publish(epoch_seen=m.epoch if m else 0)
            await asyncio.sleep(period)

    async def run(self) -> None:
        from ..lsp.client import new_async_client
        from ..lsp.params import Params
        from ..lsp.server import new_async_server
        from .gateway import GatewayMiner
        from .scheduler import Scheduler

        lsp = self.params or Params()
        server = await new_async_server(0, lsp)
        sched = Scheduler(server)
        inner = f"127.0.0.1:{server.port}"
        self.gw = GatewayMiner(
            parent_connect=self._parent_connect,
            bridge_connect=lambda: new_async_client(inner, lsp),
            inner_scheds=[sched], params=self.gw_params,
            backoff_s=self.backoff_s,
            name=f"gateway[{os.getpid()}]")
        coros = [sched.run(), self.gw.run_forever(), self._watch_loop()]
        coros += [self._child_loop(inner) for _ in range(self.children)]
        if self._rollup is not None:
            coros.append(self._publish_loop())
        tasks = [asyncio.ensure_future(c) for c in coros]
        try:
            await asyncio.gather(*tasks)
        finally:
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
            await server.close()


# ------------------------------------------------------- process cluster

class ProcCluster:
    """Spawn and fault a whole topology of REAL OS processes — the
    harness behind the tier-1 procs smoke leg, the process chaos storms
    in tests/test_chaos.py, and ``loadharness --procs``.

    The cluster only SPAWNS and SIGNALS; failure detection is entirely
    the router's beat watch (no test-hook kill path — the acceptance
    criterion). ``kill_replica`` is a raw SIGKILL; ``stop_replica`` /
    ``cont_replica`` model the partitioned-but-alive wedge (SIGSTOP
    freezes the beat writer while the OS keeps its sockets alive).
    """

    def __init__(self, statedir: str, replicas: int = 2, miners: int = 1,
                 env: Optional[dict] = None, fake_miners: bool = False,
                 gateways: int = 0):
        self.statedir = statedir
        self.n = replicas
        self.m = miners
        self.g = gateways
        self.fake = fake_miners
        self.env = dict(os.environ)
        # Children must never touch JAX or pay emitter/probe overhead.
        self.env.update({"JAX_PLATFORMS": "cpu",
                         "DBM_METRICS_INTERVAL_S": "0",
                         "DBM_QUEUE_ALARM_S": "0"})
        if fake_miners:
            # Fake miners fabricate hashes by construction, so the
            # verification tier would reject every Result and quarantine
            # the whole pool (the in-process harness legs pass
            # verify=VerifyParams(enabled=False) for the same reason) —
            # the control plane is the thing measured here. An explicit
            # env override still wins.
            self.env["DBM_VERIFY"] = "0"
            # Same reasoning for the probabilistic audit plane (its env
            # default flipped on in ISSUE 20): an audit re-grants a
            # subwindow to a second fake miner, whose fabricated
            # sub-argmin "beats" the original's and convicts it.
            self.env["DBM_AUDIT_P"] = "0"
        self.env.update(env or {})
        self.procs: Dict[str, object] = {}      # name -> Popen

    # -- spawning ------------------------------------------------------

    def _spawn(self, name: str, args: List[str]):
        import subprocess
        import sys
        proc = subprocess.Popen(
            [sys.executable, "-m",
             "distributed_bitcoinminer_tpu.apps.procs", *args],
            env=self.env, cwd=os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))),
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        self.procs[name] = proc
        return proc

    def start(self) -> None:
        os.makedirs(self.statedir, exist_ok=True)
        self._spawn("router", ["router", self.statedir])
        for rid in range(self.n):
            self.spawn_replica(rid)
        for i in range(self.m):
            args = ["miner", self.statedir]
            if self.fake:
                args.append("--fake")
            self._spawn(f"miner{i}", args)
        for i in range(self.g):
            args = ["gateway", self.statedir]
            if self.fake:
                args.append("--fake")
            self._spawn(f"gateway{i}", args)

    def spawn_replica(self, rid: int):
        return self._spawn(f"replica{rid}",
                           ["replica", self.statedir, "--rid", str(rid)])

    def respawn_router(self):
        return self._spawn("router", ["router", self.statedir])

    # -- faults --------------------------------------------------------

    def _signal(self, name: str, sig: int) -> bool:
        proc = self.procs.get(name)
        if proc is None or proc.poll() is not None:
            return False
        os.kill(proc.pid, sig)
        return True

    def kill_replica(self, rid: int) -> bool:
        import signal
        return self._signal(f"replica{rid}", signal.SIGKILL)

    def stop_replica(self, rid: int) -> bool:
        import signal
        return self._signal(f"replica{rid}", signal.SIGSTOP)

    def cont_replica(self, rid: int) -> bool:
        import signal
        return self._signal(f"replica{rid}", signal.SIGCONT)

    def kill_router(self) -> bool:
        import signal
        return self._signal("router", signal.SIGKILL)

    def replica_alive(self, rid: int) -> bool:
        proc = self.procs.get(f"replica{rid}")
        return proc is not None and proc.poll() is None

    # -- observation ---------------------------------------------------

    def membership(self) -> Optional[Membership]:
        return read_membership(self.statedir)

    async def wait_live(self, k: int, timeout_s: float = 20.0,
                        miners: int = 0) -> Membership:
        """Wait until the advertised membership has ``k`` live replicas
        (and, optionally, the beats show ``miners`` joined miners)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            m = self.membership()
            if m is not None and len(m.live) == k:
                if miners <= sum(b.miners for b in
                                 read_beats(self.statedir)
                                 if b.rid in m.live
                                 and b.serving):
                    return m
            await asyncio.sleep(0.05)
        raise TimeoutError(
            f"membership never reached {k} live / {miners} miners: "
            f"{self.membership() and self.membership().to_dict()}")

    def close(self) -> None:
        import signal
        for name, proc in self.procs.items():
            if proc.poll() is None:
                try:
                    os.kill(proc.pid, signal.SIGCONT)  # unfreeze first
                    proc.terminate()
                except OSError:
                    pass
        for proc in self.procs.values():
            try:
                proc.wait(timeout=5)
            except Exception:  # noqa: BLE001 — teardown must finish
                try:
                    proc.kill()
                    proc.wait(timeout=5)
                except Exception:  # noqa: BLE001
                    pass


# -------------------------------------------------------------------- CLI

def main(argv=None) -> int:
    """CLI: ``procs {replica|router|miner|gateway} <statedir>
    [options]`` — the process entrypoints ProcCluster (and operators)
    spawn."""
    import argparse
    import sys
    argv = sys.argv[1:] if argv is None else argv
    ap = argparse.ArgumentParser(prog="procs", description=__doc__)
    sub = ap.add_subparsers(dest="role", required=True)
    rep = sub.add_parser("replica")
    rep.add_argument("statedir")
    rep.add_argument("--rid", type=int, required=True)
    rep.add_argument("--port", type=int, default=0)
    rout = sub.add_parser("router")
    rout.add_argument("statedir")
    mine = sub.add_parser("miner")
    mine.add_argument("statedir")
    mine.add_argument("--fake", action="store_true",
                      help="instant fake compute (loadharness --procs)")
    gate = sub.add_parser("gateway")
    gate.add_argument("statedir")
    gate.add_argument("--children", type=int, default=1,
                      help="in-process child miners behind the inner "
                           "scheduler (default 1)")
    gate.add_argument("--fake", action="store_true",
                      help="instant fake compute (loadharness --procs)")
    args = ap.parse_args(argv)

    from ..utils import configure_logging, from_env
    from ..utils.metrics import set_proc_identity
    configure_logging(logging.INFO)
    cfg = from_env()
    try:
        if args.role == "replica":
            proc = ReplicaProcess(args.statedir, args.rid,
                                  port=args.port, params=cfg.params,
                                  lease=cfg.lease, cache=cfg.cache,
                                  stripe=cfg.stripe, qos=cfg.qos)
            if rollup_enabled():
                # Env-armed process: every emitter snapshot line and
                # flight-recorder dump self-attributes (ISSUE 18).
                set_proc_identity("replica", args.rid, proc.incarnation)
            outcome = asyncio.run(proc.run())
            return FENCED_EXIT if outcome == "fenced" else 0
        if args.role == "router":
            router = Router(args.statedir)
            if rollup_enabled():
                set_proc_identity("router", 0, router.incarnation)
            asyncio.run(router.run())
            return 0
        factory = None
        if args.fake:
            factory = lambda d, b: _InstantSearcher(d)  # noqa: E731
        if args.role == "gateway":
            from ..utils.config import gateway_from_env
            gwp = gateway_from_env()
            if not gwp.enabled:
                # Mirror apps.gateway.serve: the flat-topology pin must
                # refuse loudly, not run a silently degraded tier.
                logger.error("DBM_GATEWAY=0: the gateway role is "
                             "disabled (flat topology pin)")
                return 2
            gw_agent = GatewayAgent(args.statedir, params=cfg.params,
                                    searcher_factory=factory,
                                    children=args.children, gateway=gwp)
            if rollup_enabled():
                set_proc_identity("gateway", os.getpid(),
                                  gw_agent.incarnation)
            asyncio.run(gw_agent.run())
            return 0
        agent = MinerAgent(args.statedir, params=cfg.params,
                           searcher_factory=factory)
        if rollup_enabled():
            set_proc_identity("miner", os.getpid(), agent.incarnation)
        asyncio.run(agent.run())
        return 0
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
