"""Tenant plane: conn lifecycle, admission, queue, QoS/DRR, trace/alarm.

The other half of the ISSUE 11 plane split (see ``apps/miner_plane.py``
for the interface overview). This module owns everything
TENANT-FACING:

- the request QUEUE — stored as an insertion-ordered map plus a
  per-tenant FIFO index, so every hot operation is O(1)-amortized
  (enqueue, head pop, targeted dequeue, a tenant's purge) and the
  QoS pump's per-tenant head scan is O(backlogged tenants), not
  O(queued requests): the old list-scan shape was an O(n²) melt under
  a 10k-tenant arrival storm (ISSUE 11). ``Scheduler.queue`` remains
  a list view for tests/operators.
- ADMISSION and SHEDDING — per-tenant token buckets, the oldest-first
  overload shed, and the conn-close signalling (classic LSP has no
  reject message);
- the :class:`~..apps.qos.QosPlane` (deficit-round-robin state) and the
  per-tenant weights;
- TRACE bookkeeping — the TraceBuffer, export TrackSet, the
  ``DBM_TRACE_SAMPLE`` sampling decision (unsampled requests carry the
  shared :data:`~..utils.metrics.NULL_TRACE` and never register), and
  the queue-age / in-flight age ALARMS with their trace dumps.

The scheduler keeps the request state machine (merge, barriers,
in-flight set) and drives this plane through plain method calls; the
miner plane never touches tenant state.
"""

from __future__ import annotations

import json
import logging
import time
from collections import deque
from typing import Callable, Dict, Optional

from ..utils import trace as _tracing
from ..utils.config import LeaseParams, QosParams
from ..utils.metrics import (LATENCY_BUCKETS_S, NULL_TRACE, Registry,
                             RequestTrace, TraceBuffer)
from .qos import QosPlane

logger = logging.getLogger("dbm.scheduler")

__all__ = ["TenantPlane"]


class TenantPlane:
    """The tenant-facing half of the scheduler (see module docstring).

    Injected pieces: the shared metrics ``Registry``, the scheduler's
    counter bump (``count``), the QoS/lease param blocks, the admission
    ``clock`` (virtual under dbmcheck), and ``close_conn`` — the
    transport close used by the shed path.
    """

    def __init__(self, metrics: Registry, count: Callable[..., None],
                 qos: QosParams, lease: LeaseParams, *,
                 clock=None, close_conn: Optional[Callable] = None,
                 trace_on: bool = False,
                 trace_sample: Optional[float] = None,
                 capture=None):
        self.metrics = metrics
        self._count = count
        self.qos = qos
        self.lease = lease
        self._close_conn = close_conn
        self._trace_on = trace_on
        # Workload capture plane (ISSUE 15): the scheduler hands its
        # capture handle down so the shed path records one event per
        # victim (None = plane off, the hook is one attribute test).
        self._capture = capture
        # Trace sampling (ISSUE 11, DBM_TRACE_SAMPLE): 1.0 = stock
        # (every request allocates a real RequestTrace), read once at
        # construction like every other scheduler param.
        self.trace_sample = (trace_sample if trace_sample is not None
                             else _tracing.sample_rate())
        self._arrival_seq = 0
        self.qos_plane = QosPlane(
            metrics, clock=clock if clock is not None else time.monotonic)
        self._tenant_weights: dict = {}    # programmatic overrides
        # Queue: insertion-ordered map (arrival order) + per-tenant FIFO
        # index. ``qkey`` stamps live on the Request.
        self._queue: Dict[int, object] = {}
        self._by_tenant: Dict[object, deque] = {}
        self._next_qkey = 0
        self.traces = TraceBuffer()
        self.tracks = _tracing.TrackSet()
        # Lazy-DRR entry hook (ISSUE 12): the scheduler registers a
        # callable fired on EVERY enqueue (including direct driver
        # injections) so ring membership tracks backlog without a
        # per-pump sync scan. None = stock walk, no hook cost.
        self.backlog_hook = None
        self._cache_trace_seq = 0
        self._queue_depth = metrics.gauge("queue_depth")
        self._queue_wait = metrics.histogram("queue_wait_s",
                                             LATENCY_BUCKETS_S)

    # ------------------------------------------------------------ tenants

    def weight_for(self, tenant) -> float:
        w = self._tenant_weights.get(tenant)
        return w if w is not None else self.qos.weight_for(tenant)

    def set_weight(self, tenant, weight: float) -> None:
        self._tenant_weights[tenant] = max(weight, 1e-3)
        self.qos_plane.set_weight(tenant, weight)

    def tenant(self, conn_id):
        """The QoS tenant state for a conn, created with the configured
        weight and admission bucket on first sight."""
        return self.qos_plane.tenant(conn_id, self.weight_for(conn_id),
                                     self.qos.rate, self.qos.burst)

    def admit(self, conn_id) -> bool:
        """Create-on-first-sight + spend one admission token; False =
        shed at admission (the caller never queues the request)."""
        self.tenant(conn_id)
        return self.qos_plane.admit(conn_id)

    # -------------------------------------------------------------- queue

    @property
    def queue(self) -> list:
        """Arrival-ordered list VIEW of the queued requests (the
        pre-split ``Scheduler.queue`` surface; built per access — hot
        paths use the indexed operations below).

        READ-ONLY in effect: the returned list is a fresh copy, so
        mutating it (``sched.queue.append(...)``) silently changes
        nothing — unlike the ``miners``/``parked`` views, which hand
        out the planes' live lists. Drivers that inject requests
        directly call :meth:`enqueue` instead."""
        return list(self._queue.values())

    def queue_len(self) -> int:
        return len(self._queue)

    def enqueue(self, req) -> None:
        self._next_qkey += 1
        req.qkey = self._next_qkey
        self._queue[req.qkey] = req
        self._by_tenant.setdefault(req.conn_id, deque()).append(req)
        self._queue_depth.set(len(self._queue))
        if self.backlog_hook is not None:
            self.backlog_hook(req.conn_id)

    def dequeue(self, req) -> None:
        """Remove one specific queued request (a pump grant)."""
        if self._queue.pop(req.qkey, None) is None:
            return
        dq = self._by_tenant.get(req.conn_id)
        if dq:
            if dq[0] is req:
                dq.popleft()
            else:
                try:
                    dq.remove(req)
                except ValueError:
                    pass
            if not dq:
                del self._by_tenant[req.conn_id]
        self._queue_depth.set(len(self._queue))

    def pop_head(self):
        """Pop the globally oldest queued request, or None."""
        if not self._queue:
            return None
        req = next(iter(self._queue.values()))
        self.dequeue(req)
        return req

    def head(self):
        """The globally oldest queued request without popping."""
        return next(iter(self._queue.values()), None)

    def purge_tenant(self, conn_id) -> list:
        """Remove (and return, in arrival order) every queued request
        of one tenant — client drop and shed both use this; O(own
        requests), never a full-queue scan."""
        dq = self._by_tenant.pop(conn_id, None)
        if not dq:
            return []
        out = list(dq)
        for req in out:
            self._queue.pop(req.qkey, None)
        self._queue_depth.set(len(self._queue))
        return out

    def tenant_heads(self):
        """``(tenant, oldest queued request)`` pairs, in the order
        tenants first queued work — the QoS pump's start-candidate scan,
        O(backlogged tenants)."""
        return [(t, dq[0]) for t, dq in self._by_tenant.items() if dq]

    def backlog_tenants(self) -> list:
        """Tenants with queued work, first-queued order (ring sync)."""
        return [t for t, dq in self._by_tenant.items() if dq]

    def tenant_head(self, tenant):
        """One tenant's oldest queued request, or None — the lazy
        pump's O(1) per-visit start-head lookup (ISSUE 12)."""
        dq = self._by_tenant.get(tenant)
        return dq[0] if dq else None

    def observe_queue_wait(self, waited_s: float) -> None:
        self._queue_wait.observe(waited_s)

    # ------------------------------------------------------------- traces

    def new_trace(self, **meta):
        """A request's trace: a real :class:`RequestTrace`, or the
        shared :data:`NULL_TRACE` when the deterministic sampler says
        this request is unsampled (``DBM_TRACE_SAMPLE`` < 1)."""
        self._arrival_seq += 1
        if _tracing.sample_hit(self._arrival_seq, self.trace_sample):
            return RequestTrace(**meta)
        return NULL_TRACE

    def track_tenant(self, conn_id) -> None:
        if self._trace_on:
            self.tracks.track("trace_track", tenant=str(conn_id))

    def track_miner(self, conn_id) -> None:
        if self._trace_on:
            self.tracks.track("trace_track", miner=str(conn_id))

    def retire_tenant_track(self, conn_id) -> None:
        self.tracks.retire("trace_track", tenant=str(conn_id))

    def retire_miner_track(self, conn_id) -> None:
        self.tracks.retire("trace_track", miner=str(conn_id))

    def dump_trace(self, why: str, trace) -> None:
        """Structured single-line JSON dump of one request trace — the
        queue-age alarm's "a stalled request explains itself" payload."""
        if trace is None or trace.null:
            return
        logger.warning("trace dump (%s): %s", why,
                       json.dumps(trace.to_dict(), sort_keys=True,
                                  default=str))

    def cache_replay_trace(self, conn_id, key, h: int, nonce: int) -> None:
        """An at-enqueue memo replay never builds a Request (and never
        gets a job id): trace it under a synthetic ``cache:N`` key so
        trace completeness still holds. (A replay at DISPATCH time reuses
        the queued Request's own trace instead — its enqueue stamp and
        queue wait are real history that must not be discarded.)"""
        self._cache_trace_seq += 1
        trace = self.new_trace(data=key[0], lower=key[1], upper=key[2],
                               target=key[3], client=conn_id)
        if trace.null:
            return
        trace.event("enqueue", queue_depth=len(self._queue))
        trace.event("cache_hit", at="request")
        trace.event("reply", hash=h, nonce=nonce, cached=True)
        self.traces.register(f"cache:{self._cache_trace_seq}", trace)
        self.track_tenant(conn_id)

    def register_replay(self, req) -> None:
        """Register a dispatch-time cache replay's trace under a
        synthetic key (it never gets a job id)."""
        self._cache_trace_seq += 1
        self.traces.register(f"cache:{self._cache_trace_seq}", req.trace)
        if not req.trace.null:
            self.track_tenant(req.conn_id)

    # ----------------------------------------------------------- shedding

    def shed(self, req, reason: str) -> None:
        """Shed one request under admission/overload pressure: cancel it
        through the trace/cancel path and CLOSE its conn. Classic LSP has
        no reject message, so the conn close is the signal — the client's
        transport declares the conn dead within its epoch window and
        ``submit_with_retry`` backs off and resubmits, instead of hanging
        into its wire deadline. The tenant's other QUEUED requests ride
        the same dying conn and are purged with it (in-flight work
        finishes; its reply write fails harmlessly)."""
        others = self.purge_tenant(req.conn_id)
        victims = [req] + [r for r in others if r is not req]
        for i, victim in enumerate(victims):
            self._count("qos_shed")
            if self._capture is not None:
                # One shed record per victim (ISSUE 15): purged queued
                # siblings are sheds too — the captured shed rate is
                # victims over arrivals, exactly what a replay must
                # reproduce.
                self._capture.shed(victim.conn_id,
                                   reason if i == 0 else "conn")
            self.qos_plane.on_shed(victim.conn_id,
                                   reason if i == 0 else "conn")
            victim.trace.event("cancel", reason="shed", shed_reason=reason)
            self._cache_trace_seq += 1
            self.traces.register(f"shed:{self._cache_trace_seq}",
                                 victim.trace)
            if not victim.trace.null:
                self.track_tenant(victim.conn_id)
            if self._trace_on:
                _tracing.flight("shed", tenant=victim.conn_id,
                                reason=reason)
        logger.warning(
            "QoS shed (%s): request %r [%d, %d] from tenant %d "
            "(+%d queued sibling(s)); closing its conn so the client "
            "backs off and resubmits", reason, req.data, req.lower,
            req.upper, req.conn_id, len(victims) - 1)
        if self._close_conn is not None:
            try:
                self._close_conn(req.conn_id)
            except Exception:  # noqa: BLE001 — conn may already be gone
                logger.info("shed: conn %d already closed", req.conn_id)

    # ------------------------------------------------------------- alarms

    def check_queue_age(self, inflight: dict, current,
                        miners_n: int, eligible_n: int,
                        distrusted_n: int = 0) -> None:
        """Age alarms (ROADMAP open item + ISSUE 3; per-tenant since
        ISSUE 5): the OLDEST queued request of each TENANT past
        ``lease.queue_alarm_s`` — and any request still IN FLIGHT past the
        same bound — emits a structured warning, once per bound interval
        per request, plus a full trace dump so the stall explains itself
        (a queued request's stall is usually an in-flight request's wedged
        miner, so the oldest in-flight trace is dumped alongside).

        The alarm and its dump carry the tenant's cumulative GRANT SHARE,
        so a starved mouse (near-zero share despite backlog) is
        distinguishable from a busy elephant (large share, long queue by
        its own volume). ``distrusted_n`` (ISSUE 16) names the miners
        the verification tier barred from grants, so an eligibility
        collapse under a byzantine pool reads as what it is rather
        than as a mystery stall. Observability only: never changes
        scheduling. The per-tenant-oldest scan rides the FIFO index —
        O(backlogged tenants) per sweep, not O(queued requests)
        (ISSUE 11)."""
        bound = self.lease.queue_alarm_s
        if bound <= 0:
            return
        now = time.monotonic()
        queue_alarmed = False
        for _tenant, req in self.tenant_heads():
            age = now - req.queued_at
            if age < bound or now - req.last_alarm < bound:
                continue
            req.last_alarm = now
            queue_alarmed = True
            share = self.qos_plane.grant_share(req.conn_id)
            self._count("queue_alarms")
            logger.warning(
                "tenant %d: oldest request %r [%d, %d] queued for %.1fs "
                "(bound %.1fs): grant_share=%.3f pool=%d eligible=%d "
                "distrusted=%d in_flight=%d",
                req.conn_id, req.data, req.lower, req.upper, age, bound,
                share, miners_n, eligible_n, distrusted_n, len(inflight))
            req.trace.event("queue_alarm", age_s=round(age, 3),
                            tenant=req.conn_id,
                            grant_share=round(share, 4))
            self.dump_trace("queue-age alarm: stalled request", req.trace)
        inflight_due = [
            r for r in inflight.values()
            if now - r.started >= bound
            and now - r.last_inflight_alarm >= bound]
        if queue_alarmed and current is not None \
                and current not in inflight_due:
            # An in-flight request is the usual culprit; the oldest one's
            # trace is the same document for every stalled request, so
            # dump it once per sweep — and not at all when the in-flight
            # alarm below dumps the identical document anyway.
            self.dump_trace("queue-age alarm: request in flight "
                            "ahead of the stalled one", current.trace)
        for req in inflight_due:
            age = now - req.started
            req.last_inflight_alarm = now
            share = self.qos_plane.grant_share(req.conn_id)
            self._count("inflight_alarms")
            logger.warning(
                "request %d (tenant %d) in flight for %.1fs (bound %.1fs): "
                "%d/%d chunks answered, %d granted, grant_share=%.3f",
                req.job_id, req.conn_id, age, bound, sum(req.answered),
                req.num_chunks, req.granted_chunks, share)
            req.trace.event("inflight_alarm", age_s=round(age, 3),
                            tenant=req.conn_id,
                            grant_share=round(share, 4))
            self.dump_trace("in-flight age alarm", req.trace)
        if self._trace_on and (queue_alarmed or inflight_due):
            # Flight-recorder post-mortem (ISSUE 10): the alarm's trace
            # dump explains ONE request; the ring shows what the whole
            # control plane did around the stall. Once per sweep even
            # when both alarm kinds fired — the ring is one document.
            _tracing.flight_dump("queue-age / in-flight alarm")

    def gc(self, busy: set) -> None:
        """Idle-tenant GC (rides the scheduler sweep): a tenant with no
        queued or in-flight work, nothing granted outstanding, and a
        full admission bucket carries no state worth keeping — dropping
        it frees its metric series so conn churn stays bounded over a
        long server life. Tenants the GC forgets also lose their export
        track (ISSUE 10): the track registry obeys the same churn
        rule."""
        before = set(self.qos_plane.tenants)
        self.qos_plane.gc(busy)
        for tenant in before - set(self.qos_plane.tenants):
            self.retire_tenant_track(tenant)
