"""Scheduler replica sharding: N schedulers, one socket, hashed tenants.

ISSUE 11's third movement. Everything upstream of the miners used to be
ONE scheduler draining one LSP socket; the plane split made the
scheduler a compact request state machine over a tenant plane and a
miner plane — this module runs N of them as REPLICAS behind one
transport:

- **Tenant sharding.** Every client conn id is consistent-hashed
  (:class:`HashRing`, SHA-256 points, ``VNODES`` virtual nodes per
  replica) onto one live replica. The ring's stability property — on
  replica add/remove only ~1/N of tenants move — is what makes replica
  membership changes cheap and is pinned by tests/test_plane_split.py.
- **Miner-pool slices.** A joining miner is assigned to the live
  replica with the fewest miners (balanced slices). A replica only ever
  grants to its own slice, so per-miner FIFO discipline (the k-th
  Result answers the k-th Request) holds per replica with no cross-
  replica coordination.
- **Shared replay tier.** All replicas share ONE
  :class:`~.scheduler.ResultCache`: a tenant re-hashed to a different
  replica (takeover, ring change) replays its finished answers in O(1)
  instead of re-scanning — the cache key is the full request identity,
  so the replay is sound wherever it lands.
- **Lease takeover on replica death.** :meth:`ReplicaSet.kill` (driven
  by the dbmcheck ``replica_takeover`` scenario and by tests) removes a
  replica: its miners are ADOPTED by surviving replicas — their
  still-pending chunk records ride along marked cancelled, so the
  adopted miner's in-flight answers pop in order as stale and the FIFO
  correspondence survives the ownership change — and its queued +
  in-flight requests are RE-SERVED through the new ring owner.
  Exactly-once holds because a dead replica's in-flight request never
  replied (a replied request is not in flight), and a re-serve of an
  already-finished retry replays from the shared cache.

Job ids are partitioned per replica (disjoint ``JOB_ID_STRIDE``
ranges): an adopted miner's late Result carries the dead replica's
job id, which must resolve to "stale" on the adopter — never collide
with a live job.

``DBM_REPLICAS=1`` (default) means ``apps/server.py`` runs the plain
single :class:`~.scheduler.Scheduler` — today's topology, bit-for-bit.
In-process replicas shard the CONTROL-PLANE work (queues, pumps,
sweeps, alarms — the 10k-tenant melt the load harness measures). The
MULTI-PROCESS tier (ISSUE 12, ``apps/procs.py`` + ``apps/health.py``)
runs one OS process per replica on its own socket, replaces
:meth:`ReplicaSet.kill` with missed-beat failure detection + fencing
epochs, and reuses this module's :class:`HashRing` for the
client-side tenant ring.
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
from bisect import bisect_right
from typing import Dict, List, Optional

from ..bitcoin.message import Message, MsgType, new_request
from ..lsp.errors import LspError
from ..utils._env import int_env as _int_env
from ..utils.config import CacheParams
from .scheduler import ResultCache, Scheduler

logger = logging.getLogger("dbm.replicas")

__all__ = ["HashRing", "ReplicaSet", "replicas_from_env"]


def replicas_from_env() -> int:
    """``DBM_REPLICAS`` (default 1 = the plain single scheduler)."""
    return max(1, _int_env("DBM_REPLICAS", 1))


class HashRing:
    """Consistent hash ring over replica ids.

    ``VNODES`` virtual points per replica (SHA-256 of ``"r{id}:{v}"``)
    smooth the partition; a key maps to the first point clockwise.
    Adding or removing one replica moves only the key ranges adjacent
    to its points — ~1/N of tenants — and every key not owned by the
    changed replica keeps its owner (the stability contract the
    takeover path and the plane-split tests rely on).
    """

    VNODES = 64

    def __init__(self, replica_ids: List[int]):
        self.replica_ids = list(replica_ids)
        points = []
        for rid in self.replica_ids:
            for v in range(self.VNODES):
                points.append((self._point(f"r{rid}:{v}"), rid))
        points.sort()
        self._hashes = [h for h, _ in points]
        self._owners = [rid for _, rid in points]

    @staticmethod
    def _point(key: str) -> int:
        return int.from_bytes(
            hashlib.sha256(key.encode()).digest()[:8], "big")

    def owner(self, key) -> int:
        """The replica id owning ``key`` (any hashable; conn ids here)."""
        if not self._hashes:
            raise ValueError("empty ring")
        h = self._point(f"t:{key}")
        i = bisect_right(self._hashes, h)
        if i == len(self._hashes):
            i = 0
        return self._owners[i]


class ReplicaSet:
    """N scheduler replicas behind one transport (see module docstring).

    Owns the read loop: classifies each conn (JOIN ⇒ miner, routed to
    the thinnest slice; anything else ⇒ tenant, routed by the ring) and
    feeds the owning replica's event handlers directly. Each replica
    runs its own sweep task at its lease tick.
    """

    #: Disjoint job-id range per replica (see module docstring).
    JOB_ID_STRIDE = 1 << 40

    def __init__(self, server, n: Optional[int] = None, *,
                 lease=None, cache: Optional[CacheParams] = None,
                 stripe=None, qos=None, coalesce=None, adapt=None,
                 verify=None, clock=None,
                 recv_batch: Optional[int] = None,
                 trace_sample: Optional[float] = None,
                 capture=None):
        self.server = server
        self.n = n if n is not None else replicas_from_env()
        cache = cache if cache is not None else CacheParams()
        #: The shared replay tier (None when caching is disabled).
        self.shared_cache: Optional[ResultCache] = (
            ResultCache(cache.size) if cache.enabled else None)
        self.replicas: Dict[int, Scheduler] = {}
        for rid in range(self.n):
            sched = Scheduler(
                server, lease=lease, cache=cache, stripe=stripe, qos=qos,
                coalesce=coalesce, adapt=adapt, verify=verify,
                clock=clock,
                result_cache=self.shared_cache, recv_batch=recv_batch,
                trace_sample=trace_sample, capture=capture)
            sched._next_job_id = rid * self.JOB_ID_STRIDE
            self.replicas[rid] = sched
        self.live: List[int] = list(range(self.n))
        self._miner_owner: Dict[int, int] = {}
        # Sticky tenant routing (found in a live drive): the hash ring
        # spans SERVING replicas — live AND holding at least one miner —
        # so a pool smaller than the replica count cannot strand
        # tenants on a miner-less replica (their requests would queue
        # into the age alarm forever while capacity sat idle next
        # door). The serving set changes as miners join/drop, so a
        # tenant's owner is PINNED at first request (per-tenant FIFO
        # must stay on one replica) and re-resolved only when its
        # replica leaves the live set; the pin map is GC'd against the
        # owners' active-tenant state so dead conn ids cannot grow it
        # without bound.
        self._tenant_owner: Dict[int, int] = {}
        self._serving: Optional[List[int]] = None
        self._route_ring: Optional[HashRing] = None
        self._routes_since_gc = 0
        self._sweep_tasks: Dict[int, asyncio.Task] = {}
        self._recv_batch = max(1, recv_batch if recv_batch is not None
                               else _int_env("DBM_RECV_BATCH", 64))
        self._read_nowait = getattr(server, "read_nowait", None)
        # Federation (ISSUE 20): repeat JOINs route to the existing
        # owner replica (the gateway rate-hint refresh). Same knob and
        # construction-time read as Scheduler's.
        self._gateway = _int_env("DBM_GATEWAY", 1) != 0

    # ------------------------------------------------------------- routing

    #: Tenant-pin map GC cadence, in REQUEST routes.
    ROUTE_GC_EVERY = 4096

    @property
    def ring(self) -> HashRing:
        """The current routing ring (serving replicas; see
        :meth:`owner_of`)."""
        return self._routing_ring()

    def _routing_ring(self) -> HashRing:
        # No miners ANYWHERE: route every tenant to the FIRST live
        # replica — the same replica the next JOIN will land on (the
        # thinnest-slice rule breaks ties by live order), so pre-miner
        # pins point exactly where capacity will first appear instead
        # of scattering tenants onto replicas that may stay minerless
        # (code review: an all-live fallback ring stranded tenants
        # pinned before the first JOIN).
        serving = [rid for rid in self.live
                   if self.replicas[rid].miners] or [self.live[0]]
        if serving != self._serving:
            self._serving = serving
            self._route_ring = HashRing(serving)
        return self._route_ring

    def owner_of(self, conn_id: int) -> Scheduler:
        """The replica owning tenant ``conn_id``: its sticky pin, or a
        fresh consistent-hash over the serving replicas."""
        rid = self._tenant_owner.get(conn_id)
        if rid is None or rid not in self.live:
            rid = self._routing_ring().owner(conn_id)
            self._tenant_owner[conn_id] = rid
        return self.replicas[rid]

    def _gc_tenant_pins(self) -> None:
        """Prune pins whose tenant holds NO state on its owner (not a
        QoS tenant, nothing queued, nothing in flight): shed conns get
        no drop event, so without this the pin map would grow one entry
        per conn over the server's life."""
        active: Dict[int, set] = {}
        for rid in self.live:
            sched = self.replicas[rid]
            conns = set(sched.qos_plane.tenants)
            conns.update(r.conn_id for r in sched.tenant_plane.queue)
            conns.update(r.conn_id for r in sched._inflight.values())
            active[rid] = conns
        self._tenant_owner = {
            conn: rid for conn, rid in self._tenant_owner.items()
            if rid in active and conn in active[rid]}

    def route(self, conn_id: int, payload) -> None:
        """Feed one transport item to the owning replica."""
        if isinstance(payload, Exception):
            rid = self._miner_owner.pop(conn_id, None)
            if rid is not None:
                if rid in self.live:
                    self.replicas[rid]._on_drop(conn_id)
            else:
                self.owner_of(conn_id)._on_drop(conn_id)
                self._tenant_owner.pop(conn_id, None)
            return
        try:
            msg = Message.from_json(payload)
        except ValueError:
            return
        if msg.type == MsgType.JOIN:
            # Repeat JOIN from a conn a live replica already owns as a
            # miner (ISSUE 20, DBM_GATEWAY): a rate-hint refresh, routed
            # to the existing owner — re-running the thinnest-slice pick
            # would register the same gateway on a SECOND replica.
            rid = self._miner_owner.get(conn_id)
            if self._gateway and rid is not None and rid in self.live:
                self.replicas[rid]._on_join(conn_id, msg)
                return
            # Thinnest live slice takes the new miner.
            rid = min(self.live,
                      key=lambda r: len(self.replicas[r].miners))
            self._miner_owner[conn_id] = rid
            self.replicas[rid]._on_join(conn_id, msg)
        elif msg.type == MsgType.RESULT:
            rid = self._miner_owner.get(conn_id)
            if rid is not None and rid in self.live:
                self.replicas[rid]._on_result(conn_id, msg)
        elif msg.type == MsgType.REQUEST:
            self.owner_of(conn_id)._on_request(conn_id, msg)
            self._routes_since_gc += 1
            if self._routes_since_gc >= self.ROUTE_GC_EVERY:
                self._routes_since_gc = 0
                self._gc_tenant_pins()

    # ------------------------------------------------------------ lifecycle

    async def run(self) -> None:
        """Serve until the transport closes: ONE read loop (batched like
        the single scheduler's), N replica sweeps."""
        loop = asyncio.get_running_loop()
        for rid in self.live:
            self._sweep_tasks[rid] = loop.create_task(
                self._sweep_loop(self.replicas[rid]))
        try:
            while True:
                try:
                    conn_id, payload = await self.server.read()
                except LspError:
                    return
                self.route(conn_id, payload)
                if self._recv_batch > 1 and self._read_nowait is not None:
                    for _ in range(self._recv_batch - 1):
                        item = self._read_nowait()
                        if item is None:
                            break
                        self.route(item[0], item[1])
        finally:
            for task in self._sweep_tasks.values():
                task.cancel()

    async def _sweep_loop(self, sched: Scheduler) -> None:
        while True:
            await asyncio.sleep(sched.lease.tick_s)
            try:
                sched.sweep()
            except Exception:   # noqa: BLE001 — a sweep must never die
                logger.exception("replica sweep failed; continuing")

    def kill(self, rid: int) -> None:
        """Replica death + lease takeover (tests and the dbmcheck
        ``replica_takeover`` scenario drive this; a production
        multi-process tier would trigger it from a health check).

        Order matters: miners are adopted FIRST (the survivors need the
        capacity), then the dead replica's queued and in-flight requests
        are re-served through the new ring owners. Exactly-once: the
        dead replica never replied to a request still in its queue or
        in-flight set, and a request it DID finish replays from the
        shared ResultCache wherever its tenant re-hashes."""
        if rid not in self.live:
            raise ValueError(f"replica {rid} is not live")
        dead = self.replicas[rid]
        self.live.remove(rid)
        if not self.live:
            self.live.append(rid)
            raise ValueError("cannot kill the last live replica")
        # Invalidate routing state: the serving ring rebuilds lazily,
        # and the dead replica's tenant pins re-resolve on next use.
        self._serving = None
        self._tenant_owner = {c: r for c, r in self._tenant_owner.items()
                              if r != rid}
        task = self._sweep_tasks.pop(rid, None)
        if task is not None:
            task.cancel()
        # Adopt the dead replica's miners, thinnest surviving slice
        # first. Their pending chunk records ride along CANCELLED so
        # in-flight answers pop in order as stale on the adopter.
        adopted = 0
        for conn_id, owner in list(self._miner_owner.items()):
            if owner != rid:
                continue
            target = min(self.live,
                         key=lambda r: len(self.replicas[r].miners))
            miner = dead.miner_plane.find_miner(conn_id)
            self.replicas[target].miner_plane.adopt_miner(
                conn_id,
                pending=list(miner.pending) if miner else None,
                rate_ewma=miner.rate_ewma if miner else None)
            self._miner_owner[conn_id] = target
            adopted += 1
        # Re-serve the dead replica's unanswered requests through the
        # new ring owners — via reserve_request, which charges NO
        # admission token and triggers no overload shed (this work was
        # already admitted once; a failover must not convert it into
        # sheds). A dispatched request's ``upper`` was already made
        # exclusive (+1 at load_balance) — undo it for the wire.
        reserved = 0
        for req in list(dead._inflight.values()) + dead.queue:
            upper = req.upper - 1 if req.qos_mode else req.upper
            target = self.owner_of(req.conn_id)
            target.reserve_request(req.conn_id, new_request(
                req.data, req.lower, upper, req.target))
            reserved += 1
        logger.warning(
            "replica %d killed: %d miner(s) adopted, %d request(s) "
            "re-served across %d survivor(s)", rid, adopted, reserved,
            len(self.live))
        # Wake the survivors: adopted capacity may unblock queued work.
        for r in self.live:
            self.replicas[r]._maybe_dispatch()

    # ------------------------------------------------ aggregate views

    @property
    def stats(self) -> dict:
        """Counter totals over EVERY replica (dead included — their
        served requests happened)."""
        out: dict = {}
        for sched in self.replicas.values():
            for k, v in sched.stats.items():
                out[k] = out.get(k, 0) + v
        return out

    def cluster_rollup(self) -> dict:
        """Merged metrics snapshot over EVERY replica's registry, via
        the rollup plane's merge semantics (ISSUE 18) — the in-process
        analog of ``apps/rollup.aggregate`` over published blobs, and
        the live surface the counter-sum-equals-parts test pins against
        :attr:`stats`."""
        from .rollup import merge_snapshots
        return merge_snapshots(
            (f"replica{rid}", sched.metrics.snapshot())
            for rid, sched in sorted(self.replicas.items()))

    @property
    def queue(self) -> list:
        """Queued requests across live replicas (harness/invariant
        view)."""
        return [r for rid in self.live for r in self.replicas[rid].queue]

    @property
    def _inflight(self) -> dict:
        return {job: req for rid in self.live
                for job, req in self.replicas[rid]._inflight.items()}

    @property
    def qos_plane(self):
        return _MergedQos([self.replicas[rid] for rid in self.live])

    @property
    def traces(self):
        return _MergedTraces([self.replicas[rid] for rid in self.live])


class _MergedQos:
    """Read-only merged view of live replicas' QoS planes (the dbmcheck
    accounting invariant iterates ``tenants``)."""

    def __init__(self, scheds):
        self.tenants: dict = {}
        for sched in scheds:
            self.tenants.update(sched.qos_plane.tenants)


class _MergedTraces:
    """Read-only merged view of live replicas' trace buffers (the
    span-closure invariant iterates ``items()``)."""

    def __init__(self, scheds):
        self._scheds = scheds

    def items(self):
        out = []
        for sched in self._scheds:
            out.extend(sched.traces.items())
        return out

    def get(self, key):
        for sched in self._scheds:
            hit = sched.traces.get(key)
            if hit is not None:
                return hit
        return None
