"""Scheduler federation — the GatewayMiner (ISSUE 20).

PAPER.md's plugin-boundary thesis ("a TPU pod registers as one very
wide miner") recursed one level: a **GatewayMiner** JOINs a *parent*
scheduler as ONE miner whose rate hint is the summed rate EWMAs of its
downstream pool, and re-shards every granted chunk through a stock
*inner* :class:`~.scheduler.Scheduler` (or
:class:`~.replicas.ReplicaSet`) running verbatim behind it. Chunks are
already contiguous nonce windows with exactly-once lease semantics, so
the parent sees leases, stripes, QoS, claim checks, audits and spans
exactly as it sees any miner today — **zero wire change** — and pools
compose into a tree (the PNPCoin fan-in story: no single scheduler
holds all tenant state, and fault domains nest).

Topology (one gateway shown; any number JOIN the same parent)::

    tenants ──▶ parent Scheduler ──▶ GatewayMiner (JOIN rate=Σ pool)
                      │                   │   ▲
                      ▼                   ▼   │ (bridge = one tenant
                other miners        inner Scheduler   conn, FIFO)
                                          │
                                          ▼
                                    child miners (stock, any tier)

Design rules, each load-bearing:

- **Op-blind, kernel-free**: the gateway never computes a hash. It
  brokers wire messages; the inner tier's miners own the ``SearchOp``
  seam (PR 19), so a new search op needs zero gateway changes.
- **Grant translation**: each parent REQUEST (a chunk grant, argmin or
  difficulty) is resubmitted verbatim — same data/range/target — as a
  tenant request on ONE long-lived *bridge* conn into the inner tier.
  The inner scheduler preserves the argmin strict-less barrier and the
  difficulty prefix-release internally and replies with the exact
  merged result for the window, which is precisely what the parent
  expects from a miner for that chunk.
- **In-order upward forwarding** (the PR 4 pipelined-executor
  contract: the k-th Result on a conn answers the k-th Request): the
  inner tier's per-tenant FIFO reply discipline guarantees bridge
  replies arrive in bridge-request order, and bridge requests are
  submitted in parent-grant order, so popping the pending FIFO head
  per bridge reply and writing it upward preserves the contract with
  no reordering buffer. If the bridge conn dies (inner shed closes the
  conn; transport death), the gateway reconnects and resubmits every
  unanswered pending IN ORDER — the replacement conn restarts the same
  FIFO, and the inner result cache replays already-finished windows.
- **Difficulty echo**: the forwarded Result echoes the grant's target
  (the stock miner's "until mode ran" marker): the inner tier's
  prefix-release yields the window-FIRST qualifying nonce, else the
  exact argmin, matching the echo's contract. Caveat (documented, not
  defended): if the inner merge itself was WEAK — a child answered
  without the target extension — the gateway still echoes, claiming
  window-first for a merely-qualifying nonce; the parent's own weak
  grading covers direct miners, and a weak inner subtree is the child
  cluster operator's configuration to fix.
- **Liveness = inner health**: the gateway delays its parent JOIN
  until ``min_pool`` inner miners exist, refreshes its rate hint every
  ``hint_s`` when the pool sum moves >= ~10% (a repeat JOIN over the
  existing ``Rate`` extension — ``DBM_GATEWAY`` teaches the parent to
  absorb it in place), and an *orphan watchdog* closes the parent conn
  when the inner pool stays EMPTY for ``orphan_s`` with grants
  pending: a fenced/failed child cluster becomes ONE blown lease (plus
  a drop) at the parent, recovered by the stock re-issue plane with no
  federation-aware code above.

Everything here is purely async on the ambient loop — no threads — so
the deterministic explorer (analysis/schedcheck) schedules the gateway
like any other actor, and the whole two-level topology runs under the
full invariant pack (the ``federation`` scenario).

Process deployment: ``python -m distributed_bitcoinminer_tpu.apps.gateway
<parent_hostport> [inner_port]`` (or the ``procs gateway`` role, which
adds health beats + rollup identity) owns an inner LSP server + stock
scheduler and bridges to it over localhost.
"""

from __future__ import annotations

import asyncio
import logging
from collections import deque
from typing import Awaitable, Callable, Deque, Optional

from ..bitcoin.message import (Message, MsgType, new_join, new_request,
                               new_result)
from ..lsp.params import Params
from ..utils.config import GatewayParams, gateway_from_env
from .miner_plane import MinerPlane

logger = logging.getLogger("dbm.gateway")

__all__ = ["GatewayMiner", "aggregate_rate_hint", "serve", "main"]


def aggregate_rate_hint(scheds) -> float:
    """Pool-summed rate hint (nonces/s) over one or more inner
    schedulers: the rate EWMAs of every non-quarantined inner miner
    (hinted-but-unconfirmed EWMAs count — they are the pool's best
    estimate and decay on their own), clamped to the same
    ``RATE_HINT_CAP`` the parent clamps at so an absurd sum is bounded
    at both ends of the wire. Cold miners (no EWMA yet) contribute 0 —
    a wholly-cold pool advertises no hint and the parent falls back to
    stock cold-EWMA seeding."""
    total = 0.0
    for sched in scheds:
        for m in sched.miner_plane.miners:
            if m.quarantined:
                continue
            total += m.rate_ewma or 0.0
    return min(total, MinerPlane.RATE_HINT_CAP)


class _Pending:
    """One parent grant awaiting its inner-tier result (FIFO order)."""

    __slots__ = ("msg",)

    def __init__(self, msg: Message):
        self.msg = msg


class GatewayMiner:
    """One federated miner: parent-facing conn + inner-tier bridge.

    ``parent_connect`` / ``bridge_connect`` are async callables
    returning an AsyncClient-shaped channel (async ``read()``, sync
    ``write(payload)``, async ``close()``): :func:`~..lsp.client.
    new_async_client` bound to a hostport in production, a
    ``DetServer.connect`` wrapper under dbmcheck/tests. ``inner_scheds``
    are the in-process inner scheduler(s) whose pool this gateway
    advertises (rate sum + size; the replica tier passes its replicas).

    :meth:`run` is ONE parent-conn lifetime — it returns when the
    parent conn dies or the orphan watchdog fires, closing the bridge
    so the inner tier cancels the gateway's tenant state;
    :meth:`run_forever` is the production rejoin loop.
    """

    def __init__(self, parent_connect: Callable[[], Awaitable],
                 bridge_connect: Callable[[], Awaitable],
                 inner_scheds, *,
                 params: Optional[GatewayParams] = None,
                 poll_s: float = 0.05, backoff_s: float = 0.5,
                 name: str = "gateway"):
        self.parent_connect = parent_connect
        self.bridge_connect = bridge_connect
        self.inner_scheds = list(inner_scheds)
        self.params = params if params is not None else gateway_from_env()
        self.poll_s = poll_s
        self.backoff_s = backoff_s
        self.name = name
        self._pending: Deque[_Pending] = deque()
        self._parent = None
        self._bridge = None
        self._last_hint = 0.0
        # Introspection counters (procsmoke, bench, tests).
        self.grants_taken = 0
        self.results_forwarded = 0
        self.hint_refreshes = 0
        self.orphan_drops = 0

    # ------------------------------------------------------------ pool view

    def pool_size(self) -> int:
        """Grant-eligible inner miners (non-quarantined)."""
        return sum(1 for sched in self.inner_scheds
                   for m in sched.miner_plane.miners if not m.quarantined)

    def rate_hint(self) -> float:
        return aggregate_rate_hint(self.inner_scheds)

    # ------------------------------------------------------------ lifecycle

    async def run(self) -> None:
        """One parent-conn lifetime (see class docstring)."""
        while self.pool_size() < self.params.min_pool:
            await asyncio.sleep(self.poll_s)
        self._pending.clear()
        self._parent = await self.parent_connect()
        tasks = []
        try:
            self._bridge = await self.bridge_connect()
            self._last_hint = self.rate_hint()
            self._parent.write(
                new_join(rate=int(self._last_hint)).to_json())
            logger.info("%s joined parent as one miner "
                        "(pool=%d, hint %.3g nonces/s)",
                        self.name, self.pool_size(), self._last_hint)
            tasks = [asyncio.ensure_future(c) for c in (
                self._parent_loop(), self._bridge_loop(),
                self._hint_loop(), self._orphan_loop())]
            done, _ = await asyncio.wait(
                tasks, return_when=asyncio.FIRST_COMPLETED)
            for t in done:
                exc = t.exception()
                if exc is not None:
                    # Transport death (parent or unrecoverable bridge):
                    # normal federation weather — the conn teardown
                    # below is the recovery, stock re-issue upstream.
                    logger.info("%s conn ended: %r", self.name, exc)
        finally:
            for t in tasks:
                t.cancel()
            if tasks:
                await asyncio.gather(*tasks, return_exceptions=True)
            await self._close_all()

    async def run_forever(self) -> None:
        """Production rejoin loop (the MinerWorker idiom): every parent
        death or orphan drop is followed by a fresh :meth:`run` after
        ``backoff_s`` — the gateway re-registers as a brand-new miner
        conn and the parent re-seeds it from its next JOIN hint."""
        while True:
            try:
                await self.run()
            except asyncio.CancelledError:
                raise
            except Exception:   # noqa: BLE001 — rejoin loop must survive
                logger.exception("%s run() failed; rejoining", self.name)
            await asyncio.sleep(self.backoff_s)

    async def _close_all(self) -> None:
        # Bridge FIRST: closing it is what cancels the gateway's tenant
        # state inside the inner tier (spans close, chunks recovered).
        for chan in (self._bridge, self._parent):
            if chan is None:
                continue
            try:
                await chan.close()
            except Exception:  # noqa: BLE001 — conn may already be dead
                pass
        self._bridge = None
        self._parent = None
        self._pending.clear()

    # ------------------------------------------------------------- datapath

    def _submit(self, pend: _Pending) -> None:
        # Bound-quirk translation: a miner grant carries an EXCLUSIVE
        # upper that miners scan INCLUSIVELY (ref miner.go:51-52), i.e.
        # the granted set is [lower, upper]; a tenant request's upper
        # is inclusive-on-arrival and the system scans [lower, upper+1].
        # Submitting upper-1 makes the inner tier scan exactly the
        # granted set — verbatim forwarding would scan one EXTRA nonce,
        # and an argmin landing there fails the parent's claim check.
        # (A one-nonce grant, upper == lower, floors at upper == lower:
        # the inner tier scans one extra nonce and a quirk-nonce argmin
        # re-executes off the claim-retry path — rare and safe.)
        msg = pend.msg
        self._bridge.write(new_request(
            msg.data, msg.lower, max(msg.lower, msg.upper - 1),
            msg.target).to_json())

    async def _parent_loop(self) -> None:
        """Parent grants -> pending FIFO -> inner-tier requests."""
        while True:
            payload = await self._parent.read()
            try:
                msg = Message.from_json(payload)
            except ValueError:
                continue
            if msg.type != MsgType.REQUEST:
                continue
            pend = _Pending(msg)
            self._pending.append(pend)
            self.grants_taken += 1
            try:
                self._submit(pend)
            except Exception:  # noqa: BLE001 — bridge mid-death
                # Leave it pending: the bridge loop's read failure
                # drives reconnection, which resubmits the FIFO.
                pass

    async def _bridge_loop(self) -> None:
        """Inner results -> pending FIFO head -> parent, in order."""
        while True:
            try:
                payload = await self._bridge.read()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — shed/close/transport death
                await self._bridge_reconnect()
                continue
            try:
                msg = Message.from_json(payload)
            except ValueError:
                continue
            if msg.type != MsgType.RESULT or not self._pending:
                continue
            pend = self._pending.popleft()
            # Echo the grant's target — the "until mode ran" marker a
            # stock miner sets (weak-subtree caveat: module docstring).
            self._parent.write(new_result(
                msg.hash, msg.nonce, pend.msg.target).to_json())
            self.results_forwarded += 1

    async def _bridge_reconnect(self) -> None:
        """Fresh bridge conn + in-order resubmission of every
        unanswered pending. The old conn's requests died with it inside
        the inner tier (tenant drop cancels them); the replacement conn
        starts a fresh per-tenant FIFO, so resubmitting the pendings in
        FIFO order re-establishes the k-th-reply-answers-k-th-grant
        mapping exactly. Already-finished windows replay from the inner
        result cache."""
        old, self._bridge = self._bridge, None
        if old is not None:
            try:
                await old.close()
            except Exception:  # noqa: BLE001 — already dead
                pass
        while True:
            try:
                self._bridge = await self.bridge_connect()
                break
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — inner tier restarting
                await asyncio.sleep(self.backoff_s)
        if self._pending:
            logger.info("%s bridge reconnected; resubmitting %d "
                        "unanswered grant(s) in order", self.name,
                        len(self._pending))
        for pend in self._pending:
            try:
                self._submit(pend)
            except Exception:  # noqa: BLE001 — died again already
                break   # the next read failure reconnects once more

    # ------------------------------------------------------------- liveness

    async def _hint_loop(self) -> None:
        """Periodic pool-sum refresh: a repeat JOIN over the stock Rate
        extension whenever the aggregate moved >= ~10% (or flipped
        between zero and nonzero) — chatty enough for the parent's
        stripe planner to track child churn, quiet enough to stay
        invisible next to grant traffic."""
        while True:
            await asyncio.sleep(self.params.hint_s)
            hint = self.rate_hint()
            last = self._last_hint
            moved = ((hint <= 0) != (last <= 0)
                     or (last > 0 and abs(hint - last) / last >= 0.10))
            if not moved:
                continue
            self._last_hint = hint
            self._parent.write(new_join(rate=int(hint)).to_json())
            self.hint_refreshes += 1

    async def _orphan_loop(self) -> None:
        """Orphan watchdog: an EMPTY inner pool sitting on pending
        grants for ``orphan_s`` means this gateway can only let the
        parent's leases rot — returning ends :meth:`run`, the conn
        teardown surfaces as one drop + blown lease(s) at the parent,
        and the stock re-issue plane re-grants the chunks to siblings
        immediately instead of at lease expiry."""
        loop = asyncio.get_running_loop()
        empty_since: Optional[float] = None
        while True:
            await asyncio.sleep(self.poll_s)
            if self.pool_size() > 0 or not self._pending:
                empty_since = None
                continue
            now = loop.time()
            if empty_since is None:
                empty_since = now
            elif now - empty_since >= self.params.orphan_s:
                self.orphan_drops += 1
                logger.warning(
                    "%s: inner pool empty for %.1fs with %d grant(s) "
                    "pending; dropping parent conn for stock re-issue",
                    self.name, now - empty_since, len(self._pending))
                return


async def serve(parent_hostport: str, inner_port: int = 0,
                params: Optional[Params] = None,
                gateway: Optional[GatewayParams] = None) -> None:
    """Process entry: inner LSP server + stock env-configured scheduler
    + one :class:`GatewayMiner` bridging to it over localhost. Child
    miners point at the printed inner port exactly as they would at a
    flat scheduler."""
    from ..lsp.client import new_async_client
    from ..lsp.server import new_async_server
    from .scheduler import Scheduler

    gw_params = gateway if gateway is not None else gateway_from_env()
    if not gw_params.enabled:
        raise RuntimeError("DBM_GATEWAY=0: the gateway role is disabled "
                           "(flat topology pin)")
    lsp = params or Params()
    server = await new_async_server(inner_port, lsp)
    print("Gateway inner tier listening on port", server.port, flush=True)
    sched = Scheduler(server)
    inner_hostport = f"127.0.0.1:{server.port}"
    gw = GatewayMiner(
        parent_connect=lambda: new_async_client(parent_hostport, lsp),
        bridge_connect=lambda: new_async_client(inner_hostport, lsp),
        inner_scheds=[sched], params=gw_params)
    try:
        await asyncio.gather(sched.run(), gw.run_forever())
    finally:
        await server.close()


def main(argv: Optional[list] = None) -> int:
    import sys
    argv = sys.argv if argv is None else argv
    if len(argv) not in (2, 3):
        print(f"Usage: ./{argv[0]} <parent_hostport> [inner_port]")
        return 1
    inner_port = 0
    if len(argv) == 3:
        try:
            inner_port = int(argv[2])
        except ValueError as exc:
            print("Inner port must be a number:", exc)
            return 1
    from ..utils import configure_logging, ensure_emitter, from_env
    configure_logging(logging.INFO, logfile="log.txt")
    ensure_emitter()
    cfg = from_env()
    try:
        asyncio.run(serve(argv[1], inner_port, cfg.params))
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
