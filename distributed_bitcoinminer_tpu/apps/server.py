"""Scheduler server CLI — same contract as the reference binary.

Usage: ``python -m distributed_bitcoinminer_tpu.apps.server <port>``
(ref: bitcoin/server/server.go:430-472; prints "Server listening on port N").
"""

from __future__ import annotations

import asyncio
import logging
import sys

from ..lsp.params import Params
from ..lsp.server import new_async_server
from ..utils.config import CacheParams, LeaseParams, QosParams, StripeParams
from .scheduler import Scheduler


async def serve(port: int, params: Params | None = None,
                lease: LeaseParams | None = None,
                cache: CacheParams | None = None,
                stripe: StripeParams | None = None,
                qos: QosParams | None = None,
                replicas: int | None = None) -> None:
    server = await new_async_server(port, params or Params())
    print("Server listening on port", server.port, flush=True)
    # Replica tier (ISSUE 11): DBM_REPLICAS>1 shards tenants by
    # consistent hash across N in-process scheduler replicas, each
    # owning a miner-pool slice, with one shared ResultCache replay
    # tier. The default (1) is the plain single scheduler — today's
    # topology bit-for-bit.
    from .replicas import ReplicaSet, replicas_from_env
    n = replicas if replicas is not None else replicas_from_env()
    if n > 1:
        coordinator = ReplicaSet(server, n, lease=lease, cache=cache,
                                 stripe=stripe, qos=qos)
    else:
        coordinator = Scheduler(server, lease=lease, cache=cache,
                                stripe=stripe, qos=qos)
    try:
        await coordinator.run()
    finally:
        await server.close()


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv if argv is None else argv
    if len(argv) != 2:
        print(f"Usage: ./{argv[0]} <port>", end="")
        return 1
    try:
        port = int(argv[1])
    except ValueError as exc:
        print("Port must be a number:", exc)
        return 1
    from ..utils import configure_logging, ensure_emitter, from_env
    configure_logging(logging.INFO, logfile="log.txt")
    # Periodic metrics snapshot lines into the same log (DBM_METRICS_*).
    ensure_emitter()
    cfg = from_env()
    try:
        asyncio.run(serve(port, cfg.params, cfg.lease, cfg.cache,
                          cfg.stripe, cfg.qos))
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
