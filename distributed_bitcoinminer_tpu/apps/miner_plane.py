"""Miner plane: pool membership, leases, striping, dispatch execution.

One half of the ISSUE 11 plane split. ``apps/scheduler.py`` grew ~9 PRs
of features into one 1.8k-line class; this module owns everything
MINER-FACING — the pool roster and per-miner pending FIFOs, the lease
plane (EWMA-sized leases, speculative re-issue, quarantine, the
position-aware FIFO clock), the stripe planner, parked-chunk recovery,
the windowed throughput sampler and pool EWMA, the QoS capacity pool,
and the coalescing-window slot logic — while ``apps/tenant_plane.py``
owns the tenant-facing half and the :class:`~.scheduler.Scheduler`
keeps only the request state machine (merge rules, barriers) and the
pump that joins the two.

The planes are joined by an EXPLICIT internal interface, so each side
is independently testable (tests/test_plane_split.py drives this plane
with stub callbacks) and replicable (apps/replicas.py instantiates N
scheduler replicas, each owning a miner-pool slice):

- **grant** — :meth:`MinerPlane.assign_chunk`: the scheduler (having
  decided WHO via the tenant plane's DRR) hands one chunk to one miner;
  the plane stamps the lease, appends to the miner's pending FIFO, and
  writes the wire Request through the injected ``write`` callback.
- **complete** — :meth:`MinerPlane.pop_result`: an arriving Result pops
  the miner's oldest pending chunk (in-order exactly-once LSP makes the
  k-th Result answer the k-th Request), feeds the throughput window,
  starts the next chunk's lease, absorbs parked work — and returns the
  ``(miner, chunk)`` pair for the scheduler to MERGE. The plane never
  touches merge state.
- **lease-event** — the injected ``lease_event(kind, chunk, miner, ...)``
  callback: every lease-plane state transition (``blown``, ``reissue``,
  ``quarantine``, ``quarantine_lifted``, ``park``) is reported upward
  for the scheduler's trace/flight/log fanout, keeping observability
  (tenant-plane concern) out of the mechanics. Events fire in
  transition order — ``blown`` strictly before any ``reissue`` of the
  same chunk, ``quarantine`` only after its triggering ``blown``.

State here is exactly the miner-side slice the old monolith kept:
``miners`` (join order), ``parked``, the pool-rate EWMA, and the metric
series those feed. The scheduler re-exports ``Chunk``/``MinerState``
for compatibility.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..bitcoin.message import new_request
from ..utils import trace as _tracing
from ..utils.config import (CoalesceParams, LeaseParams, StripeParams,
                            VerifyParams)
from ..utils.metrics import LATENCY_BUCKETS_S, OCCUPANCY_BUCKETS, Registry

logger = logging.getLogger("dbm.scheduler")

__all__ = ["Chunk", "MinerState", "MinerPlane"]


@dataclass
class Chunk:
    job_id: int
    data: str
    lower: int
    upper: int              # exclusive end, as sent on the wire
    target: int = 0         # difficulty target; rides every (re)assignment
    idx: int = 0            # position in the request's ascending chunk order
    # Set when the requesting client drops: the chunk stays in the miner's
    # pending FIFO (its Result must still pop in order) but no longer
    # counts against the miner's availability.
    cancelled: bool = False
    # Lease plane. Each FIFO entry is one ASSIGNMENT: a speculative
    # re-issue pushes a fresh Chunk object (same job/idx/range) onto the
    # takeover miner's FIFO with its own lease, while the blown original
    # stays in its miner's FIFO awaiting the in-order pop.
    assigned_at: float = 0.0   # monotonic stamp; reset when the lease starts
    deadline: float = 0.0      # lease expiry (monotonic)
    # Position-aware lease clock (fifo_aware): False until the chunk
    # reaches the head of its miner's FIFO. Until then the deadline is a
    # BUDGET covering the predecessors too; at the head it is re-stamped
    # to the tight single-chunk lease.
    lease_started: bool = False
    lease_blown: bool = False  # expiry observed (counted once per entry)
    reissued: bool = False     # a speculative copy is already in flight
    # Coalescing grant hint (ISSUE 9): chunks sharing a coalesce_id were
    # granted into one miner's coalescing window — they may share a
    # device launch, and they count as ONE live chunk against the QoS
    # depth cap (miner_live). None = stock accounting. A speculative
    # re-issue copy never inherits the id (fresh Chunk): the takeover
    # miner runs it solo.
    coalesce_id: Optional[int] = None

    @property
    def size(self) -> int:
        """Nonce count the miner actually scans (``Upper`` read inclusive —
        the reference bound quirk, see the scheduler module docstring)."""
        return self.upper - self.lower + 1


@dataclass
class MinerState:
    conn_id: int
    # Every Request written to this miner, in write order (see the
    # scheduler module docstring's bookkeeping-divergence note).
    pending: list = field(default_factory=list)
    # Lease plane: observed per-chunk throughput (nonces/sec EWMA; None
    # until the first Result), consecutive blown leases, and the
    # quarantine latch (set at quarantine_after blown leases, cleared by
    # any Result pop from this miner).
    rate_ewma: Optional[float] = None
    blown_streak: int = 0
    quarantined: bool = False
    # Rate-hint JOIN (ISSUE 14): True while rate_ewma holds the miner's
    # OWN (bounded, decaying) claim rather than an observed sample; the
    # first real throughput window REPLACES the hint instead of
    # blending with it.
    rate_hinted: bool = False
    # Verification tier (ISSUE 16): reputation score in
    # [trust_floor, 1.0]. Starts at full trust (the score only matters
    # once a miner MISBEHAVES), multiplies by trust_decay per
    # claim/audit failure, steps back by trust_recover per confirmed
    # pop. Below VerifyParams.trust_bar the miner is ineligible for
    # new grants exactly like a quarantined one; trust also weights
    # striping share and clamps the unauthenticated JOIN rate hint's
    # influence. Never moves off 1.0 while verification is off, so the
    # stock paths that read it see the identity weight.
    trust: float = 1.0
    # Windowed throughput sampling (ISSUE 5; see observe_result): the
    # wall-clock window currently accumulating answered nonces. Per-pop
    # size/elapsed sampling is a lie under the pipelined miner — a
    # prefetched chunk's Result lands ~1ms after its lease re-stamp and
    # reads as 10^9 nonces/s.
    win_t0: float = 0.0
    win_nonces: int = 0

    @property
    def available(self) -> bool:
        """Derived, not stored (ADVICE r2): a miner is available iff it has
        no LIVE pending chunk. Cancelled chunks still occupy the FIFO (their
        stale Results pop in order) without blocking new assignments."""
        return not any(not c.cancelled for c in self.pending)


class MinerPlane:
    """The miner-facing half of the scheduler (see module docstring).

    Injected callbacks (the internal interface's upward edges):

    - ``write(conn_id, msg)`` — wire write (the scheduler's LSP write
      with its awaiting-drop error swallow);
    - ``inflight`` — the scheduler's live ``{job_id: Request}`` mapping
      (read-only here: the sweep skips answered chunks, recovery skips
      retired jobs);
    - ``trace_get(job_id)`` — the request trace to record ``assign``
      events on (None when unsampled/unknown);
    - ``lease_event(kind, chunk, miner_conn, **info)`` — lease-plane
      transition fanout (trace/flight/log live scheduler-side);
    - ``dispatch()`` — re-enter the scheduler pump (quarantine lift
      frees capacity mid-pop, exactly like the monolith did).
    """

    #: Wall-clock span one throughput sample must cover (window-union
    #: accounting, the scheduler-side analog of the miner's
    #: _ThroughputWindow from ISSUE 4).
    RATE_WINDOW_S = 0.5
    #: Rate-hint JOIN bounds (ISSUE 14): the seeded EWMA is clamped to
    #: the cap (no miner may claim more than ~1T nonces/s — a v4 pod is
    #: ~10^11) and DECAYED by this factor per sweep until a real
    #: throughput window confirms or replaces it, so a stale or
    #: overclaimed hint bleeds away instead of oversizing stripe plans
    #: forever on a miner that never answers.
    RATE_HINT_CAP = 1e12
    RATE_HINT_DECAY = 0.98

    def __init__(self, metrics: Registry, count: Callable[..., None],
                 lease: LeaseParams, stripe: StripeParams,
                 coalesce: CoalesceParams, *,
                 write: Callable, inflight: dict, trace_get: Callable,
                 lease_event: Callable, dispatch: Callable,
                 trace_on: bool = False,
                 verify: Optional[VerifyParams] = None):
        self.metrics = metrics
        self._count = count
        self.lease = lease
        self.stripe = stripe
        self.coalesce = coalesce
        self.verify = verify if verify is not None else VerifyParams()
        self._write = write
        self._inflight = inflight
        self._trace_get = trace_get
        self._lease_event = lease_event
        self._dispatch = dispatch
        self._trace_on = trace_on
        self.miners: list[MinerState] = []      # join order, like minersArray
        self._by_conn: dict[int, MinerState] = {}   # O(1) lookup (ISSUE 11)
        self.parked: list[Chunk] = []           # chunks of dropped miners
        self.pool_rate: Optional[float] = None  # pool-wide throughput EWMA
        #: True while pool_rate holds only a JOIN hint (ISSUE 14): the
        #: first real window sample REPLACES it, and it decays like the
        #: per-miner hint until then.
        self._pool_hinted = False
        #: Per-miner chunk-seconds overrides (ISSUE 14 satellite: the
        #: DBM_ADAPT_PER_MINER setpoints). Consulted by stripe_chunks;
        #: written by the scheduler's adapt apply; retired on drop.
        self.chunk_s_overrides: dict[int, float] = {}
        self._next_coalesce_id = 0
        self._pool_size = metrics.gauge("pool_size")
        self._pool_quarantined = metrics.gauge("pool_quarantined")
        self._lease_min_remaining = metrics.gauge("lease_min_remaining_s")
        self._lease_wait = metrics.histogram("lease_wait_s",
                                             LATENCY_BUCKETS_S)
        # Striping plane (dispatch pipeline): chunks per miner share.
        self._stripe_depth = metrics.histogram("stripe_chunks_per_share",
                                               OCCUPANCY_BUCKETS)

    # ------------------------------------------------------------- roster

    def update_pool_gauges(self) -> None:
        self._pool_size.set(len(self.miners))
        self._pool_quarantined.set(
            sum(1 for m in self.miners if m.quarantined))

    def find_miner(self, conn_id: int) -> Optional[MinerState]:
        return self._by_conn.get(conn_id)

    def on_join(self, conn_id: int, rate_hint: float = 0.0) -> MinerState:
        """A joining miner immediately absorbs one parked chunk, if any
        (ref: server.go:222-244). ``rate_hint`` (nonces/s, 0 = none —
        every stock miner) seeds the rate EWMA BOUNDED at
        ``RATE_HINT_CAP`` and flagged unconfirmed, so lease sizing and
        stripe plans treat a cold 1B-nps mesh as wide from its first
        chunk — the hint is seeded before the parked-chunk absorption
        below so even that first lease is sized from it.

        The hint is an UNAUTHENTICATED self-report (ISSUE 16 bugfix):
        its seeded value is clamped by the miner's trust score (full
        trust at a fresh join — the identity), its downstream influence
        on striping share is weighted by trust (:meth:`stripe_chunks`),
        and the first claim/audit failure DISCARDS it outright
        (:meth:`trust_fail`) — a byzantine miner cannot hold an
        inflated grant share past its first lie, and can never confirm
        the claim without actually doing the work."""
        miner = MinerState(conn_id=conn_id)
        if rate_hint > 0:
            miner.rate_ewma = min(float(rate_hint),
                                  self.RATE_HINT_CAP) * miner.trust
            miner.rate_hinted = True
            self.metrics.gauge("miner_rate_nps",
                               miner=str(conn_id)).set(miner.rate_ewma)
            if self.pool_rate is None:
                # An empty pool's first hinted miner IS the pool; a
                # warm pool's EWMA is measurement and outranks claims.
                self.pool_rate = miner.rate_ewma
                self._pool_hinted = True
                self.metrics.gauge("pool_rate_nps").set(self.pool_rate)
        chunk = self.next_parked()
        if chunk is not None:
            self.assign_chunk(miner, chunk, kind="parked")
        self.miners.append(miner)
        self._by_conn[conn_id] = miner
        self.update_pool_gauges()
        return miner

    def refresh_rate_hint(self, miner: MinerState, rate_hint: float) -> None:
        """Repeat-JOIN rate-hint refresh (ISSUE 20): a GatewayMiner
        re-sends its JOIN whenever its downstream pool sum moves, so the
        hint must UPDATE the existing MinerState instead of minting a
        duplicate roster entry.

        Semantics mirror :meth:`on_join`'s seeding rules: the new hint is
        clamped to ``RATE_HINT_CAP`` and trust-weighted (an untrusted
        refresher cannot inflate its share any more than an untrusted
        joiner can). While the EWMA is still hint-only (unconfirmed), the
        refresh simply replaces it. Once a real throughput window has
        confirmed a MEASURED rate, the measurement outranks claims —
        except on >=2x divergence either way, which for a gateway means
        the pool behind it genuinely changed shape (children joined or a
        child cluster died) faster than the EWMA can track; then the
        fresh pool-sum re-seeds it, flagged unconfirmed again so decay
        applies until the next real window. ``rate_hint <= 0`` is a
        no-op (a stock miner's hintless repeat JOIN carries no claim)."""
        if rate_hint <= 0:
            return
        hinted = min(float(rate_hint), self.RATE_HINT_CAP) * miner.trust
        measured = miner.rate_ewma is not None and not miner.rate_hinted
        if measured:
            assert miner.rate_ewma is not None
            diverged = (hinted >= miner.rate_ewma * 2.0
                        or hinted <= miner.rate_ewma * 0.5)
            if not diverged:
                return
        miner.rate_ewma = hinted
        miner.rate_hinted = True
        self.metrics.gauge("miner_rate_nps",
                           miner=str(miner.conn_id)).set(miner.rate_ewma)
        self._count("rate_hints_refreshed")

    def adopt_miner(self, conn_id: int, pending: Optional[list] = None,
                    rate_ewma: Optional[float] = None) -> MinerState:
        """Replica lease takeover (apps/replicas.py): adopt a miner that
        a DEAD replica owned. Its still-pending chunk records arrive
        marked CANCELLED — the miner will answer them in order and each
        pops here as stale, preserving the k-th-Result-answers-k-th-
        Request discipline across the ownership change — and its
        observed throughput EWMA carries over so lease sizing stays
        warm. The adopting replica assigns NEW chunks behind the dead
        ones."""
        miner = MinerState(conn_id=conn_id, rate_ewma=rate_ewma)
        for chunk in pending or []:
            chunk.cancelled = True
            miner.pending.append(chunk)
        self.miners.append(miner)
        self._by_conn[conn_id] = miner
        self.update_pool_gauges()
        return miner

    def drop_miner(self, conn_id: int) -> Optional[MinerState]:
        """Remove a dropped miner and retire its labeled series; the
        caller (scheduler) recovers its chunks via :meth:`recover`."""
        miner = self._by_conn.pop(conn_id, None)
        if miner is None:
            return None
        self.miners.remove(miner)
        self.chunk_s_overrides.pop(conn_id, None)
        self.update_pool_gauges()
        # Retire the dead conn-id's labeled series: stale values must
        # not linger in snapshots, and reconnect churn (every rejoin
        # is a fresh conn id) must not exhaust the family cardinality
        # bound over a long server life.
        self.metrics.remove("miner_rate_nps", miner=str(conn_id))
        self.metrics.remove("lease_remaining_s", miner=str(conn_id))
        self.metrics.remove("adapt_chunk_s_miner", miner=str(conn_id))
        self.metrics.remove("miner_trust", miner=str(conn_id))
        return miner

    def recover(self, miner: MinerState) -> None:
        """Recover every unanswered chunk of a dropped miner
        (ref: server.go:326-376, single-chunk version). Chunks whose idx
        already merged (speculation winner landed first) and chunks with
        a live speculative copy in another FIFO need no recovery — the
        copy is tracked independently."""
        for chunk in miner.pending:
            req = self._inflight.get(chunk.job_id)
            if req is None or chunk.cancelled:
                continue
            if req.answered[chunk.idx] or chunk.reissued:
                continue
            takeover = next((m for m in self.eligible()), None)
            if takeover is not None:
                self.assign_chunk(takeover, chunk, kind="recovered")
            else:
                self.parked.append(chunk)
                self._lease_event("park", chunk, miner.conn_id)

    # ----------------------------------------------------------- selection

    def next_parked(self, skip_key=None) -> Optional[Chunk]:
        """Pop the next parked chunk that still NEEDS executing, discarding
        stale ones: a parked chunk whose idx was meanwhile answered by a
        speculation winner (its copy blew a lease, was re-issued, and the
        re-issue landed first) — or whose ``(job_id, idx)`` matches
        ``skip_key``, the assignment the caller is answering right now —
        would only burn a full scan to pop as a duplicate."""
        while self.parked:
            chunk = self.parked.pop(0)
            req = self._inflight.get(chunk.job_id)
            if req is None or req.answered[chunk.idx]:
                continue
            if skip_key is not None and \
                    (chunk.job_id, chunk.idx) == skip_key:
                continue
            return chunk
        return None

    def distrusted(self, miner: MinerState) -> bool:
        """Verification tier (ISSUE 16): a miner whose trust score fell
        below the bar is barred from NEW grants exactly like a
        quarantined one. Trust never moves off 1.0 while verification
        is off, so this is one always-false comparison on the stock
        path."""
        return miner.trust < self.verify.trust_bar

    def eligible(self) -> list[MinerState]:
        """Miners that may take new work: available, not quarantined,
        and (verification tier) not distrusted."""
        return [m for m in self.miners
                if m.available and not m.quarantined
                and not self.distrusted(m)]

    def desperation_pool(self) -> list[MinerState]:
        """Last-resort pool when the WHOLE pool is quarantined or
        distrusted: the least-bad available such miner (lowest blown
        streak, then highest trust, then highest observed throughput),
        or nothing. Any healthy miner — even a busy one that will free
        up — disables desperation: waiting for it beats feeding a
        known-bad one."""
        if not self.lease.desperation or not self.miners:
            return []
        if not all(m.quarantined or self.distrusted(m)
                   for m in self.miners):
            return []
        avail = [m for m in self.miners if m.available]
        if not avail:
            return []
        return [min(avail, key=lambda m: (m.blown_streak, -m.trust,
                                          -(m.rate_ewma or 0.0)))]

    def pick_auditor(self, *exclude: int):
        """Auditor selection (ISSUE 16): any trusted, unquarantined
        miner other than the excluded conn ids — explicitly NOT
        ``eligible()``, whose availability test would mean "no audits
        while the pool is busy", i.e. never mid-request, exactly when
        claims race. An audit subwindow is tiny next to a chunk, so it
        queues on the least-loaded candidate's FIFO (ties keep join
        order, like every assignment path)."""
        cands = [m for m in self.miners
                 if m.conn_id not in exclude
                 and not m.quarantined and not self.distrusted(m)]
        if not cands:
            return None
        return min(cands, key=self.miner_live)

    def miner_live(self, miner: MinerState) -> int:
        """Live (non-cancelled) chunks in a miner's pending FIFO, with
        a coalescing window's chunks counting as ONE (they share one
        device launch on the miner — ISSUE 9): the QoS depth cap bounds
        launches in flight, not rows per launch."""
        n = 0
        groups = set()
        for c in miner.pending:
            if c.cancelled:
                continue
            if c.coalesce_id is None:
                n += 1
            else:
                groups.add(c.coalesce_id)
        return n + len(groups)

    def capacity_pool(self, depth: int) -> list[MinerState]:
        """Miners that may take an incremental QoS chunk: not
        quarantined, below the per-miner live-FIFO cap, and not sitting
        on a blown-lease chunk (a wedged miner's blown original stays
        live in its FIFO awaiting the in-order pop — the stock path's
        ``available`` never feeds such a miner either, and a mouse
        granted behind it would stall a full lease period), least-loaded
        first (ties keep join order — the reference's assignment
        order)."""
        pool = [m for m in self.miners
                if not m.quarantined and not self.distrusted(m)
                and self.miner_live(m) < depth
                and not any(c.lease_blown and not c.cancelled
                            for c in m.pending)]
        pool.sort(key=self.miner_live)
        return pool

    # ------------------------------------------------- coalescing windows

    def coalescible_cost(self, target: int, cost: int) -> bool:
        """May a grant of ``cost`` nonces (difficulty ``target``) enter
        a coalescing window? Argmin mode only, and SMALL twice over: an
        absolute nonce bound (``max_nonces``) and an estimated-seconds
        bound at the pool rate (``small_s``) — only a chunk whose scan
        is launch-overhead-scale belongs in a shared launch; an absolute
        bound alone would misclassify a slow pool's rate-scaled
        elephant chunks as mice and serialize the elephant onto one
        miner's window."""
        if not self.coalesce.enabled or target \
                or cost > self.coalesce.max_nonces:
            return False
        rate = self.pool_rate
        if rate is not None and rate > 0:
            return cost <= rate * self.coalesce.small_s
        return True

    def window_slot(self, window: dict, job_id: int):
        """The first open coalescing-window slot that can take a chunk
        of ``job_id``: a free lane, NOT already holding this job
        (windows batch across requests; stacking one request's own
        chunks would just re-merge what the chunk planner split), on a
        live non-quarantined miner. Returns ``(miner, slot)`` or
        ``(None, None)``. ONE definition shared by pump candidacy
        (:meth:`window_room`) and the grant itself — if the two
        drifted, the pump could admit a candidate the grant cannot
        place and spin (code review, PR 8)."""
        for conn_id, slot in window.items():
            if slot[1] >= self.coalesce.lanes or job_id in slot[2]:
                continue
            m = self.find_miner(conn_id)
            if m is not None and not m.quarantined:
                return m, slot
        return None, None

    def window_room(self, window: dict, job_id: int = 0) -> bool:
        """Any joinable window for ``job_id``? (See :meth:`window_slot`.)"""
        if not window:
            return False
        return self.window_slot(window, job_id)[0] is not None

    def open_window(self, window: dict, miner: MinerState,
                    job_id: int) -> int:
        """Open a fresh window on ``miner`` for this pump pass; returns
        the new coalesce id."""
        self._next_coalesce_id += 1
        cid = self._next_coalesce_id
        window[miner.conn_id] = [cid, 1, {job_id}]
        return cid

    # ------------------------------------------------------------ striping

    def stripe_chunks(self, miner: MinerState, share: int) -> int:
        """Chunk count for one miner's share: ``ceil(share / (rate *
        chunk_s))`` capped at ``stripe.depth``. 1 (the stock even split)
        when striping is off, the share is trivial, or no throughput has
        been observed yet — a cold pool's first request is always
        bit-identical to the reference split, so the parity/conformance
        shape needs no knob to reproduce."""
        if not self.stripe.enabled or share <= 1:
            return 1
        rate = miner.rate_ewma if miner.rate_ewma is not None \
            else self.pool_rate
        if rate is None or rate <= 0:
            return 1
        # Verification tier (ISSUE 16): striping share is weighted by
        # trust — the rate feeding the plan may be an UNAUTHENTICATED
        # JOIN self-report (rate_hinted), so a byzantine miner
        # overclaiming 1000x must not win a proportionally deep stripe
        # plan once it has been caught lying. trust == 1.0 (stock, and
        # every honest miner) is the identity weight.
        rate *= miner.trust
        # Per-miner setpoint override (DBM_ADAPT_PER_MINER) over the
        # pool-wide knob: in a 100x-skewed heterogeneous pool one
        # seconds-of-work value cannot hit both tiers' force-latency
        # setpoints.
        chunk_s = self.chunk_s_overrides.get(miner.conn_id,
                                             self.stripe.chunk_s)
        target = max(1, int(rate * chunk_s))
        return max(1, min(self.stripe.depth, -(-share // target)))

    def observe_stripe(self, n_chunks: int) -> None:
        self._stripe_depth.observe(n_chunks)

    # ------------------------------------------------------ grant/complete

    def assign_chunk(self, miner: MinerState, chunk: Chunk,
                     kind: str = "initial") -> None:
        """GRANT edge of the internal interface: one chunk onto one
        miner's pending FIFO, lease stamped, wire Request written."""
        chunk.assigned_at = time.monotonic()
        chunk.lease_blown = False
        chunk.reissued = False
        chunk.lease_started = False
        chunk.deadline = 0.0
        miner.pending.append(chunk)
        # Position-aware lease clock (see the scheduler docstring): a
        # chunk at the FIFO head starts its tight lease now; one
        # assigned behind other entries gets a BUDGET deadline (latest
        # predecessor expiry + its own lease) that is tightened when it
        # reaches the head (pop_result) — so a deep healthy FIFO never
        # blows spuriously, but a FIFO wedged at its head still expires.
        # fifo_aware=False restores the at-assignment clock.
        if not self.lease.fifo_aware or len(miner.pending) == 1:
            self.start_lease(miner, chunk)
        else:
            now = chunk.assigned_at
            ahead = max((c.deadline for c in miner.pending[:-1]),
                        default=now)
            chunk.deadline = max(now, ahead) + self.lease_for(miner, chunk)
        trace = self._trace_get(chunk.job_id)
        if trace is not None:
            trace.event("assign", miner=miner.conn_id, idx=chunk.idx,
                        lower=chunk.lower, upper=chunk.upper, kind=kind,
                        fifo_pos=len(miner.pending) - 1,
                        lease_started=chunk.lease_started)
        if self._trace_on:
            _tracing.flight("assign", job=chunk.job_id, idx=chunk.idx,
                            miner=miner.conn_id, kind=kind)
        self._write(miner.conn_id,
                    new_request(chunk.data, chunk.lower, chunk.upper,
                                chunk.target))

    def pop_result(self, conn_id: int):
        """COMPLETE edge: an arriving Result pops the miner's oldest
        pending chunk. Feeds the throughput window, starts the next
        FIFO entry's lease, absorbs one parked chunk when freed —
        returns ``(miner, chunk)`` for the scheduler to merge, or None
        when the conn is no miner / has nothing pending."""
        miner = self.find_miner(conn_id)
        if miner is None or not miner.pending:
            return None
        chunk = miner.pending.pop(0)   # the Result answers the oldest Request
        self.observe_result(miner, chunk)
        # Position-aware leases: the next FIFO entry is what the miner
        # computes now — start its clock (no-op when already started, i.e.
        # fifo_aware off or it was assigned to an empty FIFO).
        if miner.pending and not miner.pending[0].lease_started:
            self.start_lease(miner, miner.pending[0])
        # A freed miner immediately absorbs one parked chunk
        # (ref: server.go:285-304) — BEFORE the scheduler's stale-Result
        # return, so a miner freed by a stale answer still rescues parked
        # work. The just-popped (job, idx) is excluded: this very Result
        # is about to answer it, so a parked speculative copy of it is
        # garbage — not work to hand back to the miner that just did it.
        # Verification tier (ISSUE 16): a DISTRUSTED miner stops
        # absorbing parked work (quarantine lifts on any pop above, but
        # trust does not — a caught liar re-fed its own rejected chunk
        # would lie forever) unless desperation says it is the whole
        # pool's least-bad option. Stock path: distrusted() is one
        # always-false comparison and short-circuits the rest.
        if self.parked and miner.available and (
                not self.distrusted(miner)
                or miner in self.desperation_pool()):
            parked = self.next_parked(skip_key=(chunk.job_id, chunk.idx))
            if parked is not None:
                self.assign_chunk(miner, parked, kind="parked")
        return miner, chunk

    # --------------------------------------------------------- lease plane

    def start_lease(self, miner: MinerState, chunk: Chunk) -> None:
        """Start the lease clock: the miner is (about to be) computing this
        chunk. ``assigned_at`` is re-stamped so both the expiry log and the
        throughput sample measure actual compute time, not FIFO wait."""
        now = time.monotonic()
        if chunk.assigned_at:
            self._lease_wait.observe(now - chunk.assigned_at)
        chunk.assigned_at = now
        chunk.deadline = now + self.lease_for(miner, chunk)
        chunk.lease_started = True

    def observe_result(self, miner: MinerState, chunk: Chunk) -> None:
        """Per-pop bookkeeping: throughput sampling, streak reset,
        quarantine lift. Runs for EVERY pop — stale and cancelled chunks
        were computed too, so they are valid throughput samples, and an
        answer is an answer for quarantine purposes ("until it answers
        again").

        Throughput is sampled over a WALL-CLOCK WINDOW per miner, not per
        pop: the pipelined miner computes chunk k+1 while k's result is
        in flight, so k+1's Result arrives milliseconds after its lease
        re-stamp and a per-pop size/elapsed sample reads as 10^9
        nonces/s — which then poisons every consumer (stripe plans grow
        one-giant-chunk, the QoS wholesale gate misclassifies elephants,
        leases collapse to the floor). Accumulating answered nonces until
        ``RATE_WINDOW_S`` of wall clock has passed measures the miner's
        true OUTPUT rate regardless of internal overlap."""
        alpha = self.lease.ewma_alpha
        now = time.monotonic()
        if chunk.assigned_at and not chunk.lease_blown and not chunk.target:
            # Two exclusions keep the sample set honest (they also RESET
            # the window below). Blown-lease answers: a wedged miner's
            # eventual 60s "sample" would inflate its (and the pool's)
            # lease to minutes and blunt re-wedge detection. Difficulty
            # chunks: an in-kernel early exit may scan 1% of the range,
            # so size/elapsed would overestimate throughput ~100x and
            # starve every later stock chunk's lease.
            if miner.win_nonces == 0 \
                    or now - miner.win_t0 > 4 * self.RATE_WINDOW_S:
                # Fresh (or stale — an idle gap must not deflate the
                # sample) window, anchored at this chunk's lease start.
                miner.win_t0 = chunk.assigned_at or now
                miner.win_nonces = 0
            miner.win_nonces += chunk.size
            elapsed = now - miner.win_t0
            if elapsed >= self.RATE_WINDOW_S:
                rate = miner.win_nonces / elapsed
                miner.win_t0, miner.win_nonces = now, 0
                # A JOIN rate hint is a CLAIM: the first real window
                # sample replaces it outright (blending a 100x-off
                # claim in would poison the EWMA for many windows).
                if miner.rate_hinted:
                    miner.rate_hinted = False
                    miner.rate_ewma = None
                if self._pool_hinted:
                    self._pool_hinted = False
                    self.pool_rate = None
                miner.rate_ewma = rate if miner.rate_ewma is None else \
                    alpha * rate + (1 - alpha) * miner.rate_ewma
                self.pool_rate = rate if self.pool_rate is None else \
                    alpha * rate + (1 - alpha) * self.pool_rate
                self.metrics.gauge(
                    "miner_rate_nps",
                    miner=str(miner.conn_id)).set(miner.rate_ewma)
                self.metrics.gauge("pool_rate_nps").set(self.pool_rate)
        else:
            miner.win_t0, miner.win_nonces = 0.0, 0
        miner.blown_streak = 0
        # Verification tier (ISSUE 16): confirmed work recovers trust
        # one step toward full. The scheduler's claim check runs AFTER
        # this pop-side step, so a lying Result's trust_fail decay
        # lands last — multiplicative decay dominates the additive
        # step and a liar can never net-gain trust from the very
        # Result that convicted it. Stock path: one always-false
        # comparison.
        if miner.trust < 1.0:
            miner.trust = min(1.0, miner.trust + self.verify.trust_recover)
            self.metrics.gauge("miner_trust",
                               miner=str(miner.conn_id)).set(miner.trust)
        if miner.quarantined:
            miner.quarantined = False
            self.update_pool_gauges()
            self._lease_event("quarantine_lifted", chunk, miner.conn_id)
            self._dispatch()

    def trust_fail(self, miner: MinerState, reason: str) -> float:
        """Verification tier (ISSUE 16): decay ``miner``'s trust after a
        claim or audit failure (``reason`` is ``"claim"``/``"audit"``,
        counted per kind). Multiplicative decay clamped at the floor —
        repeat offenses drive the score below ``trust_bar`` (grant
        ineligibility) fast, while the floor keeps recovery through
        confirmed work possible. An UNCONFIRMED join rate hint dies on
        the first lie (the PR 14 bugfix's teeth): a self-reported rate
        from a miner caught fabricating results is worthless, and
        keeping it would let the liar hold its inflated stripe share
        through the whole decay horizon. Returns the new score."""
        v = self.verify
        miner.trust = max(v.trust_floor, miner.trust * v.trust_decay)
        self._count("trust_decays_" + reason)
        self.metrics.gauge("miner_trust",
                           miner=str(miner.conn_id)).set(miner.trust)
        if self._trace_on:
            _tracing.flight("trust_decayed", miner=miner.conn_id,
                            trust=round(miner.trust, 4), reason=reason)
        if miner.rate_hinted:
            miner.rate_hinted = False
            miner.rate_ewma = None
            self.metrics.remove("miner_rate_nps",
                                miner=str(miner.conn_id))
        return miner.trust

    def decay_rate_hints(self) -> None:
        """One sweep tick of unconfirmed rate-hint decay (ISSUE 14):
        hinted EWMAs bleed toward zero until a real throughput window
        confirms a measured rate — a stale/overclaimed hint on a miner
        that never answers must stop inflating stripe plans and leases
        within a bounded horizon (half-life ~34 ticks at 0.98)."""
        for m in self.miners:
            if m.rate_hinted and m.rate_ewma:
                m.rate_ewma *= self.RATE_HINT_DECAY
                self.metrics.gauge("miner_rate_nps",
                                   miner=str(m.conn_id)).set(m.rate_ewma)
        if self._pool_hinted and self.pool_rate:
            self.pool_rate *= self.RATE_HINT_DECAY
            self.metrics.gauge("pool_rate_nps").set(self.pool_rate)

    def set_chunk_s_override(self, conn_id: int, chunk_s: float) -> None:
        """Per-miner chunk-seconds setpoint (ISSUE 14 satellite,
        ``DBM_ADAPT_PER_MINER``): the adapt plane's per-miner chunk
        controller writes its value here; :meth:`stripe_chunks` sizes
        that miner's stripe chunks from it instead of the pool-wide
        knob. Gauge retired with the miner (:meth:`drop_miner`)."""
        self.chunk_s_overrides[conn_id] = chunk_s
        self.metrics.gauge("adapt_chunk_s_miner",
                           miner=str(conn_id)).set(chunk_s)

    def clear_chunk_s_overrides(self) -> None:
        """The pool re-converged (adapt un-fork): every per-miner
        setpoint retires — a stale fork must not shadow the live
        pool-wide knob — and the labeled gauges go with them."""
        for conn_id in self.chunk_s_overrides:
            self.metrics.remove("adapt_chunk_s_miner",
                                miner=str(conn_id))
        self.chunk_s_overrides.clear()

    def pin_rates(self, rate: float, include_hinted: bool = False) -> None:
        """Test/bench/scenario helper: pin every (by default un-hinted)
        miner's rate EWMA and the POOL rate to ``rate``, clearing the
        pool's hint flag — the one blessed way to warm a harness pool
        without reaching into the hint bookkeeping (the rate-hint JOIN
        path stays live for hinted miners)."""
        for m in self.miners:
            if include_hinted or not m.rate_hinted:
                m.rate_ewma = rate
        self.pool_rate = rate
        self._pool_hinted = False

    def service_sample(self, chunk: Chunk):
        """``(service_s, margin_frac)`` of a JUST-POPPED chunk for the
        self-tuning plane (ISSUE 13), derived from the lease plane's
        own stamps — service is elapsed since the lease started (the
        miner was actually computing it, not FIFO-waiting), margin is
        the unspent fraction of its lease. ``(None, None)`` when the
        stamps cannot speak honestly: the lease never started, the
        chunk blew (its elapsed measures the wedge, not the work), it
        was cancelled, or leases are off (infinite margin)."""
        if not chunk.lease_started or chunk.lease_blown \
                or chunk.cancelled or not chunk.assigned_at:
            return None, None
        lease_span = chunk.deadline - chunk.assigned_at
        if not (lease_span > 0) or lease_span == float("inf"):
            return None, None
        now = time.monotonic()
        service = max(0.0, now - chunk.assigned_at)
        margin = max(0.0, (chunk.deadline - now) / lease_span)
        return service, margin

    def lease_for(self, miner: MinerState, chunk: Chunk) -> float:
        """Lease duration for assigning ``chunk`` to ``miner``: headroom
        over the EWMA-predicted scan time, clamped below; a flat grace when
        nothing has been observed yet (cold pool)."""
        if not self.lease.enabled:
            return float("inf")
        rate = miner.rate_ewma if miner.rate_ewma is not None \
            else self.pool_rate
        if rate is None or rate <= 0:
            return self.lease.grace_s
        return max(self.lease.floor_s, chunk.size / rate * self.lease.factor)

    def cancel_job(self, job_id: int) -> None:
        """Mark a retiring job's still-pending chunks cancelled (the
        pool frees immediately; late Results pop as stale) and discard
        its parked chunks."""
        for m in self.miners:
            for c in m.pending:
                if c.job_id == job_id:
                    c.cancelled = True
        self.parked = [c for c in self.parked if c.job_id != job_id]

    def clear_lease_gauges(self) -> None:
        """No live leases remain: clear the remaining-lease gauges so an
        idle system's snapshot doesn't keep reporting the retired job's
        last sweep values as work in flight."""
        for m in self.miners:
            self.metrics.remove("lease_remaining_s",
                                miner=str(m.conn_id))
        self._lease_min_remaining.set(0.0)

    def check_leases(self) -> None:
        """One lease sweep: blow expired leases (quarantining repeat
        offenders) and speculatively re-issue each blown chunk to an
        eligible miner — first Result wins, the loser pops as a duplicate
        (the scheduler's merge). A blown chunk with no taker stays watched
        and is re-issued on a later sweep once a miner frees up or joins.

        Every in-flight job is swept: the stock FIFO path has at most one,
        but the QoS plane (ISSUE 5) runs several concurrently — a wedged
        miner holding a mouse's chunk must blow even while an elephant's
        chunks are also live."""
        if not self._inflight:
            return
        now = time.monotonic()
        # Per-miner MINIMUM remaining lease (a deep budgeted chunk must not
        # mask the head chunk's imminent expiry), set after the sweep.
        per_miner_remaining: dict[int, float] = {}
        for miner in list(self.miners):
            for chunk in list(miner.pending):
                if chunk.cancelled:
                    continue
                curr = self._inflight.get(chunk.job_id)
                if curr is None or curr.answered[chunk.idx]:
                    continue
                if not chunk.lease_blown:
                    if now < chunk.deadline:
                        remaining = chunk.deadline - now
                        prev = per_miner_remaining.get(miner.conn_id)
                        if prev is None or remaining < prev:
                            per_miner_remaining[miner.conn_id] = remaining
                        continue
                    chunk.lease_blown = True
                    self._count("leases_blown")
                    # With the at-assignment clock (fifo_aware=False) a
                    # chunk can blow while entries still sit AHEAD of it —
                    # the miner never even reached it. Counted so the
                    # position-aware fix has before/after evidence. (With
                    # fifo_aware, a pre-head blow means the budgeted
                    # deadline covering the predecessors ALSO ran out —
                    # the whole pipeline is overdue, not spurious.)
                    spurious = (not self.lease.fifo_aware
                                and miner.pending[0] is not chunk)
                    if spurious:
                        self._count("leases_blown_spurious")
                    miner.blown_streak += 1
                    self._lease_event("blown", chunk, miner.conn_id,
                                      streak=miner.blown_streak,
                                      spurious=spurious,
                                      overdue_s=now - chunk.assigned_at)
                    if (miner.blown_streak >= self.lease.quarantine_after
                            and not miner.quarantined):
                        miner.quarantined = True
                        self._count("quarantines")
                        self.update_pool_gauges()
                        self._lease_event("quarantine", chunk,
                                          miner.conn_id,
                                          streak=miner.blown_streak)
                if chunk.reissued:
                    continue
                takeover = next(
                    (m for m in self.eligible() if m is not miner), None)
                if takeover is None:
                    continue   # retry next sweep
                chunk.reissued = True
                self._count("reissues")
                self._lease_event("reissue", chunk, miner.conn_id,
                                  to_miner=takeover.conn_id)
                self.assign_chunk(
                    takeover,
                    Chunk(chunk.job_id, chunk.data, chunk.lower,
                          chunk.upper, target=chunk.target, idx=chunk.idx),
                    kind="reissue")
        # Miners with no live unexpired lease this sweep (blown, answered,
        # or idle) lose their series: a stale positive "remaining" on a
        # blown lease would read as healthy headroom.
        for m in self.miners:
            if m.conn_id not in per_miner_remaining:
                self.metrics.remove("lease_remaining_s",
                                    miner=str(m.conn_id))
        for conn_id, remaining in per_miner_remaining.items():
            self.metrics.gauge("lease_remaining_s",
                               miner=str(conn_id)).set(remaining)
        self._lease_min_remaining.set(
            min(per_miner_remaining.values()) if per_miner_remaining
            else 0.0)
