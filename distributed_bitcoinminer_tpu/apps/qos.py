"""Fair-share QoS plane: per-tenant accounting the scheduler mounts in
front of dispatch (ISSUE 5).

The stock coordinator drains ONE global FIFO with whole-request dispatch:
a 2^40-range elephant parks every later request until its last chunk
merges, and nothing bounds intake — an overload storm just grows the
deque until every client times out at once (the queue-age alarm from
ISSUE 2 *names* that starvation; this plane fixes it). PNPCoin
(PAPERS.md, arXiv 2208.12628) frames the same coordinator as a general
multi-tenant compute service, and what makes such a service multi-tenant
is exactly this layer — the fairness + admission plane any
inference-serving stack runs in front of its batch scheduler.

Three mechanisms, all tenant-keyed by the client conn id (no wire
change; ``utils.config.QosParams`` holds the knobs):

- **Deficit-round-robin at chunk granularity.** Each tenant carries a
  deficit counter in NONCES. :meth:`QosPlane.pick` walks the active ring:
  a tenant whose deficit covers its head item's cost is granted; one that
  cannot afford it is topped up by ``weight * quantum`` once per pass and
  the ring rotates. The quantum is the largest candidate cost of the
  pass, so the classic DRR guarantee holds: every tenant with backlog is
  granted within ``ceil(1/weight)`` ring passes — no starvation — and
  sustained grant share converges to the weight ratio. The *items* being
  granted are chunks (the EWMA-sized pieces the striping plane of ISSUE 4
  introduced), so an elephant yields the pool to a mouse between chunks
  instead of at its last merge. The scheduler owns chunk planning and
  miner selection; this plane only answers "whose turn is it".

- **Token-bucket admission.** Per-tenant bucket of ``burst`` tokens
  refilled at ``rate``/s; a request arriving on an empty bucket is shed
  at admission (the scheduler never queues it). ResultCache replays are
  checked BEFORE admission in the scheduler, so a retry storm of
  already-answered requests never burns quota.

- **In-flight caps + shed bookkeeping.** ``max_inflight`` bounds each
  tenant's granted-but-unanswered chunks (the scheduler filters
  candidates on it); the scheduler's oldest-first overload shedding
  (``max_queued``) reports here so the per-tenant counters and the
  ``qos_shed`` totals ride the ISSUE 3 metrics registry.

Metric series (scheduler registry, mounted under ``sched.``):
``qos_tenants`` gauge, ``qos_grant_share{tenant=}`` gauges (cumulative
granted-nonce share), ``qos_granted_chunks{tenant=}`` counters,
``qos_shed_reason{reason=}`` counters, and the plane-neutral
``qos_grants`` / ``qos_shed`` totals the scheduler keeps in its stats
view. Tenant series are removed when the tenant is forgotten (conn drop
or idle GC), so conn churn can never exhaust the registry's cardinality
bound.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Dict, Optional

from ..utils.metrics import Registry

__all__ = ["TokenBucket", "TenantState", "QosPlane", "LAZY_REMOVE"]

#: Sentinel a :meth:`QosPlane.pick_lazy` head callback returns when the
#: tenant has NO backlog at all (nothing queued, no ungranted chunks):
#: the walk drops it from the ring on the spot, forfeiting its deficit
#: (the idle-banks-no-credit rule, applied lazily instead of by the
#: stock pump's per-pass ``sync_backlog`` scan).
LAZY_REMOVE = object()


class TokenBucket:
    """Classic token bucket: ``burst`` capacity, ``rate`` tokens/s refill.

    ``rate <= 0`` means admission is disabled — :meth:`take` always
    grants and the bucket reports full.
    """

    __slots__ = ("rate", "burst", "_tokens", "_t", "_clock")

    def __init__(self, rate: float, burst: float,
                 clock: Callable[[], float] = time.monotonic):
        self.rate = rate
        self.burst = max(1.0, burst)
        self._tokens = self.burst
        self._clock = clock
        self._t = clock()

    def _refill(self) -> None:
        now = self._clock()
        self._tokens = min(self.burst,
                           self._tokens + (now - self._t) * self.rate)
        self._t = now

    def take(self, n: float = 1.0) -> bool:
        """Spend ``n`` tokens; False (and no spend) when short."""
        if self.rate <= 0:
            return True
        self._refill()
        if self._tokens < n:
            return False
        self._tokens -= n
        return True

    def set_rate(self, rate: float, burst: Optional[float] = None) -> None:
        """Re-rate a LIVE bucket (the adaptive admission controller,
        ISSUE 13): accrued tokens are settled at the OLD rate first so
        an adjustment never retroactively mints or burns credit, then
        the new rate (and optionally burst) applies from now."""
        self._refill()
        self.rate = rate
        if burst is not None:
            self.burst = max(1.0, burst)
            self._tokens = min(self._tokens, self.burst)

    @property
    def level(self) -> float:
        if self.rate <= 0:
            return self.burst
        self._refill()
        return self._tokens

    @property
    def full(self) -> bool:
        return self.level >= self.burst - 1e-9


class TenantState:
    """Per-tenant DRR + admission state (one per live client conn)."""

    __slots__ = ("tenant", "weight", "deficit", "inflight",
                 "granted_nonces", "granted_chunks", "shed", "bucket")

    def __init__(self, tenant, weight: float, bucket: TokenBucket):
        self.tenant = tenant
        self.weight = max(weight, 1e-3)
        self.deficit = 0.0
        self.inflight = 0          # granted, not yet answered, chunks
        self.granted_nonces = 0
        self.granted_chunks = 0
        self.shed = 0
        self.bucket = bucket


class QosPlane:
    """Tenant registry + DRR scheduler state. The Scheduler executes
    every decision (it owns chunk plans, miners, and the wire); the plane
    owns whose-turn-is-it and the per-tenant accounting."""

    #: Safety valve on the DRR walk: weights are clamped to >= 1e-3 in
    #: TenantState, but a pick must terminate even on corrupted state.
    MAX_PASSES = 1024

    #: Every this-many sweeps the share gauges get a FULL refresh; in
    #: between only tenants granted since the last sweep (the dirty
    #: set) are re-set — the O(tenants) per-sweep gauge walk was a
    #: 10k-tenant melt point (ISSUE 11), and an idle tenant's share
    #: gauge going stale against a grown total for a bounded number of
    #: sweeps is an accepted observability trade (the dump/alarm paths
    #: compute shares directly, never from the gauge).
    FULL_REFRESH_SWEEPS = 32

    def __init__(self, metrics: Registry,
                 clock: Callable[[], float] = time.monotonic):
        self.metrics = metrics
        self._clock = clock
        self.tenants: Dict[object, TenantState] = {}
        # DRR ring: BACKLOGGED tenants only (ISSUE 11). The ring used to
        # hold every known tenant, so each pick's walk rotated past the
        # whole idle population — O(tenants) per grant at 10k tenants.
        # Membership now tracks backlog (sync_backlog + pick's candidate
        # ensure); the walk among backlogged tenants — and the cycle
        # top-up sequence they observe — is unchanged, because visiting
        # an idle tenant was always a no-op rotate.
        self.ring: deque = deque()        # backlogged tenant ids, DRR order
        self._in_ring: set = set()
        self.total_granted_nonces = 0
        # Tenants already topped up in the CURRENT ring cycle (classic
        # DRR adds quantum once per round, not once per missed pick).
        self._topped: set = set()
        # Tenants granted since the last sweep (share-gauge dirty set).
        self._dirty_shares: set = set()
        self._sweeps = 0
        # Lazy-walk incremental quantum bound (ISSUE 12): the largest
        # head cost SEEN so far by pick_lazy, reset when the ring
        # drains. The stock pick recomputes max(candidates) per pick —
        # O(candidates); the lazy walk grows this bound incrementally
        # as heads are priced, which keeps the classic DRR guarantee
        # (top-up >= weight * any candidate cost once that cost has
        # been seen) at O(1) per visit. A larger-than-necessary quantum
        # only coarsens grant granularity — share still converges to
        # the weight RATIO, because every tenant tops up from the same
        # bound.
        self._lazy_quantum = 0.0
        self._g_tenants = metrics.gauge("qos_tenants")

    # ------------------------------------------------------------- tenants

    def tenant(self, tenant, weight: float = 1.0, rate: float = 0.0,
               burst: float = 8.0) -> TenantState:
        """The tenant's state, created on first sight with the given
        weight/bucket parameters (later calls ignore them — use
        :meth:`set_weight` to change a live tenant)."""
        st = self.tenants.get(tenant)
        if st is None:
            st = TenantState(tenant, weight,
                             TokenBucket(rate, burst, self._clock))
            self.tenants[tenant] = st
            self._g_tenants.set(len(self.tenants))
        return st

    def _ensure_ring(self, tenant) -> None:
        if tenant not in self._in_ring:
            self._in_ring.add(tenant)
            self.ring.append(tenant)

    def sync_backlog(self, backlogged) -> None:
        """Reconcile ring membership with the CURRENT backlogged tenant
        set (the scheduler computes it from its queue + ungranted
        chunked in-flight requests at pump start). Deficits obey the
        classic-DRR idle-time-banks-no-credit rule, enforced at BOTH
        membership edges so it cannot be dodged: a tenant leaving the
        ring forfeits its deficit, and one (re-)ENTERING starts from
        zero — the scheduler's pump may legitimately early-exit without
        syncing while a tenant sits idle (the ISSUE 11 O(1) no-op
        exits), so exit-time zeroing alone could let credit survive an
        unobserved idle gap (code review). The old implementation was
        an O(all tenants) reset loop on every pump; this is O(changes),
        and departures rebuild the deque in ONE pass rather than one
        O(ring) ``remove`` per departing tenant."""
        ordered = list(backlogged)     # caller order = arrival order
        present = set(ordered)
        gone = self._in_ring - present
        if gone:
            self.ring = deque(t for t in self.ring if t not in gone)
            self._in_ring -= gone
            self._topped -= gone
            for tenant in gone:
                st = self.tenants.get(tenant)
                if st is not None:
                    st.deficit = 0.0
        for tenant in ordered:         # deterministic join order
            if tenant not in self._in_ring:
                st = self.tenants.get(tenant)
                if st is not None:
                    st.deficit = 0.0   # idle credit never re-enters
                self._ensure_ring(tenant)

    def backlog_enter(self, tenant) -> None:
        """Lazy-mode ring entry (ISSUE 12): called the moment a tenant
        GAINS backlog (request enqueued, chunked activation with chunks
        left) instead of by a per-pass ``sync_backlog`` scan. A tenant
        (re-)entering the ring starts from zero deficit — the same
        idle-banks-no-credit rule ``sync_backlog`` enforces at both
        membership edges; one already IN the ring keeps its earned
        deficit (continuity)."""
        if tenant in self._in_ring:
            return
        st = self.tenants.get(tenant)
        if st is not None:
            st.deficit = 0.0
        self._ensure_ring(tenant)

    def set_weight(self, tenant, weight: float) -> None:
        if tenant in self.tenants:
            self.tenants[tenant].weight = max(weight, 1e-3)

    def forget(self, tenant) -> None:
        """Drop a tenant for good (conn closed, or idle GC): frees its
        metric series so conn churn cannot exhaust the cardinality
        bound."""
        if self.tenants.pop(tenant, None) is None:
            return
        self._topped.discard(tenant)
        self._dirty_shares.discard(tenant)
        if tenant in self._in_ring:
            self._in_ring.discard(tenant)
            try:
                self.ring.remove(tenant)
            except ValueError:
                pass
        self.metrics.remove("qos_grant_share", tenant=str(tenant))
        self.metrics.remove("qos_granted_chunks", tenant=str(tenant))
        self._g_tenants.set(len(self.tenants))

    def gc(self, busy: set) -> None:
        """Forget every tenant that is not in ``busy`` (no queued or
        in-flight work), has nothing granted outstanding, and whose
        admission bucket is full (nothing left to remember). Called from
        the scheduler's sweep so a long server life stays bounded by the
        live tenant set. Also refreshes grant-share gauges via
        :meth:`_update_shares` — the DIRTY set every sweep, everyone
        every :attr:`FULL_REFRESH_SWEEPS`-th (:meth:`on_grant` only
        re-sets the granted tenant's gauge, so idle tenants' gauges go
        boundedly stale against the grown total between full
        refreshes)."""
        for tenant in [t for t, st in self.tenants.items()
                       if t not in busy and st.inflight == 0
                       and st.bucket.full]:
            self.forget(tenant)
        self._update_shares()

    # ----------------------------------------------------------- admission

    def admit(self, tenant) -> bool:
        """Spend one admission token; False = shed at admission."""
        return self.tenants[tenant].bucket.take(1.0)

    def on_shed(self, tenant, reason: str) -> None:
        st = self.tenants.get(tenant)
        if st is not None:
            st.shed += 1
        self.metrics.counter(   # dbmlint: ok[cardinality] bounded:
            # reason is always one of the scheduler's literal shed kinds
            # ("admission" / "overload" / "conn"), never an entity id.
            "qos_shed_reason", reason=reason).inc()

    # ----------------------------------------------------------------- DRR

    def pick(self, candidates: Dict[object, int]) -> Optional[object]:
        """DRR selection among ``{tenant: next_item_cost_in_nonces}``.

        Classic deficit-round-robin with a PERSISTENT ring head: the
        tenant at the head is granted while its deficit covers its head
        item's cost (the ring does not advance on a grant — a tenant
        serves its quantum's worth of chunks contiguously), is topped up
        by ``weight * quantum`` at most ONCE per full ring cycle, and
        the ring rotates past it once it cannot afford even after the
        cycle's top-up. The quantum is the largest candidate cost of
        this pick, so at least one tenant can always eventually afford,
        every backlogged tenant is granted within ``ceil(1/weight)``
        cycles (no starvation), and sustained grant share in NONCES
        converges to the weight ratio. (Topping up once per MISS instead
        of once per CYCLE — the naive loop — banks unbounded credit for
        whichever tenant sits at the head, and one mispriced cost then
        starves the rest of the ring; see test_qos.py.)

        The caller must already have filtered candidates down to
        EXECUTABLE work (a miner with capacity, under the in-flight cap).
        Returns the granted tenant — the caller then performs the grant
        and reports it via :meth:`on_grant`, which debits the deficit —
        or None when there are no candidates.
        """
        if not candidates:
            return None
        for tenant in candidates:
            self.tenant(tenant)
            self._ensure_ring(tenant)   # ring membership for late joiners
        quantum = max(candidates.values()) or 1
        visited = 0
        for _ in range(self.MAX_PASSES * max(1, len(self.ring))):
            tenant = self.ring[0]
            cost = candidates.get(tenant)
            if cost is not None:
                st = self.tenants[tenant]
                if st.deficit >= cost:
                    return tenant
                if tenant not in self._topped:
                    self._topped.add(tenant)
                    st.deficit += st.weight * quantum
                    if st.deficit >= cost:
                        return tenant
            # Not grantable (no backlog, at cap, no miner capacity) or
            # cannot afford this cycle: move the head on.
            self.ring.rotate(-1)
            visited += 1
            if visited >= len(self.ring):
                visited = 0
                self._topped.clear()   # a new cycle may top up afresh
        return next(iter(candidates))   # unreachable safety valve

    def pick_lazy(self, head_fn) -> Optional[object]:
        """Lazy ring-ordered DRR selection (ISSUE 12, ``DBM_QOS_LAZY``).

        The stock :meth:`pick` consumes a fully materialized candidate
        map — the scheduler rebuilds it with an O(backlogged-tenants)
        heads scan before EVERY grant, the per-completion melt behind
        the N=1 superlinear tail at 10k tenants (BENCH_r06). Here the
        walk itself drives candidate discovery: ``head_fn(tenant)``
        prices ONE tenant's next grantable item on demand and returns

        - a positive cost in nonces (grantable now),
        - ``None`` (backlogged but not grantable this instant — at its
          in-flight cap, or no executable slot), or
        - :data:`LAZY_REMOVE` (no backlog at all — dropped from the
          ring on the spot, deficit forfeited).

        DRR semantics are the stock ones: persistent ring head, top-up
        at most once per cycle, rotate past a tenant that cannot afford
        after its cycle top-up. The quantum is the INCREMENTAL bound
        :attr:`_lazy_quantum` (max head cost seen so far) instead of a
        per-pick max over all candidates; since the bound dominates
        every priced cost, a backlogged tenant still affords within
        ``ceil(1/weight)`` cycles of its pricing, and sustained share
        still converges to the weight ratio. Amortized cost per grant
        is O(visited tenants) with the head staying put while its
        deficit lasts — O(1) for homogeneous traffic — instead of
        O(backlogged) per grant.
        """
        for _cycle in range(self.MAX_PASSES):
            visited = 0
            candidate_seen = False
            while visited < len(self.ring):
                if not self.ring:
                    break
                tenant = self.ring[0]
                cost = head_fn(tenant)
                if cost is LAZY_REMOVE:
                    self.ring.popleft()
                    self._in_ring.discard(tenant)
                    self._topped.discard(tenant)
                    st = self.tenants.get(tenant)
                    if st is not None:
                        st.deficit = 0.0   # idle credit never survives
                    continue               # next head, visit not spent
                if cost is not None:
                    candidate_seen = True
                    if cost > self._lazy_quantum:
                        self._lazy_quantum = float(cost)
                    st = self.tenant(tenant)
                    if st.deficit >= cost:
                        return tenant
                    if tenant not in self._topped:
                        self._topped.add(tenant)
                        st.deficit += st.weight * self._lazy_quantum
                        if st.deficit >= cost:
                            return tenant
                self.ring.rotate(-1)
                visited += 1
            if not self.ring:
                self._lazy_quantum = 0.0   # idle plane: fresh bound
                return None
            if not candidate_seen:
                return None                # nothing grantable this pass
            self._topped.clear()           # new cycle may top up afresh
        return None                        # safety valve (corrupt state)

    def on_grant(self, tenant, nonces: int) -> None:
        """Account one executed grant: debit the deficit, bump in-flight
        and the granted tenant's share gauge. Only the GRANTED tenant's
        gauge is re-set here (O(1) per grant — a full recompute would
        make every grant O(tenants)); the other tenants' gauges, stale
        by the grown total, are refreshed once per sweep from :meth:`gc`."""
        st = self.tenant(tenant)
        st.deficit = max(0.0, st.deficit - nonces)
        st.inflight += 1
        st.granted_chunks += 1
        st.granted_nonces += nonces
        self.total_granted_nonces += nonces
        self._dirty_shares.add(tenant)
        self.metrics.counter("qos_granted_chunks", tenant=str(tenant)).inc()
        self.metrics.gauge("qos_grant_share", tenant=str(tenant)).set(
            st.granted_nonces / self.total_granted_nonces)

    def on_chunk_answered(self, tenant) -> None:
        st = self.tenants.get(tenant)
        if st is not None and st.inflight > 0:
            st.inflight -= 1

    def release(self, tenant, outstanding: int) -> None:
        """A request retired with ``outstanding`` granted-but-unanswered
        chunks (prefix release, client drop): free the tenant's slots."""
        st = self.tenants.get(tenant)
        if st is not None:
            st.inflight = max(0, st.inflight - max(0, outstanding))

    def grant_share(self, tenant) -> float:
        """Cumulative granted-nonce share of one tenant (0 when nothing
        has been granted process-wide)."""
        st = self.tenants.get(tenant)
        if st is None or not self.total_granted_nonces:
            return 0.0
        return st.granted_nonces / self.total_granted_nonces

    def _update_shares(self) -> None:
        """Refresh share gauges: the DIRTY set (tenants granted since
        the last sweep) every sweep, everyone every
        :attr:`FULL_REFRESH_SWEEPS`-th sweep (bounding how stale an
        idle tenant's gauge can go against the grown total) — the
        O(active) replacement for the old every-sweep full walk."""
        if not self.total_granted_nonces:
            return
        self._sweeps += 1
        if self._sweeps % self.FULL_REFRESH_SWEEPS == 0:
            targets = self.tenants.items()
        else:
            targets = [(t, self.tenants[t]) for t in self._dirty_shares
                       if t in self.tenants]
        for tenant, st in targets:
            self.metrics.gauge("qos_grant_share", tenant=str(tenant)).set(
                st.granted_nonces / self.total_granted_nonces)
        self._dirty_shares.clear()
