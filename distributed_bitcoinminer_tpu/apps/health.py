"""Membership/health plane for the multi-process replica tier (ISSUE 12).

PR 11's in-process :class:`~.replicas.ReplicaSet` proved exactly-once
lease takeover for replicas killed by a METHOD CALL (``kill()``). The
multi-process tier replaces that test hook with OBSERVED failure: each
replica process heartbeats a small state blob (the :class:`Beat` —
serving bit, miner-slice size, queue depth, the membership epoch it has
seen), and a router declares a replica dead after ``miss_k`` missed
beats, bumps the FENCING EPOCH, and publishes the new membership. Every
piece of that logic lives HERE, transport-free — the real router
(``apps/procs.py``) drives it over a shared state directory with wall
clocks, and the dbmcheck ``health_takeover`` scenario drives the same
code over a virtual clock with an in-memory beat bus, so the
detection/fencing state machine the processes run is the one the
deterministic explorer proves.

The three objects:

- :class:`Beat` — one replica's heartbeat blob. ``seq`` must advance
  every beat; a frozen seq is a missed beat whatever the wall clock
  says (a SIGSTOPped process's stale file keeps its old mtime AND its
  old seq — the monitor never trusts file timestamps).
- :class:`BeatMonitor` — missed-beat failure detection: a replica whose
  seq has not advanced within ``miss_k * beat_s`` of the observer's
  clock is DEAD. Purely a function of (observations, now).
- :class:`Membership` — the advertised ring + the fencing ledger.
  ``epoch`` bumps on every change. Declaring a replica dead records its
  ``(rid, incarnation)`` in ``fenced``: a fenced incarnation is NEVER
  re-admitted (only a fresh incarnation of the rid is), its late
  Results land on conns its clients/miners have already abandoned, and
  its cache spool lines are dropped at ingest
  (:meth:`Membership.writer_fenced`) — the "declared dead but still
  serving" partitioned-replica case resolves stale everywhere.

Fencing contract (the dbmcheck scenario's invariant): once
``declare_dead(rid)`` has been observed by a replica (its own
``(rid, incarnation)`` in ``fenced``), that replica must STOP SERVING —
close its transport so its clients resubmit to the new ring owner and
its miners rejoin a survivor. Until it observes the fence it may keep
serving; that window is safe because (a) its clients' retry plane has
already abandoned the conns its late Results ride, and (b) every answer
is a pure function of the request key, so even a delivered late Result
is bit-identical to the survivor's — the fence exists to bound waste
and to keep the replicated cache tier hygienic, not to patch a
correctness hole.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional

__all__ = ["Beat", "BeatMonitor", "Membership", "SeqFreshness"]


class SeqFreshness:
    """Generic seq-advance freshness tracking (the BeatMonitor core).

    Tracks, per arbitrary hashable key, the last ``(gen, seq)`` pair seen
    and WHEN it advanced: an observation advances iff the key is new, the
    generation changed (a restart is always fresh), or the seq grew
    within the same generation. ``stale(now)`` lists keys whose seq has
    been frozen past ``window_s`` of the observer's clock — re-reading an
    unchanged blob never refreshes the deadline, so a SIGSTOPped writer's
    lingering file is a death, not a heartbeat. Extracted from
    :class:`BeatMonitor` (which delegates here) so the metric-rollup
    plane (ISSUE 18) applies the identical staleness rule to published
    snapshot blobs, keyed by ``(role, rid)`` instead of rid.
    """

    def __init__(self, window_s: float):
        self.window_s = max(1e-3, window_s)
        self._last: Dict[object, tuple] = {}      # key -> (gen, seq)
        self._fresh_at: Dict[object, float] = {}  # key -> when it advanced

    def observe(self, key, gen, seq, now: float) -> bool:
        """Record one observation; True when it ADVANCED the key."""
        prev = self._last.get(key)
        advanced = (prev is None or gen != prev[0] or seq > prev[1])
        if advanced:
            self._last[key] = (gen, seq)
            self._fresh_at[key] = now
        return advanced

    def fresh_at(self, key) -> Optional[float]:
        """When the key last advanced (observer clock), or None."""
        return self._fresh_at.get(key)

    def age_s(self, key, now: float) -> Optional[float]:
        """Seconds since the key last advanced, or None when unseen."""
        at = self._fresh_at.get(key)
        return None if at is None else max(0.0, now - at)

    def stale(self, now: float) -> List[object]:
        """Keys whose seq has been frozen past the window."""
        return [k for k, at in self._fresh_at.items()
                if now - at > self.window_s]

    def keys(self) -> List[object]:
        return list(self._last.keys())

    def forget(self, key) -> None:
        self._last.pop(key, None)
        self._fresh_at.pop(key, None)


@dataclass
class Beat:
    """One replica heartbeat (the small state blob on the wire/file)."""

    rid: int
    incarnation: str        # unique per process start (pid + stamp)
    seq: int                # MUST advance every beat
    port: int = 0           # the replica's own LSP socket
    serving: bool = True
    miners: int = 0         # miner-slice size (agent placement hint)
    queue_depth: int = 0
    epoch_seen: int = 0     # membership epoch the replica last observed

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "Beat":
        return cls(**{k: d[k] for k in
                      ("rid", "incarnation", "seq", "port", "serving",
                       "miners", "queue_depth", "epoch_seen") if k in d})


class BeatMonitor:
    """Missed-beat failure detection over observed :class:`Beat`\\ s.

    ``observe(beat, now)`` records a beat; ``dead(now)`` lists replicas
    whose seq has not advanced within ``miss_k * beat_s`` of ``now``.
    The deadline is re-anchored ONLY when seq advances — replaying a
    stale blob (same seq) does not count as life, which is what makes a
    SIGSTOPped process's lingering state file a death, not a heartbeat.
    """

    def __init__(self, beat_s: float, miss_k: int):
        self.beat_s = max(1e-3, beat_s)
        self.miss_k = max(1, miss_k)
        self._last: Dict[int, Beat] = {}      # rid -> newest beat
        self._fresh = SeqFreshness(self.beat_s * self.miss_k)

    @property
    def window_s(self) -> float:
        """Seconds of seq silence that mean death."""
        return self._fresh.window_s

    def observe(self, beat: Beat, now: float) -> bool:
        """Record one beat; True when it ADVANCED the replica's seq
        (same-or-older seqs, e.g. a re-read of a stale file, do not
        refresh the death deadline)."""
        advanced = self._fresh.observe(beat.rid, beat.incarnation,
                                       beat.seq, now)
        if advanced:
            self._last[beat.rid] = beat
        return advanced

    def last(self, rid: int) -> Optional[Beat]:
        return self._last.get(rid)

    def beats(self) -> List[Beat]:
        return list(self._last.values())

    def dead(self, now: float) -> List[int]:
        """Replica ids whose seq has been frozen past the window."""
        return self._fresh.stale(now)

    def forget(self, rid: int) -> None:
        """Stop watching a declared-dead replica (it re-enters the
        watch when a fresh incarnation beats)."""
        self._last.pop(rid, None)
        self._fresh.forget(rid)


class Membership:
    """The advertised ring + fencing ledger the router publishes.

    ``live`` maps rid -> {port, incarnation}; ``epoch`` bumps on every
    membership change; ``fenced`` maps rid -> {incarnation, epoch} for
    the LAST fenced incarnation of that rid (one suffices: a rid has at
    most one live incarnation, and older fenced ones can never beat
    again without being re-fenced as stale by the incarnation check).
    """

    def __init__(self):
        self.epoch = 0
        self.live: Dict[int, dict] = {}
        self.fenced: Dict[int, dict] = {}

    # ------------------------------------------------------------ changes

    def admit(self, beat: Beat) -> bool:
        """Admit a beating replica: first sight of the rid, or a FRESH
        incarnation of a previously fenced/dead one. A fenced
        incarnation is never re-admitted — that is the fence. Returns
        True when membership changed."""
        fence = self.fenced.get(beat.rid)
        if fence is not None and fence["incarnation"] == beat.incarnation:
            return False        # the fenced incarnation itself: refused
        entry = self.live.get(beat.rid)
        if entry is not None and entry["incarnation"] == beat.incarnation:
            if entry.get("port") == beat.port:
                return False    # already live, nothing changed
        self.live[beat.rid] = {"port": beat.port,
                               "incarnation": beat.incarnation}
        self.epoch += 1
        return True

    def declare_dead(self, rid: int) -> bool:
        """Missed-beat death: drop the rid from the ring and FENCE its
        incarnation at the new epoch. Returns True when it was live."""
        entry = self.live.pop(rid, None)
        if entry is None:
            return False
        self.epoch += 1
        self.fenced[rid] = {"incarnation": entry["incarnation"],
                            "epoch": self.epoch}
        return True

    # ------------------------------------------------------------ queries

    def is_fenced(self, rid: int, incarnation: str) -> bool:
        """Has THIS incarnation of ``rid`` been declared dead? (What a
        replica checks about itself to decide to stop serving.)"""
        fence = self.fenced.get(rid)
        return fence is not None and fence["incarnation"] == incarnation

    def writer_fenced(self, rid: int, incarnation: str) -> bool:
        """Should a cache-spool line from this writer be dropped?
        Everything a fenced incarnation wrote is refused — conservative
        (its pre-death entries are sacrificed too), but a replicated-
        cache miss only degrades to recompute, never to a wrong reply,
        and a fenced process's post-death writes must never propagate."""
        return self.is_fenced(rid, incarnation)

    def to_dict(self) -> dict:
        return {"epoch": self.epoch,
                "live": {str(r): dict(v) for r, v in self.live.items()},
                "fenced": {str(r): dict(v)
                           for r, v in self.fenced.items()}}

    @classmethod
    def from_dict(cls, d: dict) -> "Membership":
        m = cls()
        m.epoch = int(d.get("epoch", 0))
        m.live = {int(r): dict(v)
                  for r, v in (d.get("live") or {}).items()}
        m.fenced = {int(r): dict(v)
                    for r, v in (d.get("fenced") or {}).items()}
        return m


@dataclass
class RouterState:
    """One router tick's working state (monitor + membership), bundled
    so the file-based router and the dbmcheck model share the exact
    tick logic via :func:`router_tick`."""

    monitor: BeatMonitor
    membership: Membership = field(default_factory=Membership)


def router_tick(state: RouterState, beats: List[Beat],
                now: float) -> bool:
    """One detection/advertisement tick, shared by the real router and
    the dbmcheck ``health_takeover`` model: feed the freshly read beats
    to the monitor, admit fresh serving incarnations, declare
    missed-beat deaths. Returns True when membership changed (the
    file-based router republishes only then)."""
    changed = False
    for beat in beats:
        advanced = state.monitor.observe(beat, now)
        if beat.serving:
            if state.membership.admit(beat):
                changed = True
        elif advanced:
            # Graceful leave: a live incarnation beating serving=False
            # fences itself immediately instead of burning the missed-
            # beat window.
            entry = state.membership.live.get(beat.rid)
            if entry is not None and \
                    entry["incarnation"] == beat.incarnation:
                state.membership.declare_dead(beat.rid)
                state.monitor.forget(beat.rid)
                changed = True
    for rid in state.monitor.dead(now):
        if state.membership.declare_dead(rid):
            changed = True
        state.monitor.forget(rid)
    return changed


__all__.extend(["RouterState", "router_tick"])
