"""Cluster metric rollup: per-process snapshot blobs -> one snapshot.

Every observability plane before this one — metrics (PR 3), traces
(PR 9), capture (PR 15) — is strictly per-process: a ``--procs``
deployment emits N interleaved JSONL streams no tool merges, so "is the
cluster healthy" means a human grepping router, replica, and miner-agent
logs side by side. This module is the merge point (ISSUE 18): each
env-armed process publishes a versioned snapshot blob of its metrics
:class:`~..utils.metrics.Registry` into the health-beat state directory
(same atomic tmp+rename discipline as beats and membership, stamped with
role/rid/incarnation, a publish seq, and the membership epoch it has
seen), and :func:`aggregate` merges the blobs into ONE coherent cluster
snapshot that ``scripts/dbmtop.py``, the SLO tracker (``apps/slo.py``),
the loadharness ``--procs`` gates, and ``dbmtrace summarize`` all read.

Merge semantics, per metric kind:

- **counters** — summed across sources per series key: the cluster's
  ``sched.results_sent`` is exactly the sum of the per-process
  registries (test-pinned in tests/test_rollup.py);
- **histograms** — cumulative-``le`` buckets merged elementwise when the
  bounds agree (they do for every built-in family — buckets are frozen
  at construction), kept per-source under a ``proc`` label otherwise;
- **gauges** — last-write-wins scalars cannot be meaningfully summed
  across processes, so each stays per-source under a ``proc`` label;
- **EWMAs** — combined sample-weighted (``sum(v*n)/sum(n)``): a replica
  that has folded in 10x the samples carries 10x the weight.

The ``proc`` label is a dynamic, churn-prone label (miner agents come
and go with their pids), so it rides the same cardinality discipline as
every other dynamic label in the tree: per-source series are admitted
through a :class:`SourceSet` bounded by ``DBM_METRICS_MAX_SERIES``, a
retired source (fenced replica, expired miner agent) frees its slot via
``retire_proc``, and overflow is COUNTED in the merged snapshot's
``series_overflow``, never silently dropped. The dbmlint cardinality
analyzer knows ``proc_series``/``retire_proc`` as a registration/
retirement pair (satellite of ISSUE 18).

Staleness: a frozen publisher is FLAGGED, not averaged in. Stateless
readers (``dbmtop --once``, the loadharness gate) age each blob's own
wall stamp against the publisher's advertised beat cadence times
``DBM_ROLLUP_STALE_K``; the long-lived console additionally runs a
:class:`~.health.SeqFreshness` tracker (the BeatMonitor core, extracted
for exactly this reuse) keyed by ``(role, rid)`` so a replayed stale
blob never counts as life. A fenced replica incarnation's blob is
dropped from cluster totals exactly like its cache spool lines
(status ``fenced``), and blobs stale past many windows are garbage
collected by the router alongside fenced spools.

Everything is behind ``DBM_ROLLUP`` (default 1 for env-armed processes;
the knob-off matrix leg pins 0 = bit-for-bit stock: no publisher
construction, no blobs, no identity stamps).
"""

from __future__ import annotations

import os
import time
from typing import Dict, Iterable, List, Optional, Tuple

from ..utils._env import float_env as _float_env, int_env as _int_env
from .health import Membership, SeqFreshness

__all__ = ["rollup_enabled", "stale_k", "blob_path", "read_blobs",
           "RollupPublisher", "SourceSet", "merge_snapshots",
           "hist_quantile", "aggregate", "RollupState",
           "gc_stale_blobs"]

#: Blob format version (readers skip versions they do not understand).
BLOB_V = 1

_PREFIX = "metrics_"


def rollup_enabled() -> bool:
    """``DBM_ROLLUP`` (default 1): the cluster rollup plane — env-armed
    processes publish metric snapshot blobs into the state directory and
    stamp their logs with process identity; 0 = bit-for-bit stock."""
    return _int_env("DBM_ROLLUP", 1) != 0


def stale_k() -> int:
    """``DBM_ROLLUP_STALE_K`` (default = ``DBM_HEALTH_MISS_K``'s
    default, 3): publish periods of silence before a source's blob is
    flagged stale and dropped from cluster totals."""
    return max(1, _int_env("DBM_ROLLUP_STALE_K",
                           _int_env("DBM_HEALTH_MISS_K", 3)))


def blob_path(statedir: str, role: str, rid) -> str:
    """State-plane path of one source's snapshot blob. Keyed by (role,
    rid) — NOT incarnation — so a respawned process overwrites its
    predecessor's blob instead of leaking one file per restart."""
    return os.path.join(statedir, f"{_PREFIX}{role}_{rid}.json")


def read_blobs(statedir: str) -> List[dict]:
    """Every well-formed snapshot blob in the state directory, sorted by
    (role, rid) for deterministic aggregation."""
    from .procs import read_json
    out = []
    try:
        names = os.listdir(statedir)
    except OSError:
        return out
    for name in names:
        if not (name.startswith(_PREFIX) and name.endswith(".json")):
            continue
        d = read_json(os.path.join(statedir, name))
        if (isinstance(d, dict) and d.get("v") == BLOB_V
                and isinstance(d.get("snapshot"), dict)
                and "role" in d and "rid" in d):
            out.append(d)
    out.sort(key=lambda d: (str(d["role"]), str(d["rid"])))
    return out


class RollupPublisher:
    """One process's side of the rollup plane: periodic atomic snapshot
    blobs into the state directory.

    ``publish()`` is called from the process's existing beat/tick loop
    (replica beat loop, router tick, miner-agent beat task) — no new
    thread, one registry snapshot + one small file write per beat.
    Never raises: metrics publishing must not take down a serving
    process (a full disk degrades observability, not service).
    """

    def __init__(self, statedir: str, role: str, rid, incarnation: str,
                 registry=None, beat_s: Optional[float] = None):
        from ..utils import metrics as _metrics
        self.statedir = statedir
        self.role = str(role)
        self.rid = rid
        self.incarnation = str(incarnation)
        self.registry = registry if registry is not None \
            else _metrics.registry()
        if beat_s is None:
            beat_s = _float_env("DBM_HEALTH_BEAT_S", 0.5)
        #: Advertised cadence: readers size the staleness window from
        #: the blob itself, so a console run without the cluster's env
        #: still judges freshness by the publisher's actual period.
        self.beat_s = max(0.01, float(beat_s))
        self.seq = 0
        self.path = blob_path(statedir, self.role, self.rid)

    def publish(self, epoch_seen: int = 0, final: bool = False) -> bool:
        """Write one blob (seq advances per call). True on success."""
        from .procs import write_json_atomic
        self.seq += 1
        doc = {"v": BLOB_V, "role": self.role, "rid": self.rid,
               "inc": self.incarnation, "seq": self.seq,
               "wall": time.time(), "beat_s": self.beat_s,
               "epoch_seen": int(epoch_seen), "final": bool(final),
               "snapshot": self.registry.snapshot()}
        try:
            write_json_atomic(self.path, doc)
            return True
        except OSError:
            return False


# ------------------------------------------------------------------ merging


class SourceSet:
    """Bounded admission of per-source (``proc``-labeled) series.

    The ``proc`` label space is unbounded under miner-agent churn (one
    value per agent pid), so it gets the registry's own cardinality
    discipline: at most ``max_series`` distinct label sets per family,
    further sets are refused and counted (``overflows``), and a retired
    source frees its slot. ``proc_series``/``retire_proc`` mirror the
    ``counter``/``remove`` and ``track``/``retire`` pairs the dbmlint
    cardinality analyzer enforces — a dynamic ``proc`` label needs a
    same-module retirement path.
    """

    def __init__(self, max_series: Optional[int] = None):
        self.max_series = (max_series if max_series is not None
                           else _int_env("DBM_METRICS_MAX_SERIES", 64))
        self._families: Dict[str, set] = {}
        self.overflows = 0

    @staticmethod
    def _key(labels: dict) -> Tuple[Tuple[str, str], ...]:
        return tuple(sorted((k, str(v)) for k, v in labels.items()))

    def proc_series(self, family: str, **labels) -> bool:
        """Admit one labeled series into ``family``; False (and counted)
        past the cardinality bound."""
        key = self._key(labels)
        admitted = self._families.setdefault(family, set())
        if key in admitted:
            return True
        if len(admitted) >= self.max_series:
            self.overflows += 1
            return False
        admitted.add(key)
        return True

    def retire_proc(self, family: str, **labels) -> None:
        """Free a retired source's slot (fenced replica / expired miner
        agent) — churn cycles slots instead of exhausting them."""
        self._families.get(family, set()).discard(self._key(labels))

    def sources(self, family: str) -> List[Tuple[Tuple[str, str], ...]]:
        return sorted(self._families.get(family, set()))


def _with_proc(series_key: str, proc: str) -> str:
    """``name`` -> ``name{proc=X}``; ``name{a=b}`` -> ``name{a=b,proc=X}``."""
    if series_key.endswith("}"):
        return f"{series_key[:-1]},proc={proc}}}"
    return f"{series_key}{{proc={proc}}}"


def merge_snapshots(sources: Iterable[Tuple[str, dict]],
                    source_set: Optional[SourceSet] = None) -> dict:
    """Merge per-process registry snapshots into one cluster snapshot.

    ``sources`` is ``(proc_key, snapshot)`` pairs (snapshot as produced
    by ``Registry.snapshot()``). Pure function of its inputs — merging
    the same blobs twice yields the identical document (the idempotence
    property tests/test_rollup.py pins). Counters sum; histograms merge
    elementwise when bucket bounds agree, else fall back to per-source;
    gauges stay per-source under a ``proc`` label; EWMAs combine
    sample-weighted. Per-source series go through ``source_set`` (a
    fresh bound when None) so ``proc`` cardinality is capped.
    """
    if source_set is None:
        source_set = SourceSet()
    out = {"counters": {}, "gauges": {}, "histograms": {}, "ewmas": {},
           "series_overflow": 0, "sources": 0}
    ewma_acc: Dict[str, list] = {}   # key -> [weighted_sum, samples]
    for proc, snap in sources:
        out["sources"] += 1
        out["series_overflow"] += int(snap.get("series_overflow", 0))
        admitted = source_set.proc_series("rollup_sources", proc=proc)
        for key, v in (snap.get("counters") or {}).items():
            out["counters"][key] = out["counters"].get(key, 0) + int(v)
        for key, v in (snap.get("gauges") or {}).items():
            if not admitted:
                out["series_overflow"] += 1
                continue
            out["gauges"][_with_proc(key, proc)] = v
        for key, h in (snap.get("histograms") or {}).items():
            cur = out["histograms"].get(key)
            if cur is not None and cur.get("le") == h.get("le"):
                cur["counts"] = [a + b for a, b in
                                 zip(cur["counts"], h["counts"])]
                cur["count"] += int(h.get("count", 0))
                cur["sum"] = round(cur["sum"] + float(h.get("sum", 0.0)),
                                   6)
            elif cur is None:
                out["histograms"][key] = {
                    "le": list(h.get("le") or []),
                    "counts": list(h.get("counts") or []),
                    "count": int(h.get("count", 0)),
                    "sum": float(h.get("sum", 0.0))}
            else:
                # Bucket-bound mismatch (custom buckets on one source):
                # summing would lie, so this source's copy stays
                # attributed under its proc label.
                if not admitted:
                    out["series_overflow"] += 1
                    continue
                out["histograms"][_with_proc(key, proc)] = dict(h)
        for key, e in (snap.get("ewmas") or {}).items():
            v, n = e.get("value"), int(e.get("samples", 0))
            acc = ewma_acc.setdefault(key, [0.0, 0])
            if v is not None and n > 0:
                acc[0] += float(v) * n
                acc[1] += n
    for key, (ws, n) in ewma_acc.items():
        out["ewmas"][key] = {
            "value": round(ws / n, 6) if n else None, "samples": n}
    # series_overflow counts SERIES dropped in THIS merge (per skipped
    # gauge/histogram) — not SourceSet.overflows, which is cumulative
    # across refreshes and would inflate a long-lived console's totals.
    for kind in ("counters", "gauges", "histograms", "ewmas"):
        out[kind] = dict(sorted(out[kind].items()))
    return out


def hist_quantile(h: Optional[dict], q: float) -> Optional[float]:
    """The ``q``-quantile upper bound from a cumulative-``le`` snapshot
    histogram (the bound of the first bucket covering ``q`` of the
    observations). None when empty/absent or when the quantile lies in
    the +Inf bucket — the caller renders that as ``>max_bound``."""
    if not h or not h.get("count"):
        return None
    target = q * h["count"]
    for bound, cum in zip(h.get("le") or [], h.get("counts") or []):
        if cum >= target:
            return float(bound)
    return None


# ---------------------------------------------------------------- aggregate


#: Headline per-source stats surfaced on each proc row (dbmtop columns,
#: SLO worst-offender attribution) — family name -> row key. Counters
#: and gauges sum across label sets within the family.
_DETAIL_COUNTERS = (("sched.results_sent", "results"),
                    ("sched.qos_shed", "shed"),
                    ("sched.leases_blown", "leases_blown"))
_DETAIL_GAUGES = (("sched.queue_depth", "queue"),
                  ("sched.pool_size", "pool"),
                  ("sched.lease_min_remaining_s", "lease_min_s"))


def _family_values(section: dict, family: str) -> List[float]:
    pref = family + "{"
    return [float(v) for k, v in section.items()
            if k == family or k.startswith(pref)
            if isinstance(v, (int, float))]


def _proc_detail(snap: dict) -> dict:
    counters = snap.get("counters") or {}
    gauges = snap.get("gauges") or {}
    detail: dict = {}
    for family, out_key in _DETAIL_COUNTERS:
        vals = _family_values(counters, family)
        if vals:
            detail[out_key] = int(sum(vals))
    for family, out_key in _DETAIL_GAUGES:
        vals = _family_values(gauges, family)
        if vals:
            detail[out_key] = round(sum(vals), 3)
    trust = _family_values(gauges, "sched.miner_trust")
    if trust:
        detail["trust_min"] = round(min(trust), 3)
    p99 = hist_quantile((snap.get("histograms") or {})
                        .get("sched.queue_wait_s"), 0.99)
    if p99 is not None:
        detail["queue_wait_p99_s"] = p99
    for family in ("miner.nonces_per_s", "sched.pool_rate_nps"):
        e = (snap.get("ewmas") or {}).get(family) or \
            (snap.get("gauges") or {}).get(family)
        v = e.get("value") if isinstance(e, dict) else e
        if isinstance(v, (int, float)):
            detail["nps"] = round(float(v), 1)
            break
    return detail


def aggregate(statedir: str, *, now: Optional[float] = None,
              membership: Optional[Membership] = None,
              source_set: Optional[SourceSet] = None) -> dict:
    """One cluster snapshot from the state directory's blobs.

    Per source: status ``fenced`` (a fenced replica incarnation — its
    numbers are dropped exactly like its cache spool lines), ``stale``
    (wall stamp older than ``beat_s * DBM_ROLLUP_STALE_K`` — a frozen
    publisher is flagged, not averaged in), or ``fresh`` (merged into
    the cluster totals). Pure function of (files, now): re-reading the
    same directory yields the identical document.
    """
    from .procs import read_membership
    if now is None:
        now = time.time()
    if membership is None:
        membership = read_membership(statedir)
    k = stale_k()
    procs_out: List[dict] = []
    fresh: List[Tuple[str, dict]] = []
    for blob in read_blobs(statedir):
        role, rid = str(blob["role"]), blob["rid"]
        inc = str(blob.get("inc", ""))
        window_s = max(0.01, float(blob.get("beat_s", 0.5))) * k
        age_s = max(0.0, now - float(blob.get("wall", 0.0)))
        if role == "replica" and membership is not None \
                and membership.is_fenced(int(rid), inc):
            status = "fenced"
        elif age_s > window_s:
            status = "stale"
        else:
            status = "fresh"
        proc_key = f"{role}{rid}"
        procs_out.append({
            "proc": proc_key, "role": role, "rid": rid, "inc": inc,
            "seq": int(blob.get("seq", 0)), "status": status,
            "age_s": round(age_s, 3), "window_s": round(window_s, 3),
            "epoch_seen": int(blob.get("epoch_seen", 0)),
            "detail": _proc_detail(blob["snapshot"])})
        if status == "fresh":
            fresh.append((proc_key, blob["snapshot"]))
    doc = {"v": BLOB_V, "event": "rollup", "at": now,
           "procs": procs_out,
           "cluster": merge_snapshots(fresh, source_set=source_set)}
    if membership is not None:
        doc["membership"] = membership.to_dict()
    return doc


class RollupState:
    """Long-lived aggregation state for the live console.

    Adds what the stateless :func:`aggregate` cannot have: seq-advance
    freshness (a SIGSTOPped publisher whose blob keeps being re-read
    never counts as alive — same :class:`~.health.SeqFreshness` rule the
    BeatMonitor runs), a shared :class:`SourceSet` so the ``proc`` label
    bound holds across refreshes with retirement on fence/expiry, and
    the membership epoch timeline dbmtop renders.
    """

    #: Windows of continuous staleness before a source's slot is retired
    #: (its series bound slot frees; a revived source re-admits).
    RETIRE_K = 20

    def __init__(self, statedir: str, history: int = 32):
        self.statedir = statedir
        self.sources = SourceSet()
        self._fresh: Optional[SeqFreshness] = None
        self._epochs: List[Tuple[float, int]] = []   # (wall, epoch)
        self.history = history

    def epochs(self) -> List[Tuple[float, int]]:
        return list(self._epochs)

    def refresh(self, now: Optional[float] = None) -> dict:
        """One console frame: aggregate + seq-freshness overlay."""
        if now is None:
            now = time.time()
        doc = aggregate(self.statedir, now=now, source_set=self.sources)
        window = max((p["window_s"] for p in doc["procs"]), default=1.0)
        if self._fresh is None:
            self._fresh = SeqFreshness(window)
        self._fresh.window_s = max(1e-3, window)
        stale_keys = set()
        for p in doc["procs"]:
            key = (p["role"], p["rid"])
            self._fresh.observe(key, p["inc"], p["seq"], now)
        stale_keys.update(self._fresh.stale(now))
        for p in doc["procs"]:
            key = (p["role"], p["rid"])
            if p["status"] == "fresh" and key in stale_keys:
                # Wall stamp advanced but seq did not (replayed/cloned
                # blob): the seq rule wins, exactly as for beats.
                p["status"] = "stale"
            if p["status"] != "fresh":
                age = self._fresh.age_s(key, now)
                if p["status"] == "fenced" or (
                        age is not None
                        and age > self._fresh.window_s * self.RETIRE_K):
                    self.sources.retire_proc("rollup_sources",
                                             proc=p["proc"])
        epoch = (doc.get("membership") or {}).get("epoch")
        if epoch is not None and (not self._epochs
                                  or self._epochs[-1][1] != epoch):
            self._epochs.append((now, int(epoch)))
            del self._epochs[:-self.history]
        return doc


def gc_stale_blobs(statedir: str, *, now: Optional[float] = None,
                   retire_k: int = RollupState.RETIRE_K) -> int:
    """Unlink snapshot blobs dead past ``retire_k`` staleness windows.

    The router calls this alongside ``gc_fenced_spools``: a freshly
    fenced/killed process's blob stays VISIBLE (flagged, excluded from
    totals — the operator sees the death), but a blob nobody has
    refreshed for many windows is litter from long-gone incarnations
    (miner agents churn pids) and is removed. Returns blobs unlinked.
    """
    if now is None:
        now = time.time()
    removed = 0
    for blob in read_blobs(statedir):
        window_s = max(0.01, float(blob.get("beat_s", 0.5))) * stale_k()
        age_s = now - float(blob.get("wall", 0.0))
        if age_s > window_s * max(1, retire_k):
            try:
                os.unlink(blob_path(statedir, str(blob["role"]),
                                    blob["rid"]))
                removed += 1
            except OSError:
                pass
    return removed
