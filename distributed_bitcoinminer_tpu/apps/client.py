"""The request client: submit one nonce range, await the merged Result.

Same contract as the reference submitter (ref: bitcoin/client/client.go):
write Request(message, 0, maxNonce), block on Read, report
``Result <hash> <nonce>`` or ``Disconnected`` when the connection is lost.
"""

from __future__ import annotations

from typing import Optional, Tuple

from ..bitcoin.message import Message, MsgType, new_request
from ..lsp.client import new_async_client
from ..lsp.errors import LspError
from ..lsp.params import Params


async def submit(hostport: str, message: str, max_nonce: int,
                 params: Optional[Params] = None) -> Optional[Tuple[int, int]]:
    """Submit and await one request; None means the connection was lost."""
    client = await new_async_client(hostport, params)
    client.write(new_request(message, 0, max_nonce).to_json())
    try:
        payload = await client.read()
    except LspError:
        return None
    finally:
        await client.close()
    msg = Message.from_json(payload)
    if msg.type != MsgType.RESULT:
        return None
    return msg.hash, msg.nonce


async def stream_until(hostport: str, message: str, target: int,
                       span: int = 1 << 24, start: int = 0,
                       max_nonce: Optional[int] = None,
                       params: Optional[Params] = None,
                       ) -> Optional[Tuple[int, int, int]]:
    """Difficulty-target mode (BASELINE config 5): stream Requests span by
    span until a merged Result beats ``target``.

    Pure protocol addition — each span rides a stock Request, the scheduler
    dynamically rebalances every span over the live miner pool, and miners
    early-exit in-kernel via their own target heuristics if they implement
    one. Returns (hash, nonce, spans_scanned) or None on disconnect /
    exhausted ``max_nonce``.

    ``max_nonce=None`` bounds the stream at the end of the nonce space
    (2^64 - 1) rather than looping forever on an unreachable target
    (ADVICE r1/r2): the op hashes ``"<data> <nonce>"`` with a uint64 nonce
    (ref: bitcoin/hash.go:13-17), so the search space is finite.
    """
    from ..bitcoin.hash import MAX_U64
    if max_nonce is None:
        max_nonce = MAX_U64
    client = await new_async_client(hostport, params)
    spans = 0
    lower = start
    try:
        while lower <= max_nonce:
            upper = min(lower + span - 1, max_nonce)
            client.write(new_request(message, lower, upper).to_json())
            try:
                payload = await client.read()
            except LspError:
                return None
            msg = Message.from_json(payload)
            if msg.type != MsgType.RESULT:
                return None
            spans += 1
            if msg.hash < target:
                return msg.hash, msg.nonce, spans
            lower = upper + 1
        return None
    finally:
        await client.close()


def printable_result(result: Optional[Tuple[int, int]]) -> str:
    """Exact stdout contract of the reference (client.go:61-68)."""
    if result is None:
        return "Disconnected"
    return f"Result {result[0]} {result[1]}"


def main(argv=None) -> int:
    """CLI contract of the reference binary (ref: client.go:24-58):
    ``client <hostport> <message> <maxNonce>``."""
    import asyncio
    import sys
    argv = sys.argv if argv is None else argv
    if len(argv) != 4:
        print(f"Usage: ./{argv[0]} <hostport> <message> <maxNonce>", end="")
        return 1
    try:
        max_nonce = int(argv[3])
        if max_nonce < 0:
            raise ValueError
    except ValueError:
        print(f"{argv[3]} is not a number.")
        return 1
    from ..utils import from_env
    try:
        result = asyncio.run(submit(argv[1], argv[2], max_nonce,
                                    from_env().params))
    except LspError as exc:
        print("Failed to connect to server:", exc)
        return 1
    print(printable_result(result))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
