"""The request client: submit one nonce range, await the merged Result.

Same contract as the reference submitter (ref: bitcoin/client/client.go):
write Request(message, 0, maxNonce), block on Read, report
``Result <hash> <nonce>`` or ``Disconnected`` when the connection is lost.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional, Tuple

from ..bitcoin.message import Message, MsgType, new_request
from ..lsp.client import new_async_client
from ..lsp.errors import LspError
from ..lsp.params import Params
from ..utils._env import int_env as _int_env
from ..utils.config import RetryParams
from ..utils.metrics import registry as _registry

logger = logging.getLogger("dbm.client")

# Client-side retry metrics (utils/metrics.py): how often the retry plane
# actually fires, and how attempts resolve.
_M = _registry()
_MET_ATTEMPTS = _M.counter("client.retry_attempts")
_MET_OUTCOME = {k: _M.counter("client.retry_outcomes", outcome=k)
                for k in ("ok", "exhausted")}
_MET_RESULT_S = _M.histogram("client.result_latency_s")


async def submit(hostport: str, message: str, max_nonce: int,
                 params: Optional[Params] = None) -> Optional[Tuple[int, int]]:
    """Submit and await one request; None means the connection was lost.

    The stock exact-arg-min mode is the target-0 special case of
    :func:`submit_until` (target 0 serializes to reference-identical
    bytes, message.py)."""
    result = await submit_until(hostport, message, max_nonce, 0, params)
    return None if result is None else result[:2]


async def submit_until(hostport: str, message: str, max_nonce: int,
                       target: int, params: Optional[Params] = None,
                       ) -> Optional[Tuple[int, int, bool]]:
    """Difficulty-target mode, native protocol: one Request carrying
    ``Target`` (wire extension, see bitcoin/message.py).

    The scheduler fans the target out with every chunk and miners
    early-exit in-kernel at their chunk's first qualifying nonce
    (models.NonceSearcher.search_until), so a loose target completes far
    ahead of the full arg-min scan. Returns ``(hash, nonce, found)`` —
    found means ``hash < target`` and, when every miner speaks the
    extension, ``nonce`` is the FIRST qualifying nonce of the scanned
    range; found=False hands back the exact arg-min (target missed
    everywhere). None = connection lost. For a STOCK scheduler that drops
    the Target key, use :func:`stream_until` instead — it needs nothing
    beyond the reference wire.
    """
    client = await new_async_client(hostport, params)
    client.write(new_request(message, 0, max_nonce, target).to_json())
    try:
        payload = await client.read()
    except LspError:
        return None
    finally:
        await client.close()
    try:
        msg = Message.from_json(payload)
    except ValueError:
        return None
    if msg.type != MsgType.RESULT:
        return None
    return msg.hash, msg.nonce, msg.hash < target


async def stream_until(hostport: str, message: str, target: int,
                       span: int = 1 << 24, start: int = 0,
                       max_nonce: Optional[int] = None,
                       params: Optional[Params] = None,
                       ) -> Optional[Tuple[int, int, int]]:
    """Difficulty-target mode (BASELINE config 5): stream Requests span by
    span until a merged Result beats ``target``.

    Stock-wire strategy: each span rides a reference-shaped Request, so it
    works against ANY scheduler — but miners run full arg-min per span
    (the early exit is only span-granular). Against THIS framework's
    scheduler prefer :func:`submit_until`, which threads the target to the
    miners' in-kernel early exit. Returns (hash, nonce, spans_scanned) or
    None on disconnect / exhausted ``max_nonce``.

    ``max_nonce=None`` bounds the stream at the end of the nonce space
    (2^64 - 1) rather than looping forever on an unreachable target
    (ADVICE r1/r2): the op hashes ``"<data> <nonce>"`` with a uint64 nonce
    (ref: bitcoin/hash.go:13-17), so the search space is finite.
    """
    from ..bitcoin.hash import MAX_U64
    if max_nonce is None:
        max_nonce = MAX_U64
    client = await new_async_client(hostport, params)
    spans = 0
    lower = start
    try:
        while lower <= max_nonce:
            upper = min(lower + span - 1, max_nonce)
            client.write(new_request(message, lower, upper).to_json())
            try:
                payload = await client.read()
            except LspError:
                return None
            try:
                msg = Message.from_json(payload)
            except ValueError:
                return None
            if msg.type != MsgType.RESULT:
                return None
            spans += 1
            if msg.hash < target:
                return msg.hash, msg.nonce, spans
            lower = upper + 1
        return None
    finally:
        await client.close()


async def submit_with_retry(hostport: str, message: str, max_nonce: int,
                            target: int = 0,
                            params: Optional[Params] = None,
                            retry: Optional[RetryParams] = None,
                            tenant_key=None,
                            ) -> Optional[Tuple[int, int, bool]]:
    """Idempotent submit with timeout + exponential backoff + reconnect.

    The reference submitter is one-shot: a lost connection, a scheduler
    restart, or a Result that never comes (e.g. the request was in flight
    when the coordinator state was lost) all surface as ``Disconnected``
    or a hang. Here each attempt is a FRESH LSP connection carrying the
    same Request; on transport death or a per-attempt ``timeout_s``
    expiring, the attempt's connection is closed — the scheduler sees the
    drop and cancels any in-flight work for it (client-drop path), so the
    resubmission cannot double-deliver — and the next attempt reconnects
    and resubmits after an exponential backoff. A scheduler restart
    therefore degrades to latency, not a hang.

    Idempotency argument: the search is a pure function of
    ``(message, range, target)``, so re-executing it is harmless, and at
    most one Result reaches the caller because every attempt but the
    returning one has its connection closed before the next begins.

    **Replica-aware ring mode (ISSUE 12).** ``hostport`` may name the
    multi-process replica tier's state directory as ``ring:<statedir>``
    (apps/procs.py). Each attempt then RE-RESOLVES the target replica:
    the tenant key (``tenant_key``, default the message itself — any
    stable value; the server-side tenant identity is the conn id, the
    hash only picks a replica stably) is consistent-hashed over the
    ADVERTISED live ring from ``membership.json``. A replica killed or
    fenced mid-request surfaces as a dead conn / expired attempt; the
    next attempt re-reads the membership — by then the router's
    missed-beat detection has re-ringed — and reconnects to the NEW
    owner, where the request either replays from the replicated cache
    tier or recomputes. While no membership is readable (router
    restarting) the attempt burns its backoff and retries: the client
    backs off THROUGH router restarts rather than failing.

    Returns ``(hash, nonce, found)`` like :func:`submit_until`, or None
    once every attempt is exhausted.
    """
    retry = retry if retry is not None else RetryParams()
    delay = retry.backoff_s
    t0 = asyncio.get_running_loop().time()
    ring_dir: Optional[str] = None
    if hostport.startswith("ring:"):
        ring_dir = hostport[len("ring:"):]
        if tenant_key is None:
            tenant_key = message
    for attempt in range(max(1, retry.attempts)):
        _MET_ATTEMPTS.inc()
        if attempt:
            await asyncio.sleep(delay)
            delay = min(delay * 2, retry.backoff_cap_s)
        target_hostport = hostport
        if ring_dir is not None:
            from .procs import resolve_owner
            owner = resolve_owner(ring_dir, tenant_key)
            if owner is None:
                logger.info("attempt %d: no advertised ring yet; "
                            "backing off", attempt + 1)
                continue
            _rid, target_hostport = owner
        try:
            client = await new_async_client(target_hostport, params)
        except LspError as exc:
            logger.info("attempt %d: connect failed (%s); will retry",
                        attempt + 1, exc)
            continue
        try:
            client.write(
                new_request(message, 0, max_nonce, target).to_json())
            if retry.timeout_s > 0:
                payload = await asyncio.wait_for(client.read(),
                                                 retry.timeout_s)
            else:
                payload = await client.read()
        except (LspError, asyncio.TimeoutError) as exc:
            logger.info("attempt %d: no Result (%r); will retry",
                        attempt + 1, exc)
            continue
        finally:
            # Close on EVERY exit — retry paths, success, and
            # cancellation from an outer deadline (which would otherwise
            # leak the endpoint). NOTE the close is only a local flush:
            # classic LSP has no close handshake, so the scheduler learns
            # of this conn's death from its epoch timer (epoch_limit *
            # epoch_millis later) and only then cancels the abandoned
            # request. A resubmission arriving before that queues behind
            # the zombie — extra latency and one duplicated scan, never a
            # wrong or doubled answer (the dead conn can't deliver).
            # Budget timeout_s/backoff_s above the epoch death window
            # when tuning tight-latency retries.
            await client.close()
        try:
            msg = Message.from_json(payload)
        except ValueError:
            continue
        if msg.type != MsgType.RESULT:
            continue
        _MET_OUTCOME["ok"].inc()
        _MET_RESULT_S.observe(asyncio.get_running_loop().time() - t0)
        return msg.hash, msg.nonce, bool(target) and msg.hash < target
    _MET_OUTCOME["exhausted"].inc()
    return None


def printable_result(result: Optional[Tuple[int, int]]) -> str:
    """Exact stdout contract of the reference (client.go:61-68)."""
    if result is None:
        return "Disconnected"
    return f"Result {result[0]} {result[1]}"


def main(argv=None) -> int:
    """CLI contract of the reference binary (ref: client.go:24-58):
    ``client <hostport> <message> <maxNonce>``, extended with an optional
    trailing ``[target]`` selecting difficulty mode (:func:`submit_until`;
    stdout contract unchanged — the printed Result is the first qualifying
    nonce, or the exact arg-min when no nonce beats the target)."""
    import sys
    argv = sys.argv if argv is None else argv
    if len(argv) not in (4, 5):
        print(f"Usage: ./{argv[0]} <hostport> <message> <maxNonce>", end="")
        return 1
    def parse_u64(arg: str):
        # Mirrors Go's strconv.ParseUint(s, 10, 64) in the reference
        # client: ASCII decimal digits only (bare int() would also take
        # '+5', ' 5 ', '1_0', and Unicode digits), bounded to uint64,
        # same diagnostic on failure.
        if arg.isascii() and arg.isdigit() and int(arg) < (1 << 64):
            return int(arg)
        print(f"{arg} is not a number.")
        return None

    max_nonce = parse_u64(argv[3])
    if max_nonce is None:
        return 1
    # target 0 means "no target" (message.py) and selects the stock path,
    # same as omitting the argument.
    target = 0
    if len(argv) == 5:
        target = parse_u64(argv[4])
        if target is None:
            return 1
    from ..utils import from_env
    cfg = from_env()
    # Retry is an explicit opt-in with more than one attempt: the retry
    # path changes the reference CLI contract (a transport death becomes
    # reconnect+resubmit, and a connect failure prints "Disconnected"
    # instead of "Failed to connect"). A missing, unparsable, 0, or 1
    # value keeps the reference behavior.
    # A ring:<statedir> target (the multi-process replica tier) is only
    # meaningful through the replica-aware retry plane: owner
    # re-resolution happens per attempt.
    want_retry = _int_env("DBM_RETRY_ATTEMPTS", 0) > 1 \
        or argv[1].startswith("ring:")
    try:
        if want_retry:
            until = asyncio.run(submit_with_retry(
                argv[1], argv[2], max_nonce, target, cfg.params, cfg.retry))
            result = until if until is None else until[:2]
        elif target:
            until = asyncio.run(submit_until(argv[1], argv[2], max_nonce,
                                             target, cfg.params))
            result = until if until is None else until[:2]
        else:
            result = asyncio.run(submit(argv[1], argv[2], max_nonce,
                                        cfg.params))
    except LspError as exc:
        print("Failed to connect to server:", exc)
        return 1
    print(printable_result(result))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
