"""Interactive LSP echo runners, flag-compatible with the reference harness
(ref: srunner/srunner.go, crunner/crunner.go)."""
