"""Echo server runner — interactive LSP exerciser.

Flag-compatible with the reference binary (ref: srunner/srunner.go:15-72):
``--port --rdrop --wdrop --elim --ems --wsize --maxbackoff -v``, with the
same stdout lines so shell drivers written against the stock harness work.
Go's ``flag`` package spellings are accepted too — ``-port=9999``,
``-port 9999``, ``-v`` — so stock-harness command lines run unmodified
(VERDICT r3: argparse alone rejects single-dash long flags).
"""

from __future__ import annotations

import argparse
import asyncio
import re
import sys

from .. import lspnet
from ..lsp.errors import LspError
from ..lsp.params import (DEFAULT_EPOCH_LIMIT, DEFAULT_EPOCH_MILLIS,
                          DEFAULT_MAX_BACKOFF_INTERVAL, DEFAULT_WINDOW_SIZE,
                          Params)
from ..lsp.server import new_async_server


def build_parser(role: str) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog=role, allow_abbrev=False)
    p.add_argument("--port", type=int, default=9999, help="port number")
    p.add_argument("--rdrop", type=int, default=0,
                   help="network read drop percent")
    p.add_argument("--wdrop", type=int, default=0,
                   help="network write drop percent")
    p.add_argument("--elim", type=int, default=DEFAULT_EPOCH_LIMIT,
                   help="epoch limit")
    p.add_argument("--ems", type=int, default=DEFAULT_EPOCH_MILLIS,
                   help="epoch duration (ms)")
    p.add_argument("--wsize", type=int, default=DEFAULT_WINDOW_SIZE,
                   help="window size")
    p.add_argument("--maxbackoff", type=int,
                   default=DEFAULT_MAX_BACKOFF_INTERVAL,
                   help="maximum interval epoch")
    p.add_argument("-v", action="store_true", help="show runner logs")
    # Observability extension (no reference analog): start the in-process
    # metrics emitter at this interval — one JSON snapshot line per period
    # through the dbm.metrics logger (utils/metrics.py). 0 = off (default,
    # keeping stock-harness stdout byte-compatible).
    p.add_argument("--metrics", type=float, default=0.0, metavar="SECONDS",
                   help="metrics snapshot interval in seconds (0 = off)")
    return p


def normalize_go_flags(argv, parser: argparse.ArgumentParser) -> list:
    """Rewrite Go-``flag``-style single-dash long options to argparse's
    double-dash form: ``-port=9999`` / ``-port 9999`` -> ``--port ...``.

    Only tokens whose name part matches one of ``parser``'s long options
    are rewritten, so values (including negative numbers) and unknown
    flags pass through untouched and still produce argparse's usual
    errors. ``--`` ends flag parsing, as in both Go and argparse.
    """
    known = {opt for action in parser._actions
             for opt in action.option_strings if opt.startswith("--")}
    argv = list(sys.argv[1:] if argv is None else argv)
    out = []
    for i, arg in enumerate(argv):
        if arg == "--":
            out.extend(argv[i:])
            break
        m = re.match(r"^-([A-Za-z][A-Za-z0-9_]*)(=.*)?$", arg)
        if m and f"--{m.group(1)}" in known:
            arg = "-" + arg
        out.append(arg)
    return out


def params_from_args(args) -> Params:
    return Params(epoch_limit=args.elim, epoch_millis=args.ems,
                  window_size=args.wsize, max_backoff_interval=args.maxbackoff)


async def run_server(args) -> None:
    lspnet.set_server_read_drop_percent(args.rdrop)
    lspnet.set_server_write_drop_percent(args.wdrop)
    print(f"Starting server on port {args.port}...", flush=True)
    try:
        server = await new_async_server(args.port, params_from_args(args))
    except OSError as exc:
        print(f"Failed to start Server on port {args.port}: {exc}")
        return
    print("Server waiting for clients...", flush=True)
    while True:
        try:
            conn_id, item = await server.read()
        except LspError:
            return
        if isinstance(item, Exception):
            print(f"Client {conn_id} has died: {item}", flush=True)
            continue
        try:
            server.write(conn_id, item)
        except LspError as exc:
            print(f"Server failed to write to connection {conn_id}: {exc}",
                  flush=True)


def main(argv=None) -> int:
    parser = build_parser("srunner")
    args = parser.parse_args(normalize_go_flags(argv, parser))
    if args.v:
        lspnet.enable_debug_logs(True)
    if args.metrics > 0:
        from ..utils import configure_logging, ensure_emitter
        # packet_trace must echo -v: configure_logging sets the lspnet
        # trace switch to EXACTLY its argument, so the default (False)
        # would silently undo the enable_debug_logs above.
        configure_logging(packet_trace=args.v)
        ensure_emitter(args.metrics)
    try:
        asyncio.run(run_server(args))
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
