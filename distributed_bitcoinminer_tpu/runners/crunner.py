"""Echo client runner — interactive LSP exerciser.

Flag-compatible with the reference binary (ref: crunner/crunner.go:16-81):
``--host --port --rdrop --wdrop --elim --ems --wsize --maxbackoff -v``,
plus Go ``flag`` spellings (``-port=9999``; see srunner.normalize_go_flags).
Reads whitespace-separated tokens from stdin, echoes each through the server.
"""

from __future__ import annotations

import asyncio
import sys

from .. import lspnet
from ..lsp.client import new_async_client
from ..lsp.errors import LspError
from .srunner import build_parser, normalize_go_flags, params_from_args


async def run_client(args) -> None:
    lspnet.set_client_read_drop_percent(args.rdrop)
    lspnet.set_client_write_drop_percent(args.wdrop)
    # join_host_port brackets IPv6 literals, matching the client's
    # Go-strict split_host_port (--host ::1 would otherwise read as
    # "too many colons").
    hostport = lspnet.join_host_port(args.host, args.port)
    print(f"Connecting to server at '{hostport}'...", flush=True)
    try:
        client = await new_async_client(hostport, params_from_args(args))
    except LspError as exc:
        print(f"Failed to connect to server at {hostport}: {exc}")
        return
    # QoS tenant plumbing (ISSUE 5): tenancy is keyed off the conn id the
    # server assigned this client — no wire change, so the id IS the
    # tenant id. With --qos-weight the runner surfaces that id and the
    # exact DBM_QOS_WEIGHTS fragment to export on the scheduler side
    # (weights live with the scheduler, never on the wire). Gated on the
    # flag so default stdout stays byte-compatible with the stock harness.
    if args.qos_weight > 0:
        print(f"Connected as tenant {client.conn_id()}", flush=True)
        print(f"QoS weight {args.qos_weight:g}: export "
              f"DBM_QOS_WEIGHTS={client.conn_id()}:{args.qos_weight:g} "
              f"on the scheduler", flush=True)
    try:
        loop = asyncio.get_running_loop()
        while True:
            print("Client: ", end="", flush=True)
            line = await loop.run_in_executor(None, sys.stdin.readline)
            if not line:
                return
            for token in line.split():
                try:
                    client.write(token.encode("utf-8"))
                except LspError as exc:
                    print(f"Client {client.conn_id()} failed to write to "
                          f"server: {exc}", flush=True)
                    return
                try:
                    payload = await client.read()
                except LspError as exc:
                    print(f"Client {client.conn_id()} failed to read from "
                          f"server: {exc}", flush=True)
                    return
                print(f"Server: {payload.decode('utf-8', 'replace')}",
                      flush=True)
    finally:
        print("Exiting...", flush=True)


def main(argv=None) -> int:
    parser = build_parser("crunner")
    parser.add_argument("--host", type=str, default="127.0.0.1",
                        help="server host address")
    # Fair-share QoS plumbing (ISSUE 5): tenant identity is the conn id
    # (printed after connect); the weight itself is scheduler-side
    # configuration (DBM_QOS_WEIGHTS / Scheduler.set_tenant_weight), so
    # the flag emits the mapping line for the operator.
    parser.add_argument("--qos-weight", type=float, default=0.0,
                        metavar="W", dest="qos_weight",
                        help="intended DRR weight for this tenant "
                             "(prints the scheduler-side DBM_QOS_WEIGHTS "
                             "mapping; 0 = unset)")
    args = parser.parse_args(normalize_go_flags(argv, parser))
    if args.v:
        lspnet.enable_debug_logs(True)
    if args.metrics > 0:
        from ..utils import configure_logging, ensure_emitter
        # packet_trace echoes -v (configure_logging sets the lspnet trace
        # switch to exactly its argument; the default would undo -v).
        configure_logging(packet_trace=args.v)
        ensure_emitter(args.metrics)
    try:
        asyncio.run(run_client(args))
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
