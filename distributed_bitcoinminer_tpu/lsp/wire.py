"""Allocation-lean LSP wire codec: the per-packet fast path (ISSUE 17).

``Message.to_json``/``from_json`` are the REFERENCE codec — Go
``encoding/json`` field order, standard base64, ``null`` payload — and
every byte they emit is pinned by the Go-replay goldens. They are also
the per-message cost the datapath pays millions of times: an f-string
build plus two str/bytes round-trips on encode, a ``json.loads`` dict
plus ``base64.b64decode`` on decode.

This module provides byte-for-byte-identical fast paths:

- :func:`encode_data` / :func:`encode_ack` / :func:`encode_connect`:
  the three hot message kinds, assembled in ONE C-level template
  substitution over precompiled byte templates plus ``binascii``
  base64 — no intermediate str objects, no dict, no json module.
  (A reused-bytearray assembly variant measured ~1.6x stock against
  the template's ~2.5x: the frame must be returned as immutable bytes
  anyway — ``_Pending`` retains it for retransmit — so buffer reuse
  only added copies. The measurement lives with the fuzz leg in
  ``tests/test_transport_fast.py``.) Output is bit-identical to
  ``to_json`` of the equivalent :class:`~.message.Message` — the fuzz
  round-trip leg and the Go-replay goldens pin it.
- :func:`decode`: strict scanner for the canonical frame layout the
  encoders (ours and Go's) emit. Anything non-canonical — reordered
  keys, whitespace, floats, unknown fields — falls back to
  ``Message.from_json``, so the ACCEPTED language and every error path
  are exactly the stock codec's. Corrupt base64 is re-validated with
  the same alphabet rule ``b64decode(validate=True)`` applies.
- :func:`checksum`: the wire checksum via one big-int ``int.from_bytes``
  + modular fold instead of a per-byte-pair Python loop. Exact for any
  payload a UDP datagram can carry (< 64 KiB, where the reference's
  32-bit masking is a no-op); larger payloads take the stock loop.

``DBM_WIRE_FAST=0`` routes every call back to the stock codec — the
knob-off matrix leg runs the transport suites that way, so stock parity
stays covered both as an equality assertion AND as live wire traffic.
"""

from __future__ import annotations

import re
from binascii import Error as _B64Error, a2b_base64, b2a_base64

from ..utils._env import int_env as _int_env
from .checksum import int2checksum, make_checksum
from .message import Message, MsgType

__all__ = ["encode_data", "encode_ack", "encode_connect", "encode",
           "decode", "checksum", "fast_enabled"]

#: Read once at import (endpoints are constructed after env is set; the
#: tier-1 matrix leg flips it per process). ``refresh()`` re-reads for
#: tests that monkeypatch the environment mid-process.
_FAST = _int_env("DBM_WIRE_FAST", 1) != 0


def fast_enabled() -> bool:
    return _FAST


def refresh() -> None:
    """Re-read ``DBM_WIRE_FAST`` (test hook; endpoints read per call)."""
    global _FAST
    _FAST = _int_env("DBM_WIRE_FAST", 1) != 0


# ------------------------------------------------------------------ encode

#: Canonical frame templates (Go struct field order — ref: lsp/message.go).
_FMT_DATA = b'{"Type":1,"ConnID":%d,"SeqNum":%d,"Size":%d,"Checksum":%d,"Payload":"%s"}'
_TAIL_DATA = b'"}'
#: Acks/Connects carry no payload: the whole frame is one format.
_FMT_ACK = b'{"Type":2,"ConnID":%d,"SeqNum":%d,"Size":0,"Checksum":0,"Payload":null}'
_FRAME_CONNECT = b'{"Type":0,"ConnID":0,"SeqNum":0,"Size":0,"Checksum":0,"Payload":null}'


# dbmlint: hotpath
def encode_data(conn_id: int, seq_num: int, size: int, cksum: int,
                payload: bytes) -> bytes:
    """Wire bytes of ``new_data(...).to_json()``, one template pass."""
    if not _FAST:
        return Message(MsgType.DATA, conn_id, seq_num, size, cksum,
                       payload).to_json()
    return _FMT_DATA % (conn_id, seq_num, size, cksum,
                        b2a_base64(payload, newline=False))


# dbmlint: hotpath
def encode_ack(conn_id: int, seq_num: int) -> bytes:
    """Wire bytes of ``new_ack(conn_id, seq_num).to_json()``."""
    if not _FAST:
        return Message(MsgType.ACK, conn_id, seq_num).to_json()
    return _FMT_ACK % (conn_id, seq_num)


def encode_connect() -> bytes:
    """Wire bytes of ``new_connect().to_json()`` (cold path: once/conn)."""
    if not _FAST:
        return Message(MsgType.CONNECT).to_json()
    return _FRAME_CONNECT


def encode(msg: Message) -> bytes:
    """Fast-encode an arbitrary :class:`Message`; non-canonical shapes
    (a payload-carrying Ack, a sized Connect) take ``to_json`` so output
    is identical for EVERY message, not just the hot kinds."""
    if _FAST and msg.type == MsgType.DATA and msg.payload is not None:
        return encode_data(msg.conn_id, msg.seq_num, msg.size,
                           msg.checksum, msg.payload)
    if _FAST and msg.type == MsgType.ACK and msg.size == 0 \
            and msg.checksum == 0 and msg.payload is None:
        return encode_ack(msg.conn_id, msg.seq_num)
    return msg.to_json()


# ------------------------------------------------------------------ decode

_P_TYPE = b'{"Type":'
_P_CONN = b',"ConnID":'
_P_SEQ = b',"SeqNum":'
_P_SIZE = b',"Size":'
_P_CK = b',"Checksum":'
_P_PAY = b',"Payload":'
#: The exact alphabet rule ``base64.b64decode(validate=True)`` enforces
#: (CPython checks this regex, then lets binascii do padding checks):
#: the fast path must DROP the same corrupt frames the stock path drops.
_B64_RE = re.compile(rb"[A-Za-z0-9+/]*={0,2}")
_MSGTYPE = (MsgType.CONNECT, MsgType.DATA, MsgType.ACK)


def _field_int(raw: bytes, start: int, sep: bytes) -> "tuple[int, int] | None":
    """Parse the decimal between ``start`` and the next ``sep``; returns
    (value, index_after_sep) or None when the frame is non-canonical."""
    end = raw.find(sep, start)
    if end < 0:
        return None
    digits = raw[start:end]
    if not (digits.isdigit()
            or (digits[:1] == b"-" and digits[1:].isdigit())):
        return None
    return int(digits), end + len(sep)


# dbmlint: hotpath
def _decode_fast(raw: bytes) -> "Message | None":
    """Canonical-layout scanner; None means "not canonical, fall back"."""
    if not raw.startswith(_P_TYPE):
        return None
    got = _field_int(raw, 8, _P_CONN)
    if got is None:
        return None
    mtype, i = got
    if not 0 <= mtype <= 2:
        return None
    got = _field_int(raw, i, _P_SEQ)
    if got is None:
        return None
    conn_id, i = got
    got = _field_int(raw, i, _P_SIZE)
    if got is None:
        return None
    seq_num, i = got
    got = _field_int(raw, i, _P_CK)
    if got is None:
        return None
    size, i = got
    got = _field_int(raw, i, _P_PAY)
    if got is None:
        return None
    cksum, i = got
    tail = raw[i:]
    if tail == b"null}":
        payload = None
    elif tail[:1] == b'"' and tail[-2:] == _TAIL_DATA:
        b64 = tail[1:-2]
        if _B64_RE.fullmatch(b64) is None:
            return None     # stock path raises on this frame: fall back
        try:
            payload = a2b_base64(b64)
        except _B64Error:
            return None     # bad padding: fall back to the stock error
    else:
        return None
    return Message(_MSGTYPE[mtype], conn_id, seq_num, size, cksum, payload)


def decode(raw: bytes) -> Message:
    """Parse one wire frame. Raises ValueError on malformed input with
    the stock codec's exact semantics (the caller drops the packet)."""
    if _FAST:
        msg = _decode_fast(raw)
        if msg is not None:
            return msg
    return Message.from_json(raw)


# ---------------------------------------------------------------- checksum

#: Above this payload length the reference's 32-bit masking inside
#: ``bytearray2checksum``/``make_checksum`` can bite (word-sum >= 2^32
#: needs ~128 KiB); UDP tops out below 64 KiB, so the guard only routes
#: pathological non-datagram inputs to the stock loop.
_MOD_EXACT_LIMIT = 65536


# dbmlint: hotpath
def checksum(conn_id: int, seq_num: int, size: int, payload: bytes) -> int:
    """``make_checksum`` equivalence via modular arithmetic.

    The wire checksum is a base-2^16 digit sum with end-around carry,
    i.e. arithmetic mod 65535 (with the fold mapping nonzero multiples
    to 0xFFFF, never 0). ``int.from_bytes(payload, "little")`` is that
    digit string as ONE number, so the payload's word-sum is congruent
    to it mod 65535 — one C call replaces the per-byte-pair loop.
    """
    if not _FAST or len(payload) >= _MOD_EXACT_LIMIT:
        return make_checksum(conn_id, seq_num, size, payload)
    total = (int2checksum(conn_id) + int2checksum(seq_num)
             + int2checksum(size))
    n = int.from_bytes(payload, "little") if payload else 0
    if total == 0 and n == 0:
        return 0
    return (total + n % 0xFFFF - 1) % 0xFFFF + 1
