"""LSP endpoint configuration (ref: lsp/params.go:8-42)."""

from __future__ import annotations

from dataclasses import dataclass

DEFAULT_EPOCH_LIMIT = 5
DEFAULT_EPOCH_MILLIS = 2000
DEFAULT_WINDOW_SIZE = 1
DEFAULT_MAX_BACKOFF_INTERVAL = 0


@dataclass
class Params:
    # Epochs that may pass with no inbound traffic before the connection is lost.
    epoch_limit: int = DEFAULT_EPOCH_LIMIT
    # Milliseconds between epoch ticks.
    epoch_millis: int = DEFAULT_EPOCH_MILLIS
    # Max unacknowledged data messages outstanding at once.
    window_size: int = DEFAULT_WINDOW_SIZE
    # Cap on the gap (in epochs) between two retransmissions of one message.
    max_backoff_interval: int = DEFAULT_MAX_BACKOFF_INTERVAL

    def __str__(self) -> str:
        return (f"[EpochLimit: {self.epoch_limit}, EpochMillis: {self.epoch_millis}, "
                f"WindowSize: {self.window_size}, "
                f"MaxBackOffInterval: {self.max_backoff_interval}]")
