"""The LSP connection state machine, shared by client and server endpoints.

One :class:`Conn` owns all state for a single connection — send window +
overflow buffer, retransmit backoff bookkeeping, receive reordering, epoch
heartbeat/loss timers, and the close handshake. All methods run on a single
asyncio event loop, so the structure is race-free by construction (the
equivalent of the reference's one-goroutine-owns-the-state channel design;
ref: lsp/client_impl.go mainRoutine, lsp/server_impl.go clientMain).

State machine (explicit, replacing the reference's boolean-flag interplay):

    CONNECTING --ack(0)--> UP --begin_close--> CLOSING --flushed--> CLOSED
         |                 |                      |
         +--epoch limit--> LOST <--epoch limit----+

Retransmission reproduces the reference's observable backoff pattern
XXOXOOX0000X (ref: lsp/client_impl.go resendRoutine:230-257): a message is
sent, then resent when ``epochs_passed >= cur_backoff``; the backoff goes
0 -> 1 -> 2x thereafter, capped at ``max_backoff_interval``.
"""

from __future__ import annotations

import asyncio
import enum
import time
from collections import deque
from typing import Callable, Optional

from .checksum import make_checksum
from .errors import ConnectionClosed, ConnectionLost, ConnectTimeout
from .message import Message, MsgType, new_ack, new_data
from .params import Params
from .timerwheel import wheel_enabled, wheel_for
from ..utils.metrics import (LATENCY_BUCKETS_S, OCCUPANCY_BUCKETS,
                             registry as _registry)

# Process-wide transport metrics (utils/metrics.py). Handles are hoisted to
# module scope: the receive path runs per packet, so per-call registry
# lookups would be the one avoidable cost. Counts aggregate over every Conn
# in the process — per-conn labels would be unbounded cardinality for a
# long-lived server.
_M = _registry()
_MET_EPOCHS = _M.counter("lsp.epochs")
_MET_HEARTBEATS = _M.counter("lsp.heartbeats_sent")
_MET_RECV_DUP = _M.counter("lsp.recv_discards", reason="duplicate")
_MET_CONN_LOST = _M.counter("lsp.conns_lost")
_MET_SEND_WINDOW = _M.histogram("lsp.send_window_occupancy",
                                buckets=OCCUPANCY_BUCKETS)
_MET_RECV_PENDING = _M.histogram("lsp.recv_pending_occupancy",
                                 buckets=OCCUPANCY_BUCKETS)
_MET_RTT = _M.histogram("lsp.msg_rtt_s", buckets=LATENCY_BUCKETS_S)
_MET_DROP_LENGTH = _M.counter("lsp.integrity_drops", reason="length")
_MET_DROP_CHECKSUM = _M.counter("lsp.integrity_drops", reason="checksum")


class ConnState(enum.Enum):
    CONNECTING = "connecting"
    UP = "up"
    CLOSING = "closing"
    CLOSED = "closed"
    LOST = "lost"


class _Pending:
    """One unacknowledged outbound message and its retransmit schedule."""

    __slots__ = ("seq", "raw", "cur_backoff", "epochs_passed", "fresh",
                 "sent_at", "retransmitted")

    def __init__(self, seq: int, raw: bytes):
        self.seq = seq
        self.raw = raw
        self.cur_backoff = 0
        self.epochs_passed = 0
        # Sent between epoch ticks: the first tick after the send does not
        # count toward the retransmit schedule (approximates the reference's
        # per-message timer phase within the graded 4-6 sends/14 epochs law).
        self.fresh = True
        # RTT metric plane: stamp of the (latest) first transmission; a
        # retransmitted message's eventual ack is ambiguous (Karn's rule),
        # so only never-retransmitted messages contribute RTT samples.
        self.sent_at = 0.0
        self.retransmitted = False


class Conn:
    """One LSP connection. Owner provides I/O + delivery callbacks."""

    def __init__(
        self,
        params: Params,
        conn_id: int,
        send_raw: Callable[[bytes], None],
        deliver: Callable[[bytes], None],
        broken: Callable[[Exception], None],
        connect_msg: Optional[Message] = None,
        deliver_ready: Optional[Callable[[], bool]] = None,
    ):
        self.params = params
        self.conn_id = conn_id
        self._send_raw = send_raw
        self._deliver = deliver
        self._broken = broken
        # Delivery back-pressure probe (server read-queue bound, ref:
        # lsp/server_impl.go:112): when it returns False, the next in-order
        # message is parked in ``_recv_pending`` WITHOUT an ack — the
        # peer's send window cannot slide past an unacked head, so it
        # stalls at W outstanding and memory stays bounded end-to-end
        # without blocking the event loop (the asyncio analog of the
        # reference's goroutine blocking on its full 500-chan). The owner
        # calls :meth:`resume_delivery` when the app frees queue room; the
        # parked head is acked at delivery time.
        self._deliver_ready = deliver_ready or (lambda: True)

        self.state = ConnState.CONNECTING if connect_msg is not None else ConnState.UP

        # Send side. Data sequence numbers start at 1 on both roles.
        self._next_seq = 1
        self._window: dict[int, _Pending] = {}
        self._buffer: deque[_Pending] = deque()

        # The in-flight Connect request, retransmitted like a window element.
        self._connect_pending: Optional[_Pending] = None
        self.connected: asyncio.Future = asyncio.get_running_loop().create_future()
        if connect_msg is not None:
            self._connect_pending = _Pending(0, connect_msg.to_json())
            self._send_raw(self._connect_pending.raw)
        else:
            self.connected.set_result(conn_id)

        # Receive side: in-order reassembly. ``_recv_unacked`` holds the
        # (at most one) parked back-pressure head whose ack is deferred to
        # delivery; its retransmits must NOT take the duplicate re-ack
        # path, or the peer's window would slide past an undelivered
        # message the app might never get room for.
        self._recv_expected = 1
        self._recv_pending: dict[int, bytes] = {}
        self._recv_unacked: set[int] = set()

        # Epoch bookkeeping. Loss detection counts ALL inbound messages
        # (ref connDropTimer resets on gotMessageChan); the heartbeat
        # reminder is suppressed only by SUBSTANTIVE traffic (data / data
        # acks), because on a mutually idle link the reference's reminder
        # race resolves toward firing every epoch on both sides — a peer's
        # heartbeat must not starve ours, or its loss detector (fed only
        # by our sends) counts up to the epoch limit on a live link.
        self._silent_epochs = 0
        self._got_traffic = False
        self._got_payload_traffic = False

        self.closed_event = asyncio.Event()
        # Epoch timer: the shared per-loop timer wheel by default (one
        # sleeping task services every conn on this loop — 10k conns is
        # 10k heap entries, not 10k tasks; ISSUE 11), or the stock
        # per-conn task under DBM_TIMER_WHEEL=0. Tick schedule and
        # semantics are identical either way (first tick at +epoch,
        # next relative to when this one ran).
        self._epoch_task: Optional[asyncio.Task] = None
        self._wheel = None
        self._wheel_handle = None
        if wheel_enabled():
            self._wheel = wheel_for(asyncio.get_running_loop())
            self._wheel_handle = self._wheel.add(
                self.params.epoch_millis / 1000.0, self._tick)
        else:
            self._epoch_task = asyncio.get_running_loop().create_task(
                self._epoch_loop())

    # ------------------------------------------------------------- send path

    def write(self, payload: bytes) -> None:
        if self.state in (ConnState.CLOSING, ConnState.CLOSED, ConnState.LOST):
            raise ConnectionClosed(f"conn {self.conn_id}: write after close/loss")
        seq = self._next_seq
        self._next_seq += 1
        checksum = make_checksum(self.conn_id, seq, len(payload), payload)
        msg = new_data(self.conn_id, seq, len(payload), payload, checksum)
        pending = _Pending(seq, msg.to_json())
        if self._can_admit(seq):
            self._window[seq] = pending
            pending.sent_at = time.monotonic()
            self._send_raw(pending.raw)
            _MET_SEND_WINDOW.observe(len(self._window))
        else:
            self._buffer.append(pending)

    def _can_admit(self, seq: int) -> bool:
        # Window rule (ref: lsp/client_impl.go:381-389): at most W unacked
        # messages, all within [oldest_unacked, oldest_unacked + W).
        if len(self._window) >= self.params.window_size:
            return False
        base = min(self._window) if self._window else seq
        return seq < base + self.params.window_size

    def _refill_window(self) -> None:
        while self._buffer and self._can_admit(self._buffer[0].seq):
            pending = self._buffer.popleft()
            self._window[pending.seq] = pending
            pending.sent_at = time.monotonic()   # first real transmission
            self._send_raw(pending.raw)
            _MET_SEND_WINDOW.observe(len(self._window))

    @property
    def flushed(self) -> bool:
        return not self._window and not self._buffer

    # ---------------------------------------------------------- receive path

    def on_message(self, msg: Message) -> None:
        """Handle one integrity-checked inbound message."""
        self._got_traffic = True
        if msg.type != MsgType.ACK or msg.seq_num != 0:
            self._got_payload_traffic = True
        if msg.type == MsgType.DATA:
            self._on_data(msg)
        elif msg.type == MsgType.ACK:
            self._on_ack(msg)

    def _on_data(self, msg: Message) -> None:
        if self.state in (ConnState.CLOSED, ConnState.LOST):
            return
        if self.state == ConnState.CONNECTING:
            # Data from the server implies our Connect was accepted (the
            # explicit Ack(id, 0) was lost/delayed): establish implicitly so
            # the ack below carries the right conn id and delivery proceeds.
            self.conn_id = msg.conn_id
            self.state = ConnState.UP
            self._connect_pending = None
            if not self.connected.done():
                self.connected.set_result(msg.conn_id)
        seq = msg.seq_num
        if seq < self._recv_expected or seq in self._recv_pending:
            # Duplicates of ACKED messages are re-acked (exactly-once
            # delivery comes from receive-side dedup, not ack suppression;
            # ref: lsp/server_impl.go:462-470). A retransmit of the parked
            # unacked back-pressure head stays unacked until delivery.
            _MET_RECV_DUP.inc()
            if seq not in self._recv_unacked:
                self._send_raw(new_ack(self.conn_id, seq).to_json())
            return
        if seq == self._recv_expected and self.state == ConnState.UP and \
                not self._deliver_ready():
            # Back-pressure: park the head unacked; see the __init__ note.
            # Out-of-order messages are still admitted (and acked) below —
            # they are bounded by the peer's window, which cannot slide
            # past this unacked head.
            self._recv_pending[seq] = msg.payload or b""
            self._recv_unacked.add(seq)
            return
        self._send_raw(new_ack(self.conn_id, seq).to_json())
        self._recv_pending[seq] = msg.payload or b""
        _MET_RECV_PENDING.observe(len(self._recv_pending))
        self._drain()

    def _drain(self) -> None:
        """Deliver the in-order run while the owner's queue has room; the
        parked back-pressure head is acked here, at delivery time."""
        while self._recv_expected in self._recv_pending and (
                self.state != ConnState.UP or self._deliver_ready()):
            seq = self._recv_expected
            payload = self._recv_pending.pop(seq)
            if seq in self._recv_unacked:
                self._recv_unacked.discard(seq)
                self._send_raw(new_ack(self.conn_id, seq).to_json())
            self._recv_expected += 1
            if self.state == ConnState.UP:
                self._deliver(payload)

    def resume_delivery(self) -> None:
        """Owner hook: queue room reappeared (the app read); deliver any
        messages that stranded when :meth:`_drain` hit the cap — inbound
        traffic is NOT guaranteed to re-trigger it (an acked out-of-order
        backlog has no retransmits coming)."""
        if self.state in (ConnState.UP, ConnState.CLOSING):
            self._drain()

    def _on_ack(self, msg: Message) -> None:
        if msg.seq_num == 0:
            # Heartbeat — or the connect ack while CONNECTING.
            if self.state == ConnState.CONNECTING:
                self.conn_id = msg.conn_id
                self.state = ConnState.UP
                self._connect_pending = None
                if not self.connected.done():
                    self.connected.set_result(msg.conn_id)
            return
        pending = self._window.pop(msg.seq_num, None)
        if pending is None:
            return
        if not pending.retransmitted and pending.sent_at:
            # Send->ack RTT, Karn-filtered (see _Pending).
            _MET_RTT.observe(time.monotonic() - pending.sent_at)
        self._refill_window()
        if self.state == ConnState.CLOSING and self.flushed:
            self._finish(ConnState.CLOSED)

    # ------------------------------------------------------------ epoch loop

    async def _epoch_loop(self) -> None:
        epoch = self.params.epoch_millis / 1000.0
        while True:
            await asyncio.sleep(epoch)
            if not self._tick():
                return

    def _tick(self) -> bool:
        """One epoch. Returns False when the connection is finished."""
        _MET_EPOCHS.inc()
        # Loss detection (ref: lsp/client_impl.go timeRoutine:258-286).
        if self._got_traffic:
            self._silent_epochs = 0
            self._got_traffic = False
        else:
            self._silent_epochs += 1
            if self._silent_epochs >= self.params.epoch_limit:
                if self.state == ConnState.CONNECTING:
                    self._fail_connect(ConnectTimeout(
                        f"no connect ack after {self.params.epoch_limit} epochs"))
                else:
                    self._declare_lost()
                return False

        # Heartbeat, idle-only (VERDICT r4): the reference re-arms its
        # reminder timer on every inbound message and sends Ack(connID, 0)
        # only after a receive-silent epoch (ref: lsp/client_impl.go:268-281,
        # server_impl.go:396-420) — so a BUSY link emits no reminder acks.
        # On an idle link, peer heartbeats arrive one epoch + latency apart,
        # so the reference's reminder reliably fires anyway: idleness is
        # judged on substantive traffic only (see __init__ note).
        if not self._got_payload_traffic and \
                self.state in (ConnState.UP, ConnState.CLOSING):
            self._send_raw(new_ack(self.conn_id, 0).to_json())
            _MET_HEARTBEATS.inc()
        self._got_payload_traffic = False

        # Retransmits: the Connect request and every unacked window element.
        retransmit = list(self._window.values())
        if self._connect_pending is not None:
            retransmit.append(self._connect_pending)
        for pending in retransmit:
            if pending.fresh:
                pending.fresh = False
            elif pending.epochs_passed >= pending.cur_backoff:
                self._send_raw(pending.raw)
                pending.retransmitted = True
                # Labeled by the backoff level that TRIGGERED this resend
                # (0, 1, 2, 4, ... capped): the distribution is the
                # XXOXOOX retransmission-law shape, observable per process.
                _M.counter(   # dbmlint: ok[cardinality] bounded:
                    # backoff levels are 0, 1, 2, 4, ... capped at the
                    # max_backoff_interval knob — log2(cap)+2 values.
                    "lsp.retransmits",
                    backoff=str(pending.cur_backoff)).inc()
                pending.epochs_passed = 0
                if pending.cur_backoff == 0:
                    pending.cur_backoff = min(1, self.params.max_backoff_interval)
                else:
                    pending.cur_backoff = min(pending.cur_backoff * 2,
                                              self.params.max_backoff_interval)
            else:
                pending.epochs_passed += 1
        return True

    # ----------------------------------------------------------- termination

    def begin_close(self) -> None:
        """Graceful close: flush window + buffer, then finish (ref: §3.5)."""
        if self.state in (ConnState.CLOSED, ConnState.LOST):
            self.closed_event.set()
            return
        if self.state == ConnState.CONNECTING:
            self._fail_connect(ConnectionClosed("closed during connect"))
            return
        self.state = ConnState.CLOSING
        if self.flushed:
            self._finish(ConnState.CLOSED)

    def abort(self) -> None:
        """Immediate teardown with no flush (endpoint shutdown path)."""
        if self.state not in (ConnState.CLOSED, ConnState.LOST):
            self._finish(ConnState.CLOSED)

    def _declare_lost(self) -> None:
        _MET_CONN_LOST.inc()
        self._finish(ConnState.LOST)
        self._broken(ConnectionLost(f"conn {self.conn_id}: epoch limit reached"))

    def _fail_connect(self, exc: Exception) -> None:
        self._finish(ConnState.LOST)
        if not self.connected.done():
            self.connected.set_exception(exc)

    def _finish(self, final_state: ConnState) -> None:
        self.state = final_state
        self._window.clear()
        self._buffer.clear()
        self._recv_unacked.clear()
        self._connect_pending = None
        self.closed_event.set()
        task = self._epoch_task
        if task is not None and task is not asyncio.current_task():
            task.cancel()
        self._epoch_task = None
        if self._wheel is not None and self._wheel_handle is not None:
            self._wheel.cancel(self._wheel_handle)
            self._wheel_handle = None


def integrity_check(msg: Message) -> bool:
    """Validate (and possibly truncate) an inbound message.

    Rules (ref: lsp/client_impl.go integrityCheck:200-213): Connect/Ack are
    exempt; short payloads are rejected; long payloads are truncated to
    ``Size`` before the checksum is verified.
    """
    if msg.type in (MsgType.CONNECT, MsgType.ACK):
        return True
    payload = msg.payload if msg.payload is not None else b""
    if len(payload) < msg.size:
        _MET_DROP_LENGTH.inc()
        return False
    if len(payload) > msg.size:
        payload = payload[: msg.size]
        msg.payload = payload
    ok = make_checksum(msg.conn_id, msg.seq_num, msg.size,
                       payload) == msg.checksum
    if not ok:
        _MET_DROP_CHECKSUM.inc()
    return ok
