"""Asyncio shell around the sans-io LSP core (:mod:`.core`).

The protocol state machine — window/backoff/reorder/epoch/close semantics
— lives entirely in :class:`~.core.ConnCore`; see its module docstring
for the contract. This module adapts it to an event loop: every core
input runs on the loop (race-free by construction, the equivalent of the
reference's one-goroutine-owns-the-state design), the core's ``outbox``
is flushed to the owner's ``send_raw`` after each input (the flush is
one syscall burst under ``sendmmsg``), the core's one timer request is
serviced by the shared per-loop timer wheel (or a per-conn task under
``DBM_TIMER_WHEEL=0``), and the core's app-event callbacks are mapped to
the asyncio surface endpoints await (``connected`` future,
``closed_event``).

:class:`Conn`'s public surface is unchanged from when it WAS the state
machine — ``write`` / ``on_message`` / ``resume_delivery`` /
``begin_close`` / ``abort`` / ``flushed`` / ``state`` / ``conn_id`` /
``connected`` / ``closed_event`` — so ``server.py``/``client.py`` drive
it exactly as before. ``ConnState`` and ``integrity_check`` re-export
from :mod:`.core` for the same reason.
"""

from __future__ import annotations

import asyncio
from typing import Callable, Optional

from .core import ConnCore, ConnState, integrity_check
from .message import Message
from .params import Params
from .timerwheel import wheel_enabled, wheel_for

__all__ = ["Conn", "ConnState", "integrity_check"]


class Conn:
    """One LSP connection on an event loop. Owner provides I/O + delivery
    callbacks; protocol logic is the sans-io core's."""

    __slots__ = ("_core", "_send_raw", "connected", "closed_event",
                 "_epoch_task", "_wheel", "_wheel_handle")

    def __init__(
        self,
        params: Params,
        conn_id: int,
        send_raw: Callable[[bytes], None],
        deliver: Callable[[bytes], None],
        broken: Callable[[Exception], None],
        connect_msg: Optional[Message] = None,
        deliver_ready: Optional[Callable[[], bool]] = None,
    ):
        self._send_raw = send_raw
        loop = asyncio.get_running_loop()
        self.connected: asyncio.Future = loop.create_future()
        self.closed_event = asyncio.Event()

        self._core = ConnCore(
            params, conn_id,
            connect=connect_msg is not None,
            deliver=deliver,
            broken=broken,
            on_connected=self._when_connected,
            on_connect_failed=self._when_connect_failed,
            on_closed=self._when_closed,
            deliver_ready=deliver_ready,
        )
        if connect_msg is None:
            self.connected.set_result(conn_id)

        # Epoch timer: the shared per-loop timer wheel by default (one
        # sleeping task services every conn on this loop — 10k conns is
        # 10k heap entries, not 10k tasks; ISSUE 11), or the stock
        # per-conn task under DBM_TIMER_WHEEL=0. Tick schedule and
        # semantics are identical either way (first tick at +epoch,
        # next relative to when this one ran).
        self._epoch_task: Optional[asyncio.Task] = None
        self._wheel = None
        self._wheel_handle = None
        if wheel_enabled():
            self._wheel = wheel_for(loop)
            self._wheel_handle = self._wheel.add(
                self._core.epoch_interval_s, self._tick)
        else:
            self._epoch_task = loop.create_task(self._epoch_loop())

        self._flush()

    # ------------------------------------------------------- core adaptation

    @property
    def params(self) -> Params:
        return self._core.params

    @property
    def conn_id(self) -> int:
        return self._core.conn_id

    @property
    def state(self) -> ConnState:
        return self._core.state

    @property
    def flushed(self) -> bool:
        return self._core.flushed

    def _flush(self) -> None:
        """Drain the core's outbound burst to the socket layer. A batching
        endpoint turns the whole burst into one ``sendmmsg`` at pump exit."""
        outbox = self._core.outbox
        if outbox:
            send = self._send_raw
            for raw in outbox:
                send(raw)
            outbox.clear()

    def _when_connected(self, conn_id: int) -> None:
        if not self.connected.done():
            self.connected.set_result(conn_id)

    def _when_connect_failed(self, exc: Exception) -> None:
        if not self.connected.done():
            self.connected.set_exception(exc)

    def _when_closed(self) -> None:
        self.closed_event.set()
        task = self._epoch_task
        if task is not None and task is not asyncio.current_task():
            task.cancel()
        self._epoch_task = None
        if self._wheel is not None and self._wheel_handle is not None:
            self._wheel.cancel(self._wheel_handle)
            self._wheel_handle = None

    # --------------------------------------------------------- input surface

    def write(self, payload: bytes) -> None:
        self._core.write(payload)
        self._flush()

    def on_message(self, msg: Message) -> None:
        """Handle one integrity-checked inbound message."""
        self._core.on_message(msg)
        self._flush()

    def resume_delivery(self) -> None:
        """Owner hook: app read freed queue room; deliver stranded backlog."""
        self._core.resume_delivery()
        self._flush()

    # ------------------------------------------------------------ epoch loop

    async def _epoch_loop(self) -> None:
        epoch = self._core.epoch_interval_s
        while True:
            await asyncio.sleep(epoch)
            if not self._tick():
                return

    def _tick(self) -> bool:
        """One epoch. Returns False when the connection is finished."""
        alive = self._core.on_epoch()
        self._flush()
        return alive

    # ----------------------------------------------------------- termination

    def begin_close(self) -> None:
        """Graceful close: flush window + buffer, then finish (ref: §3.5)."""
        self._core.begin_close()
        self._flush()

    def abort(self) -> None:
        """Immediate teardown with no flush (endpoint shutdown path)."""
        self._core.abort()
