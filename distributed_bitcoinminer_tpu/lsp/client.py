"""LSP client endpoint: async engine + Go-style blocking facade.

Same four-method surface as the reference ``Client`` interface
(ref: lsp/client_api.go:6-30): ``conn_id``, blocking ``read``, non-blocking
``write``, flushing ``close``.
"""

from __future__ import annotations

import asyncio
from typing import Optional, Union

from .. import lspnet
from . import wire
from ._engine import Conn, ConnState, integrity_check
from ._loop import run_sync
from .errors import ConnectionClosed, LspError
from .message import MsgType, new_connect
from .params import Params


class AsyncClient:
    """Asyncio-native LSP client. Create via :func:`new_async_client`."""

    def __init__(self) -> None:
        self._ep: Optional[lspnet.UDPEndpoint] = None
        self._conn: Optional[Conn] = None
        self._read_queue: asyncio.Queue[Union[bytes, Exception]] = asyncio.Queue()
        self._recv_task: Optional[asyncio.Task] = None

    async def _connect(self, host: str, port: int, params: Params) -> None:
        self._ep = await lspnet.dial_udp(host, port)
        self._conn = Conn(
            params=params,
            conn_id=0,
            send_raw=lambda raw: self._ep.send(raw),
            deliver=self._read_queue.put_nowait,
            broken=self._read_queue.put_nowait,
            connect_msg=new_connect(),
        )
        self._recv_task = asyncio.get_running_loop().create_task(self._recv_loop())
        self._recv_task.add_done_callback(self._recv_done)
        try:
            await self._conn.connected
        except LspError:
            await self._teardown()
            raise

    def _recv_done(self, task: asyncio.Task) -> None:
        # A crashed receive loop must not leave the endpoint silently deaf.
        if task.cancelled():
            return
        exc = task.exception()
        if exc is not None:
            self._read_queue.put_nowait(
                ConnectionClosed(f"receive loop crashed: {exc!r}"))

    async def _recv_loop(self) -> None:
        # Burst drain (ISSUE 17): one awaited recv per burst, then
        # recv_nowait until momentarily dry — a recvmmsg batch is
        # processed in one synchronous sweep, not one loop round-trip
        # per datagram.
        while True:
            item = await self._ep.recv()
            if item is None:
                return
            while item is not None:
                self._on_datagram(item)
                item = self._ep.recv_nowait()

    def _on_datagram(self, item: tuple) -> None:
        raw, _addr = item
        try:
            msg = wire.decode(raw)
        except ValueError:
            return
        if not integrity_check(msg):
            return
        if msg.type == MsgType.CONNECT:
            return  # clients never accept connects
        self._conn.on_message(msg)

    # ------------------------------------------------------------ public API

    def conn_id(self) -> int:
        return self._conn.conn_id if self._conn else 0

    async def read(self) -> bytes:
        item = await self._read_queue.get()
        if isinstance(item, Exception):
            # Leave the error visible for any other pending readers.
            self._read_queue.put_nowait(item)
            raise item
        return item

    def write(self, payload: bytes) -> None:
        self._conn.write(payload)

    async def close(self) -> None:
        if self._conn is None:
            return
        self._conn.begin_close()
        await self._conn.closed_event.wait()
        await self._teardown()
        self._read_queue.put_nowait(ConnectionClosed("client closed"))

    async def _teardown(self) -> None:
        if self._recv_task is not None:
            self._recv_task.cancel()
            try:
                await self._recv_task
            except asyncio.CancelledError:
                pass
            self._recv_task = None
        if self._conn is not None:
            self._conn.abort()
        if self._ep is not None:
            self._ep.close()

    @property
    def state(self) -> ConnState:
        return self._conn.state if self._conn else ConnState.CLOSED


async def new_async_client(hostport: str, params: Optional[Params] = None) -> AsyncClient:
    """Connect to an LSP server; raises ConnectTimeout after EpochLimit
    epochs. ``hostport`` is parsed with Go ``net.SplitHostPort`` semantics
    (incl. bracketed IPv6 literals, ref: lspnet/net.go:86-89)."""
    from ..lspnet import split_host_port
    host, port = split_host_port(hostport)
    client = AsyncClient()
    await client._connect(host or "127.0.0.1", int(port), params or Params())
    return client


class Client:
    """Blocking facade over :class:`AsyncClient` (Go-style surface)."""

    def __init__(self, inner: AsyncClient):
        self._inner = inner

    def conn_id(self) -> int:
        return self._inner.conn_id()

    def read(self) -> bytes:
        return run_sync(self._inner.read())

    def write(self, payload: bytes) -> None:
        run_sync(self._write_async(payload))

    async def _write_async(self, payload: bytes) -> None:
        self._inner.write(payload)

    def close(self) -> None:
        run_sync(self._inner.close())


def new_client(hostport: str, params: Optional[Params] = None) -> Client:
    return Client(run_sync(new_async_client(hostport, params)))
