"""Sans-io LSP protocol core: the state machine with the I/O cut off.

One :class:`ConnCore` owns ALL protocol state for one connection — send
window + overflow buffer, retransmit backoff bookkeeping, receive
reordering, epoch heartbeat/loss accounting, and the close handshake —
and touches NOTHING else: no sockets, no awaits, no asyncio, no wall
clock it didn't get injected. Inputs are plain method calls (an
integrity-checked inbound :class:`~.message.Message`, an app write, an
epoch-timer event); outputs are

- **outbound packets**: wire frames appended to :attr:`ConnCore.outbox`
  (a plain list the driving shell drains after every input — one drain
  per input is one syscall burst under ``sendmmsg``);
- **timer requests**: :attr:`epoch_interval_s` names the one periodic
  timer the core needs; the shell calls :meth:`on_epoch` at that period
  until it returns False (connection finished) — the sans-io analog of
  the reference's per-conn epoch goroutine;
- **app events**: synchronous callbacks (``deliver``, ``broken``,
  ``on_connected`` / ``on_connect_failed``, ``on_closed``). Delivery is
  a callback rather than a polled queue because back-pressure is
  consulted MID-DRAIN: ``deliver_ready()`` must observe the app queue
  as each message lands, or a backlog drain would overshoot the cap.

Two shells drive it: ``_engine.Conn`` (asyncio — real UDP endpoints,
timer wheel or per-conn tasks) and ``lspnet/detnet.py`` (the
deterministic explorer — synchronous pumps, zero-clock, no timers), so
dbmcheck explores the REAL protocol code, and a C/Rust shell stays
possible without forking protocol logic (ISSUE 17).

State machine, retransmission law, heartbeat/loss semantics are the
reference's, unchanged — see the docstrings below and the original
notes in ``_engine.py`` history (ref: lsp/client_impl.go mainRoutine,
lsp/server_impl.go clientMain):

    CONNECTING --ack(0)--> UP --begin_close--> CLOSING --flushed--> CLOSED
         |                 |                      |
         +--epoch limit--> LOST <--epoch limit----+

Flattened state (ISSUE 17, 100k-live-conn budget): the send window is a
ring of ``window_size`` slots (``seq % W`` — the window rule keeps live
seqs within [base, base+W), so the mapping is collision-free) instead
of a dict with an O(W) ``min()`` on every admit; the receive reorder
buffer is the same ring shape with a lazily-created spillover dict for
frames beyond the ring (a peer with a wider window than ours — never
hit by our own endpoints, kept for safety); everything is ``__slots__``
and the overflow deque is lazily allocated (an idle conn is one slotted
object + two small lists).
"""

from __future__ import annotations

import enum
import time
from collections import deque
from typing import Callable, List, Optional

from . import wire
from .errors import ConnectionClosed, ConnectionLost, ConnectTimeout
from .message import Message, MsgType
from .params import Params
from ..utils.metrics import (LATENCY_BUCKETS_S, OCCUPANCY_BUCKETS,
                             registry as _registry)

__all__ = ["ConnCore", "ConnState", "integrity_check"]

# Process-wide transport metrics (utils/metrics.py). Handles are hoisted
# to module scope: the receive path runs per packet, so per-call registry
# lookups would be the one avoidable cost. Counts aggregate over every
# conn in the process — per-conn labels would be unbounded cardinality
# for a long-lived server.
_M = _registry()
_MET_EPOCHS = _M.counter("lsp.epochs")
_MET_HEARTBEATS = _M.counter("lsp.heartbeats_sent")
_MET_RECV_DUP = _M.counter("lsp.recv_discards", reason="duplicate")
_MET_CONN_LOST = _M.counter("lsp.conns_lost")
_MET_SEND_WINDOW = _M.histogram("lsp.send_window_occupancy",
                                buckets=OCCUPANCY_BUCKETS)
_MET_RECV_PENDING = _M.histogram("lsp.recv_pending_occupancy",
                                 buckets=OCCUPANCY_BUCKETS)
_MET_RTT = _M.histogram("lsp.msg_rtt_s", buckets=LATENCY_BUCKETS_S)
_MET_DROP_LENGTH = _M.counter("lsp.integrity_drops", reason="length")
_MET_DROP_CHECKSUM = _M.counter("lsp.integrity_drops", reason="checksum")


class ConnState(enum.Enum):
    CONNECTING = "connecting"
    UP = "up"
    CLOSING = "closing"
    CLOSED = "closed"
    LOST = "lost"


class _Pending:
    """One unacknowledged outbound message and its retransmit schedule."""

    __slots__ = ("seq", "raw", "cur_backoff", "epochs_passed", "fresh",
                 "sent_at", "retransmitted")

    def __init__(self, seq: int, raw: bytes):
        self.seq = seq
        self.raw = raw
        self.cur_backoff = 0
        self.epochs_passed = 0
        # Sent between epoch ticks: the first tick after the send does not
        # count toward the retransmit schedule (approximates the reference's
        # per-message timer phase within the graded 4-6 sends/14 epochs law).
        self.fresh = True
        # RTT metric plane: stamp of the (latest) first transmission; a
        # retransmitted message's eventual ack is ambiguous (Karn's rule),
        # so only never-retransmitted messages contribute RTT samples.
        self.sent_at = 0.0
        self.retransmitted = False


def _true() -> bool:
    return True


def _ignore(_arg=None) -> None:
    return None


class ConnCore:
    """One LSP connection's pure state machine. See the module docstring
    for the input/output contract; a shell MUST drain :attr:`outbox`
    after every input call (``write`` / ``on_message`` / ``on_epoch`` /
    ``begin_close`` / ``resume_delivery`` / construction)."""

    __slots__ = (
        "params", "conn_id", "state", "outbox",
        "_deliver", "_broken", "_on_connected", "_on_connect_failed",
        "_on_closed", "_deliver_ready", "_clock",
        "_next_seq", "_win_slots", "_win_count", "_win_base", "_buffer",
        "_connect_pending",
        "_recv_expected", "_recv_ring", "_recv_spill", "_recv_count",
        "_recv_unacked_seq",
        "_silent_epochs", "_got_traffic", "_got_payload_traffic",
    )

    def __init__(
        self,
        params: Params,
        conn_id: int,
        *,
        connect: bool = False,
        deliver: Callable[[bytes], None] = _ignore,
        broken: Callable[[Exception], None] = _ignore,
        on_connected: Callable[[int], None] = _ignore,
        on_connect_failed: Callable[[Exception], None] = _ignore,
        on_closed: Callable[[], None] = _ignore,
        deliver_ready: Optional[Callable[[], bool]] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.params = params
        self.conn_id = conn_id
        self.outbox: List[bytes] = []
        self._deliver = deliver
        self._broken = broken
        self._on_connected = on_connected
        self._on_connect_failed = on_connect_failed
        self._on_closed = on_closed
        # Delivery back-pressure probe (server read-queue bound, ref:
        # lsp/server_impl.go:112): when it returns False, the next in-order
        # message is parked in the reorder ring WITHOUT an ack — the
        # peer's send window cannot slide past an unacked head, so it
        # stalls at W outstanding and memory stays bounded end-to-end
        # without blocking the shell. The owner calls
        # :meth:`resume_delivery` when the app frees queue room; the
        # parked head is acked at delivery time.
        self._deliver_ready = deliver_ready or _true
        # Injected clock feeds ONLY the RTT metric plane (Karn-filtered
        # send->ack samples). detnet injects a zero clock: ``sent_at``
        # stays falsy, no samples are recorded, and the core performs no
        # syscalls at all — fully deterministic.
        self._clock = clock

        self.state = ConnState.CONNECTING if connect else ConnState.UP

        # Send side. Data sequence numbers start at 1 on both roles.
        # Ring window: live seqs sit in [base, base+W) at slot seq % W.
        w = params.window_size
        self._next_seq = 1
        self._win_slots: List[Optional[_Pending]] = [None] * w
        self._win_count = 0
        self._win_base = 1
        self._buffer: Optional[deque] = None   # lazily-created overflow

        # The in-flight Connect request, retransmitted like a window element.
        self._connect_pending: Optional[_Pending] = None
        if connect:
            self._connect_pending = _Pending(0, wire.encode_connect())
            self.outbox.append(self._connect_pending.raw)

        # Receive side: in-order reassembly ring + spillover.
        # ``_recv_unacked_seq`` is the (at most one) parked back-pressure
        # head whose ack is deferred to delivery; its retransmits must
        # NOT take the duplicate re-ack path, or the peer's window would
        # slide past an undelivered message the app might never get room
        # for.
        self._recv_expected = 1
        self._recv_ring: List[Optional[bytes]] = [None] * w
        self._recv_spill: Optional[dict] = None
        self._recv_count = 0
        self._recv_unacked_seq = -1

        # Epoch bookkeeping. Loss detection counts ALL inbound messages
        # (ref connDropTimer resets on gotMessageChan); the heartbeat
        # reminder is suppressed only by SUBSTANTIVE traffic (data / data
        # acks), because on a mutually idle link the reference's reminder
        # race resolves toward firing every epoch on both sides — a peer's
        # heartbeat must not starve ours, or its loss detector (fed only
        # by our sends) counts up to the epoch limit on a live link.
        self._silent_epochs = 0
        self._got_traffic = False
        self._got_payload_traffic = False

    # --------------------------------------------------------- timer surface

    @property
    def epoch_interval_s(self) -> float:
        """The one periodic timer this core requests of its shell."""
        return self.params.epoch_millis / 1000.0

    @property
    def finished(self) -> bool:
        return self.state in (ConnState.CLOSED, ConnState.LOST)

    # ------------------------------------------------------------- send path

    def write(self, payload: bytes) -> None:
        if self.state in (ConnState.CLOSING, ConnState.CLOSED, ConnState.LOST):
            raise ConnectionClosed(f"conn {self.conn_id}: write after close/loss")
        seq = self._next_seq
        self._next_seq += 1
        cksum = wire.checksum(self.conn_id, seq, len(payload), payload)
        pending = _Pending(seq, wire.encode_data(
            self.conn_id, seq, len(payload), cksum, payload))
        if self._can_admit(seq):
            self._admit(pending)
        else:
            if self._buffer is None:
                self._buffer = deque()
            self._buffer.append(pending)

    def _can_admit(self, seq: int) -> bool:
        # Window rule (ref: lsp/client_impl.go:381-389): at most W unacked
        # messages, all within [oldest_unacked, oldest_unacked + W). The
        # ring keeps ``_win_base`` at the oldest live seq, so the old
        # O(W) ``min(window)`` is one attribute read.
        w = self.params.window_size
        if self._win_count >= w:
            return False
        return self._win_count == 0 or seq < self._win_base + w

    def _admit(self, pending: _Pending) -> None:
        """Place one message in the ring and transmit it."""
        if self._win_count == 0:
            self._win_base = pending.seq
        self._win_slots[pending.seq % self.params.window_size] = pending
        self._win_count += 1
        pending.sent_at = self._clock()
        self.outbox.append(pending.raw)
        _MET_SEND_WINDOW.observe(self._win_count)

    def _refill_window(self) -> None:
        buf = self._buffer
        while buf and self._can_admit(buf[0].seq):
            self._admit(buf.popleft())

    @property
    def flushed(self) -> bool:
        return self._win_count == 0 and not self._buffer

    # ---------------------------------------------------------- receive path

    def on_message(self, msg: Message) -> None:
        """Handle one integrity-checked inbound message."""
        self._got_traffic = True
        if msg.type != MsgType.ACK or msg.seq_num != 0:
            self._got_payload_traffic = True
        if msg.type == MsgType.DATA:
            self._on_data(msg)
        elif msg.type == MsgType.ACK:
            self._on_ack(msg)

    # -- reorder-ring helpers. The ring covers [expected, expected+R);
    # an entry stored to spill stays there until drained even if the
    # ring window slides over its seq, so both stores are checked.

    def _recv_has(self, seq: int) -> bool:
        ring = self._recv_ring
        r = len(ring)
        if self._recv_expected <= seq < self._recv_expected + r \
                and ring[seq % r] is not None:
            return True
        spill = self._recv_spill
        return spill is not None and seq in spill

    def _recv_put(self, seq: int, payload: bytes) -> None:
        ring = self._recv_ring
        r = len(ring)
        if self._recv_expected <= seq < self._recv_expected + r:
            ring[seq % r] = payload
        else:
            if self._recv_spill is None:
                self._recv_spill = {}
            self._recv_spill[seq] = payload
        self._recv_count += 1

    def _recv_pop_expected(self) -> bytes:
        seq = self._recv_expected
        ring = self._recv_ring
        payload = ring[seq % len(ring)]
        if payload is not None:
            ring[seq % len(ring)] = None
        else:
            payload = self._recv_spill.pop(seq)
        self._recv_count -= 1
        return payload

    def _on_data(self, msg: Message) -> None:
        if self.state in (ConnState.CLOSED, ConnState.LOST):
            return
        if self.state == ConnState.CONNECTING:
            # Data from the server implies our Connect was accepted (the
            # explicit Ack(id, 0) was lost/delayed): establish implicitly so
            # the ack below carries the right conn id and delivery proceeds.
            self.conn_id = msg.conn_id
            self.state = ConnState.UP
            self._connect_pending = None
            self._on_connected(msg.conn_id)
        seq = msg.seq_num
        if seq < self._recv_expected or self._recv_has(seq):
            # Duplicates of ACKED messages are re-acked (exactly-once
            # delivery comes from receive-side dedup, not ack suppression;
            # ref: lsp/server_impl.go:462-470). A retransmit of the parked
            # unacked back-pressure head stays unacked until delivery.
            _MET_RECV_DUP.inc()
            if seq != self._recv_unacked_seq:
                self.outbox.append(wire.encode_ack(self.conn_id, seq))
            return
        if seq == self._recv_expected and self.state == ConnState.UP and \
                not self._deliver_ready():
            # Back-pressure: park the head unacked; see the __init__ note.
            # Out-of-order messages are still admitted (and acked) below —
            # they are bounded by the peer's window, which cannot slide
            # past this unacked head.
            self._recv_put(seq, msg.payload or b"")
            self._recv_unacked_seq = seq
            return
        self.outbox.append(wire.encode_ack(self.conn_id, seq))
        self._recv_put(seq, msg.payload or b"")
        _MET_RECV_PENDING.observe(self._recv_count)
        self._drain()

    def _drain(self) -> None:
        """Deliver the in-order run while the owner's queue has room; the
        parked back-pressure head is acked here, at delivery time."""
        while self._recv_has(self._recv_expected) and (
                self.state != ConnState.UP or self._deliver_ready()):
            seq = self._recv_expected
            payload = self._recv_pop_expected()
            if seq == self._recv_unacked_seq:
                self._recv_unacked_seq = -1
                self.outbox.append(wire.encode_ack(self.conn_id, seq))
            self._recv_expected += 1
            if self.state == ConnState.UP:
                self._deliver(payload)

    def resume_delivery(self) -> None:
        """Owner hook: queue room reappeared (the app read); deliver any
        messages that stranded when :meth:`_drain` hit the cap — inbound
        traffic is NOT guaranteed to re-trigger it (an acked out-of-order
        backlog has no retransmits coming)."""
        if self.state in (ConnState.UP, ConnState.CLOSING):
            self._drain()

    def _on_ack(self, msg: Message) -> None:
        if msg.seq_num == 0:
            # Heartbeat — or the connect ack while CONNECTING.
            if self.state == ConnState.CONNECTING:
                self.conn_id = msg.conn_id
                self.state = ConnState.UP
                self._connect_pending = None
                self._on_connected(msg.conn_id)
            return
        seq = msg.seq_num
        w = self.params.window_size
        if self._win_count == 0 or not \
                self._win_base <= seq < self._win_base + w:
            return
        pending = self._win_slots[seq % w]
        if pending is None or pending.seq != seq:
            return
        self._win_slots[seq % w] = None
        self._win_count -= 1
        if self._win_count and seq == self._win_base:
            # Advance base to the next live slot (<= W-1 probes; every
            # live seq is in (base, base+W) at its unique slot).
            b = seq + 1
            while self._win_slots[b % w] is None:
                b += 1
            self._win_base = b
        if not pending.retransmitted and pending.sent_at:
            # Send->ack RTT, Karn-filtered (see _Pending).
            _MET_RTT.observe(self._clock() - pending.sent_at)
        self._refill_window()
        if self.state == ConnState.CLOSING and self.flushed:
            self._finish(ConnState.CLOSED)

    # ------------------------------------------------------------ epoch tick

    def on_epoch(self) -> bool:
        """One epoch-timer event. Returns False when the connection is
        finished (the shell stops the timer)."""
        _MET_EPOCHS.inc()
        # Loss detection (ref: lsp/client_impl.go timeRoutine:258-286).
        if self._got_traffic:
            self._silent_epochs = 0
            self._got_traffic = False
        else:
            self._silent_epochs += 1
            if self._silent_epochs >= self.params.epoch_limit:
                if self.state == ConnState.CONNECTING:
                    self._fail_connect(ConnectTimeout(
                        f"no connect ack after {self.params.epoch_limit} epochs"))
                else:
                    self._declare_lost()
                return False

        # Heartbeat, idle-only (VERDICT r4): the reference re-arms its
        # reminder timer on every inbound message and sends Ack(connID, 0)
        # only after a receive-silent epoch (ref: lsp/client_impl.go:268-281,
        # server_impl.go:396-420) — so a BUSY link emits no reminder acks.
        # On an idle link, peer heartbeats arrive one epoch + latency apart,
        # so the reference's reminder reliably fires anyway: idleness is
        # judged on substantive traffic only (see __init__ note).
        if not self._got_payload_traffic and \
                self.state in (ConnState.UP, ConnState.CLOSING):
            self.outbox.append(wire.encode_ack(self.conn_id, 0))
            _MET_HEARTBEATS.inc()
        self._got_payload_traffic = False

        # Retransmits: the Connect request and every unacked window
        # element, in seq order from the ring base (the dict the ring
        # replaced iterated in insertion == seq order).
        w = self.params.window_size
        if self._win_count:
            base = self._win_base
            for off in range(w):
                pending = self._win_slots[(base + off) % w]
                if pending is not None:
                    self._retransmit_tick(pending)
        if self._connect_pending is not None:
            self._retransmit_tick(self._connect_pending)
        return True

    def _retransmit_tick(self, pending: _Pending) -> None:
        if pending.fresh:
            pending.fresh = False
        elif pending.epochs_passed >= pending.cur_backoff:
            self.outbox.append(pending.raw)
            pending.retransmitted = True
            # Labeled by the backoff level that TRIGGERED this resend
            # (0, 1, 2, 4, ... capped): the distribution is the
            # XXOXOOX retransmission-law shape, observable per process.
            _M.counter(   # dbmlint: ok[cardinality] bounded:
                # backoff levels are 0, 1, 2, 4, ... capped at the
                # max_backoff_interval knob — log2(cap)+2 values.
                "lsp.retransmits",
                backoff=str(pending.cur_backoff)).inc()
            pending.epochs_passed = 0
            if pending.cur_backoff == 0:
                pending.cur_backoff = min(1, self.params.max_backoff_interval)
            else:
                pending.cur_backoff = min(pending.cur_backoff * 2,
                                          self.params.max_backoff_interval)
        else:
            pending.epochs_passed += 1

    # ----------------------------------------------------------- termination

    def begin_close(self) -> None:
        """Graceful close: flush window + buffer, then finish (ref: §3.5)."""
        if self.state in (ConnState.CLOSED, ConnState.LOST):
            self._on_closed()
            return
        if self.state == ConnState.CONNECTING:
            self._fail_connect(ConnectionClosed("closed during connect"))
            return
        self.state = ConnState.CLOSING
        if self.flushed:
            self._finish(ConnState.CLOSED)

    def abort(self) -> None:
        """Immediate teardown with no flush (endpoint shutdown path)."""
        if self.state not in (ConnState.CLOSED, ConnState.LOST):
            self._finish(ConnState.CLOSED)

    def _declare_lost(self) -> None:
        _MET_CONN_LOST.inc()
        self._finish(ConnState.LOST)
        self._broken(ConnectionLost(f"conn {self.conn_id}: epoch limit reached"))

    def _fail_connect(self, exc: Exception) -> None:
        self._finish(ConnState.LOST)
        self._on_connect_failed(exc)

    def _finish(self, final_state: ConnState) -> None:
        self.state = final_state
        if self._win_count:
            w = self.params.window_size
            for i in range(w):
                self._win_slots[i] = None
            self._win_count = 0
        self._buffer = None
        self._recv_unacked_seq = -1
        self._connect_pending = None
        self._on_closed()


def integrity_check(msg: Message) -> bool:
    """Validate (and possibly truncate) an inbound message.

    Rules (ref: lsp/client_impl.go integrityCheck:200-213): Connect/Ack are
    exempt; short payloads are rejected; long payloads are truncated to
    ``Size`` before the checksum is verified.
    """
    if msg.type in (MsgType.CONNECT, MsgType.ACK):
        return True
    payload = msg.payload if msg.payload is not None else b""
    if len(payload) < msg.size:
        _MET_DROP_LENGTH.inc()
        return False
    if len(payload) > msg.size:
        payload = payload[: msg.size]
        msg.payload = payload
    ok = wire.checksum(msg.conn_id, msg.seq_num, msg.size,
                       payload) == msg.checksum
    if not ok:
        _MET_DROP_CHECKSUM.inc()
    return ok
