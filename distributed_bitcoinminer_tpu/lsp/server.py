"""LSP server endpoint: async engine + Go-style blocking facade.

Same surface as the reference ``Server`` interface (ref: lsp/server_api.go:
6-39): blocking ``read`` (any client), non-blocking ``write(conn_id)``,
non-blocking ``close_conn``, blocking flushing ``close``. One asyncio loop
owns every connection's state — the multi-connection analog of the
reference's mainRoutine/clientMain goroutine pair (ref: lsp/server_impl.go).
"""

from __future__ import annotations

import asyncio
from typing import Optional, Tuple, Union

from .. import lspnet
from . import wire
from ._engine import Conn, ConnState, integrity_check
from ._loop import run_sync
from .errors import ConnectionClosed, LspError
from .message import MsgType
from .params import Params

ReadItem = Tuple[int, Union[bytes, Exception]]

# Delivery-queue bound, matching the reference server's buffered read
# channel (ref: lsp/server_impl.go:112, `make(chan *Message, 500)`). At the
# cap, a connection parks its next in-order message UNACKED instead of
# queueing it (see _engine.Conn deliver_ready): the sender's window cannot
# slide past the unacked head, so it stalls at W outstanding and a
# never-reading app observes back-pressure, not unbounded memory. Reads at
# the cap wake the connections to drain (read(), resume_delivery).
# Connection-death notices bypass the cap — they must always surface.
READ_QUEUE_CAP = 500


class AsyncServer:
    """Asyncio-native LSP server. Create via :func:`new_async_server`."""

    def __init__(self, params: Params):
        self._params = params
        self._ep: Optional[lspnet.UDPEndpoint] = None
        self._conns: dict[int, Conn] = {}
        self._addr_map: dict[tuple, int] = {}
        self._conn_addr: dict[int, tuple] = {}
        self._next_conn_id = 1
        self._read_queue: asyncio.Queue[Union[ReadItem, Exception]] = asyncio.Queue()
        self._recv_task: Optional[asyncio.Task] = None
        self._reaper_tasks: set[asyncio.Task] = set()
        self._closed = False

    async def _start(self, port: int, host: str = "127.0.0.1") -> None:
        self._ep = await lspnet.listen_udp(host, port)
        self._recv_task = asyncio.get_running_loop().create_task(self._recv_loop())
        self._recv_task.add_done_callback(self._recv_done)

    def _recv_done(self, task: asyncio.Task) -> None:
        # A crashed receive loop must not leave the endpoint silently deaf.
        if task.cancelled():
            return
        exc = task.exception()
        if exc is not None:
            self._read_queue.put_nowait(
                ConnectionClosed(f"receive loop crashed: {exc!r}"))

    @property
    def port(self) -> int:
        return self._ep.sockname[1]

    # -------------------------------------------------------------- receive

    async def _recv_loop(self) -> None:
        # Burst drain (ISSUE 17): one awaited recv per burst, then
        # recv_nowait until momentarily dry — a recvmmsg batch is
        # processed in one synchronous sweep, not one loop round-trip
        # per datagram.
        while True:
            item = await self._ep.recv()
            if item is None:
                return
            while item is not None:
                self._on_datagram(item)
                item = self._ep.recv_nowait()

    def _on_datagram(self, item: tuple) -> None:
        raw, addr = item
        try:
            msg = wire.decode(raw)
        except ValueError:
            return
        if not integrity_check(msg):
            return
        if msg.type == MsgType.CONNECT:
            self._on_connect(addr)
            return
        conn = self._conns.get(msg.conn_id)
        if conn is not None:
            conn.on_message(msg)

    def _on_connect(self, addr: tuple) -> None:
        if self._closed:
            return
        existing = self._addr_map.get(addr)
        if existing is not None:
            # Repeat Connect (our ack was lost): re-ack with the same id
            # (ref: lsp/server_impl.go searchClient dedup, :327-332).
            self._ep.send(wire.encode_ack(existing, 0), addr)
            return
        conn_id = self._next_conn_id
        self._next_conn_id += 1
        conn = Conn(
            params=self._params,
            conn_id=conn_id,
            send_raw=lambda raw, a=addr: self._ep.send(raw, a),
            deliver=lambda payload, cid=conn_id: self._read_queue.put_nowait(
                (cid, payload)),
            broken=lambda exc, cid=conn_id: self._on_broken(cid, exc),
            deliver_ready=lambda: self._read_queue.qsize() < READ_QUEUE_CAP,
        )
        self._conns[conn_id] = conn
        self._addr_map[addr] = conn_id
        self._conn_addr[conn_id] = addr
        self._ep.send(wire.encode_ack(conn_id, 0), addr)

    def _on_broken(self, conn_id: int, exc: Exception) -> None:
        self._read_queue.put_nowait((conn_id, exc))
        self._remove(conn_id)

    def _remove(self, conn_id: int) -> None:
        self._conns.pop(conn_id, None)
        addr = self._conn_addr.pop(conn_id, None)
        if addr is not None:
            self._addr_map.pop(addr, None)

    # ------------------------------------------------------------ public API

    async def read(self) -> ReadItem:
        """Next in-order (conn_id, payload); (conn_id, exc) when a conn died.

        Raises ConnectionClosed once the server itself has been closed.
        """
        # Reading at the cap frees delivery room: wake the connections so
        # back-pressured messages drain now (inbound traffic alone cannot
        # be relied on to re-trigger delivery — an acked out-of-order
        # backlog has no retransmits coming).
        was_full = self._read_queue.qsize() >= READ_QUEUE_CAP
        item = await self._read_queue.get()
        if isinstance(item, Exception):
            self._read_queue.put_nowait(item)
            raise item
        if was_full:
            for conn in list(self._conns.values()):
                conn.resume_delivery()
        return item

    def read_nowait(self) -> Optional[ReadItem]:
        """The next already-delivered item without awaiting, or None.

        The scheduler's batched recv drain (ISSUE 11): one awaited
        :meth:`read` per batch, then ``read_nowait`` until the queue is
        momentarily dry — each asyncio queue ``get`` await costs a loop
        round-trip, and at 10k active conns those round-trips dominate
        the recv path. Semantics match :meth:`read` exactly (including
        the back-pressure wake and the server-closed sentinel, which is
        left in place for the next awaited read to raise).
        """
        was_full = self._read_queue.qsize() >= READ_QUEUE_CAP
        try:
            item = self._read_queue.get_nowait()
        except asyncio.QueueEmpty:
            return None
        if isinstance(item, Exception):
            self._read_queue.put_nowait(item)
            return None
        if was_full:
            for conn in list(self._conns.values()):
                conn.resume_delivery()
        return item

    def write(self, conn_id: int, payload: bytes) -> None:
        conn = self._conns.get(conn_id)
        if conn is None or conn.state not in (ConnState.UP,):
            raise ConnectionClosed(f"conn {conn_id} does not exist or is closed")
        conn.write(payload)

    def close_conn(self, conn_id: int) -> None:
        """Non-blocking graceful close of one connection."""
        conn = self._conns.get(conn_id)
        if conn is None:
            raise ConnectionClosed(f"conn {conn_id} does not exist")
        conn.begin_close()
        task = asyncio.get_running_loop().create_task(self._reap(conn_id, conn))
        self._reaper_tasks.add(task)
        task.add_done_callback(self._reaper_tasks.discard)

    async def _reap(self, conn_id: int, conn: Conn) -> None:
        await conn.closed_event.wait()
        self._remove(conn_id)

    async def close(self) -> None:
        """Flush and close every connection, then tear down the socket."""
        if self._closed:
            return
        self._closed = True
        conns = list(self._conns.values())
        for conn in conns:
            conn.begin_close()
        if conns:
            await asyncio.gather(*(c.closed_event.wait() for c in conns))
        for task in list(self._reaper_tasks):
            task.cancel()
        if self._recv_task is not None:
            self._recv_task.cancel()
            try:
                await self._recv_task
            except asyncio.CancelledError:
                pass
            self._recv_task = None
        for conn in list(self._conns.values()):
            conn.abort()
        self._conns.clear()
        self._addr_map.clear()
        self._conn_addr.clear()
        if self._ep is not None:
            self._ep.close()
        self._read_queue.put_nowait(ConnectionClosed("server closed"))

    def conn_state(self, conn_id: int) -> Optional[ConnState]:
        conn = self._conns.get(conn_id)
        return conn.state if conn else None


async def new_async_server(port: int, params: Optional[Params] = None,
                           host: str = "127.0.0.1") -> AsyncServer:
    server = AsyncServer(params or Params())
    await server._start(port, host)
    return server


class Server:
    """Blocking facade over :class:`AsyncServer` (Go-style surface)."""

    def __init__(self, inner: AsyncServer):
        self._inner = inner

    @property
    def port(self) -> int:
        return self._inner.port

    def read(self) -> ReadItem:
        return run_sync(self._inner.read())

    def write(self, conn_id: int, payload: bytes) -> None:
        run_sync(self._call(self._inner.write, conn_id, payload))

    def close_conn(self, conn_id: int) -> None:
        run_sync(self._call(self._inner.close_conn, conn_id))

    def close(self) -> None:
        run_sync(self._inner.close())

    @staticmethod
    async def _call(fn, *args):
        return fn(*args)


def new_server(port: int, params: Optional[Params] = None) -> Server:
    return Server(run_sync(new_async_server(port, params)))
