"""``recvmmsg``/``sendmmsg`` via ctypes: one syscall per burst (ISSUE 17).

The stock asyncio datagram path costs one ``recvfrom`` and one ``sendto``
syscall per packet. Under an echo storm the datapath handles bursts —
the recv pump drains what arrived, and every inbound Data produces an
Ack at pump exit — so Linux's batched datagram syscalls amortize the
kernel crossing over up to ``DBM_MMSG_BATCH`` packets in each direction.

This module is the raw syscall wrapper only: :class:`MmsgSocket` owns
the preallocated receive buffers and the ctypes header arrays (iovec /
msghdr / mmsghdr / sockaddr_in), built ONCE and reused for every call —
the per-burst Python work is slicing received bytes out of the reused
buffers and pointing iovecs at outgoing frames. Event-loop integration
(readable callbacks, send-flush scheduling, fault pipeline, metrics)
lives in ``lspnet/net.py``'s ``MmsgEndpoint``; availability gating and
graceful fallback to one-per-syscall live there too, keyed on
:func:`available` (Linux + libc symbols + AF_INET). No new
dependencies: ``ctypes`` against the already-loaded libc.

IPv4 only — the sockaddr storage is ``sockaddr_in``. Non-IPv4 binds
fall back to the stock endpoint at the caller.
"""

from __future__ import annotations

import ctypes
import errno
import os
import socket
import sys
from typing import List, Optional, Tuple

__all__ = ["available", "MmsgSocket", "RECV_BUF_SIZE"]

#: Max UDP datagram; each preallocated recv buffer is this large, so no
#: inbound datagram is ever truncated.
RECV_BUF_SIZE = 65535


class _iovec(ctypes.Structure):
    # iov_base as c_char_p (same pointer ABI as void*): the send path
    # assigns a frame's ``bytes`` object straight to the field — no
    # per-frame c_char_p()/cast() pair — and ctypes' _objects tracking
    # keeps the frame alive for the call.
    _fields_ = [("iov_base", ctypes.c_char_p),
                ("iov_len", ctypes.c_size_t)]


class _sockaddr_in(ctypes.Structure):
    _fields_ = [("sin_family", ctypes.c_uint16),
                ("sin_port", ctypes.c_uint16),      # network byte order
                ("sin_addr", ctypes.c_uint8 * 4),   # network byte order
                ("sin_zero", ctypes.c_uint8 * 8)]


class _msghdr(ctypes.Structure):
    # Field types per glibc's struct msghdr on Linux; ctypes inserts the
    # same alignment padding the C ABI does (namelen u32 -> pad -> ptr).
    _fields_ = [("msg_name", ctypes.c_void_p),
                ("msg_namelen", ctypes.c_uint32),
                ("msg_iov", ctypes.POINTER(_iovec)),
                ("msg_iovlen", ctypes.c_size_t),
                ("msg_control", ctypes.c_void_p),
                ("msg_controllen", ctypes.c_size_t),
                ("msg_flags", ctypes.c_int)]


class _mmsghdr(ctypes.Structure):
    _fields_ = [("msg_hdr", _msghdr),
                ("msg_len", ctypes.c_uint)]


def _load_libc():
    if not sys.platform.startswith("linux"):
        return None
    try:
        libc = ctypes.CDLL(None, use_errno=True)
        libc.recvmmsg  # noqa: B018 — symbol probe; AttributeError = absent
        libc.sendmmsg  # noqa: B018
    except (OSError, AttributeError):
        return None
    libc.recvmmsg.restype = ctypes.c_int
    libc.sendmmsg.restype = ctypes.c_int
    return libc


_LIBC = _load_libc()


def available() -> bool:
    """True when batched datagram syscalls exist on this platform."""
    return _LIBC is not None


class MmsgSocket:
    """Preallocated recv/send header arrays over one UDP socket fd.

    Not thread-safe; one owner (the event loop) calls
    :meth:`recv_burst` / :meth:`send_burst`, each exactly one syscall.
    """

    def __init__(self, fd: int, batch: int):
        if _LIBC is None:
            raise OSError("recvmmsg/sendmmsg unavailable on this platform")
        self._fd = fd
        self._batch = batch

        # Receive side: buffers + headers wired once, reused every call.
        self._r_bufs = [ctypes.create_string_buffer(RECV_BUF_SIZE)
                        for _ in range(batch)]
        self._r_iovs = (_iovec * batch)()
        self._r_names = (_sockaddr_in * batch)()
        self._r_hdrs = (_mmsghdr * batch)()
        for i in range(batch):
            self._r_iovs[i].iov_base = ctypes.cast(self._r_bufs[i],
                                                   ctypes.c_char_p)
            self._r_iovs[i].iov_len = RECV_BUF_SIZE
            hdr = self._r_hdrs[i].msg_hdr
            hdr.msg_name = ctypes.cast(ctypes.byref(self._r_names[i]),
                                       ctypes.c_void_p)
            hdr.msg_namelen = ctypes.sizeof(_sockaddr_in)
            hdr.msg_iov = ctypes.pointer(self._r_iovs[i])
            hdr.msg_iovlen = 1

        # Send side: headers reused; iov_base is pointed at each outgoing
        # frame's bytes per call (the caller keeps the frames referenced
        # for the duration of send_burst).
        self._s_iovs = (_iovec * batch)()
        self._s_hdrs = (_mmsghdr * batch)()
        for i in range(batch):
            hdr = self._s_hdrs[i].msg_hdr
            hdr.msg_iov = ctypes.pointer(self._s_iovs[i])
            hdr.msg_iovlen = 1

        # Peer-address caches, both directions (ISSUE 17 hot path): the
        # peer set is small and stable (one address per live client), so
        # the per-packet inet_ntoa/ntohs decode and the per-frame
        # sockaddr_in pack are paid once per PEER, not once per packet.
        # Entries are tiny and live for the socket's lifetime.
        self._raddr_cache: dict = {}
        self._saddr_cache: dict = {}

    # -------------------------------------------------------------- receive

    def recv_burst(self) -> List[Tuple[bytes, Tuple[str, int]]]:
        """One ``recvmmsg``: every datagram already queued, up to the
        batch size. Returns [] when the socket has nothing (EAGAIN)."""
        n = _LIBC.recvmmsg(self._fd, self._r_hdrs, self._batch, 0, None)
        if n <= 0:
            if n == 0:
                return []
            err = ctypes.get_errno()
            if err in (errno.EAGAIN, errno.EWOULDBLOCK, errno.EINTR):
                return []
            raise OSError(err, os.strerror(err))
        out = []
        cache = self._raddr_cache
        for i in range(n):
            length = self._r_hdrs[i].msg_len
            name = self._r_names[i]
            key = (bytes(name.sin_addr), name.sin_port)
            addr = cache.get(key)
            if addr is None:
                addr = (socket.inet_ntoa(key[0]), socket.ntohs(key[1]))
                cache[key] = addr
            # string_at copies exactly `length` bytes out of the reused
            # buffer (the .raw property would materialize all 64 KiB
            # first — measured at ~60% of recv_burst's cost).
            out.append((ctypes.string_at(self._r_bufs[i], length), addr))
            # The kernel overwrote namelen with the actual address size;
            # restore the storage size for the next call.
            self._r_hdrs[i].msg_hdr.msg_namelen = ctypes.sizeof(_sockaddr_in)
        return out

    # ----------------------------------------------------------------- send

    def send_burst(self,
                   items: List[Tuple[bytes, Optional[Tuple[str, int]]]]) -> int:
        """One ``sendmmsg`` over up to ``batch`` (frame, addr) pairs; an
        addr of None sends on the connected socket's peer. Returns how
        many datagrams the kernel accepted (possibly fewer than offered);
        raises BlockingIOError when not even the first would go out."""
        count = min(len(items), self._batch)
        cache = self._saddr_cache
        for i in range(count):
            data, addr = items[i]
            iov = self._s_iovs[i]
            iov.iov_base = data
            iov.iov_len = len(data)
            hdr = self._s_hdrs[i].msg_hdr
            if addr is None:
                hdr.msg_name = None
                hdr.msg_namelen = 0
            else:
                entry = cache.get(addr)
                if entry is None:
                    name = _sockaddr_in()
                    name.sin_family = socket.AF_INET
                    name.sin_port = socket.htons(addr[1])
                    packed = socket.inet_aton(addr[0])
                    for j in range(4):
                        name.sin_addr[j] = packed[j]
                    # The struct is kept alive by the cache entry; the
                    # pointer is therefore stable and reusable.
                    entry = (name, ctypes.cast(ctypes.byref(name),
                                               ctypes.c_void_p))
                    cache[addr] = entry
                hdr.msg_name = entry[1]
                hdr.msg_namelen = ctypes.sizeof(_sockaddr_in)
        n = _LIBC.sendmmsg(self._fd, self._s_hdrs, count, 0)
        if n < 0:
            err = ctypes.get_errno()
            if err in (errno.EAGAIN, errno.EWOULDBLOCK, errno.EINTR):
                raise BlockingIOError(err, os.strerror(err))
            raise OSError(err, os.strerror(err))
        return n
