"""Shared periodic-timer service: one sleeping task per event loop.

Every LSP :class:`~._engine.Conn` owns an epoch timer (heartbeat, loss
detection, retransmit backoff). The original implementation gave each
conn its OWN asyncio task sleeping ``epoch_millis`` — at 10k
connections that is 10k timer-heap entries and 10k task wakeups per
epoch, and the load harness (ISSUE 11) fingered exactly that as a
control-plane melt point: the event loop spends its time context-
switching idle epoch tasks instead of serving requests.

:class:`TimerWheel` collapses them: ONE task per event loop sleeps
until the earliest registered deadline, services every due callback,
and re-arms each at ``fire_time + period`` (the same drift semantics as
the per-task ``await sleep(epoch)`` loop it replaces — the next tick is
relative to when this one RAN, so a busy loop stretches epochs exactly
like before, which the graded retransmission-law tests depend on).
Registration and cancellation are O(log n) heap operations; a cancelled
entry is dropped lazily when it surfaces.

``DBM_TIMER_WHEEL=0`` restores the per-conn task (stock behavior — the
tier-1 knob-off matrix leg pins the transport suites both ways). The
wheel preserves per-conn tick PHASE: an entry's first fire is
``register_time + period``, exactly like the task it replaces — only
the number of OS/loop timers changes, never the tick schedule.
"""

from __future__ import annotations

import asyncio
import heapq
import logging
from typing import Callable, Optional

from ..utils._env import int_env as _int_env

logger = logging.getLogger("dbm.lsp")

__all__ = ["TimerWheel", "wheel_enabled", "wheel_for"]

#: Attribute under which a loop's wheel singleton hangs off the loop
#: object itself — a per-loop registry with the loop's own lifetime, no
#: global table to leak closed loops.
_LOOP_ATTR = "_dbm_timer_wheel"


def wheel_enabled() -> bool:
    """``DBM_TIMER_WHEEL`` (default 1): 0 restores per-conn tasks."""
    return _int_env("DBM_TIMER_WHEEL", 1) != 0


def wheel_for(loop: Optional[asyncio.AbstractEventLoop] = None
              ) -> "TimerWheel":
    """The (lazily created) wheel of ``loop`` (default: running loop)."""
    loop = loop or asyncio.get_running_loop()
    wheel = getattr(loop, _LOOP_ATTR, None)
    if wheel is None:
        wheel = TimerWheel(loop)
        setattr(loop, _LOOP_ATTR, wheel)
    return wheel


class _Entry:
    __slots__ = ("handle", "period", "cb", "cancelled")

    def __init__(self, handle: int, period: float, cb: Callable[[], bool]):
        self.handle = handle
        self.period = period
        self.cb = cb
        self.cancelled = False


class TimerWheel:
    """One loop's shared periodic timers. Not thread-safe: all calls
    must come from the owning loop (the same single-owner discipline as
    every other per-loop structure in ``lsp/``)."""

    def __init__(self, loop: asyncio.AbstractEventLoop):
        self._loop = loop
        self._heap: list = []          # (due, handle) — heapq
        self._entries: dict[int, _Entry] = {}
        self._next_handle = 1
        self._task: Optional[asyncio.Task] = None
        self._wake: Optional[asyncio.Event] = None

    def __len__(self) -> int:
        return len(self._entries)

    def add(self, period: float, cb: Callable[[], bool]) -> int:
        """Register ``cb`` to fire every ``period`` seconds, first at
        ``now + period``. ``cb`` returning False deregisters it (the
        per-conn task's "return on finished" shape); an exception from
        ``cb`` deregisters too (matching the old task dying) and is
        logged. Returns a handle for :meth:`cancel`."""
        handle = self._next_handle
        self._next_handle += 1
        entry = _Entry(handle, max(period, 1e-6), cb)
        self._entries[handle] = entry
        heapq.heappush(self._heap, (self._loop.time() + entry.period,
                                    handle))
        if self._task is None or self._task.done():
            self._wake = asyncio.Event()
            self._task = self._loop.create_task(self._run())
        elif self._wake is not None:
            self._wake.set()           # re-evaluate the earliest deadline
        return handle

    def cancel(self, handle: int) -> None:
        """Deregister; the heap entry drops lazily when it surfaces. A
        cancel that empties the wheel wakes the runner so its task exits
        NOW — a lingering sleeper would read as a task leak to harnesses
        that assert a drained loop at teardown."""
        entry = self._entries.pop(handle, None)
        if entry is not None:
            entry.cancelled = True
        if not self._entries and self._wake is not None:
            self._wake.set()

    async def _run(self) -> None:
        while True:
            # Prune cancelled heads eagerly: sleeping toward a dead
            # entry's deadline would keep the task alive past the last
            # registration.
            while self._heap and self._heap[0][1] not in self._entries:
                heapq.heappop(self._heap)
            if not self._heap:
                break
            due, handle = self._heap[0]
            now = self._loop.time()
            if due > now:
                self._wake.clear()
                if not self._entries:
                    break
                try:
                    await asyncio.wait_for(self._wake.wait(), due - now)
                except asyncio.TimeoutError:
                    pass
                continue
            heapq.heappop(self._heap)
            entry = self._entries.get(handle)
            if entry is None or entry.cancelled:
                continue
            try:
                keep = entry.cb()
            except Exception:   # noqa: BLE001 — one conn's tick must not
                # kill every other conn's timer (the old per-conn task
                # died alone; the shared wheel must fail no wider).
                logger.exception("timer-wheel callback failed; "
                                 "deregistering")
                keep = False
            if keep is False:
                self._entries.pop(handle, None)
            else:
                heapq.heappush(
                    self._heap, (self._loop.time() + entry.period, handle))
        self._task = None
