"""LSP error taxonomy.

The reference returns plain Go errors; here each failure mode is a distinct
exception so applications can branch on cause. The sync facades convert these
to (value, error) pairs where a Go-like surface is needed.
"""


class LspError(Exception):
    """Base class for all LSP failures."""


class ConnectTimeout(LspError):
    """Connect handshake received no Ack within EpochLimit epochs."""


class ConnectionLost(LspError):
    """EpochLimit epochs passed with no traffic from the peer."""


class ConnectionClosed(LspError):
    """The local endpoint was explicitly closed."""
