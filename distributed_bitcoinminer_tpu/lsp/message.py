"""LSP wire message: type, connection id, sequence number, size, checksum, payload.

Wire format is Go ``encoding/json`` of the reference's ``Message`` struct
(ref: lsp/message.go:11-55): all six fields always present, ``Payload`` is
standard-base64 (or ``null`` when absent). Field order in the emitted JSON
matches Go's struct order so captured goldens compare byte-for-byte.
"""

from __future__ import annotations

import base64
import enum
import json
from dataclasses import dataclass, field


class MsgType(enum.IntEnum):
    CONNECT = 0  # sent by clients to establish a connection
    DATA = 1     # sent by either side to transfer a payload
    ACK = 2      # acknowledges a connect or data message; seq 0 = heartbeat


@dataclass
class Message:
    type: MsgType = MsgType.CONNECT
    conn_id: int = 0
    seq_num: int = 0
    size: int = 0
    checksum: int = 0
    payload: bytes | None = field(default=None)

    def to_json(self) -> bytes:
        """Marshal exactly like Go ``json.Marshal(&Message{...})``."""
        if self.payload is None:
            p = "null"
        else:
            p = '"' + base64.b64encode(self.payload).decode("ascii") + '"'
        return (
            '{"Type":%d,"ConnID":%d,"SeqNum":%d,"Size":%d,"Checksum":%d,"Payload":%s}'
            % (int(self.type), self.conn_id, self.seq_num, self.size,
               self.checksum, p)
        ).encode("ascii")

    @classmethod
    def from_json(cls, data: bytes) -> "Message":
        """Unmarshal; raises ValueError on malformed input (caller drops packet)."""
        try:
            obj = json.loads(data)
        except json.JSONDecodeError as e:
            raise ValueError(f"bad LSP message: {e}") from e
        if not isinstance(obj, dict):
            raise ValueError("bad LSP message: not an object")
        raw_payload = obj.get("Payload")
        payload = None
        if raw_payload is not None:
            try:
                payload = base64.b64decode(raw_payload, validate=True)
            except Exception as e:  # noqa: BLE001 — any decode failure is a bad packet
                raise ValueError(f"bad LSP payload: {e}") from e
        try:
            return cls(
                type=MsgType(obj.get("Type", 0)),
                conn_id=int(obj.get("ConnID", 0)),
                seq_num=int(obj.get("SeqNum", 0)),
                size=int(obj.get("Size", 0)),
                checksum=int(obj.get("Checksum", 0)),
                payload=payload,
            )
        except (ValueError, TypeError) as e:
            raise ValueError(f"bad LSP message fields: {e}") from e

    def __str__(self) -> str:
        # Same pretty-print shape as the reference (ref: lsp/message.go:58-74).
        if self.type == MsgType.CONNECT:
            return f"[Connect {self.conn_id} {self.seq_num}]"
        if self.type == MsgType.DATA:
            body = self.payload.decode("utf-8", "replace") if self.payload else ""
            return f"[Data {self.conn_id} {self.seq_num} {self.checksum} {body}]"
        return f"[Ack {self.conn_id} {self.seq_num}]"


def new_connect() -> Message:
    return Message(type=MsgType.CONNECT)


def new_data(conn_id: int, seq_num: int, size: int, payload: bytes,
             checksum: int) -> Message:
    return Message(type=MsgType.DATA, conn_id=conn_id, seq_num=seq_num,
                   size=size, checksum=checksum, payload=payload)


def new_ack(conn_id: int, seq_num: int) -> Message:
    return Message(type=MsgType.ACK, conn_id=conn_id, seq_num=seq_num)
