"""LSP integrity checksum.

Semantics match the reference bit-for-bit (ref: lsp/checksum.go:10-48 and the
fold in lsp/client_impl.go:183-198): sum 16-bit little-endian halves of the
header integers and of the payload (odd tail zero-padded) into a 32-bit
accumulator, then fold carries down to 16 bits.
"""

from __future__ import annotations

_U32 = 0xFFFFFFFF


def int2checksum(value: int) -> int:
    """32-bit partial sum for one header integer (two LE 16-bit halves)."""
    v = value & _U32
    return (v & 0xFFFF) + (v >> 16)


def bytearray2checksum(value: bytes) -> int:
    """32-bit partial sum over LE 16-bit chunks; odd trailing byte zero-padded."""
    total = 0
    n = len(value)
    even = n - (n % 2)
    for i in range(0, even, 2):
        total += value[i] | (value[i + 1] << 8)
    if n % 2:
        total += value[-1]
    return total & _U32


def make_checksum(conn_id: int, seq_num: int, size: int, payload: bytes) -> int:
    """Fold the four partial sums into the final 16-bit wire checksum."""
    total = (int2checksum(conn_id) + int2checksum(seq_num)
             + int2checksum(size) + bytearray2checksum(payload)) & _U32
    while total > 0xFFFF:
        total = (total >> 16) + (total & 0xFFFF)
    return total
