"""LSP — Live Sequence Protocol: reliable, in-order, exactly-once transport over UDP.

Provides the same guarantees as the reference Go implementation
(/root/reference/p1/src/github.com/cmu440/lsp): sliding-window flow control,
per-message exponential-backoff retransmission, epoch heartbeats,
connection-loss detection, integrity checksums, and graceful close.
"""

from .message import Message, MsgType, new_connect, new_data, new_ack
from .checksum import int2checksum, bytearray2checksum, make_checksum
from .params import Params
from .client import Client, new_client
from .server import Server, new_server
from .errors import LspError, ConnectionLost, ConnectionClosed, ConnectTimeout

__all__ = [
    "Message", "MsgType", "new_connect", "new_data", "new_ack",
    "int2checksum", "bytearray2checksum", "make_checksum",
    "Params", "Client", "new_client", "Server", "new_server",
    "LspError", "ConnectionLost", "ConnectionClosed", "ConnectTimeout",
]
