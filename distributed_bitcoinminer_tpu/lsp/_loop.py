"""Shared background event loop for the synchronous (Go-style) API facade.

All LSP endpoints created through the sync API run on one daemon-thread
asyncio loop; blocking calls bridge in with ``run_coroutine_threadsafe``.
"""

from __future__ import annotations

import asyncio
import threading
from typing import Awaitable, TypeVar

T = TypeVar("T")

_lock = threading.Lock()
_loop: asyncio.AbstractEventLoop | None = None


def get_loop() -> asyncio.AbstractEventLoop:
    global _loop
    with _lock:
        if _loop is None or _loop.is_closed():
            loop = asyncio.new_event_loop()
            thread = threading.Thread(target=loop.run_forever,
                                      name="lsp-event-loop", daemon=True)
            thread.start()
            _loop = loop
        return _loop


def run_sync(coro: Awaitable[T], timeout: float | None = None) -> T:
    return asyncio.run_coroutine_threadsafe(coro, get_loop()).result(timeout)
