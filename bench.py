#!/usr/bin/env python
"""Headline benchmark: nonce-search throughput of one TPU miner.

Prints ONE JSON line:
``{"metric": ..., "value": N, "unit": "nonces/sec", "vs_baseline": N}``.

The reference publishes no numbers (see BASELINE.md); the baseline is the
structural estimate of the Go miner's single-threaded hot loop
(ref: bitcoin/miner/miner.go:53-59 — one stdlib sha256 + string format per
nonce), taken at the generous top of its 10^6-10^7 nonces/s envelope.

Hardening (round-2, per VERDICT):

- The accelerator backend is probed in a *subprocess* with a deadline, so a
  wedged chip can never hang the bench; on probe failure the bench falls
  back to CPU and still prints a real (CPU) measurement with the probe
  error recorded in ``detail``.
- Any exception still produces the one JSON line (value 0, error recorded)
  with exit code 0 rather than a bare traceback.
- The measured range lives in a single digit class with one batch geometry,
  so exactly ONE XLA compilation signature is warmed before timing, and the
  persistent compilation cache is configured so re-runs skip even that.
- Tier selection via ``DBM_COMPUTE`` (auto | jnp | pallas); auto measures
  both device tiers and reports the faster.
- ``DBM_TRACE_XPROF=<dir>`` captures a JAX profiler trace of one timed
  search per tier into ``<dir>/<tier>`` for TensorBoard/XProf (the A2
  hook; ``DBM_TRACE`` itself switches the request-scoped tracing plane,
  utils/trace.py).
"""

from __future__ import annotations

import json
import os
import sys
import time

GO_MINER_BASELINE_NPS = 1.0e7  # upper structural estimate, BASELINE.md
_REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _REPO)

from distributed_bitcoinminer_tpu.utils._env import (   # noqa: E402
    float_env as _float_env, int_env as _int_env, str_env as _str_env)


def _emit(value: float, detail: dict) -> None:
    print(json.dumps({
        "metric": "nonce_search_throughput",
        "value": round(value, 1),
        "unit": "nonces/sec",
        "vs_baseline": round(value / GO_MINER_BASELINE_NPS, 4),
        "detail": detail,
    }), flush=True)


def _measure(searcher, lower: int, upper: int, min_time_s: float,
             timer_cls) -> tuple[float, float, int]:
    """(nonces/sec, seconds, repeats) — repeats the identical search (same
    compile signature) until the timed window passes ``min_time_s``."""
    count = upper - lower + 1
    with timer_cls() as t:
        searcher.search(lower, upper)
    secs, reps = t.seconds, 1
    while secs < min_time_s and reps < 64:
        more = min(64 - reps, max(1, int(min_time_s / max(secs / reps, 1e-9))
                                  - reps))
        with timer_cls() as t:
            for _ in range(more):
                searcher.search(lower, upper)
        secs += t.seconds
        reps += more
    return count * reps / secs, secs, reps


def _measure_overlapped(searcher, lower: int, upper: int, reps: int,
                        timer_cls) -> float:
    """nonces/sec with dispatch/finalize pipelined: every repetition is
    enqueued before the first result is forced, so device compute overlaps
    host readback + merge (SURVEY §7's double-buffering; only searchers
    exposing dispatch/finalize support it)."""
    count = upper - lower + 1
    with timer_cls() as t:
        batches = [searcher.dispatch(lower, upper) for _ in range(reps)]
        for b in batches:
            searcher.finalize(b, lower)
    return count * reps / t.seconds


def _pipeline_probe(data: str, lower: int, count: int, batch: int,
                    reps: int = 3) -> dict:
    """END-TO-END dispatch-pipeline before/after (ISSUE 4): a real
    scheduler + one jnp-tier miner over localhost LSP serve ``reps``
    requests of the EXACT bench geometry (raw ranged Requests — the
    ``submit`` helper pins ``Lower`` to 0, which would drag in every
    small digit class and its compile signatures).

    Three legs, miner-side pipeline being the measured knob:

    - ``on_nps``   — striping (default depth) + pipelined miner;
    - ``off_nps``  — same striping, ``DBM_PIPELINE=0`` serial miner (the
      acceptance comparison: identical chunk plan, overlap removed);
    - ``stock_nps`` — striping AND pipeline off (the pre-ISSUE-4 shape,
      for context).

    Striping is forced deterministic (tiny ``chunk_s`` -> the depth cap
    splits every request into ``depth`` equal chunks) so all legs see an
    identical, small compile-signature set; two warm requests per leg —
    the first on a cold pool (never striped), the second striped — pay
    every XLA signature outside the timed window. Leases are relaxed so
    first-run compiles cannot blow a lease mid-probe and re-issue chunks
    into the timed window.

    Noise discipline: the bench box's background load swings a single
    leg's rate by ±25% — more than the overlap win itself on a 2-core
    container (compute and serialize share the same cores, so only the
    true idle windows — LSP latency, asyncio gaps, result fetch — are
    hideable; the ~1.8x chip gap collapses to single digits here). The
    on and off legs are therefore INTERLEAVED over
    ``DBM_BENCH_PIPELINE_ROUNDS`` rounds (default 6) with the in-round
    order swapped each round (kills order bias), and each side reports
    its MEDIAN round. Median, not best-of: the container's cgroup
    cpu-shares make the noise two-sided (a leg can burst above its fair
    share on an idle host just as easily as lose cycles to a neighbor),
    so max() measures the luckiest burst, and one outlier leg flips the
    sign of the comparison — observed live while building this. The
    per-round samples ride the artifact for auditability.
    """
    import asyncio

    from distributed_bitcoinminer_tpu.apps.miner import MinerWorker
    from distributed_bitcoinminer_tpu.apps.scheduler import Scheduler
    from distributed_bitcoinminer_tpu.bitcoin.message import (Message,
                                                              MsgType,
                                                              new_request)
    from distributed_bitcoinminer_tpu.lsp.client import new_async_client
    from distributed_bitcoinminer_tpu.lsp.params import Params
    from distributed_bitcoinminer_tpu.lsp.server import new_async_server
    from distributed_bitcoinminer_tpu.models import NonceSearcher
    from distributed_bitcoinminer_tpu.utils.config import (CacheParams,
                                                           LeaseParams,
                                                           StripeParams)
    from distributed_bitcoinminer_tpu.utils.metrics import registry

    params = Params(epoch_limit=30, epoch_millis=500, window_size=32,
                    max_backoff_interval=2)
    depth = StripeParams().depth

    async def leg(pipeline: bool, stripe: bool) -> float:
        server = await new_async_server(0, params)
        sched = Scheduler(
            server,
            cache=CacheParams(enabled=False),   # reps repeat the same key
            lease=LeaseParams(grace_s=120.0, floor_s=30.0,
                              queue_alarm_s=0.0),
            stripe=StripeParams(enabled=stripe, chunk_s=0.001,
                                depth=depth))
        sched_task = asyncio.create_task(sched.run())
        worker = MinerWorker(
            f"127.0.0.1:{server.port}", params=params,
            searcher_factory=lambda d, b: NonceSearcher(
                d, batch=batch, tier="jnp"),
            pipeline=pipeline)
        await worker.join()
        worker_task = asyncio.create_task(worker.run())
        client = await new_async_client(f"127.0.0.1:{server.port}", params)
        try:
            async def ask():
                client.write(
                    new_request(data, lower, lower + count - 1).to_json())
                while True:
                    m = Message.from_json(await client.read())
                    if m.type == MsgType.RESULT:
                        return m
            for _ in range(2):
                await asyncio.wait_for(ask(), 600)
            t0 = time.time()
            for _ in range(reps):
                await asyncio.wait_for(ask(), 600)
            return count * reps / (time.time() - t0)
        finally:
            await client.close()
            worker_task.cancel()
            sched_task.cancel()
            await worker.close()
            await server.close()

    rounds = max(1, _int_env("DBM_BENCH_PIPELINE_ROUNDS", 6))
    on_samples, off_samples = [], []
    # Stock legs BRACKET the rounds (one before, one after, median-of-2):
    # a single un-interleaved sample would re-import the exact +-25%
    # noise exposure the interleaving exists to kill.
    stock_samples = [asyncio.run(leg(False, False))]
    snap = {}
    for rnd in range(rounds):
        order = (True, False) if rnd % 2 == 0 else (False, True)
        for pipelined in order:
            (on_samples if pipelined else off_samples).append(
                asyncio.run(leg(pipelined, True)))
            if pipelined and not snap:
                # Occupancy/overlap gauges of the FIRST pipelined leg
                # (each leg's worker overwrites the process-registry
                # gauges).
                snap = registry().snapshot().get("gauges", {})
    stock_samples.append(asyncio.run(leg(False, False)))
    from statistics import median
    on_nps, off_nps = median(on_samples), median(off_samples)
    stock_nps = median(stock_samples)
    return {
        "on_nps": round(on_nps, 1),
        "off_nps": round(off_nps, 1),
        "stock_nps": round(stock_nps, 1),
        "gain": round(on_nps / off_nps - 1, 4),
        "gain_vs_stock": round(on_nps / stock_nps - 1, 4),
        "on_samples": [round(x, 1) for x in on_samples],
        "off_samples": [round(x, 1) for x in off_samples],
        "stock_samples": [round(x, 1) for x in stock_samples],
        "occupancy": snap.get("miner.pipeline_occupancy"),
        "overlap_ratio": snap.get("miner.pipeline_overlap_ratio"),
        "stripe_depth": depth,
        "requests": reps,
        "range": count,
    }


class _StormHarness:
    """Shared scaffolding of the bench's mixed-load storm probes
    (``_qos_probe`` / ``_batch_probe``) — the extraction ISSUE 9
    deliberately deferred to "the next bench-touching PR" (this one).

    Everything the two probes had duplicated lives here once:

    - the probe transport params (tight epochs, wide window);
    - the probe batch floor (>= 2^16: at the bench's 8192 a 2^24 share
      is 2048 Python-level device dispatches whose GIL churn starves
      the scheduler/client event loops for ~second-long stretches; at
      2^16 the compute stays inside XLA with the GIL released, so the
      measured latencies are queueing, not interpreter contention);
    - the DEDICATED client thread pool (never ``asyncio.to_thread``:
      blocked client threads would exhaust the default executor that
      the miners' own ``to_thread`` compute shares — clients holding
      every worker while waiting for results the workers would compute
      is a deadlock, observed live while building the batch probe);
    - the per-leg cluster lifecycle (server + scheduler + N in-process
      jnp-tier miners over real localhost LSP, with the shared
      measurement hardening: result cache OFF because rounds repeat
      identical keys, leases OFF because a first-in-process compile
      can run minutes and a blown lease would drag re-issue state into
      the timed round, striping OFF because EWMA-sized stripe chunks
      recompile mid-leg);
    - the self-scheduled blocking client (own thread + own event loop
      per request: the main loop shares the GIL with the miners'
      jit-dispatch threads and its timers drift ~1s under compute, so
      clients scheduled on it submit LATE and record near-zero FIFO
      waits — client-side stamps are honest only off the compute
      loop; raw ranged Requests on a FRESH conn each, because the
      ``submit`` helper pins Lower to 0 — dragging in every small
      digit class and its compile signatures — and a fresh conn per
      request is exactly the multi-tenant shape);
    - interleaved order-swapped rounds with median aggregation (the
      box's cgroup cpu-shares noise is two-sided: a leg can burst
      above its fair share as easily as lose cycles, so max() measures
      the luckiest burst and one outlier flips the comparison's sign);
    - the ``detail.trace`` summary (ISSUE 10): per-phase medians from
      the stitched miner-side spans of a leg's scheduler, so the probe
      artifact decomposes where a request's wall time went (scheduler
      queue vs miner queue vs dispatch vs force) instead of reporting
      one opaque latency.
    """

    def __init__(self, data: str, lower: int, batch: int,
                 max_clients: int):
        from concurrent.futures import ThreadPoolExecutor

        from distributed_bitcoinminer_tpu.lsp.params import Params
        self.data = data
        self.lower = lower
        self.probe_batch = max(batch, 1 << 16)
        self.params = Params(epoch_limit=30, epoch_millis=500,
                             window_size=32, max_backoff_interval=2)
        self.clients_pool = ThreadPoolExecutor(
            max_workers=max_clients + 2, thread_name_prefix="bench-client")

    def warm_searcher(self):
        """A jnp-tier searcher at the probe geometry for precompiling
        signatures OUTSIDE the legs (the jit cache is process-wide): a
        first-in-process compile can run minutes on this box — inside a
        leg that lands mid-warm-storm and skews it."""
        from distributed_bitcoinminer_tpu.models import NonceSearcher
        return NonceSearcher(self.data, batch=self.probe_batch,
                             tier="jnp")

    def cluster(self, qos, coalesce=None, n_miners=2, miner_kw=None):
        """Async context manager: one leg's scheduler + miner cluster
        (shared hardening defaults; ``qos``/``coalesce`` are the leg's
        measured knobs, ``miner_kw`` extra MinerWorker kwargs)."""
        import asyncio
        import contextlib

        from distributed_bitcoinminer_tpu.apps.miner import MinerWorker
        from distributed_bitcoinminer_tpu.apps.scheduler import Scheduler
        from distributed_bitcoinminer_tpu.lsp.server import new_async_server
        from distributed_bitcoinminer_tpu.models import NonceSearcher
        from distributed_bitcoinminer_tpu.utils.config import (CacheParams,
                                                               LeaseParams,
                                                               StripeParams)
        harness = self

        @contextlib.asynccontextmanager
        async def _cluster():
            server = await new_async_server(0, harness.params)
            sched = Scheduler(
                server,
                cache=CacheParams(enabled=False),
                lease=LeaseParams(enabled=False, queue_alarm_s=0.0),
                stripe=StripeParams(enabled=False),
                qos=qos, coalesce=coalesce)
            sched_task = asyncio.create_task(sched.run())
            hostport = f"127.0.0.1:{server.port}"
            workers, tasks = [], []
            try:
                for _ in range(n_miners):
                    w = MinerWorker(
                        hostport, params=harness.params,
                        searcher_factory=lambda d, b: NonceSearcher(
                            d, batch=harness.probe_batch, tier="jnp"),
                        **(miner_kw or {}))
                    await w.join()
                    tasks.append(asyncio.create_task(w.run()))
                    workers.append(w)
                yield _Cluster(harness, sched, hostport)
            finally:
                for t in tasks:
                    t.cancel()
                for w in workers:
                    await w.close()
                sched_task.cancel()
                await server.close()

        return _cluster()

    def interleaved(self, rounds: int, leg) -> tuple[list, list]:
        """Run ``leg(on: bool)`` over ``rounds`` interleaved rounds with
        the in-round order swapped each round (kills order bias);
        returns ``(on_rounds, off_rounds)`` of the legs' dicts."""
        import asyncio
        on_rounds, off_rounds = [], []
        for rnd in range(max(1, rounds)):
            order = (True, False) if rnd % 2 == 0 else (False, True)
            for on in order:
                (on_rounds if on else off_rounds).append(
                    asyncio.run(leg(on)))
        return on_rounds, off_rounds


class _Cluster:
    """One live probe cluster (yielded by ``_StormHarness.cluster``)."""

    def __init__(self, harness: _StormHarness, sched, hostport: str):
        self.harness = harness
        self.sched = sched
        self.hostport = hostport

    def ask_blocking(self, lo: int, count: int):
        """One raw ranged Request -> Result on its own thread's own
        event loop + fresh conn (see the harness docstring)."""
        import asyncio

        from distributed_bitcoinminer_tpu.bitcoin.message import (
            Message, MsgType, new_request)
        from distributed_bitcoinminer_tpu.lsp.client import new_async_client

        async def go():
            client = await new_async_client(self.hostport,
                                            self.harness.params)
            try:
                client.write(new_request(
                    self.harness.data, lo, lo + count - 1).to_json())
                while True:
                    m = Message.from_json(
                        await asyncio.wait_for(client.read(), 600))
                    if m.type == MsgType.RESULT:
                        return m
            finally:
                await client.close()
        return asyncio.run(go())

    def run_one(self, t0: float, lo: int, count: int,
                delay: float) -> tuple[float, float]:
        """Self-scheduled submit from a common ``t0`` (``time.sleep``,
        not ``asyncio.sleep`` — honest stamps need the wall clock of a
        thread the compute loop cannot drift); returns (start, end)."""
        time.sleep(max(0.0, t0 + delay - time.time()))
        m0 = time.time()
        self.ask_blocking(lo, count)
        return m0, time.time()

    def submit(self, loop, t0: float, lo: int, count: int, delay: float):
        """``run_one`` on the harness's dedicated client pool."""
        return loop.run_in_executor(self.harness.clients_pool,
                                    self.run_one, t0, lo, count, delay)

    def trace_summary(self) -> dict:
        """``detail.trace``: per-phase medians over this leg's stitched
        traces (ISSUE 10) — scheduler queue wait plus every miner-side
        span phase, with span/request counts so a probe whose spans
        went missing is visible as such rather than silently lacking
        keys."""
        from statistics import median

        from distributed_bitcoinminer_tpu.utils.trace import SPAN_PHASES
        sched_queue, phases = [], {}
        traces = self.sched.traces.items()
        for _key, t in traces:
            events = t.to_dict()["events"]
            enq = next((e for e in events if e["event"] == "enqueue"),
                       None)
            disp = next((e for e in events if e["event"] == "dispatch"),
                        None)
            if enq is not None and disp is not None:
                sched_queue.append(disp["t"] - enq["t"])
            for e in events:
                if e["event"] != "miner_span":
                    continue
                for ph in SPAN_PHASES:
                    v = e.get(ph)
                    if isinstance(v, (int, float)):
                        phases.setdefault(ph, []).append(float(v))
        out = {"requests": len(traces),
               "spans": len(next(iter(phases.values()), []))}
        if sched_queue:
            out["sched_queue_s_p50"] = round(median(sched_queue), 6)
        for ph, xs in sorted(phases.items()):
            out[f"miner_{ph}_p50"] = round(median(xs), 6)
        return out


def _qos_probe(data: str, lower: int, batch: int) -> dict:
    """Mixed-load QoS before/after (ISSUE 5): one ELEPHANT plus a train
    of MICE through a real scheduler + two jnp-tier miners over localhost
    LSP, with the fair-share plane off vs on.

    Off leg: the reference one-request-in-flight FIFO — every mouse
    queues behind the elephant's last merge. On leg: the elephant is
    split into ``max_chunks`` equal chunks granted by DRR, so mice
    interleave mid-elephant and their reply latency collapses to ~one
    chunk of queueing; the elephant pays the interleaved mice's compute
    plus grant overhead (the acceptance bound: <= 10% completion-time
    regression at the median).

    Determinism discipline (same spirit as ``_pipeline_probe``):
    ``chunk_s`` is pinned so the ``max_chunks`` cap — not the throughput
    EWMA — sizes the elephant plan (always exactly 8 x 2^22, ~the
    production default of one second of pool work per chunk) while a
    whole mouse fits ONE chunk (2^14): one compile signature each,
    warmed by an untimed storm before the timed rounds, and a mouse
    pays one grant round-trip instead of eight.
    Measurement hardening (client threading, probe batch floor, cache/
    lease/stripe pins, interleaved order-swapped rounds) lives in
    :class:`_StormHarness` — shared with ``_batch_probe``; every
    aggregate is a MEDIAN across rounds, mice p99 additionally pools
    every round's latencies, and ``trace`` carries the per-phase span
    medians (ISSUE 10) of the last ON leg.
    """
    import asyncio
    from statistics import median

    from distributed_bitcoinminer_tpu.utils.config import QosParams

    elephant_count = 1 << 25        # ~1-2s of pool work on the jnp tier
    mouse_count = 1 << 14
    n_mice = 4
    h = _StormHarness(data, lower, batch, max_clients=n_mice + 1)

    def qos_params(enabled: bool) -> QosParams:
        # chunk_s is picked so pool_rate * chunk_s lands in
        # [mouse_count, elephant_count / max_chunks] across ±10x rate
        # drift (pool EWMA ~5-15M nps on this box): the MAX_CHUNKS cap —
        # not the EWMA — then sizes the elephant plan (8 x 2^22, one
        # signature) while a whole mouse fits ONE chunk (2^14, also one
        # signature — and one grant round-trip, not eight).
        return QosParams(enabled=enabled, wholesale_s=0.3, chunk_s=0.03,
                         max_chunks=8, depth=2)

    async def leg(qos_on: bool) -> dict:
        async with h.cluster(qos=qos_params(qos_on)) as cl:
            async def storm():
                t0 = time.time()
                loop = asyncio.get_running_loop()
                tasks = [cl.submit(loop, t0, lower, elephant_count, 0.0)]
                for i in range(n_mice):
                    # The elephant holds the pool before the mice land.
                    tasks.append(cl.submit(loop, t0, lower, mouse_count,
                                           0.2 + 0.05 * i))
                e0, e1 = await tasks[0]
                mice = await asyncio.gather(*tasks[1:])
                return e1 - e0, sorted(e - s for s, e in mice)

            # TWO warm storms (untimed). The first runs on a COLD pool —
            # everything dispatches wholesale by design (reference
            # parity), warming the wholesale split signatures and
            # seeding the throughput EWMA. The second runs warm, so the
            # on-leg's elephant/mice actually take the CHUNKED path and
            # pay the 2^22-chunk and 2^14-chunk signatures outside the
            # timed window.
            await storm()
            await storm()
            elephant_s, mice_lat = await storm()
            return {"elephant_s": elephant_s, "mice": mice_lat,
                    "qos_grants": cl.sched.stats["qos_grants"],
                    "trace": cl.trace_summary()}

    # Precompile every signature a leg can hit OUTSIDE the legs (the
    # jit cache is process-wide, same idiom as test_pipeline's jnp
    # warm): a first-in-process compile can run minutes on this box —
    # inside a leg that lands mid-warm-storm and skews it.
    warm = h.warm_searcher()
    for span in (elephant_count // 2,      # wholesale share, 2 miners
                 elephant_count // 8,      # QoS elephant chunk (cap 8)
                 mouse_count,              # QoS mouse chunk (whole mouse)
                 mouse_count // 2):        # wholesale mouse share
        warm.search(lower, lower + span)

    rounds = max(1, _int_env("DBM_BENCH_QOS_ROUNDS", 3))
    on_rounds, off_rounds = h.interleaved(rounds, leg)

    def pool(legs):
        return sorted(x for r in legs for x in r["mice"])

    def pct(xs, q):
        return xs[min(len(xs) - 1, int(q * len(xs)))] if xs else None

    on_mice, off_mice = pool(on_rounds), pool(off_rounds)
    on_eleph = median(r["elephant_s"] for r in on_rounds)
    off_eleph = median(r["elephant_s"] for r in off_rounds)
    return {
        "elephant_range": elephant_count,
        "mouse_range": mouse_count,
        "mice_per_round": n_mice,
        "rounds": rounds,
        "on": {
            "mice_p50_s": round(median(on_mice), 4),
            "mice_p99_s": round(pct(on_mice, 0.99), 4),
            "elephant_s": round(on_eleph, 4),
            "qos_grants": on_rounds[0]["qos_grants"],
        },
        "off": {
            "mice_p50_s": round(median(off_mice), 4),
            "mice_p99_s": round(pct(off_mice, 0.99), 4),
            "elephant_s": round(off_eleph, 4),
        },
        # The two acceptance numbers: mice latency improvement and the
        # elephant's completion-time cost, both at the median.
        "mice_p50_speedup": round(median(off_mice) / median(on_mice), 3),
        "mice_p99_speedup": round(pct(off_mice, 0.99) / pct(on_mice, 0.99),
                                  3),
        "elephant_regression": round(on_eleph / off_eleph - 1, 4),
        "on_samples": [[round(x, 4) for x in r["mice"]] for r in on_rounds],
        "off_samples": [[round(x, 4) for x in r["mice"]]
                        for r in off_rounds],
        "elephant_samples": {
            "on": [round(r["elephant_s"], 3) for r in on_rounds],
            "off": [round(r["elephant_s"], 3) for r in off_rounds]},
        # Per-phase span medians (ISSUE 10) of the last ON leg: where a
        # request's wall time actually went, end to end.
        "trace": on_rounds[-1]["trace"],
    }


def _batch_probe(data: str, lower: int, batch: int) -> dict:
    """Continuous-batching before/after (ISSUE 9): mice requests/s and
    device dispatches-per-mouse at fixed elephant goodput, coalescing
    off vs on, through a real scheduler + two jnp-tier miners over
    localhost LSP.

    Both legs run the QoS plane (the coalescing window rides the QoS
    pump); the measured knob is ``DBM_COALESCE`` — scheduler window +
    miner-side batched dispatch together. The elephant is chunked by
    the ``max_chunks`` cap (the ``_qos_probe`` one-signature
    discipline) into 32 x 2^20 — ~0.1s of pool work each, so a granted
    mice window waits a tenth of a second behind an elephant chunk,
    not half of one. 16 mice of 2^14 land near-simultaneously while it
    grinds, so they BACKLOG behind the saturated pool and a freed slot
    batches the queue through one coalescing window — the traffic
    shape the plane exists for (a mouse trickle coalesces less; that
    is by design, not a measurement artifact).

    What this box can and cannot show: dispatches-per-mouse is the
    STRUCTURAL result (the launch count collapse is deterministic);
    mice requests/s on a 2-core CPU container is bounded by the FIXED
    per-request cost — LSP serialize, scheduler merge, client reply,
    all GIL-serialized — which coalescing deliberately does not touch
    (the wire contract stays per-request), while the per-launch
    dispatch+force it does amortize costs microseconds on CPU vs the
    ~65 ms/force the axon tunnel charges a real chip (the
    finalize-blocked 229M vs 420M dispatch-rate gap, PR 4). Expect the
    rate gain here to sit inside the box's noise envelope, and read
    the chip-side ROADMAP follow-up for the real mice-rate
    measurement — the same CPU-bounded/chip-target verdict shape PR 4
    recorded for the dispatch pipeline itself. A closed-loop mice
    variant was tried and REJECTED while building this: per-tenant
    serial trains couple each mouse's latency to its window's queueing
    behind elephant chunks, so it measures the batching
    latency/throughput tradeoff (adverse on a compute-cheap box), not
    launch amortization.

    Dispatches-per-mouse: each leg first times the elephant ALONE and
    reads the ``model.device_launches`` delta (its launch count is
    deterministic: 32 chunks x one pow2 sub each), then the mixed storm;
    mice launches = mixed delta - elephant-alone delta, divided by the
    mice count. The miners are in-process, so the process registry sees
    every launch. Measurement hardening (per-client threads with
    self-scheduled submits, the dedicated client pool, probe batch >=
    2^16, leases + striping + cache pinned off, interleaved
    order-swapped rounds) lives in :class:`_StormHarness` — shared with
    ``_qos_probe``; two untimed storms warm the signatures per leg,
    rounds come from ``DBM_BENCH_BATCH_ROUNDS`` (default 3) and every
    aggregate is a median. ``trace`` carries the per-phase span medians
    (ISSUE 10) of the last ON leg — the coalesced path's dispatch/force
    amortization, visible per request.
    """
    import asyncio
    from statistics import median

    from distributed_bitcoinminer_tpu.utils.config import (CoalesceParams,
                                                           QosParams)
    from distributed_bitcoinminer_tpu.utils.metrics import registry

    elephant_count = 1 << 25
    mouse_count = 1 << 14
    n_mice = 16
    lanes = 8
    launches = registry().counter("model.device_launches")
    h = _StormHarness(data, lower, batch, max_clients=n_mice + 1)

    async def leg(coalesce_on: bool) -> dict:
        # Deterministic chunk plan (the _qos_probe discipline): the
        # max_chunks cap (not the EWMA) sizes the elephant at 32 x 2^20
        # — one signature, ~0.1s of pool work each, so a mice window
        # granted behind one elephant chunk waits a tenth of a second,
        # not half of one. The explicit max_nonces bound (2^16) keeps
        # elephant chunks OUT of the windows deterministically (2^20
        # chunks would pass the default absolute bound and could join
        # mice windows, muddying both legs).
        async with h.cluster(
                qos=QosParams(enabled=True, wholesale_s=0.3, chunk_s=0.03,
                              max_chunks=32, depth=2),
                coalesce=CoalesceParams(enabled=coalesce_on, lanes=lanes,
                                        max_nonces=1 << 16),
                miner_kw=dict(coalesce=coalesce_on, coalesce_lanes=lanes,
                              coalesce_max=1 << 16,
                              # Local queue deeper than a full window, or
                              # the drain races the reader and splits
                              # windows.
                              pipeline_depth=2 * lanes)) as cl:
            async def storm(with_mice: bool):
                t0 = time.time()
                loop = asyncio.get_running_loop()
                tasks = [cl.submit(loop, t0, lower, elephant_count, 0.0)]
                if with_mice:
                    # One simultaneous wave: the mice must BACKLOG
                    # behind the elephant-saturated pool for a freed
                    # slot to batch them (the coalescing shape); a
                    # staggered wave leaks early mice into solo grants
                    # and under-measures the structural launch collapse.
                    for i in range(n_mice):
                        tasks.append(cl.submit(
                            loop, t0, lower + i * mouse_count,
                            mouse_count, 0.2))
                e0, e1 = await tasks[0]
                done = await asyncio.gather(*tasks[1:])
                mice_window = (max(e for _s, e in done)
                               - min(s for s, _e in done)) if done else 0.0
                return e1 - e0, mice_window

            # Two untimed warm storms (cold-pool wholesale signatures +
            # EWMA seeding, then the chunked/coalesced signatures).
            await storm(True)
            await storm(True)
            before = launches.value
            elephant_solo_s, _ = await storm(False)
            elephant_launches = launches.value - before
            before = launches.value
            elephant_s, mice_window = await storm(True)
            mice_launches = launches.value - before - elephant_launches
            return {
                "elephant_s": elephant_s,
                "elephant_solo_s": elephant_solo_s,
                "mice_window_s": mice_window,
                "mice_per_s": n_mice / mice_window,
                "dispatches_per_mouse": mice_launches / n_mice,
                "window_grants": cl.sched.stats["qos_window_grants"],
                "trace": cl.trace_summary(),
            }

    # Precompile outside the legs (process-wide jit cache): wholesale
    # shares, QoS chunks, and the coalesced pow2 row buckets a mice
    # wave can produce.
    warm = h.warm_searcher()
    for span in (elephant_count // 2, elephant_count // 32,
                 mouse_count, mouse_count // 2):
        warm.search(lower, lower + span)
    entries = [(warm, lower + i * mouse_count,
                lower + (i + 1) * mouse_count - 1) for i in range(lanes)]
    for width in (2, 3, 5, 8):       # pow2 buckets 2/4/8 + odd padding
        warm.finalize_batch(warm.dispatch_batch(entries[:width]))

    rounds = max(1, _int_env("DBM_BENCH_BATCH_ROUNDS", 3))
    on_rounds, off_rounds = h.interleaved(rounds, leg)

    def med(legs, key):
        return median(r[key] for r in legs)

    on_dpm = med(on_rounds, "dispatches_per_mouse")
    off_dpm = med(off_rounds, "dispatches_per_mouse")
    on_rps, off_rps = med(on_rounds, "mice_per_s"), med(off_rounds,
                                                       "mice_per_s")
    on_eleph, off_eleph = med(on_rounds, "elephant_s"), med(off_rounds,
                                                            "elephant_s")
    return {
        "elephant_range": elephant_count,
        "mouse_range": mouse_count,
        "mice_per_round": n_mice,
        "coalesce_lanes": lanes,
        "rounds": rounds,
        "on": {
            "dispatches_per_mouse": round(on_dpm, 3),
            "mice_per_s": round(on_rps, 2),
            "elephant_s": round(on_eleph, 3),
            "window_grants": on_rounds[0]["window_grants"],
        },
        "off": {
            "dispatches_per_mouse": round(off_dpm, 3),
            "mice_per_s": round(off_rps, 2),
            "elephant_s": round(off_eleph, 3),
        },
        # The three acceptance numbers: launch amortization, mice
        # throughput, and the elephant's completion cost.
        "dispatch_reduction": round(off_dpm / on_dpm, 2) if on_dpm
        else None,
        "mice_rate_gain": round(on_rps / off_rps - 1, 4),
        "elephant_regression": round(on_eleph / off_eleph - 1, 4),
        "on_samples": [
            {k: round(r[k], 4) for k in
             ("dispatches_per_mouse", "mice_per_s", "elephant_s")}
            for r in on_rounds],
        "off_samples": [
            {k: round(r[k], 4) for k in
             ("dispatches_per_mouse", "mice_per_s", "elephant_s")}
            for r in off_rounds],
        # Per-phase span medians (ISSUE 10) of the last ON leg.
        "trace": on_rounds[-1]["trace"],
    }


def _load_probe() -> dict:
    """Control-plane load curve (ISSUE 11): tenants vs p50/p99/shed-rate
    for 1 vs N in-process scheduler replicas, on the socket-free detnet
    transport with instant miners (``apps/loadharness.py`` — compute is
    removed so the CONTROL PLANE is the only thing measured).

    Legs are interleaved order-swapped (1-replica then N, order
    flipped each round) and median-aggregated, the repo's storm-probe
    noise discipline; queue capacity is split across replicas so the
    1-vs-N comparison runs at equal total admission capacity (equal
    shed rate by construction). The top tenant count additionally runs
    a DE-MELT knob comparison — ``DBM_RECV_BATCH=1`` +
    ``DBM_TRACE_SAMPLE=1.0`` (the gated de-melts off, i.e. stock recv
    and full per-request trace allocation) vs the tuned settings — so
    the artifact carries before/after evidence for the knob-gated part
    of the ISSUE 11 de-melt (the structural part — indexed queues,
    backlogged-only DRR ring, hoisted pump bounds, O(1) pump no-op
    exits — is knobless and in both legs; the session's profile put
    the pre-fix shape at ~4.6x slower at 2k tenants).

    ``DBM_BENCH_LOAD=0`` skips; ``DBM_BENCH_LOAD_TENANTS`` (comma list,
    default "500,2000") sets the curve points — the checked-in
    BENCH_r06 artifact was generated at "500,2000,10000" — and
    ``DBM_BENCH_LOAD_ROUNDS`` (default 2) the rounds per point.
    """
    from distributed_bitcoinminer_tpu.apps.loadharness import (
        load_curve, run_load, run_load_procs)

    points = []
    for part in _str_env("DBM_BENCH_LOAD_TENANTS", "500,2000").split(","):
        part = part.strip()
        if part.isdigit() and int(part) > 0:
            points.append(int(part))
    points = points or [500, 2000]
    rounds = max(1, _int_env("DBM_BENCH_LOAD_ROUNDS", 2))
    curve = load_curve(points, replica_counts=(1, 4), rounds=rounds,
                       max_queued=4 * max(points))
    top = max(points)
    knob_stock = run_load(tenants=top, replicas=1, recv_batch=1,
                          trace_sample=1.0, max_queued=4 * top)
    tuned = run_load(tenants=top, replicas=1, recv_batch=64,
                     trace_sample=0.01, max_queued=4 * top)
    # Lazy-DRR A/B (ISSUE 12, DBM_QOS_LAZY): the stock candidate walk
    # vs the lazy ring walk at the top tenant count, single replica —
    # the per-completion heads scan is the N=1 melt being closed.
    lazy_off = run_load(tenants=top, replicas=1, qos_lazy=False,
                        max_queued=4 * top)
    lazy_on = run_load(tenants=top, replicas=1, qos_lazy=True,
                       max_queued=4 * top)
    # In-process vs MULTI-PROCESS replicas at equal tenant count
    # (ISSUE 12; real sockets + real processes put a floor on this leg,
    # so it runs at a bounded tenant count). DBM_BENCH_LOAD_PROCS=0
    # skips it.
    procs_cmp = None
    if _str_env("DBM_BENCH_LOAD_PROCS", "1") != "0":
        pt = min(500, top)
        inproc = run_load(tenants=pt, replicas=2, miners=4,
                          max_queued=4 * pt)
        procs = run_load_procs(tenants=pt, replicas=2, miners=4)
        keys = ("makespan_s", "admitted_per_s", "p50_s", "p99_s",
                "cpu_s_per_request", "shed_rate")
        procs_cmp = {"tenants": pt,
                     "inprocess_r2": {k: inproc[k] for k in keys},
                     "procs_r2": {k: procs[k] for k in keys}}
    return {
        "points": curve["points"],
        "rounds": rounds,
        "demelt": {
            "tenants": top,
            "knobs_stock": {k: knob_stock[k] for k in
                            ("makespan_s", "p50_s", "p99_s",
                             "cpu_s_per_request")},
            "tuned": {k: tuned[k] for k in
                      ("makespan_s", "p50_s", "p99_s",
                       "cpu_s_per_request")},
            "lazy_off": {k: lazy_off[k] for k in
                         ("makespan_s", "p50_s", "p99_s",
                          "cpu_s_per_request")},
            "lazy_on": {k: lazy_on[k] for k in
                        ("makespan_s", "p50_s", "p99_s",
                         "cpu_s_per_request")},
        },
        "procs": procs_cmp,
        "samples": [
            {k: leg.get(k) for k in
             ("tenants", "replicas", "makespan_s", "admitted_per_s",
              "p50_s", "p99_s", "shed_rate", "cpu_s_per_request")}
            for leg in curve["samples"]],
    }


def _rollup_probe() -> dict:
    """Rollup-plane overhead A/B (ISSUE 18, ``detail.rollup``): the
    multi-process loadharness leg with the cluster rollup plane ON vs
    OFF (``DBM_ROLLUP`` pinned in the children's env), interleaved
    order-swapped and median-aggregated — publish is one registry
    snapshot + one small atomic file write per beat per process, and
    the acceptance bar is that the A/B stays within storm noise. Plus
    the micro costs: median ``publish()`` and ``aggregate()`` wall time
    over a synthetic 4-source state directory, so the per-beat price is
    measured directly rather than inferred from the storm.

    ``DBM_BENCH_ROLLUP=0`` skips; ``DBM_BENCH_ROLLUP_ROUNDS`` (default
    2) sets the A/B rounds.
    """
    import shutil
    import tempfile
    from statistics import median

    from distributed_bitcoinminer_tpu.apps.loadharness import (
        run_load_procs)
    from distributed_bitcoinminer_tpu.apps.rollup import (
        RollupPublisher, aggregate)
    from distributed_bitcoinminer_tpu.utils.metrics import Registry

    rounds = max(1, _int_env("DBM_BENCH_ROLLUP_ROUNDS", 2))
    keys = ("makespan_s", "admitted_per_s", "p99_s",
            "cpu_s_per_request", "shed_rate")
    legs: dict = {"on": [], "off": []}
    for rnd in range(rounds):
        order = ("on", "off") if rnd % 2 == 0 else ("off", "on")
        for name in order:
            leg = run_load_procs(tenants=150, replicas=2, miners=2,
                                 rollup=(name == "on"), timeout_s=120.0)
            legs[name].append(leg)
    out = {"rounds": rounds, "tenants": 150}
    for name in ("on", "off"):
        out[name] = {k: (round(median(v), 6) if v else None)
                     for k in keys
                     for v in [[leg[k] for leg in legs[name]
                                if leg.get(k) is not None]]}
    if out["on"]["makespan_s"] and out["off"]["makespan_s"]:
        out["makespan_ratio"] = round(
            out["on"]["makespan_s"] / out["off"]["makespan_s"], 4)
    # Micro: direct per-call costs on a synthetic 4-source directory.
    d = tempfile.mkdtemp(prefix="dbm_bench_rollup_")
    try:
        pubs = []
        for rid in range(4):
            reg = Registry()
            for i in range(40):
                reg.counter(f"sched.c{i}").inc(i)
            reg.histogram("sched.queue_wait_s").observe(0.01)
            pubs.append(RollupPublisher(d, "replica", rid, f"i{rid}",
                                        registry=reg))
        pub_times, agg_times = [], []
        for _ in range(50):
            t0 = time.perf_counter()
            pubs[0].publish()
            pub_times.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            aggregate(d)
            agg_times.append(time.perf_counter() - t0)
        out["publish_ms"] = round(median(pub_times) * 1e3, 4)
        out["aggregate_ms"] = round(median(agg_times) * 1e3, 4)
    finally:
        shutil.rmtree(d, ignore_errors=True)
    return out


def _replay_probe() -> dict:
    """Workload capture→replay fidelity (ISSUE 15, ``detail.replay``):
    capture a synthesized uniform storm on the detnet harness
    (``run_load`` with the capture plane armed), re-drive the capture
    through :func:`~...apps.loadharness.run_replay`, and embed the
    side-by-side report — the capture's own admitted/s, shed rate,
    p50/p99 and per-phase span medians next to each replay round's,
    plus the fidelity verdict (``within`` = the replay reproduced the
    shape inside the stated bounds). Replay rounds are
    median-aggregated on the fidelity ratios; the capture leg runs
    once (it IS the artifact under test).

    ``DBM_BENCH_REPLAY=0`` skips; ``DBM_BENCH_REPLAY_ROUNDS`` (default
    2) sets the replay rounds.
    """
    import os
    import tempfile
    from statistics import median

    from distributed_bitcoinminer_tpu.apps.loadharness import (
        run_load, run_replay)

    rounds = max(1, _int_env("DBM_BENCH_REPLAY_ROUNDS", 2))
    fd, path = tempfile.mkstemp(prefix="dbm_bench_cap_",
                                suffix=".jsonl")
    os.close(fd)
    try:
        cap_leg = run_load(tenants=400, replicas=1, miners=4,
                           req_nonces=256, capture_path=path,
                           timeout_s=120.0)
        reps = [run_replay(path, timeout_s=120.0)
                for _ in range(rounds)]
    finally:
        for suffix in ("", ".1"):
            try:
                os.unlink(path + suffix)
            except OSError:
                pass
    keys = ("admitted_ratio", "p99_ratio", "shed_delta")
    med = {}
    for key in keys:
        vals = [r["fidelity"][key] for r in reps
                if r.get("fidelity", {}).get(key) is not None]
        med[key] = round(median(vals), 4) if vals else None
    out = {
        "rounds": rounds,
        "capture_leg": {k: cap_leg.get(k) for k in
                        ("requests", "completed", "shed_rate",
                         "admitted_per_s", "p50_s", "p99_s")},
        "capture": reps[-1]["capture"],
        "replay": {k: reps[-1].get(k) for k in
                   ("requests", "completed", "shed_rate",
                    "admitted_per_s", "p50_s", "p99_s", "trace")},
        "fidelity_median": med,
        # A timed-out round can still carry a violation-free fidelity
        # dict (hung tenants are not sheds); it must not read as a
        # healthy round trip (code review).
        "within": all(r["fidelity"]["within"] and not r.get("timed_out")
                      for r in reps),
        "samples": [dict(r["fidelity"],
                         **({"timed_out": True} if r.get("timed_out")
                            else {})) for r in reps],
    }
    return out


def _adapt_probe() -> dict:
    """Self-tuning control plane A/B (ISSUE 13, ``detail.adapt``): the
    three adversarial load-harness workloads — mice stampede, tenant
    churn storm, elephant convoy (``apps/loadharness.WORKLOADS``) —
    each run with the STATIC knob defaults every deployment would ship
    vs the ``DBM_ADAPT`` setpoint controllers, on the socket-free
    detnet transport with RATE-LIMITED fake miners (known service
    capacity; the control plane and its controllers are the only
    things measured). Legs are interleaved order-swapped per round and
    median-aggregated, the repo's storm-probe noise discipline.

    Acceptance shape (ISSUE 13): adaptive beats static on >= 2 of the
    3 workloads (p99 at equal admitted/s — congestion admission trades
    a bounded shed for queue-age control — or admitted/s at equal
    shed), is within noise on the rest, and the elephant-convoy
    completion regression bound (makespan_ratio <= 1.10) holds.

    ``DBM_BENCH_ADAPT=0`` skips; ``DBM_BENCH_ADAPT_ROUNDS`` (default
    3) sets the rounds per workload.
    """
    from distributed_bitcoinminer_tpu.apps.loadharness import \
        adversarial_ab

    rounds = max(1, _int_env("DBM_BENCH_ADAPT_ROUNDS", 3))
    return adversarial_ab(rounds=rounds)


def _mesh_mixed_pool() -> dict:
    """Heterogeneous-pool storm (ISSUE 14, ``detail.mesh.mixed_pool``):
    one 100x rate-skewed "mesh" miner (its EWMA seeded by the rate-hint
    JOIN, never pinned) next to two host-tier miners under the REAL
    scheduler on detnet; a chunked elephant plus mice drive grants
    across the skew. Records per-miner GRANT SHARE (nonces written)
    against the final rate-EWMA ratio — the acceptance rule is share
    tracking the EWMA ratio within 25% for the dominant tier, with no
    tier-aware placement code anywhere (the DRR/capacity planes do it).
    """
    import asyncio

    from distributed_bitcoinminer_tpu.apps.scheduler import Scheduler
    from distributed_bitcoinminer_tpu.bitcoin.message import (
        Message, MsgType, new_join, new_request)
    from distributed_bitcoinminer_tpu.bitcoin.message import new_result
    from distributed_bitcoinminer_tpu.lspnet.detnet import DetServer
    from distributed_bitcoinminer_tpu.utils.config import (
        AdaptParams, CoalesceParams, LeaseParams, QosParams,
        StripeParams, VerifyParams)

    RATES = {"mesh": 200_000.0, "host_a": 2_000.0, "host_b": 2_000.0}
    ELEPHANT = 150_000
    granted: dict = {}

    async def run() -> dict:
        server = DetServer()
        sched = Scheduler(
            server,
            lease=LeaseParams(grace_s=5.0, floor_s=2.0, tick_s=0.1,
                              queue_alarm_s=30.0),
            qos=QosParams(enabled=True, chunk_s=0.05, max_chunks=256,
                          depth=2, wholesale_s=0.2),
            stripe=StripeParams(enabled=False),
            coalesce=CoalesceParams(enabled=False),
            adapt=AdaptParams(enabled=False),
            # The miners below answer with deterministic non-oracle
            # hashes (the probe measures placement, not merges), which
            # the claim check would reject.
            verify=VerifyParams(enabled=False))
        stask = asyncio.create_task(sched.run())
        miner_tasks = []

        async def miner(name: str, rate: float, hint: float) -> None:
            chan = server.connect()
            chan.write(new_join(rate=int(hint)).to_json())
            try:
                while True:
                    msg = Message.from_json(await chan.read())
                    if msg.type != MsgType.REQUEST:
                        continue
                    size = msg.upper - msg.lower + 1
                    granted[name] = granted.get(name, 0) + size
                    await asyncio.sleep(size / rate)
                    # Deterministic non-oracle hash (loadharness idiom):
                    # the probe measures PLACEMENT, not merges.
                    chan.write(new_result(
                        (1 << 50) + msg.lower, msg.lower).to_json())
            except Exception:   # noqa: BLE001 — conn closed at teardown
                return

        # The wide miner announces itself via the rate-hint JOIN; the
        # host tier warms through the pinned pool rate below.
        miner_tasks.append(asyncio.create_task(
            miner("mesh", RATES["mesh"], RATES["mesh"])))
        for name in ("host_a", "host_b"):
            miner_tasks.append(asyncio.create_task(
                miner(name, RATES[name], 0)))
        for _ in range(200):
            if len(sched.miners) == 3:
                break
            await asyncio.sleep(0.01)
        # Host tier warmed to its measured rate, pool pinned at the
        # majority tier; the mesh miner's EWMA stays on its JOIN hint.
        sched.miner_plane.pin_rates(RATES["host_a"])

        async def client(data: str, upper: int) -> None:
            chan = server.connect()
            chan.write(new_request(data, 0, upper).to_json())
            while True:
                msg = Message.from_json(await chan.read())
                if msg.type == MsgType.RESULT:
                    await chan.close()
                    return

        jobs = [asyncio.create_task(client("mesh elephant",
                                           ELEPHANT - 1))]
        for j in range(4):
            jobs.append(asyncio.create_task(
                client(f"mesh mouse {j}", 499)))
        await asyncio.wait_for(asyncio.gather(*jobs), 120)
        ewmas = {}
        for m in sched.miners:
            ewmas[m.conn_id] = m.rate_ewma or 0.0
        for t in miner_tasks:
            t.cancel()
        stask.cancel()
        total = sum(granted.values()) or 1
        rate_total = sum(RATES.values())
        rows = {}
        for name, rate in RATES.items():
            share = granted.get(name, 0) / total
            expect = rate / rate_total
            rows[name] = {
                "rate_nps": rate,
                "granted_nonces": granted.get(name, 0),
                "grant_share": round(share, 4),
                "rate_share": round(expect, 4),
                "tracking_error": round(abs(share - expect) / expect, 4)
                if expect else None,
            }
        return {
            "elephant_nonces": ELEPHANT,
            "tiers": rows,
            "leases_blown": sched.stats["leases_blown"],
            # The acceptance gate: the wide tier's grant share tracks
            # its rate share within 25%.
            "share_tracks_rate_25pct":
                rows["mesh"]["tracking_error"] is not None
                and rows["mesh"]["tracking_error"] <= 0.25,
        }

    return asyncio.run(run())


def _federation_probe() -> dict:
    """Scheduler-federation probe (ISSUE 20, ``detail.federation``):
    what does the extra tier COST, and does grant placement still track
    capacity through it?

    Two measurements on detnet (sockets + asyncio only, no JAX):

    - ``overhead_ratio``: the same workload (one chunked elephant plus
      mice) against the same 4-child pool, run FLAT (children JOIN the
      scheduler directly) and FEDERATED (2 GatewayMiners x 2 children
      re-sharding through stock inner schedulers), averaged over
      ``DBM_BENCH_FEDERATION_ROUNDS`` rounds. The ratio of makespans is
      the federation tax — the extra hop plus the inner tier's own
      lease/QoS machinery.
    - ``skew``: a >= 10x child-pool skew between the two gateways
      (pool sums 40k vs 4k nonces/s); each gateway's GRANT SHARE
      (nonces its children scanned) is recorded against its advertised
      rate share, with the relative ``tracking_error`` — the parent
      sees only two JOIN rate hints, so this is the whole-cluster
      placement fidelity of the pool-summed Rate extension.

    ``DBM_BENCH_FEDERATION=0`` skips.
    """
    import asyncio
    import time

    from distributed_bitcoinminer_tpu.apps.gateway import GatewayMiner
    from distributed_bitcoinminer_tpu.apps.scheduler import Scheduler
    from distributed_bitcoinminer_tpu.bitcoin.message import (
        Message, MsgType, new_join, new_request, new_result)
    from distributed_bitcoinminer_tpu.lspnet.detnet import DetServer
    from distributed_bitcoinminer_tpu.utils.config import (
        AdaptParams, CoalesceParams, GatewayParams, LeaseParams,
        QosParams, StripeParams, VerifyParams)

    rounds = max(1, int(_str_env("DBM_BENCH_FEDERATION_ROUNDS", "2")
                        or 2))
    ELEPHANT = 60_000
    MICE = 4

    def mk_sched(server) -> Scheduler:
        return Scheduler(
            server,
            lease=LeaseParams(grace_s=5.0, floor_s=2.0, tick_s=0.1,
                              queue_alarm_s=30.0),
            qos=QosParams(enabled=True, chunk_s=0.05, max_chunks=256,
                          depth=2, wholesale_s=0.2),
            stripe=StripeParams(enabled=False),
            coalesce=CoalesceParams(enabled=False),
            adapt=AdaptParams(enabled=False),
            # Deterministic non-oracle hashes below (the probe measures
            # placement and makespan, not merges) — claim checks off.
            verify=VerifyParams(enabled=False))

    async def miner(server, rate: float, granted: dict,
                    key: str) -> None:
        chan = server.connect()
        chan.write(new_join(rate=int(rate)).to_json())
        try:
            while True:
                msg = Message.from_json(await chan.read())
                if msg.type != MsgType.REQUEST:
                    continue
                size = msg.upper - msg.lower + 1
                granted[key] = granted.get(key, 0) + size
                await asyncio.sleep(size / rate)
                chan.write(new_result(
                    (1 << 50) + msg.lower, msg.lower).to_json())
        except Exception:   # noqa: BLE001 — conn closed at teardown
            return

    async def drive(server) -> float:
        """Elephant + mice against ``server``; returns the makespan."""
        async def client(data: str, upper: int) -> None:
            chan = server.connect()
            chan.write(new_request(data, 0, upper).to_json())
            while True:
                msg = Message.from_json(await chan.read())
                if msg.type == MsgType.RESULT:
                    await chan.close()
                    return

        t0 = time.monotonic()
        jobs = [asyncio.create_task(client("fed elephant",
                                           ELEPHANT - 1))]
        for j in range(MICE):
            jobs.append(asyncio.create_task(
                client(f"fed mouse {j}", 499)))
        await asyncio.wait_for(asyncio.gather(*jobs), 120)
        return time.monotonic() - t0

    async def run_flat(rates) -> float:
        server = DetServer()
        sched = mk_sched(server)
        granted: dict = {}
        tasks = [asyncio.create_task(sched.run())]
        tasks += [asyncio.create_task(miner(server, r, granted, "flat"))
                  for r in rates]
        while len(sched.miners) < len(rates):
            await asyncio.sleep(0.01)
        makespan = await drive(server)
        for t in tasks:
            t.cancel()
        return makespan

    async def run_fed(cluster_rates) -> tuple:
        parent_srv = DetServer()
        parent = mk_sched(parent_srv)
        granted: dict = {}
        tasks = [asyncio.create_task(parent.run())]
        gws = []
        for i, rates in enumerate(cluster_rates):
            inner_srv = DetServer()
            inner = mk_sched(inner_srv)
            tasks.append(asyncio.create_task(inner.run()))
            tasks += [asyncio.create_task(
                miner(inner_srv, r, granted, f"gw{i}")) for r in rates]

            async def connect(srv=inner_srv):
                return srv.connect()

            async def connect_parent():
                return parent_srv.connect()

            gw = GatewayMiner(
                connect_parent, connect, [inner],
                params=GatewayParams(enabled=True, hint_s=0.5,
                                     min_pool=len(rates),
                                     orphan_s=10.0),
                poll_s=0.01, name=f"gw{i}")
            gws.append(gw)
            tasks.append(asyncio.create_task(gw.run_forever()))
        while len(parent.miners) < len(cluster_rates):
            await asyncio.sleep(0.01)
        makespan = await drive(parent_srv)
        for t in tasks:
            t.cancel()
        return makespan, granted, gws

    POOL = [10_000.0] * 4
    CLUSTERS = [POOL[:2], POOL[2:]]
    flat_s, fed_s = [], []
    for _ in range(rounds):
        flat_s.append(asyncio.run(run_flat(POOL)))
        fed_s.append(asyncio.run(run_fed(CLUSTERS))[0])
    flat_mean = sum(flat_s) / len(flat_s)
    fed_mean = sum(fed_s) / len(fed_s)

    # The >= 10x skew leg: pool sums 40k vs 4k nonces/s.
    SKEW = [[20_000.0, 20_000.0], [2_000.0, 2_000.0]]
    skew_makespan, skew_granted, gws = asyncio.run(run_fed(SKEW))
    total = sum(skew_granted.values()) or 1
    rate_total = sum(sum(c) for c in SKEW)
    skew_rows = {}
    for i, rates in enumerate(SKEW):
        share = skew_granted.get(f"gw{i}", 0) / total
        expect = sum(rates) / rate_total
        skew_rows[f"gw{i}"] = {
            "pool_rate_nps": sum(rates),
            "granted_nonces": skew_granted.get(f"gw{i}", 0),
            "grant_share": round(share, 4),
            "rate_share": round(expect, 4),
            "tracking_error": round(abs(share - expect) / expect, 4)
            if expect else None,
        }
    return {
        "rounds": rounds,
        "elephant_nonces": ELEPHANT,
        "flat_makespan_s": round(flat_mean, 3),
        "federated_makespan_s": round(fed_mean, 3),
        "overhead_ratio": round(fed_mean / flat_mean, 4)
        if flat_mean else None,
        "skew": {
            "makespan_s": round(skew_makespan, 3),
            "skew_ratio": 10.0,
            "tiers": skew_rows,
            "grants_taken": {g.name: g.grants_taken for g in gws},
            "hint_refreshes": {g.name: g.hint_refreshes for g in gws},
        },
    }


def _mesh_probe() -> dict:
    """Mesh-plane probe (ISSUE 14, ``detail.mesh``) — ALSO the
    ``MULTICHIP_r06.json`` artifact schema (``schema: mesh_scaling_v1``)
    the chip chain records on real devices.

    Per-device-count scaling sweep (1/2/4/8, capped at the available
    device count): nonces/s of the carry-chained whole-mesh span,
    device launches per span, host transfers per span (must be 1 — the
    one-pair-per-span contract), and host-crossing BYTES per span (the
    20-byte carry). On CPU the virtual devices share physical cores, so
    the CPU curve proves overhead/correctness, not speedup — the
    per-core efficiency field is what the chip run populates. Plus the
    heterogeneous mixed-pool storm (:func:`_mesh_mixed_pool`).
    ``DBM_BENCH_MESH=0`` skips.
    """
    import jax

    from distributed_bitcoinminer_tpu.models import MeshNonceSearcher
    from distributed_bitcoinminer_tpu.models.miner_model import \
        _MET_LAUNCHES
    from distributed_bitcoinminer_tpu.parallel import make_mesh

    devices = jax.devices()
    platform = devices[0].platform
    batch = (1 << 12) if platform == "cpu" else (1 << 20)
    data = "bench mesh"
    lower = 102_400_000                 # aligned, single 10^9 block
    span = batch * 64
    sweep = []
    counts = [n for n in (1, 2, 4, 8) if n <= len(devices)]
    for n in counts:
        s = MeshNonceSearcher(data, batch=batch, mesh=make_mesh(n))
        upper = lower + span - 1
        s.search(lower, upper)          # warm: one compile per count
        fetches = [0]
        orig_get = jax.device_get

        def counting_get(x, _f=fetches):
            _f[0] += 1
            return orig_get(x)

        launches0 = _MET_LAUNCHES.value
        jax.device_get = counting_get
        t0 = time.perf_counter()
        reps = 0
        try:
            while time.perf_counter() - t0 < 1.0:
                s.search(lower, upper)
                reps += 1
        finally:
            jax.device_get = orig_get
        secs = time.perf_counter() - t0
        launches_timed = _MET_LAUNCHES.value - launches0
        handle = s.dispatch(lower, upper)
        nbytes = int(getattr(handle, "nbytes", 0))
        s.finalize(handle, lower)
        sweep.append({
            "n_devices": n,
            "nps": round(span * reps / secs, 1),
            "dispatches_per_span": round(launches_timed / reps, 3),
            "host_transfers_per_span": round(fetches[0] / reps, 3),
            "host_bytes_per_span": nbytes,
        })
    base = sweep[0]["nps"] if sweep else 0.0
    for row in sweep:
        row["efficiency_per_core"] = (
            round(row["nps"] / (base * row["n_devices"]), 3)
            if base else None)
    return {
        "schema": "mesh_scaling_v1",
        "platform": platform,
        "devices_available": len(devices),
        "batch": batch,
        "span_nonces": span,
        "sweep": sweep,
        "mixed_pool": _mesh_mixed_pool(),
    }


def _devloop_probe(data: str) -> dict:
    """Device-resident span loop A/B (ISSUE 19, ``detail.devloop``):
    devloop on vs off on the jnp tier, interleaved order-swapped rounds,
    recording nonces/s, device launches per span, host transfers per
    span, and host-crossing BYTES per span for each leg — plus the
    difficulty-mode time-to-first-hit A/B (``DBM_DEVLOOP_UNTIL``) and a
    pallas-interpret counters/parity leg.

    Geometry: RAGGED sub count (767 = nine pow2 terms) at a small
    batch, batch-aligned lower inside one decimal block. That is where
    the devloop's structural win lives on CPU: the stock path's span
    rate is already device-looped per sub, so the on/off delta is the
    per-launch dispatch+force cost times the pow2 term count (9 -> 1
    launches/span) plus the fetch collapse (9 triples -> one 20-byte
    carry). At the headline bench geometry (one pow2-aligned sub) the
    two paths are within noise BY CONSTRUCTION — this probe exists
    because the headline number cannot show the launch amortization.
    The span estimates ~4 ms on this box — 2x the est-seconds mouse
    floor (``_DEVLOOP_MIN_EST_S``), and a silent mid-measurement
    fallback to stock cannot hide: it would surface as the ON leg's
    ``launches_per_span`` rising above 1.

    Timing is PAIRED, not blocked: each round runs one ON span and one
    OFF span back to back (order swapped every round) and the legs
    accumulate their own wall time, so sub-second CPU frequency/
    co-tenant drift cancels instead of landing on whichever leg ran
    second — blocked 1 s legs measured the box's drift envelope
    (±20 %) on this 2-core container, paired spans hold +-4 %.

    The pallas leg runs under interpret on CPU, where timing is
    meaningless — it records the launch/transfer/byte counters and
    bit-parity only (the chip chain's devloop-smoke stage is where the
    pallas rate measurement lives). ``DBM_BENCH_DEVLOOP=0`` skips;
    ``DBM_BENCH_DEVLOOP_PAIRS`` (default 120) sets the paired reps.
    """
    import jax
    from statistics import median

    from distributed_bitcoinminer_tpu.bitcoin.hash import hash_op, scan_min
    from distributed_bitcoinminer_tpu.models import NonceSearcher
    from distributed_bitcoinminer_tpu.models.miner_model import \
        _MET_LAUNCHES

    batch = 64
    nsub = 767                           # 9 pow2 terms: the ragged case
    count = batch * nsub
    lower = ((10_000_000 // batch) + 1) * batch   # aligned, one 10^7 block
    upper = lower + count - 1
    pairs = max(8, _int_env("DBM_BENCH_DEVLOOP_PAIRS", 120))

    def counted(searcher, fn):
        """(fn result, launches, host fetch calls, host bytes) — counts
        ``model.device_launches`` deltas and wraps ``jax.device_get`` to
        tally fetch calls and the bytes they move."""
        fetches, nbytes = [0], [0]
        orig_get = jax.device_get

        def counting_get(x):
            fetches[0] += 1
            got = orig_get(x)
            for leaf in jax.tree_util.tree_leaves(got):
                nbytes[0] += int(getattr(leaf, "nbytes", 0) or 0)
            return got

        launches0 = _MET_LAUNCHES.value
        jax.device_get = counting_get
        try:
            out = fn(searcher)
        finally:
            jax.device_get = orig_get
        return out, _MET_LAUNCHES.value - launches0, fetches[0], nbytes[0]

    knobs = ("DBM_DEVLOOP", "DBM_DEVLOOP_UNTIL", "DBM_DEVLOOP_PALLAS")
    saved = {k: os.environ.get(k) for k in knobs}
    try:
        # One searcher per leg, each warmed under BOTH knob states (the
        # ON searcher warms its stock signatures too, so even an
        # est-floor fallback could never compile mid-measurement). A
        # fresh knob read happens at every dispatch, so toggling the env
        # var re-routes the SAME searcher — separate searchers keep the
        # ON leg's rate EWMA unpolluted by stock spans.
        os.environ.pop("DBM_DEVLOOP_UNTIL", None)
        os.environ.pop("DBM_DEVLOOP_PALLAS", None)
        searchers = {}
        for name, knob in (("on", "1"), ("off", "0")):
            os.environ["DBM_DEVLOOP"] = knob
            s = NonceSearcher(data, batch=batch, tier="jnp")
            s.search(lower, upper)                    # warm
            searchers[name] = s
        os.environ["DBM_DEVLOOP"] = "0"
        searchers["on"].search(lower, upper)          # warm stock sigs too
        acc = {name: {"t": 0.0, "reps": 0, "launches": 0, "fetches": 0,
                      "nbytes": 0, "result": None} for name in ("on",
                                                                "off")}
        for i in range(pairs):
            order = ("on", "off") if i % 2 == 0 else ("off", "on")
            for name in order:
                os.environ["DBM_DEVLOOP"] = "1" if name == "on" else "0"
                a = acc[name]

                def one(s, _a=a):
                    t0 = time.perf_counter()
                    _a["result"] = s.search(lower, upper)
                    return time.perf_counter() - t0

                dt, launches, fetches, nbytes = counted(
                    searchers[name], one)
                a["t"] += dt
                a["reps"] += 1
                a["launches"] += launches
                a["fetches"] += fetches
                a["nbytes"] += nbytes

        jnp_ab = {}
        for name in ("on", "off"):
            a = acc[name]
            jnp_ab[name] = {
                "nps": round(count * a["reps"] / a["t"], 1),
                "launches_per_span": round(a["launches"] / a["reps"], 3),
                "host_transfers_per_span": round(
                    a["fetches"] / a["reps"], 3),
                "host_bytes_per_span": round(a["nbytes"] / a["reps"], 1),
            }
        on_nps = jnp_ab["on"]["nps"]
        off_nps = jnp_ab["off"]["nps"]
        jnp_ab["devloop_speedup"] = (round(on_nps / off_nps, 3)
                                     if off_nps else None)
        jnp_ab["parity"] = (
            tuple(int(v) for v in acc["on"]["result"])
            == tuple(int(v) for v in acc["off"]["result"]))

        # Difficulty-mode TTFH A/B: a target that first qualifies ~1.5%
        # into the span. The devloop's on-device first-hit predicate
        # exits after ~hit/batch sub-windows; the stock path must finish
        # the whole 2^18-lane leading pow2 sub before its host-side
        # check sees the hit. Warmed with target 0 (never hits — the
        # full-scan compile) so the timed call replays the signature.
        os.environ["DBM_DEVLOOP"] = "1"
        hit = lower + 8_000
        target = hash_op(data, hit) + 1
        until_ab = {"hit_offset": hit - lower}
        u_results = {}
        for name, knob in (("on", "1"), ("off", "0")):
            os.environ["DBM_DEVLOOP_UNTIL"] = knob
            s = NonceSearcher(data, batch=batch, tier="jnp")
            s.search_until(lower, upper, 0)           # warm
            times = []
            for _ in range(5):
                t0 = time.perf_counter()
                u_results[name] = s.search_until(lower, upper, target)
                times.append(time.perf_counter() - t0)
            until_ab[name] = {"ttfh_s": round(median(times), 5),
                              "found": bool(u_results[name][2])}
        on_t = until_ab["on"]["ttfh_s"]
        off_t = until_ab["off"]["ttfh_s"]
        until_ab["ttfh_speedup"] = round(off_t / on_t, 3) if on_t else None
        until_ab["parity"] = (
            tuple(int(v) for v in u_results["on"][:2])
            == tuple(int(v) for v in u_results["off"][:2])
            and u_results["on"][2] == u_results["off"][2])

        # Pallas leg, interpret on CPU: tiny geometry (16 grid steps),
        # counters + bit-parity vs the host oracle only.
        os.environ.pop("DBM_DEVLOOP_UNTIL", None)
        p_batch, p_nsub = 128, 15
        p_lower = ((1_000_000 // p_batch) + 1) * p_batch
        p_upper = p_lower + p_batch * p_nsub - 1
        oracle = scan_min(data, p_lower, p_upper)
        pallas_ab = {"batch": p_batch, "nsub": p_nsub}
        for name, knob in (("on", "1"), ("off", "0")):
            os.environ["DBM_DEVLOOP"] = "1" if name == "on" else "0"
            os.environ["DBM_DEVLOOP_PALLAS"] = knob
            s = NonceSearcher(data, batch=p_batch, tier="pallas")
            s.search(p_lower, p_upper)                # warm
            got, launches, fetches, nbytes = counted(
                s, lambda s_: s_.search(p_lower, p_upper))
            pallas_ab[name] = {
                "launches_per_span": launches,
                "host_transfers_per_span": fetches,
                "host_bytes_per_span": nbytes,
                "parity": tuple(int(v) for v in got) == oracle,
            }
        return {
            "schema": "devloop_ab_v1",
            "batch": batch,
            "nsub": nsub,
            "span_nonces": count,
            "pairs": pairs,
            "jnp": jnp_ab,
            "until": until_ab,
            "pallas_interpret": pallas_ab,
        }
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def main() -> int:
    # Transport datapath modes (ISSUE 17) FIRST — both are socket-only
    # measurements with no JAX involved, and the child leg IS the timed
    # window, so neither may pay backend probing or the jax import below.
    if "--transport-child" in sys.argv:
        from distributed_bitcoinminer_tpu.apps.transportbench import (
            echo_storm_child)
        print(json.dumps(echo_storm_child()), flush=True)
        return 0
    if "--transport-only" in sys.argv:
        from distributed_bitcoinminer_tpu.apps.transportbench import (
            standalone_artifact)
        print(json.dumps(standalone_artifact(_REPO)), flush=True)
        return 0

    from distributed_bitcoinminer_tpu.utils.config import probe_backend
    from distributed_bitcoinminer_tpu.utils.metrics import ensure_emitter
    # Metrics plane live during the measurement (DBM_METRICS_INTERVAL_S;
    # 0 disables the emitter — the overhead-comparison baseline). The
    # final registry snapshot is embedded in the artifact either way.
    ensure_emitter()
    init_deadline = _float_env("DBM_BENCH_INIT_TIMEOUT", 300.0)
    if _str_env("DBM_BENCH_PROBE", "1") == "0":
        # Probe opt-out (ISSUE 4 satellite): trust JAX_PLATFORMS as-is —
        # chip-less boxes pin cpu and stop paying the init deadline (and
        # the artifact stops carrying the recurring probe error).
        probe = {"skipped": True}
    else:
        # probe_backend memoizes per process, so the miner workers the
        # pipeline probe spawns below never re-pay the deadline.
        probe = probe_backend(init_deadline, _REPO)
    force_cpu = "error" in probe

    if force_cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    if force_cpu:
        # Config-level force: the image's sitecustomize hooks backend
        # resolution, so the env var alone does not stop jax.devices() from
        # touching the real backend (VERDICT round-1 root cause).
        jax.config.update("jax_platforms", "cpu")
    else:
        # Honor a user-supplied JAX_PLATFORMS even when the accelerator
        # probe succeeds (same sitecustomize-override mechanism).
        from distributed_bitcoinminer_tpu.utils.config import (
            apply_jax_platform_env)
        apply_jax_platform_env()
    # Host-keyed cache: artifacts AOT-compiled on another machine hang or
    # SIGILL when loaded here (see utils/config.host_cache_dir).
    from distributed_bitcoinminer_tpu.utils.config import host_cache_dir
    jax.config.update("jax_compilation_cache_dir", host_cache_dir(_REPO))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    from distributed_bitcoinminer_tpu.bitcoin.hash import scan_min
    from distributed_bitcoinminer_tpu.models import (
        NonceSearcher, ShardedNonceSearcher)
    from distributed_bitcoinminer_tpu.parallel import make_mesh
    from distributed_bitcoinminer_tpu.utils.config import jax_devices_robust
    from distributed_bitcoinminer_tpu.utils.profiling import (Timer,
                                                              device_trace,
                                                              xprof_dir)

    # Same resolution order as the probe child and the miners — a bare
    # jax.devices() here could crash on the exact pin the robust probe
    # just recovered from (code-review r4).
    devices = jax_devices_robust()
    on_accel = devices[0].platform != "cpu"
    batch = (1 << 20) if on_accel else (1 << 13)
    # One digit class, one aligned 10^9 block geometry => ONE compile
    # signature for the whole measurement (VERDICT round-1 weakness 5: the
    # old [0, 2^26) range spanned 8 digit classes = 8 compilations).
    # 2^29 per search: every jit invocation costs ~34 ms of axon-tunnel
    # enqueue regardless of span, so short ranges under-report the kernel
    # (round 3: 2^26 measured 863M/s overlapped where 2^29 measures
    # 1.32G); production miner chunks are larger still. 2^29 is the
    # largest span that stays inside one aligned 10^9 block from this
    # lower bound AND decomposes to a single pow2 sub-dispatch (512
    # batches) = one compile signature; 2^30 would straddle a block
    # boundary and warm ~10 signatures.
    # CPU fallback range: 2^23 — above the native scan's 2^17 MT
    # threshold, so a wedged-chip bench exercises the multithreaded fan
    # path it would actually serve (VERDICT r4), still one 8-digit class
    # (10^7 <= n < 10^8) = one compile signature for the jnp tier.
    lower = 2_000_000_000 if on_accel else 10_000_000
    count = (1 << 29) if on_accel else (1 << 23)
    upper = lower + count - 1
    min_time_s = 1.0 if on_accel else 0.5
    data = "cmu440"
    tier_req = _str_env("DBM_COMPUTE", "auto").lower()

    def build(tier: str, hoist: bool | None = None):
        if tier == "host":
            from distributed_bitcoinminer_tpu.apps.miner import HostSearcher
            return HostSearcher(data)
        if len(devices) > 1:
            return ShardedNonceSearcher(
                data, batch=batch, mesh=make_mesh(len(devices)), tier=tier,
                hoist=hoist)
        return NonceSearcher(data, batch=batch, tier=tier, hoist=hoist)

    def hoist_counters(searcher, lo, hi):
        """Hoist telemetry of the measured range's (single) block plan."""
        plans = list(searcher.plan(lo, hi)) if hasattr(searcher, "plan") \
            else []
        if not plans or plans[0].hoist is None:
            return {"enabled": False}
        h = plans[0].hoist
        return {"enabled": True, "rem": plans[0].rem, "k": plans[0].k,
                "hoisted_rounds": h.hoisted_rounds,
                "schedule_terms_hoisted": h.schedule_terms_hoisted,
                "const_schedule_blocks": sum(h.full_const)}

    if tier_req in ("jnp", "pallas", "host"):
        tiers = [tier_req]
    else:
        tiers = ["jnp", "pallas"]
        if not on_accel:
            # CPU fallback: the native SHA-NI scan is the strongest
            # host-side tier — measure it so a wedged-chip bench still
            # records the best available number. (Skipped without a
            # toolchain: the Python-oracle fallback can never win.)
            from distributed_bitcoinminer_tpu import native
            if native.available():
                tiers.append("host")
    results, errors = {}, {}
    gate_lo, gate_hi = lower, lower + 9_999
    want = scan_min(data, gate_lo, gate_hi)
    for tier in tiers:
        try:
            # The CPU pallas tier runs under the Mosaic interpreter
            # (~60K nonces/s — a correctness tier, not a perf tier): keep
            # its old 2^17 range so the fallback bench stays minutes, not
            # hours; jnp and the native MT host tier get the full range.
            t_upper = upper if (on_accel or tier != "pallas") \
                else lower + (1 << 17) - 1
            searcher = build(tier)
            got = searcher.search(gate_lo, gate_hi)
            assert got == want, f"correctness gate: {got} != {want}"
            t0 = time.time()
            searcher.search(lower, t_upper)  # compile + warm the signature
            warm_s = time.time() - t0
            if xprof_dir(tier):
                # DBM_TRACE_XPROF logdir selection lives inside
                # profiling.xprof_dir/device_trace (ISSUE 10 satellite).
                with device_trace(tier=tier):
                    searcher.search(lower, t_upper)
            rate, secs, reps = _measure(searcher, lower, t_upper, min_time_s,
                                        Timer)
            results[tier] = {"rate": rate, "secs": secs, "reps": reps,
                             "range": t_upper - lower + 1,
                             "warmup_s": round(warm_s, 3)}
            if tier == "jnp":
                # Before/after evidence for the hoist (the BENCH_r*
                # trajectory tracks the win): one cheap re-measure of the
                # same geometry with DBM_HOIST forced off. Isolated like
                # the overlap number — its failure never marks the tier.
                try:
                    plain = build(tier, hoist=False)
                    plain.search(lower, t_upper)   # warm its signature
                    no_rate, _, _ = _measure(plain, lower, t_upper,
                                             min_time_s / 2, Timer)
                    results[tier]["no_hoist_rate"] = round(no_rate, 1)
                except Exception as exc:  # noqa: BLE001
                    results[tier]["no_hoist_error"] = repr(exc)[:200]
            if hasattr(searcher, "dispatch"):
                # Isolated: a failed overlap measurement must not mark a
                # tier whose sequential number already succeeded as failed.
                try:
                    results[tier]["overlapped_rate"] = round(
                        _measure_overlapped(searcher, lower, t_upper,
                                            max(2, reps), Timer), 1)
                except Exception as exc:  # noqa: BLE001
                    results[tier]["overlapped_error"] = repr(exc)[:200]
        except Exception as exc:  # noqa: BLE001 — one tier failing must not
            # kill the other's number; keep the head AND tail of the message
            # so file:line survives truncation (ADVICE r2: the r02 Mosaic
            # error was cut mid-path).
            msg = repr(exc)
            errors[tier] = (msg if len(msg) <= 600
                            else msg[:300] + " ... " + msg[-280:])
    if not results:
        _emit(0.0, {"error": "all tiers failed", "tiers": errors,
                    "probe": probe})
        return 0

    best_tier = max(results, key=lambda t: results[t]["rate"])
    best = results[best_tier]
    # The winning tier's actual measured span — differs from `count` when
    # the capped CPU pallas tier wins (e.g. DBM_COMPUTE=pallas fallback).
    best_upper = lower + best["range"] - 1

    # Difficulty mode on the winning tier: time-to-first-hit at a ~2^-8
    # per-nonce target over the SAME range. With the in-kernel early exit
    # this must not scale with the range — it measures dispatch latency +
    # ~one batch of compute. Isolated: a failure here never touches the
    # headline number. Warm with an unreachable target (full scan) so the
    # timed run reuses the compiled signature.
    until_detail = {}
    try:
        from distributed_bitcoinminer_tpu.bitcoin.hash import scan_until
        u_searcher = build(best_tier)
        target_log2 = 56               # ~2^-8 hit chance per nonce
        target = 1 << target_log2
        u_searcher.search_until(lower, best_upper, 0)  # warm; 0 never hits
        with Timer() as t:
            u_hash, u_nonce, u_found = u_searcher.search_until(
                lower, best_upper, target)
        if u_found:
            # Exactness gate: the host oracle up to the reported hit must
            # agree this is the FIRST qualifying nonce.
            assert scan_until(data, lower, u_nonce, target) == \
                (u_hash, u_nonce, True), "until gate failed"
        until_detail = {"until_ttfh_s": round(t.seconds, 4),
                        "until_found": bool(u_found),
                        "until_target_log2": target_log2}
        # Auditability: a pallas searcher that silently degraded to the
        # jnp until tier must be visible in the recorded JSON, not only
        # in a log line.
        if getattr(u_searcher, "_until_degraded", False):
            until_detail["until_degraded_to_jnp"] = True
    except Exception as exc:  # noqa: BLE001
        until_detail = {"until_error": repr(exc)[:200]}

    # rem-sweep micro-bench (DBM_BENCH_REM_SWEEP=1): the hoist depth is a
    # function of rem = len(prefix) % 64, so sweep message lengths across
    # the word/block-boundary cases and record hoisted vs plain jnp rates
    # at a small fixed geometry. Opt-in: the default artifact is
    # unchanged and the driver's timing budget untouched.
    sweep_detail = {}
    if _str_env("DBM_BENCH_REM_SWEEP", "0") == "1":
        try:
            from distributed_bitcoinminer_tpu.utils.profiling import Timer
            sweep = []
            s_lo, s_count = 1_000_000, 1 << 20   # one 7-digit block, k=7
            for rem in (0, 4, 7, 31, 55, 62):
                s_data = "a" * (rem - 1) if rem >= 1 else "a" * 63
                entry = {"rem": rem}
                for label, hflag in (("hoist", True), ("plain", False)):
                    s = NonceSearcher(s_data, batch=batch, tier="jnp",
                                      hoist=hflag)
                    s.search(s_lo, s_lo + s_count - 1)   # warm
                    r, _, _ = _measure(s, s_lo, s_lo + s_count - 1,
                                       min_time_s / 2, Timer)
                    entry[label] = round(r, 1)
                entry.update(hoist_counters(
                    NonceSearcher(s_data, batch=batch, tier="jnp"),
                    s_lo, s_lo + s_count - 1))
                sweep.append(entry)
            sweep_detail = {"rem_sweep": sweep}
        except Exception as exc:  # noqa: BLE001
            sweep_detail = {"rem_sweep_error": repr(exc)[:200]}

    # Dispatch-pipeline e2e before/after (ISSUE 4): scheduler striping +
    # miner pipeline vs the stock even-split serial loop, through real
    # localhost LSP at the bench geometry. CPU-only (the on-chip 2^29
    # geometry would cost minutes per leg) and isolated like the other
    # auxiliary measurements; DBM_BENCH_PIPELINE=0 skips it.
    pipeline_detail = {}
    if not on_accel and "jnp" in results \
            and _str_env("DBM_BENCH_PIPELINE", "1") != "0":
        try:
            pipeline_detail = {"pipeline": _pipeline_probe(
                data, lower, count, batch)}
        except Exception as exc:  # noqa: BLE001
            pipeline_detail = {"pipeline": {"error": repr(exc)[:300]}}

    # Fair-share QoS mixed-load before/after (ISSUE 5): one elephant + a
    # mice train through real localhost LSP, DBM_QOS off vs on —
    # recording mice p50/p99 reply latency and the elephant's completion
    # time. CPU-only and isolated like the other auxiliary measurements;
    # DBM_BENCH_QOS=0 skips it.
    qos_detail = {}
    if not on_accel and "jnp" in results \
            and _str_env("DBM_BENCH_QOS", "1") != "0":
        try:
            qos_detail = {"qos": _qos_probe(data, lower, batch)}
        except Exception as exc:  # noqa: BLE001
            qos_detail = {"qos": {"error": repr(exc)[:300]}}

    # Continuous-batching before/after (ISSUE 9): mice requests/s and
    # device dispatches-per-mouse at fixed elephant goodput, coalescing
    # off vs on. CPU-only and isolated like the other auxiliary
    # measurements; DBM_BENCH_BATCH=0 skips it.
    batch_detail = {}
    if not on_accel and "jnp" in results \
            and _str_env("DBM_BENCH_BATCH", "1") != "0":
        try:
            batch_detail = {"batch": _batch_probe(data, lower, batch)}
        except Exception as exc:  # noqa: BLE001
            batch_detail = {"batch": {"error": repr(exc)[:300]}}

    # Device-resident span loop A/B (ISSUE 19): devloop on/off at the
    # ragged-sub geometry where the launch amortization is visible, with
    # per-span launch/transfer/byte counters, the difficulty-mode TTFH
    # A/B, and the pallas-interpret counters leg. CPU-only and isolated
    # like the other compute probes; DBM_BENCH_DEVLOOP=0 skips it.
    devloop_detail = {}
    if not on_accel and "jnp" in results \
            and _str_env("DBM_BENCH_DEVLOOP", "1") != "0":
        try:
            devloop_detail = {"devloop": _devloop_probe(data)}
        except Exception as exc:  # noqa: BLE001
            devloop_detail = {"devloop": {"error": repr(exc)[:300]}}

    # Control-plane load curve (ISSUE 11): tenants vs p50/p99/shed-rate
    # for 1 vs 4 scheduler replicas on detnet with instant miners —
    # no JAX compute involved, so it runs on any box. DBM_BENCH_LOAD=0
    # skips it.
    load_detail = {}
    if _str_env("DBM_BENCH_LOAD", "1") != "0":
        try:
            load_detail = {"load": _load_probe()}
        except Exception as exc:  # noqa: BLE001
            load_detail = {"load": {"error": repr(exc)[:300]}}

    # Self-tuning control plane A/B (ISSUE 13): the three adversarial
    # workloads static-vs-adaptive on detnet with rate-limited instant
    # miners — no JAX compute involved, so it runs on any box.
    # DBM_BENCH_ADAPT=0 skips it.
    adapt_detail = {}
    if _str_env("DBM_BENCH_ADAPT", "1") != "0":
        try:
            adapt_detail = {"adapt": _adapt_probe()}
        except Exception as exc:  # noqa: BLE001
            adapt_detail = {"adapt": {"error": repr(exc)[:300]}}

    # Workload capture→replay fidelity (ISSUE 15): capture a detnet
    # storm, re-drive it, gate the shape reproduction — no JAX compute
    # involved, so it runs on any box. DBM_BENCH_REPLAY=0 skips it.
    replay_detail = {}
    if _str_env("DBM_BENCH_REPLAY", "1") != "0":
        try:
            replay_detail = {"replay": _replay_probe()}
        except Exception as exc:  # noqa: BLE001
            replay_detail = {"replay": {"error": repr(exc)[:300]}}

    # Mesh plane (ISSUE 14): per-device-count scaling sweep + the
    # heterogeneous mixed-pool storm. The same dict is the
    # MULTICHIP_r06.json artifact schema. DBM_BENCH_MESH=0 skips it.
    mesh_detail = {}
    if _str_env("DBM_BENCH_MESH", "1") != "0":
        try:
            mesh_detail = {"mesh": _mesh_probe()}
        except Exception as exc:  # noqa: BLE001
            mesh_detail = {"mesh": {"error": repr(exc)[:300]}}

    # Scheduler federation (ISSUE 20): federated-vs-flat makespan at
    # equal pool size + grant-share tracking under >= 10x child-pool
    # skew — detnet sockets only, no JAX. DBM_BENCH_FEDERATION=0 skips.
    federation_detail = {}
    if _str_env("DBM_BENCH_FEDERATION", "1") != "0":
        try:
            federation_detail = {"federation": _federation_probe()}
        except Exception as exc:  # noqa: BLE001
            federation_detail = {"federation": {"error": repr(exc)[:300]}}

    # Transport datapath A/B (ISSUE 17): echo-storm msgs/s fast vs stock
    # (DBM_MMSG=0 DBM_WIRE_FAST=0) in subprocess legs, syscall economics,
    # per-conn memory — sockets only, no JAX, so it runs on any box.
    # DBM_BENCH_TRANSPORT=0 skips it.
    transport_detail = {}
    if _str_env("DBM_BENCH_TRANSPORT", "1") != "0":
        try:
            from distributed_bitcoinminer_tpu.apps.transportbench import (
                transport_probe)
            transport_detail = {"transport": transport_probe(_REPO)}
        except Exception as exc:  # noqa: BLE001
            transport_detail = {"transport": {"error": repr(exc)[:300]}}

    # Cluster rollup plane overhead A/B (ISSUE 18): --procs storm with
    # DBM_ROLLUP pinned on vs off + direct publish/aggregate micro
    # costs — files and sockets only, no JAX. DBM_BENCH_ROLLUP=0 skips.
    rollup_detail = {}
    if _str_env("DBM_BENCH_ROLLUP", "1") != "0":
        try:
            rollup_detail = {"rollup": _rollup_probe()}
        except Exception as exc:  # noqa: BLE001
            rollup_detail = {"rollup": {"error": repr(exc)[:300]}}

    from distributed_bitcoinminer_tpu.ops.sha256_pallas import peel_enabled
    from distributed_bitcoinminer_tpu.utils.metrics import registry

    _emit(best["rate"], {
        "tier": best_tier,
        "devices": len(devices),
        "platform": devices[0].platform,
        # Hoist telemetry of the measured block (jnp-tier counters; the
        # pallas peel shape consumes the same plan).
        "hoist": hoist_counters(build("jnp"), lower, best_upper),
        # Self-describing artifact: which pallas kernel shape ran
        # (chip_chain's bench-peel stage sets DBM_PEEL=1).
        **({"peel": True} if peel_enabled() else {}),
        "range": best["range"],
        "batch": batch,
        "repeats": best["reps"],
        "timed_s": round(best["secs"], 3),
        "warmup_s": best["warmup_s"],
        "all_tiers": {t: round(r["rate"], 1) for t, r in results.items()},
        # Before/after evidence for the hoist (DBM_HOIST=0 re-measure).
        **({"no_hoist": {t: r["no_hoist_rate"] for t, r in results.items()
                         if "no_hoist_rate" in r}}
           if any("no_hoist_rate" in r for r in results.values()) else {}),
        # The SURVEY §7 waterfall: sequential vs dispatch-pipelined rates.
        "overlapped": {t: r["overlapped_rate"] for t, r in results.items()
                       if "overlapped_rate" in r},
        **until_detail,
        **sweep_detail,
        **pipeline_detail,
        **qos_detail,
        **batch_detail,
        **devloop_detail,
        **load_detail,
        **adapt_detail,
        **replay_detail,
        **mesh_detail,
        **federation_detail,
        **transport_detail,
        **rollup_detail,
        # Process metrics snapshot (ISSUE 3): stable-keyed and
        # JSON-native, so BENCH_r* diffs of kernel/dispatch counters
        # (midstate cache behavior, until-tier degradations) stay
        # comparable run to run.
        "metrics": registry().snapshot(),
        **({"tier_errors": errors} if errors else {}),
        **({"probe": probe} if force_cpu else {}),
    })
    return 0


if __name__ == "__main__":
    try:
        rc = main()
    except Exception as exc:  # noqa: BLE001 — the one-JSON-line contract
        _emit(0.0, {"error": repr(exc)[:500]})
        rc = 0
    # Hard exit: the axon/jax stack leaves interpreter-shutdown finalizers
    # that can hang for minutes after the JSON line is already printed
    # (round-3 finding; the driver must never see that as a bench timeout).
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(rc)
