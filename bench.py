#!/usr/bin/env python
"""Headline benchmark: nonce-search throughput of one TPU miner.

Prints ONE JSON line:
``{"metric": ..., "value": N, "unit": "nonces/sec", "vs_baseline": N}``.

The reference publishes no numbers (see BASELINE.md); the baseline is the
structural estimate of the Go miner's single-threaded hot loop
(ref: bitcoin/miner/miner.go:53-59 — one stdlib sha256 + string format per
nonce), taken at the generous top of its 10^6-10^7 nonces/s envelope.
"""

from __future__ import annotations

import json
import sys
import time

GO_MINER_BASELINE_NPS = 1.0e7  # upper structural estimate, BASELINE.md


def main() -> None:
    import jax

    from distributed_bitcoinminer_tpu.bitcoin.hash import scan_min
    from distributed_bitcoinminer_tpu.models import (
        NonceSearcher, ShardedNonceSearcher)
    from distributed_bitcoinminer_tpu.parallel import make_mesh

    devices = jax.devices()
    on_accel = devices[0].platform != "cpu"
    batch = (1 << 20) if on_accel else (1 << 13)
    upper = ((1 << 26) - 1) if on_accel else ((1 << 18) - 1)
    data = "cmu440"

    if len(devices) > 1:
        searcher = ShardedNonceSearcher(data, batch=batch,
                                        mesh=make_mesh(len(devices)))
    else:
        searcher = NonceSearcher(data, batch=batch)

    # Correctness gate on a small range before timing.
    small = searcher.search(0, 9999)
    oracle = scan_min(data, 0, 9999)
    assert small == oracle, f"bench correctness gate failed: {small} != {oracle}"

    # Warm-up pass compiles every (rem, k, nbatches) signature of the range.
    t0 = time.time()
    searcher.search(0, upper)
    warm_s = time.time() - t0

    t0 = time.time()
    best_hash, best_nonce = searcher.search(0, upper)
    dt = time.time() - t0
    rate = (upper + 1) / dt

    print(json.dumps({
        "metric": "nonce_search_throughput",
        "value": round(rate, 1),
        "unit": "nonces/sec",
        "vs_baseline": round(rate / GO_MINER_BASELINE_NPS, 3),
        "detail": {
            "devices": len(devices),
            "platform": devices[0].platform,
            "range": upper + 1,
            "batch": batch,
            "search_s": round(dt, 3),
            "warmup_s": round(warm_s, 3),
            "min_hash": best_hash,
            "argmin_nonce": best_nonce,
        },
    }))


if __name__ == "__main__":
    sys.exit(main())
