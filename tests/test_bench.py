"""bench.py contract smoke: the driver scores the round from this output.

Runs the real benchmark as a subprocess pinned to the CPU/host tier (fast
and chip-independent) and asserts the one-JSON-line contract the driver
parses, plus the round-4 difficulty detail. A regression here would not
fail any unit test but would zero the round's recorded benchmark.
"""

import json
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_emits_one_valid_json_line_with_contract_fields():
    proc = subprocess.run(
        [sys.executable, "bench.py"], cwd=_REPO, capture_output=True,
        text=True, timeout=420,
        env={**os.environ, "JAX_PLATFORMS": "cpu", "DBM_COMPUTE": "host",
             "DBM_BENCH_INIT_TIMEOUT": "60"})
    assert proc.returncode == 0, proc.stderr[-500:]
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln]
    assert len(lines) == 1, lines
    out = json.loads(lines[0])
    assert out["metric"] == "nonce_search_throughput"
    assert out["unit"] == "nonces/sec"
    assert out["value"] > 0
    # vs_baseline derives from the UNROUNDED rate; comparing against the
    # rounded value needs a tolerance spanning both roundings.
    assert abs(out["vs_baseline"] - out["value"] / 1.0e7) < 2e-4
    detail = out["detail"]
    assert detail["tier"] == "host"
    # Difficulty-mode detail (round 4): measured and oracle-gated inside
    # bench itself; a failure would surface as until_error instead.
    assert detail.get("until_found") is True, detail
    assert "until_ttfh_s" in detail


def test_trace_dev_validates_profiler_pipeline():
    """`trace_mfu.py trace-dev` proves the profiler capture + xplane
    parse + report plumbing on CPU (round 5: the trace mode was built
    during the chip tunnel outage and must work first try on hardware).
    CPU traces carry no device plane, so the parse walks the host plane
    and says so."""
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "scripts", "trace_mfu.py"),
         "trace-dev", "15"],
        cwd=_REPO, capture_output=True, text=True, timeout=420,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-800:]
    out = json.loads(proc.stdout[proc.stdout.index("{"):])
    assert out["ops_per_nonce_census"] > 3000
    assert out["trace"]["plane_kind"] == "host-fallback"
    assert out["trace"]["planes"], "no planes parsed from the trace"
    assert out["total_device_busy_ms"] > 0
