"""Cross-request batched dispatch property suite (ISSUE 9).

Covers every layer of the batch-coalescing plane:

- **Ops**: the ``search_span_segmin`` per-request segment-min is
  bit-exact against the per-chunk ``search_span`` oracle across a
  rem x k x ragged-lane-count grid — mixed messages, multi-block
  requests, padded pow2 row buckets, masked padded lanes — and the
  gated pallas batch entry matches on a small interpret case.
- **Models**: ``NonceSearcher.dispatch_batch``/``finalize_batch``
  answer exactly like per-job ``search``, refuse incompatible mixes,
  and respect the pallas gating knob.
- **Miner**: the pipelined executor's coalescer drains compatible
  small chunks into shared launches with Results scattered strictly in
  request order; difficulty/oversize chunks never coalesce; coalescing
  OFF never drains and reproduces the stock path bit-for-bit (the
  acceptance pin, re-run under ``DBM_COALESCE=0`` in the tier-1 matrix
  leg).
- **Scheduler**: the QoS pump's coalescing window stacks several
  tenants' mice on one miner within one pump pass (shared
  ``coalesce_id`` counting as ONE live-FIFO slot) while DRR/admission
  debits stay per chunk; same-request chunks never share a window;
  the window never engages for large chunks or with the plane off.
"""

import asyncio

import numpy as np
import pytest

from distributed_bitcoinminer_tpu.apps.miner import HostSearcher, MinerWorker
from distributed_bitcoinminer_tpu.bitcoin.hash import scan_min
from distributed_bitcoinminer_tpu.bitcoin.message import (Message, MsgType,
                                                          new_request)
from distributed_bitcoinminer_tpu.models import NonceSearcher
from distributed_bitcoinminer_tpu.ops.search import pow2_bucket
from distributed_bitcoinminer_tpu.utils.config import (CoalesceParams,
                                                       LeaseParams,
                                                       QosParams)
from distributed_bitcoinminer_tpu.utils.metrics import registry

from tests.test_qos import FakeServer, pin_rate
from tests.test_scheduler_recovery import join, request

BATCH = 1 << 9          # small lanes: CPU-tier test sizing


def _searcher(data: str) -> NonceSearcher:
    return NonceSearcher(data, batch=BATCH, tier="jnp")


def _counter(name: str) -> int:
    return registry().counter(name).value


# ------------------------------------------------------------- ops / models


def test_pow2_bucket():
    assert [pow2_bucket(n) for n in (1, 2, 3, 4, 5, 8, 9, 64)] == \
        [1, 2, 4, 4, 8, 8, 16, 64]


def test_batch_bit_exact_mixed_message_grid():
    """THE acceptance property: per-request results from a coalesced
    mixed-message batch equal the per-chunk search_span oracle, across
    messages of different lengths (rem variety), ranges spanning
    multiple k classes and blocks, ragged lane counts (padded-lane
    masking inside rows), and entry counts that force pow2 row-bucket
    padding."""
    searchers = {d: _searcher(d) for d in
                 ("alpha", "bee", "a" * 40, "cmu440")}
    entries = [
        (searchers["alpha"], 100_000, 101_000),     # k=6, one block
        (searchers["bee"], 100_050, 100_949),       # same class, ragged
        (searchers["a" * 40], 99_000, 102_000),     # crosses 10^5 bound
        (searchers["cmu440"], 5, 2_500),            # k=1..4 multi-class
        (searchers["alpha"], 100_123, 100_123),     # single-nonce
    ]
    s0 = entries[0][0]
    for take in (1, 2, 3, 5):       # 3 and 5 force pow2 padding
        part = entries[:take]
        handle = s0.dispatch_batch(part)
        assert handle is not None
        got = s0.finalize_batch(handle)
        for (s, lo, up), pair in zip(part, got):
            assert pair == s.search(lo, up), (s.data, lo, up)


def test_batch_matches_oracle_scan():
    """End-to-end against the pure-host oracle (not just search_span)."""
    s = _searcher("oracle batch")
    t = _searcher("oracle batch 2")
    handle = s.dispatch_batch([(s, 1_000, 3_000), (t, 4_000, 6_000)])
    assert s.finalize_batch(handle) == [
        scan_min("oracle batch", 1_000, 3_000),
        scan_min("oracle batch 2", 4_000, 6_000)]


def test_batch_bit_exact_without_hoist():
    """DBM_HOIST=0-shaped searchers (no hoist operands) batch through
    the hoists=None kernel path, still bit-exact."""
    a = NonceSearcher("nohoist a", batch=BATCH, tier="jnp", hoist=False)
    b = NonceSearcher("nohoist b", batch=BATCH, tier="jnp", hoist=False)
    handle = a.dispatch_batch([(a, 50_000, 52_000), (b, 60_000, 61_000)])
    assert handle is not None
    assert a.finalize_batch(handle) == [a.search(50_000, 52_000),
                                        b.search(60_000, 61_000)]


def test_batch_incompatible_searchers_return_none():
    a = _searcher("one")
    b = NonceSearcher("two", batch=BATCH * 2, tier="jnp")  # batch differs
    assert a.dispatch_batch([(a, 0, 99), (b, 0, 99)]) is None


def test_batch_pallas_tier_gated_off_by_default(monkeypatch):
    monkeypatch.delenv("DBM_COALESCE_PALLAS", raising=False)
    a = NonceSearcher("gated", batch=BATCH, tier="pallas")
    b = NonceSearcher("gated2", batch=BATCH, tier="pallas")
    assert a.dispatch_batch([(a, 0, 99), (b, 0, 99)]) is None


def test_batch_empty_range_raises():
    a = _searcher("inverted")
    with pytest.raises(ValueError):
        a.dispatch_batch([(a, 100, 99)])


def test_pallas_segmin_interpret_bit_exact(monkeypatch):
    """The gated pallas batch entry (DBM_COALESCE_PALLAS=1), validated
    in the Mosaic interpreter: 2 rows (one per message), ~2 grid steps
    total — same per-request answers as the jnp path and the oracle."""
    monkeypatch.setenv("DBM_COALESCE_PALLAS", "1")
    a = NonceSearcher("cmu440", batch=256, tier="pallas")
    b = NonceSearcher("pallas", batch=256, tier="pallas")
    entries = [(a, 100_100, 100_300), (b, 100_000, 100_255)]
    handle = a.dispatch_batch(entries)
    assert handle is not None
    got = a.finalize_batch(handle)
    assert got == [scan_min("cmu440", 100_100, 100_300),
                   scan_min("pallas", 100_000, 100_255)]


def test_host_searcher_batch_contract():
    a = HostSearcher("host batch a")
    b = HostSearcher("host batch b")
    handle = a.dispatch_batch([(a, 0, 999), (b, 500, 1_499)])
    assert handle is not None
    assert a.finalize_batch(handle) == [
        scan_min("host batch a", 0, 999),
        scan_min("host batch b", 500, 1_499)]


# ------------------------------------------------------------ miner coalescer


class _ScriptClient:
    """Fake AsyncClient: serves scripted Requests, records writes, then
    parks forever (the test cancels the worker)."""

    def __init__(self, payloads):
        self._payloads = list(payloads)
        self.writes = []
        self._forever = asyncio.get_running_loop().create_future()

    async def read(self):
        if self._payloads:
            return self._payloads.pop(0)
        await self._forever

    def write(self, payload):
        self.writes.append(payload)

    async def close(self):
        pass


def _drive_worker(payloads, expect: int, **worker_kw):
    """Run a MinerWorker over a scripted client until ``expect`` Results
    land; returns the decoded replies."""
    async def scenario():
        worker = MinerWorker("unused:0", **worker_kw)
        worker.client = _ScriptClient(payloads)
        task = asyncio.create_task(worker.run())
        for _ in range(1200):
            if len(worker.client.writes) >= expect:
                break
            await asyncio.sleep(0.01)
        task.cancel()
        return [Message.from_json(w) for w in worker.client.writes]
    return asyncio.run(scenario())


#: jnp-tier factory for the worker tests; the module-level warm in the
#: first test primes every signature these geometries hit.
def _jnp_factory(d, b):
    return _searcher(d)


def test_coalescer_batches_queued_chunks_in_order():
    """Queued compatible chunks drain into shared launches; Results
    stay strictly in request order and oracle-exact."""
    ranges = [(100_000 + i * 500, 100_000 + i * 500 + 399)
              for i in range(6)]
    before = _counter("miner.chunks_coalesced")
    replies = _drive_worker(
        [new_request("coal order", lo, up).to_json() for lo, up in ranges],
        expect=6, searcher_factory=_jnp_factory, pipeline=True,
        pipeline_depth=8, coalesce=True, coalesce_lanes=8)
    assert len(replies) == 6
    for (lo, up), m in zip(ranges, replies):
        assert (m.hash, m.nonce) == scan_min("coal order", lo, up)
    # The drain actually engaged (the scripted queue is pre-filled, so
    # at least the tail of it coalesces behind the first chunk).
    assert _counter("miner.chunks_coalesced") > before


def test_coalesce_off_reproduces_stock_dispatch_bit_for_bit():
    """The acceptance pin: DBM_COALESCE=0 (coalesce=False) never drains
    — zero coalesced dispatches, every chunk its own launch — and the
    reply stream is byte-identical to the coalescing run's."""
    ranges = [(100_000 + i * 500, 100_000 + i * 500 + 399)
              for i in range(5)]
    payloads = [new_request("coal parity", lo, up).to_json()
                for lo, up in ranges]
    on = _drive_worker(list(payloads), expect=5,
                       searcher_factory=_jnp_factory, pipeline=True,
                       coalesce=True, coalesce_lanes=8)
    before_disp = _counter("miner.coalesced_dispatches")
    before_launch = _counter("model.device_launches")
    off = _drive_worker(list(payloads), expect=5,
                        searcher_factory=_jnp_factory, pipeline=True,
                        coalesce=False)
    assert _counter("miner.coalesced_dispatches") == before_disp
    # Stock path: one launch per chunk (each range is one pow2 sub).
    assert _counter("model.device_launches") - before_launch == 5

    def normalized(m):
        # The Span trace extension (ISSUE 10) carries per-run TIMINGS,
        # so with DBM_TRACE=1 (the default leg) it legitimately differs
        # between the runs; the parity claim is about the ANSWER bytes.
        # The tier-1 matrix leg re-runs this test with DBM_TRACE=0,
        # where no Span exists and this normalization is the identity —
        # true byte-for-bit coverage stays pinned there.
        m.span = None
        return m.to_json()
    assert [normalized(m) for m in off] == [normalized(m) for m in on]
    for (lo, up), m in zip(ranges, off):
        assert (m.hash, m.nonce) == scan_min("coal parity", lo, up)


def test_difficulty_and_oversize_chunks_never_coalesce():
    """A difficulty chunk between two small argmin chunks splits the
    drain (it needs the until path); an oversize chunk is equally
    excluded — all four Results still land in request order."""
    target = 1 << 60
    payloads = [
        new_request("coal mix", 100_000, 100_399).to_json(),
        new_request("coal mix", 100_400, 100_799, target).to_json(),
        new_request("coal mix", 100_800, 101_199).to_json(),
        new_request("coal mix", 101_200, 101_599).to_json(),
        # OVERSIZE: 1000 nonces > the 450 bound — must run solo.
        new_request("coal mix", 101_600, 102_599).to_json(),
    ]
    before = _counter("miner.chunks_coalesced")
    before_launches = _counter("model.device_launches")
    replies = _drive_worker(
        payloads, expect=5, searcher_factory=_jnp_factory, pipeline=True,
        coalesce=True, coalesce_lanes=8, coalesce_max=450)
    assert len(replies) == 5
    spans = [(100_000, 100_399), (100_400, 100_799), (100_800, 101_199),
             (101_200, 101_599), (101_600, 102_599)]
    from distributed_bitcoinminer_tpu.bitcoin.hash import scan_until
    for i, ((lo, up), m) in enumerate(zip(spans, replies)):
        if i == 1:   # difficulty chunk: FIRST qualifying nonce, not argmin
            want = scan_until("coal mix", lo, up, target)[:2]
        else:
            want = scan_min("coal mix", lo, up)
        assert (m.hash, m.nonce) == want, (i, lo, up)
    # The target chunk echoes its target; argmin chunks echo 0.
    assert [m.target for m in replies] == [0, target, 0, 0, 0]
    # Neither the target chunk nor the oversize chunk rode a batch: at
    # most the three small argmin chunks coalesced.
    assert _counter("miner.chunks_coalesced") - before <= 3
    # Every chunk still launched. The solo oversize chunk rides the
    # devloop when enabled (ISSUE 19): one launch per 10^k block
    # instead of one per pow2 sub, so the floor drops by one there
    # (the tier-1 matrix leg re-runs this with DBM_DEVLOOP=0 and pins
    # the stock floor).
    from distributed_bitcoinminer_tpu.models.miner_model import \
        devloop_enabled
    floor = 4 if devloop_enabled() else 5
    assert _counter("model.device_launches") - before_launches >= floor


def test_no_batch_api_degrades_in_order():
    """Two-phase searchers WITHOUT dispatch_batch (user factories) are
    served per chunk, in order — the drain must not reorder or lose."""
    class _TwoPhase:
        def __init__(self, data):
            self.data = data

        def dispatch(self, lower, upper):
            return (lower, upper)

        def finalize(self, handle, lower):
            return scan_min(self.data, handle[0], handle[1])

    ranges = [(0, 999), (1_000, 1_999), (2_000, 2_999)]
    replies = _drive_worker(
        [new_request("degrade", lo, up).to_json() for lo, up in ranges],
        expect=3, searcher_factory=lambda d, b: _TwoPhase(d),
        pipeline=True, coalesce=True)
    assert [(m.hash, m.nonce) for m in replies] == \
        [scan_min("degrade", lo, up) for lo, up in ranges]


# -------------------------------------------------------- scheduler window


MINER_A, MINER_B = 101, 102
TEN_X, TEN_Y, TEN_Z = 1, 2, 3


def _window_sched(coalesce=None, **qos_kw):
    qos_kw.setdefault("wholesale_s", 0.5)
    qos_kw.setdefault("chunk_s", 1.0)
    qos_kw.setdefault("depth", 2)
    server = FakeServer()
    from distributed_bitcoinminer_tpu.apps.scheduler import Scheduler
    sched = Scheduler(server, lease=LeaseParams(),
                      qos=QosParams(**qos_kw),
                      coalesce=coalesce if coalesce is not None
                      else CoalesceParams(enabled=True, lanes=4))
    return sched, server


def test_window_stacks_mice_from_many_tenants_on_one_miner():
    """With the pool saturated by an elephant, queued mice from several
    tenants are granted into ONE miner's coalescing window in one pump
    pass: shared coalesce_id, one live slot, per-chunk DRR accounting."""
    sched, server = _window_sched()
    join(sched, MINER_A)
    join(sched, MINER_B)
    pin_rate(sched, rate=100.0)
    # Elephant (est 100s >> wholesale_s): chunked, fills both miners to
    # the depth cap (chunk ~100 nonces at chunk_s=1.0 — too big for
    # small_s=0.25 at rate 100, so the elephant never opens windows).
    request(sched, TEN_X, "elephant", 9_999)
    assert sched.current.qos_mode == "chunked"
    # Mice from two other tenants: 10-nonce requests (est 0.1s <=
    # small_s) — they queue (pool at depth), then one freed slot's pump
    # grants them all through a window.
    request(sched, TEN_Y, "mouse y", 9)
    request(sched, TEN_Z, "mouse z", 9)
    assert len(sched.queue) == 2
    # Answer one elephant chunk on miner A: the pump runs with capacity.
    from distributed_bitcoinminer_tpu.bitcoin.message import new_result
    c = sched._find_miner(MINER_A).pending[0]
    sched._on_result(MINER_A, new_result(1_000_000 + c.lower, c.lower))
    assert sched.stats["qos_window_grants"] >= 1
    mice_chunks = [ch for m in sched.miners for ch in m.pending
                   if ch.data.startswith("mouse")]
    assert len(mice_chunks) == 2
    cids = {ch.coalesce_id for ch in mice_chunks}
    assert len(cids) == 1 and None not in cids     # shared window
    miners_used = {m.conn_id for m in sched.miners
                   for ch in m.pending if ch.data.startswith("mouse")}
    assert len(miners_used) == 1                   # one miner's window
    # The window counts as ONE live slot on its miner.
    wm = sched._find_miner(miners_used.pop())
    live_raw = sum(1 for ch in wm.pending if not ch.cancelled)
    assert sched._miner_live(wm) == live_raw - 1
    # Per-chunk accounting unchanged: each mouse tenant was debited its
    # own grant.
    assert sched.qos_plane.tenants[TEN_Y].granted_chunks == 1
    assert sched.qos_plane.tenants[TEN_Z].granted_chunks == 1


def test_window_never_stacks_same_request():
    """One request's own chunks never share a window (cross-request
    batching only): a lone small-chunked request grants at most one
    chunk per miner slot, exactly like stock."""
    sched, _server = _window_sched(chunk_s=0.1)
    join(sched, MINER_A)
    join(sched, MINER_B)
    pin_rate(sched, rate=100.0)
    # 100-nonce chunks (est 0.1s <= small_s 0.25): small, but all from
    # the same job — windows open yet never admit a second chunk.
    request(sched, TEN_X, "self", 999)
    assert sched.current.qos_mode == "chunked"
    assert sched.stats["qos_window_grants"] == 0
    per_miner = [sum(1 for ch in m.pending if not ch.cancelled)
                 for m in sched.miners]
    assert max(per_miner) <= 2        # the stock depth cap held


def test_window_disabled_is_stock():
    """CoalesceParams(enabled=False): no window grants, no coalesce_id,
    group-counting degenerates to the plain live count."""
    sched, _server = _window_sched(
        coalesce=CoalesceParams(enabled=False))
    join(sched, MINER_A)
    join(sched, MINER_B)
    pin_rate(sched, rate=100.0)
    request(sched, TEN_X, "elephant", 9_999)
    request(sched, TEN_Y, "mouse y", 9)
    request(sched, TEN_Z, "mouse z", 9)
    from distributed_bitcoinminer_tpu.bitcoin.message import new_result
    c = sched._find_miner(MINER_A).pending[0]
    sched._on_result(MINER_A, new_result(1_000_000 + c.lower, c.lower))
    assert sched.stats["qos_window_grants"] == 0
    assert all(ch.coalesce_id is None
               for m in sched.miners for ch in m.pending)
    for m in sched.miners:
        assert sched._miner_live(m) == \
            sum(1 for ch in m.pending if not ch.cancelled)


# ------------------------------------------------------------ e2e (real LSP)


def test_e2e_coalescing_cluster_oracle_exact():
    """A mice train through a real localhost LSP cluster with the full
    plane on (QoS + window + coalescing miner): every reply oracle-exact
    and the coalescer measurably engaged. Leases off and signatures
    pre-warmed (first-compile stalls would otherwise blow leases and
    nondeterminize the grant flow — the bench-probe discipline)."""
    from tests.test_apps import Cluster, fast_params
    from distributed_bitcoinminer_tpu.lsp.client import new_async_client

    params = fast_params()
    elephant = (100_000, 600_000)
    mice = [(700_000 + i * 500, 700_000 + i * 500 + 399)
            for i in range(6)]
    # Warm every signature the legs can hit, incl. the coalesced pow2
    # row buckets (process-wide jit cache).
    warm = _searcher("e2e coal")
    warm.search(elephant[0], elephant[1] + 1)
    warm.search(100_000, 100_000 + 25_001)
    entries = [(warm, lo, up) for lo, up in mice]
    for width in (2, 4, 6):
        warm.finalize_batch(warm.dispatch_batch(entries[:width]))

    async def scenario():
        async with Cluster(params) as c:
            c.scheduler.lease = LeaseParams(enabled=False,
                                            queue_alarm_s=0.0)
            c.scheduler.qos = QosParams(enabled=True, wholesale_s=0.2,
                                        chunk_s=0.5, depth=2)
            c.scheduler.coalesce = CoalesceParams(enabled=True, lanes=8)
            worker = MinerWorker(
                c.hostport, params=params,
                searcher_factory=_jnp_factory,
                pipeline=True, coalesce=True, coalesce_lanes=8)
            await worker.join()
            c.tasks.append(asyncio.create_task(worker.run()))
            c.miners.append(worker)
            pin_rate(c.scheduler, rate=50_000.0)

            async def ask(lo, up, delay=0.0):
                if delay:
                    await asyncio.sleep(delay)
                client = await new_async_client(c.hostport, params)
                try:
                    client.write(
                        new_request("e2e coal", lo, up).to_json())
                    while True:
                        m = Message.from_json(
                            await asyncio.wait_for(client.read(), 120))
                        if m.type == MsgType.RESULT:
                            return m
                finally:
                    await client.close()

            # The elephant (est 5s at the pinned rate -> chunked into
            # 25k-nonce grants) occupies the pool; the mice wave lands
            # behind it and must batch through the window.
            replies = await asyncio.gather(
                ask(*elephant),
                *(ask(lo, up, delay=0.3) for lo, up in mice))
            return replies

    before = _counter("miner.chunks_coalesced")
    replies = asyncio.run(scenario())
    for (lo, up), m in zip([elephant] + mice, replies):
        assert (m.hash, m.nonce) == scan_min("e2e coal", lo, up + 1), \
            (lo, up)   # wire upper is inclusive+1 (reference quirk)
    assert _counter("miner.chunks_coalesced") > before
