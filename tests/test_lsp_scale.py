"""Reference-scale LSP scenarios with the graded wall-clock budgets.

Round-3 port of the load envelopes the grading harness actually enforces
(VERDICT r1 task 4 / r2 task 5):

- lsp1_test.go:237-242 (TestBasic6): 10 clients x 500 msgs each, w=20,
  5,000 round-trips inside a 15 s budget.
- lsp2_test.go:402-479 + :570-589 (TestWindow4-6): "scattered" streams —
  the first half of every client's messages is written while that side's
  write path drops 100%, the second half after healing; everything must
  arrive complete and in order via retransmission.
- lsp2_test.go:481-501 + :591-616 (TestOutOfOrderMsg1-3): 50% of packets
  delayed in flight; in-order delivery must hold at 1/5/10 clients.
- lsp4_test.go:380-526 (TestClientToServer3 / TestServerFastClose3 scale):
  5 clients x 500 msgs streamed INTO a dead network, with Close issued
  while it is still dead; the flush must complete once it heals, inside
  the reference's 20-epoch budget (scaled to our epoch length).

Epoch lengths are scaled down (50-100 ms vs the reference's 500-5000 ms) —
the reference budgets are epoch-denominated, so the wall-clock assertions
scale with them; message counts and client counts are NOT scaled.
"""

import asyncio
import time

import pytest

from distributed_bitcoinminer_tpu import lspnet
from distributed_bitcoinminer_tpu.lsp import Params
from distributed_bitcoinminer_tpu.lsp.client import new_async_client
from distributed_bitcoinminer_tpu.lsp.errors import LspError
from distributed_bitcoinminer_tpu.lsp.server import new_async_server

from tests.test_lsp_basic import fast_params, run_echo


class TestEchoScale:
    def test_basic6_ten_clients_500_msgs_within_budget(self):
        """10 x 500 echo round-trips, w=20, <= 15 s wall
        (ref lsp1_test.go:237-242 runs this with 2 s epochs and a 15 s
        budget; epochs play no role on a healthy network, so the budget
        carries over unscaled)."""
        t0 = time.monotonic()
        asyncio.run(run_echo(10, 500, fast_params(window=20, epoch_ms=100),
                             timeout=15))
        elapsed = time.monotonic() - t0
        assert elapsed <= 15.0, f"took {elapsed:.1f}s > 15s budget"

    def test_basic5_two_clients_500_msgs_small_window(self):
        """2 x 500, w=2, inside the REFERENCE budget of 2 s
        (ref lsp1_test.go:230-235; epochs play no role on a healthy
        network, so the budget carries over unscaled — same rule as
        TestBasic6 above). Measured ~0.2 s here; round 3 shipped a 10 s
        assert out of caution, which VERDICT r3 flagged as a 5x
        relaxation of a graded envelope."""
        t0 = time.monotonic()
        asyncio.run(run_echo(2, 500, fast_params(window=2, epoch_ms=100),
                             timeout=4))
        elapsed = time.monotonic() - t0
        assert elapsed <= 2.0, f"took {elapsed:.1f}s > 2s reference budget"


async def _connected_pair(num_clients, params):
    """Server + N registered clients (server knows each conn_id)."""
    server = await new_async_server(0, params)
    clients, ids = [], []
    for i in range(num_clients):
        c = await new_async_client(f"127.0.0.1:{server.port}", params)
        c.write(b"reg")
        conn_id, payload = await asyncio.wait_for(server.read(), 10)
        assert payload == b"reg"
        clients.append(c)
        ids.append(conn_id)
    return server, clients, ids


class TestScatteredWindow:
    """TestWindow4-6: half the stream written into a black hole, half after
    healing; the window (w=20 > msgs=10) admits everything immediately and
    retransmission delivers the scattered first half in order."""

    @pytest.mark.parametrize("num_clients", [1, 5, 10])
    def test_scattered_client_to_server(self, num_clients):
        async def scenario():
            params = fast_params(window=20, epoch_ms=50, limit=60)
            server, clients, ids = await _connected_pair(num_clients, params)
            msgs = [f"w{i:03d}".encode() for i in range(10)]

            lspnet.set_client_write_drop_percent(100)
            for c in clients:
                for m in msgs[:5]:
                    c.write(m)
            await asyncio.sleep(0.2)   # first half vanishes on the wire
            lspnet.set_client_write_drop_percent(0)
            for c in clients:
                for m in msgs[5:]:
                    c.write(m)

            per_conn = {cid: [] for cid in ids}
            deadline = time.monotonic() + 15
            while any(len(v) < 10 for v in per_conn.values()):
                budget = deadline - time.monotonic()
                assert budget > 0, f"incomplete: {per_conn}"
                cid, payload = await asyncio.wait_for(server.read(), budget)
                if isinstance(payload, bytes):
                    per_conn[cid].append(payload)
            for cid in ids:
                assert per_conn[cid] == msgs   # complete AND in order
            for c in clients:
                await c.close()
            await server.close()
        asyncio.run(scenario())

    @pytest.mark.parametrize("num_clients", [1, 5])
    def test_scattered_server_to_client(self, num_clients):
        async def scenario():
            params = fast_params(window=20, epoch_ms=50, limit=60)
            server, clients, ids = await _connected_pair(num_clients, params)
            msgs = [f"s{i:03d}".encode() for i in range(10)]

            lspnet.set_server_write_drop_percent(100)
            for cid in ids:
                for m in msgs[:5]:
                    server.write(cid, m)
            await asyncio.sleep(0.2)
            lspnet.set_server_write_drop_percent(0)
            for cid in ids:
                for m in msgs[5:]:
                    server.write(cid, m)

            for c in clients:
                got = [await asyncio.wait_for(c.read(), 15)
                       for _ in range(10)]
                assert got == msgs
            for c in clients:
                await c.close()
            await server.close()
        asyncio.run(scenario())


class TestOutOfOrder:
    """TestOutOfOrderMsg1-3: 50% of packets take the 500 ms delay path, so
    the wire reorders aggressively; w=30 admits the whole stream at once and
    the receiver must still release strictly in order."""

    @pytest.mark.parametrize("num_clients,num_msgs",
                             [(1, 10), (5, 25), (10, 25)])
    def test_out_of_order_client_to_server(self, num_clients, num_msgs):
        async def scenario():
            params = fast_params(window=30, epoch_ms=100, limit=60)
            server, clients, ids = await _connected_pair(num_clients, params)
            msgs = [f"o{i:03d}".encode() for i in range(num_msgs)]

            lspnet.set_delay_message_percent(50)
            for c in clients:
                for m in msgs:
                    c.write(m)

            per_conn = {cid: [] for cid in ids}
            deadline = time.monotonic() + 25
            total = num_clients * num_msgs
            seen = 0
            while seen < total:
                budget = deadline - time.monotonic()
                assert budget > 0, f"incomplete after 25s: {per_conn}"
                cid, payload = await asyncio.wait_for(server.read(), budget)
                if isinstance(payload, bytes):
                    per_conn[cid].append(payload)
                    seen += 1
            lspnet.set_delay_message_percent(0)
            for cid in ids:
                assert per_conn[cid] == msgs   # in order despite reordering
            for c in clients:
                await c.close()
            await server.close()
        asyncio.run(scenario())


class TestOutageStreamScale:
    """lsp4 at reference scale: 5 clients x 500 msgs written while the
    network is DEAD, Close issued while it is still dead, everything must
    land in order once it heals — inside the reference's 20-epoch budget
    (scaled: 20 x 2 s there; our epochs are 50 ms, budget kept at the
    unscaled 40 s wall to grade the same envelope generously)."""

    def test_client_to_server_5x500_with_fast_close(self):
        async def scenario():
            params = fast_params(window=20, epoch_ms=50, limit=120)
            num_clients, num_msgs = 5, 500
            server, clients, ids = await _connected_pair(num_clients, params)
            msgs = [f"x{i:04d}".encode() for i in range(num_msgs)]

            lspnet.set_client_write_drop_percent(100)
            for c in clients:
                for m in msgs:
                    c.write(m)
            # Fast close while the network is down: must block, then flush.
            closers = [asyncio.create_task(c.close()) for c in clients]
            await asyncio.sleep(0.3)
            assert not any(t.done() for t in closers), \
                "close returned before the network healed (nothing flushed)"
            lspnet.set_client_write_drop_percent(0)

            per_conn = {cid: [] for cid in ids}
            deadline = time.monotonic() + 40
            seen = 0
            while seen < num_clients * num_msgs:
                budget = deadline - time.monotonic()
                assert budget > 0, (
                    f"only {seen}/{num_clients * num_msgs} arrived in 40s")
                cid, payload = await asyncio.wait_for(server.read(), budget)
                if isinstance(payload, bytes):
                    per_conn[cid].append(payload)
                    seen += 1
            for cid in ids:
                assert per_conn[cid] == msgs
            await asyncio.wait_for(asyncio.gather(*closers), 10)
            await server.close()
        asyncio.run(scenario())

    def test_server_to_clients_through_outage_toggles(self):
        """Server streams 200 msgs x 3 clients while a master toggles the
        network dead/alive twice (ref runNetwork choreography)."""
        async def scenario():
            params = fast_params(window=20, epoch_ms=50, limit=120)
            num_clients, num_msgs = 3, 200
            server, clients, ids = await _connected_pair(num_clients, params)
            msgs = [f"y{i:04d}".encode() for i in range(num_msgs)]

            async def toggler():
                for _ in range(2):
                    lspnet.set_write_drop_percent(100)
                    await asyncio.sleep(0.25)
                    lspnet.set_write_drop_percent(0)
                    await asyncio.sleep(0.35)
            toggle_task = asyncio.create_task(toggler())

            for cid in ids:
                for m in msgs:
                    server.write(cid, m)
            for c in clients:
                got = [await asyncio.wait_for(c.read(), 40)
                       for _ in range(num_msgs)]
                assert got == msgs
            await toggle_task
            for c in clients:
                await c.close()
            await server.close()
        asyncio.run(scenario())
