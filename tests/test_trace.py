"""End-to-end cross-process tracing + flight recorder (ISSUE 10).

Layers:

1. Wire + span units — the ``Span`` Result extension (stock bytes when
   absent, malformed values dropped), the phase vocabulary, and the
   dominant-phase naming.
2. Flight recorder / compile observer / TrackSet units — ring bound,
   dump triggers (alarm, sanitizer warning, unhandled-exception exit),
   the recompile-storm alarm under a REAL unquantized jit-signature
   churn, track retirement discipline.
3. Chrome/Perfetto export — golden format (valid JSON, pinned event key
   set, monotonic ts per track), file writing, and the
   ``scripts/dbmtrace.py convert`` CLI on dumped traces.
4. E2E — a real localhost LSP cluster where a WEDGED miner's stitched
   trace names the miner-side phase that stalled (the late stale-Result
   fold), and the ``DBM_TRACE=0`` parity pin (byte-identical Results,
   zero trace paths: no stamps, no span dicts, no Span bytes).
"""

import asyncio
import json
import logging
import sys
import time

import pytest

from distributed_bitcoinminer_tpu.apps.scheduler import Scheduler
from distributed_bitcoinminer_tpu.bitcoin.message import (Message,
                                                          new_request,
                                                          new_result)
from distributed_bitcoinminer_tpu.utils import trace
from distributed_bitcoinminer_tpu.utils.config import VerifyParams
from distributed_bitcoinminer_tpu.utils.metrics import (
    registry as process_registry)

from tests.test_scheduler_recovery import (CLIENT_X, MINER_A, MINER_B,
                                           FakeServer, join, request,
                                           result)


@pytest.fixture
def traced(monkeypatch):
    """Force the plane ON (the tier-1 matrix leg runs this module with
    DBM_TRACE=0; tests that exercise tracing must pin it themselves)
    and isolate the process singletons so counters/rings start fresh."""
    monkeypatch.setenv("DBM_TRACE", "1")
    monkeypatch.setattr(trace, "_flight", None)
    monkeypatch.setattr(trace, "_observer", None)
    yield
    trace._flight = None
    trace._observer = None


def make_traced_scheduler():
    # Scripted results carry synthetic hashes the claim check would
    # reject; verification has its own suite, so pin it off here.
    server = FakeServer()
    return Scheduler(server, verify=VerifyParams(enabled=False)), server


SPAN = {"queue_s": 0.001, "dispatch_s": 0.002, "wait_s": 0.0005,
        "force_s": 0.8, "gap_s": 0.0, "launch": 3, "lanes": 4}


# ------------------------------------------------------------- wire + spans


def test_span_rides_result_and_absent_keeps_stock_bytes():
    stock = new_result(5, 3).to_json()
    assert b"Span" not in stock
    on_wire = new_result(5, 3, span={"force_s": 0.5}).to_json()
    assert b'"Span":{"force_s":0.5}' in on_wire
    decoded = Message.from_json(on_wire)
    assert decoded.span == {"force_s": 0.5}
    # Round-trip of a span-less message is bit-stable.
    assert Message.from_json(stock).to_json() == stock


def test_malformed_span_dropped_not_fatal():
    for junk in ('"x"', "5", "[1,2]", "null", "true"):
        raw = (b'{"Type":2,"Data":"","Lower":0,"Upper":0,"Hash":1,'
               b'"Nonce":2,"Span":' + junk.encode() + b"}")
        msg = Message.from_json(raw)     # must not raise
        assert msg.span is None and msg.hash == 1


def test_slow_phase_names_dominant_phase():
    assert trace.slow_phase(SPAN) == "force"
    assert trace.slow_phase({"queue_s": 1.0, "force_s": 0.1}) == "queue"
    assert trace.slow_phase({}) is None
    assert trace.slow_phase({"force_s": "junk"}) is None


def test_fold_span_whitelists_and_names_slow(traced):
    sched, _server = make_traced_scheduler()
    join(sched, MINER_A)
    request(sched, CLIENT_X, "fold", 99)
    evil = dict(SPAN, hostile="x" * 1000, miner=999)   # injected keys
    sched._on_result(MINER_A, Message.from_json(
        new_result(7, 1, span=evil).to_json()))
    events = sched.trace(1).to_dict()["events"]
    span_ev = next(e for e in events if e["event"] == "miner_span")
    assert span_ev["miner"] == MINER_A          # not the injected 999
    assert "hostile" not in span_ev
    assert span_ev["slow"] == "force"
    assert span_ev["launch"] == 3 and span_ev["lanes"] == 4
    assert events[-1]["event"] == "reply"


def test_trace_off_no_fold_no_tracks(monkeypatch):
    monkeypatch.setenv("DBM_TRACE", "0")
    sched, _server = make_traced_scheduler()
    assert sched._trace_on is False
    join(sched, MINER_A)
    request(sched, CLIENT_X, "off", 99)
    sched._on_result(MINER_A, Message.from_json(
        new_result(7, 1, span=dict(SPAN)).to_json()))
    events = sched.trace(1).to_dict()["events"]
    assert all(e["event"] != "miner_span" for e in events)
    assert len(sched._tracks) == 0


# --------------------------------------------------------- flight recorder


def test_flight_recorder_ring_bound_and_dump(traced, caplog):
    fr = trace.FlightRecorder(cap=4)
    for i in range(10):
        fr.record("ev", i=i)
    events = fr.events()
    assert len(events) == 4
    assert [e["i"] for e in events] == [6, 7, 8, 9]   # oldest dropped
    with caplog.at_level(logging.WARNING, logger="dbm.trace"):
        fr.dump("unit test")
    line = next(r.message for r in caplog.records
                if "flight recorder dump" in r.message)
    doc = json.loads(line[line.index("): ") + 3:])
    assert doc["why"] == "unit test"
    assert [e["i"] for e in doc["events"]] == [6, 7, 8, 9]


def test_flight_recorder_cap_zero_disables(traced, caplog):
    fr = trace.FlightRecorder(cap=0)
    fr.record("ev")
    assert len(fr.events()) == 0
    with caplog.at_level(logging.WARNING, logger="dbm.trace"):
        fr.dump("nope")
    assert not any("flight recorder dump" in r.message
                   for r in caplog.records)


def test_flight_helpers_respect_knob(monkeypatch):
    monkeypatch.setenv("DBM_TRACE", "0")
    monkeypatch.setattr(trace, "_flight", None)
    trace.flight("ev")                       # no-op: ring never built
    trace.flight_dump("why")
    assert trace._flight is None


def test_excepthook_dumps_flight_ring(traced, monkeypatch, caplog):
    seen = []
    monkeypatch.setattr(sys, "excepthook", lambda *a: seen.append(a))
    monkeypatch.setattr(trace, "_excepthook_installed", False)
    trace.ensure_tracer()
    trace.flight("pre_crash", detail="x")
    with caplog.at_level(logging.WARNING, logger="dbm.trace"):
        sys.excepthook(ValueError, ValueError("boom"), None)
    assert seen and seen[0][0] is ValueError     # prior hook still ran
    dump = next(r.message for r in caplog.records
                if "flight recorder dump" in r.message)
    assert "unhandled-exception exit" in dump and "pre_crash" in dump


def test_sanitizer_warning_dumps_flight(traced, caplog):
    from distributed_bitcoinminer_tpu.utils import sanitize

    async def on_loop():
        with caplog.at_level(logging.WARNING):
            sanitize.assert_off_loop("trace-test compute")
    asyncio.run(on_loop())
    assert any("flight recorder dump" in r.message
               and "loop_blocking" in r.message for r in caplog.records)


# --------------------------------------------------------- compile observer


def test_compile_observer_counts_and_storm_episode(traced, caplog):
    ob = trace.CompileObserver(storm_n=3, storm_s=60.0)
    storms = process_registry().counter("trace.recompile_storms")
    before = storms.value
    with caplog.at_level(logging.WARNING, logger="dbm.trace"):
        assert ob.launch(("e", 1), 0.5) == 0.5      # fresh: compile
        assert ob.launch(("e", 1), 0.001) is None   # warm: counted only
        ob.launch(("e", 2), 0.2)
        ob.launch(("e", 3), 0.2)                    # 3rd fresh: storm
        ob.launch(("e", 4), 0.2)                    # still same episode
    assert storms.value == before + 1               # once per episode
    assert ob.sigs[("e", 1)]["n"] == 2
    assert any("recompile storm" in r.message for r in caplog.records)
    snap = ob.snapshot()
    assert len(snap) == 4 and all("compile_s" in v for v in snap.values())


def test_recompile_storm_fires_on_unquantized_signature_churn(
        traced, monkeypatch, caplog):
    """ISSUE 10 acceptance: churning an UNQUANTIZED value through a jit
    static boundary (here: a per-request batch size — exactly what
    pow2_bucket exists to prevent) must fire the storm alarm via the
    real model-layer launch hooks."""
    from distributed_bitcoinminer_tpu.models import NonceSearcher
    monkeypatch.setenv("DBM_TRACE_STORM_N", "4")
    storms = process_registry().counter("trace.recompile_storms")
    before = storms.value
    with caplog.at_level(logging.WARNING, logger="dbm.trace"):
        for batch in (193, 197, 199, 211, 223):     # unquantized churn
            NonceSearcher("storm", batch=batch, tier="jnp").search(
                100, 160)
    assert storms.value > before
    assert any("recompile storm" in r.message for r in caplog.records)


def test_observe_launch_off_is_one_bool_check(monkeypatch):
    monkeypatch.setenv("DBM_TRACE", "0")
    monkeypatch.setattr(trace, "_observer", None)
    with trace.observe_launch(("e", 1)) as ob:
        pass
    assert ob.compile_s is None
    assert trace._observer is None          # never constructed


# ------------------------------------------------------------------ tracks


def test_trackset_ids_retire_and_overflow_bound():
    ts = trace.TrackSet(max_tracks=2)
    a = ts.track("trace_track", miner="1")
    assert ts.track("trace_track", miner="1") == a   # stable
    b = ts.track("trace_track", miner="2")
    assert b != a
    c = ts.track("trace_track", miner="3")           # past bound
    assert c == ts.track("trace_track", miner="4")   # collapsed together
    # The overflow track holds a slot (Registry discipline): one retire
    # is not enough to mint a fresh track while it lives...
    ts.retire("trace_track", miner="1")
    assert ts.track("trace_track", miner="5") == c
    # ...but retiring the overflow track itself frees real room.
    ts.retire("trace_track", overflow="true")
    d = ts.track("trace_track", miner="6")
    assert d not in (a, b, c)
    assert dict(ts.items("trace_track")).keys() >= {
        (("miner", "2"),), (("miner", "6"),)}


def test_scheduler_retires_tracks_on_miner_and_client_drop(traced):
    sched, _server = make_traced_scheduler()
    join(sched, MINER_A)
    request(sched, CLIENT_X, "tracked", 99)
    sched._on_result(MINER_A, Message.from_json(
        new_result(7, 1, span=dict(SPAN)).to_json()))
    labels = [dict(k) for k, _ in sched._tracks.items("trace_track")]
    assert {"miner": str(MINER_A)} in labels
    assert {"tenant": str(CLIENT_X)} in labels
    sched._on_drop(MINER_A)
    sched._on_drop(CLIENT_X)
    assert sched._tracks.items("trace_track") == []


# ------------------------------------------------------------------ export

#: Every exported event draws from this key set (golden contract).
_EVENT_KEYS = {"name", "ph", "pid", "tid", "ts", "dur", "args", "s"}


def _scripted_export():
    sched, _server = make_traced_scheduler()
    join(sched, MINER_A)
    join(sched, MINER_B)
    request(sched, CLIENT_X, "golden", 199)          # 2 chunks
    sched._on_result(MINER_A, Message.from_json(
        new_result(9, 5, span=dict(SPAN)).to_json()))
    sched._on_result(MINER_B, Message.from_json(
        new_result(7, 150, span=dict(SPAN, launch=4, lanes=2,
                                     gap_s=0.01)).to_json()))
    return sched


def test_export_golden_format(traced):
    sched = _scripted_export()
    doc = sched.export_trace()
    json.loads(json.dumps(doc))                     # valid JSON
    assert set(doc) == {"traceEvents", "displayTimeUnit"}
    events = doc["traceEvents"]
    assert events, "empty export"
    per_track = {}
    for e in events:
        assert set(e) <= _EVENT_KEYS | {"args"}
        assert {"name", "ph", "pid"} <= set(e)
        if e["ph"] == "M":
            continue
        assert isinstance(e["ts"], int)
        per_track.setdefault((e["pid"], e["tid"]), []).append(e["ts"])
        if e["ph"] == "X":
            assert e["dur"] >= 0
    for track, tss in per_track.items():
        assert tss == sorted(tss), f"non-monotonic ts on track {track}"
    # One track per role: scheduler/tenant + miners, with thread names.
    names = {(e["pid"], e["args"]["name"]) for e in events
             if e["name"] == "thread_name"}
    assert (1, f"tenant {CLIENT_X}") in names
    assert (2, f"miner {MINER_A}") in names and \
        (2, f"miner {MINER_B}") in names
    # The request decomposes: queued + request slices on the tenant
    # track, per-phase slices (with launch args) on the miner tracks.
    by_name = {}
    for e in events:
        by_name.setdefault(e["name"], []).append(e)
    assert "queued" in by_name and "request 1" in by_name
    for phase in ("queue", "dispatch", "wait", "force"):
        assert phase in by_name, f"missing {phase} slice"
    launches = {e["args"].get("launch") for e in by_name["force"]}
    assert launches == {3, 4}
    # Layout pin (code review): gap is idle time BEFORE the chunk — it
    # renders FIRST on its track, and force abuts the fold stamp (no
    # phantom post-force stall).
    assert "gap" in by_name
    gap = by_name["gap"][0]
    force = next(e for e in by_name["force"]
                 if e["tid"] == gap["tid"])
    assert gap["ts"] + gap["dur"] <= force["ts"]


def test_export_writes_file(traced, tmp_path):
    sched = _scripted_export()
    out = tmp_path / "trace.json"
    doc = sched.export_trace(str(out))
    assert json.loads(out.read_text()) == json.loads(
        json.dumps(doc, sort_keys=True))


def test_dbmtrace_convert_cli(traced, tmp_path):
    sched = _scripted_export()
    dump = tmp_path / "dump.jsonl"
    lines = []
    for _key, t in sched.traces.items():
        lines.append(json.dumps(t.to_dict(), sort_keys=True))
    # One raw dict line, one alarm-style log line, one junk line, and a
    # TRUNCATED dump line (log rotation mid-write) with the marker but
    # no payload separator — skipped, never a crash (code review).
    lines.append("trace dump (queue-age alarm: stalled request): "
                 + lines[0])
    lines.append("not json at all")
    lines.append("trace dump (queue-age alarm: stalled requ")
    dump.write_text("\n".join(lines) + "\n")
    sys.path.insert(0, "scripts")
    try:
        import dbmtrace
    finally:
        sys.path.pop(0)
    out = tmp_path / "out.json"
    assert dbmtrace.main(["convert", str(dump), "-o", str(out)]) == 0
    doc = json.loads(out.read_text())
    assert doc["traceEvents"]
    phases = [e["name"] for e in doc["traceEvents"] if e["ph"] == "X"]
    assert "force" in phases


# ------------------------------------------------------------ profiling fix


def test_timer_tolerates_misuse_before_enter():
    from distributed_bitcoinminer_tpu.utils.profiling import Timer
    t = Timer()
    assert t.rate(100) == 0.0       # no TypeError
    t.__exit__(None, None, None)    # no TypeError
    assert t.seconds == 0.0
    with t:
        time.sleep(0.01)
    assert t.seconds > 0 and t.rate(10) > 0


def test_xprof_dir_knob_routing(monkeypatch, tmp_path):
    from distributed_bitcoinminer_tpu.utils.profiling import (device_trace,
                                                              xprof_dir)
    monkeypatch.delenv("DBM_TRACE_XPROF", raising=False)
    assert xprof_dir() is None and xprof_dir("jnp") is None
    with device_trace():            # env unset: no-op, no jax import
        pass
    monkeypatch.setenv("DBM_TRACE_XPROF", str(tmp_path))
    assert xprof_dir() == str(tmp_path)
    assert xprof_dir("jnp") == str(tmp_path / "jnp")


# -------------------------------------------------------------------- e2e


def _miner_with_fake_client(monkeypatch, trace_on: bool):
    from distributed_bitcoinminer_tpu.apps.miner import MinerWorker

    monkeypatch.setenv("DBM_TRACE", "1" if trace_on else "0")

    class FakeClient:
        def __init__(self):
            self.writes = []

        def write(self, payload):
            self.writes.append(payload)

    class TwoPhase:
        def __init__(self, data):
            self.data = data

        def search(self, lower, upper):
            from distributed_bitcoinminer_tpu.bitcoin.hash import scan_min
            return scan_min(self.data, lower, upper)

        def dispatch(self, lower, upper):
            return (lower, upper)

        def finalize(self, handle, lower):
            return self.search(*handle)

    w = MinerWorker("127.0.0.1:1", searcher_factory=lambda d, b: TwoPhase(d))
    w.client = FakeClient()
    return w


def test_trace_zero_parity_pin(monkeypatch):
    """DBM_TRACE=0: byte-identical Results (no Span key anywhere) and
    the zero-overhead paths — no span skeletons, no recv stamps, no
    fold. DBM_TRACE=1 on the identical chunk: same answer bytes except
    the Span extension, whose payload stays inside the vocabulary."""
    msg = Message.from_json(new_request("parity", 0, 99).to_json())

    async def serve(w, m):
        t0 = time.monotonic()
        searcher, handle, dispatch_s, span = w._resolve_and_dispatch(m)
        assert await w._finalize_and_reply(m, searcher, handle, t0,
                                           dispatch_s, span)
        return w.client.writes

    off = _miner_with_fake_client(monkeypatch, trace_on=False)
    assert off._trace is False
    assert off._span_open(msg) is None
    off_writes = asyncio.run(serve(off, msg))

    on = _miner_with_fake_client(monkeypatch, trace_on=True)
    on_writes = asyncio.run(serve(on, Message.from_json(
        new_request("parity", 0, 99).to_json())))

    assert len(off_writes) == len(on_writes) == 1
    assert b"Span" not in off_writes[0]
    off_msg = Message.from_json(off_writes[0])
    on_msg = Message.from_json(on_writes[0])
    assert (off_msg.hash, off_msg.nonce) == (on_msg.hash, on_msg.nonce)
    assert on_msg.span is not None
    assert set(on_msg.span) <= set(trace.SPAN_PHASES
                                   + trace.SPAN_EXTRAS)
    for k in ("queue_s", "dispatch_s", "wait_s", "force_s"):
        assert k in on_msg.span
    # Stripping the Span extension reproduces the stock bytes exactly.
    on_msg.span = None
    assert on_msg.to_json() == off_writes[0]


def test_wedged_miner_stall_attributed_to_phase(traced):
    """ISSUE 10 acceptance (scripted e2e): a wedged miner's chunk blows
    its lease, the re-issue rescues the request, and the wedged miner's
    LATE stale Result — carrying its span — stitches into the closed
    trace naming the miner-side phase that stalled (the blocking
    compute: force)."""
    from distributed_bitcoinminer_tpu.apps.client import submit
    from tests.test_chaos import ChaosCluster, expected, tight_lease

    async def scenario():
        async with ChaosCluster(lease=tight_lease()) as c:
            wedged = await c.add_miner("wedged")
            await c.add_miner("healthy")
            wedged_conn = wedged.conn_id
            wedged.wedge()
            r = await asyncio.wait_for(
                submit(c.hostport, "stalls", 799, c.params), 30)
            assert r == expected("stalls", 799)
            wedged.unwedge()
            assert await c.settle()
            # The late stale Result has now popped: its span is stitched
            # into the (closed) trace and names the stalled phase.
            s = c.scheduler
            for _ in range(100):
                events = s.trace(1).to_dict()["events"]
                spans = [e for e in events if e["event"] == "miner_span"
                         and e["miner"] == wedged_conn]
                if spans:
                    break
                await asyncio.sleep(0.02)
            assert spans, "wedged miner's span never stitched"
            stalled = spans[-1]
            assert stalled["slow"] == "force"
            assert stalled["force_s"] > 0.3      # the wedge, not noise
            assert stalled.get("serial") == 1    # blocking compute path
            # The healthy rescue also stitched (order-independent).
            others = [e for e in events if e["event"] == "miner_span"
                      and e["miner"] != wedged_conn]
            assert others
            assert s.trace(1).closed
            doc = s.export_trace()
            slows = {e["args"].get("slow")
                     for e in doc["traceEvents"] if e.get("args")}
            assert "force" in slows
    asyncio.run(scenario())


def test_e2e_pipelined_spans_stitch_and_flight_records(traced):
    """Happy-path e2e over real localhost LSP: every chunk of a served
    request carries a span (two-phase pipelined path), the stitched
    trace closes, and the scheduler's flight ring holds the
    dispatch/assign/reply edges."""
    from distributed_bitcoinminer_tpu.apps.client import submit
    from tests.test_chaos import ChaosCluster, expected

    async def scenario():
        async with ChaosCluster() as c:
            await c.add_miner("a")
            await c.add_miner("b")
            r = await asyncio.wait_for(
                submit(c.hostport, "traced e2e", 999, c.params), 30)
            assert r == expected("traced e2e", 999)
            s = c.scheduler
            events = s.trace(1).to_dict()["events"]
            spans = [e for e in events if e["event"] == "miner_span"]
            answered = len([e for e in events if e["event"] == "result"])
            assert len(spans) == answered >= 2    # one span per chunk
            for e in spans:
                assert e["queue_s"] >= 0 and e["force_s"] >= 0
            flight = {e["event"] for e in trace.flight_recorder().events()}
            assert {"dispatch", "assign", "reply"} <= flight
    asyncio.run(scenario())
