"""Chaos property suite: end-to-end invariants under scripted fault storms.

Exercises the robustness plane across all four layers at once — scheduler
chunk leases + speculative re-issue + quarantine, client submit-with-retry,
the lspnet per-conn partition primitive, and the seeded schedule runner in
``lspnet/chaos.py`` — over real localhost UDP.

Invariants asserted (module docstring of lspnet/chaos.py):
- every submitted request is eventually answered with the TRUE arg-min
  (checked against the host oracle);
- no Result is delivered twice on any client connection;
- after the storm heals, the pool converges back to all-available with
  nothing queued, parked, or in flight.
"""

import asyncio
import statistics
import time

import pytest

from distributed_bitcoinminer_tpu.apps.client import (submit, submit_until,
                                                      submit_with_retry)
from distributed_bitcoinminer_tpu.apps.scheduler import Scheduler
from distributed_bitcoinminer_tpu.bitcoin.hash import scan_min
from distributed_bitcoinminer_tpu.bitcoin.message import (Message, MsgType,
                                                          new_request)
from distributed_bitcoinminer_tpu.lsp import Params
from distributed_bitcoinminer_tpu.lsp.client import new_async_client
from distributed_bitcoinminer_tpu.lsp.server import new_async_server
from distributed_bitcoinminer_tpu.lspnet import chaos, partition_conn
from distributed_bitcoinminer_tpu.utils.config import (LeaseParams,
                                                       RetryParams)


@pytest.fixture(autouse=True)
def _sanitize_armed(monkeypatch):
    """ISSUE 7: this suite runs with the runtime sanitizer armed — its
    wedges, kills, and concurrent dispatch are exactly the paths the
    loop-stall watchdog and thread-ownership assertions sweep.
    Violations warn and count, never fail a test; the watchdog is
    uninstalled afterwards so timing-sensitive suites see stock
    callbacks.

    ISSUE 10: the flight recorder rides along (DBM_TRACE=1, overriding
    a matrix leg's DBM_TRACE=0 for THIS suite's wedge/kill storms) so
    every chaos run exercises ring recording + the alarm/sanitizer
    dump paths under real faults — dumps are log lines, never
    failures."""
    from distributed_bitcoinminer_tpu.utils import sanitize, trace
    monkeypatch.setenv("DBM_SANITIZE", "1")
    monkeypatch.setenv("DBM_TRACE", "1")
    trace.ensure_tracer()
    yield
    sanitize.uninstall_watchdog()


def chaos_params(epoch_ms=40, limit=4, window=5):
    return Params(epoch_limit=limit, epoch_millis=epoch_ms,
                  window_size=window, max_backoff_interval=2)


def tight_lease(quarantine_after=3):
    """Sub-second leases so a wedged miner is caught within a test run."""
    return LeaseParams(grace_s=0.6, factor=4.0, floor_s=0.3, tick_s=0.05,
                      quarantine_after=quarantine_after, ewma_alpha=0.5)


class OracleSearcher:
    """Pure-host oracle with a small fixed delay (creates race windows)."""

    def __init__(self, data: str, delay: float = 0.0):
        self.data = data
        self.delay = delay

    def search(self, lower: int, upper: int):
        if self.delay:
            time.sleep(self.delay)
        return scan_min(self.data, lower, upper)


def oracle_factory(delay: float = 0.0):
    return lambda data, batch: OracleSearcher(data, delay)


def expected(data, max_nonce):
    # The system scans [0, maxNonce+1] (reference bound quirk).
    return scan_min(data, 0, max_nonce + 1)


class ChaosCluster:
    """Scheduler + ChaosMiner pool wired for fault-injection tests."""

    def __init__(self, params=None, lease=None):
        self.params = params or chaos_params()
        self.lease = lease or tight_lease()
        self.server = None
        self.scheduler = None
        self.miners = {}
        self._sched_task = None

    async def __aenter__(self):
        self.server = await new_async_server(0, self.params)
        self.scheduler = Scheduler(self.server, lease=self.lease)
        self._sched_task = asyncio.create_task(self.scheduler.run())
        return self

    async def __aexit__(self, *exc):
        for m in self.miners.values():
            await m.close()
        self._sched_task.cancel()
        await self.server.close()

    @property
    def hostport(self):
        return f"127.0.0.1:{self.server.port}"

    async def add_miner(self, name, delay=0.02, factory=None, **kw):
        m = chaos.ChaosMiner(self.hostport, params=self.params,
                             searcher_factory=factory or
                             oracle_factory(delay),
                             name=name, **kw)
        await m.start()
        # The JOIN rides an async datagram; wait until the scheduler has
        # registered the miner so tests split work deterministically.
        for _ in range(200):
            if self.scheduler._find_miner(m.conn_id) is not None:
                break
            await asyncio.sleep(0.01)
        self.miners[name] = m
        return m

    def miner_state(self, name):
        """Scheduler-side MinerState of a named miner (None if dropped)."""
        return self.scheduler._find_miner(self.miners[name].conn_id)

    async def settle(self, timeout=8.0):
        """Wait until the pool is quiescent: nothing in flight, queued, or
        parked, and every tracked miner is available again."""
        deadline = asyncio.get_running_loop().time() + timeout
        while asyncio.get_running_loop().time() < deadline:
            s = self.scheduler
            if (s.current is None and not s.queue and not s.parked
                    and s.miners and all(m.available for m in s.miners)):
                return True
            await asyncio.sleep(0.02)
        return False


def test_wedged_miner_lease_reissue_completes():
    """ISSUE acceptance: a miner whose LSP heartbeats but whose compute is
    hung stalls its chunk; the lease expires, the chunk is speculatively
    re-issued, and the client still gets the true arg-min — the scenario
    the reference's epoch-limit-only fault detection can never resolve."""
    async def scenario():
        async with ChaosCluster() as c:
            wedged = await c.add_miner("wedged")
            await c.add_miner("healthy")
            wedged.wedge()                     # compute hangs; LSP lives
            result = await asyncio.wait_for(
                submit(c.hostport, "straggler", 799, c.params), 20)
            assert result == expected("straggler", 799)
            assert c.scheduler.stats["reissues"] >= 1
            assert c.scheduler.stats["leases_blown"] >= 1
            # The wedged miner was NEVER dropped: its transport is healthy,
            # only its compute is stuck — epoch detection alone could not
            # have saved this request.
            assert c.miner_state("wedged") is not None
            wedged.unwedge()                   # release the stale compute
            assert await c.settle()
    asyncio.run(scenario())


def test_wedged_miner_quarantined_then_lifted_on_answer():
    """A repeat offender is excluded from new assignments; its eventual
    (stale) answer lifts the quarantine."""
    async def scenario():
        async with ChaosCluster(lease=tight_lease(quarantine_after=1)) as c:
            wedged = await c.add_miner("wedged")
            await c.add_miner("healthy")
            wedged.wedge()
            r1 = await asyncio.wait_for(
                submit(c.hostport, "first storm", 399, c.params), 20)
            assert r1 == expected("first storm", 399)
            ms = c.miner_state("wedged")
            assert ms is not None and ms.quarantined
            assert c.scheduler.stats["quarantines"] >= 1
            # The next request must be served WITHOUT the quarantined
            # miner: its pool split excludes it.
            r2 = await asyncio.wait_for(
                submit(c.hostport, "second wind", 299, c.params), 20)
            assert r2 == expected("second wind", 299)
            assert all(ch.job_id != c.scheduler._next_job_id
                       for ch in ms.pending)
            wedged.unwedge()
            # The stale compute now finishes and its Result pops: any
            # answer lifts the quarantine.
            for _ in range(300):
                ms = c.miner_state("wedged")
                if ms is not None and not ms.quarantined:
                    break
                await asyncio.sleep(0.02)
            assert ms is not None and not ms.quarantined
            assert await c.settle()
    asyncio.run(scenario())


def test_no_double_result_on_speculation_race():
    """Both the wedged original and the re-issued copy eventually answer;
    the client must see exactly ONE Result (the loser pops as a stale
    duplicate inside the scheduler)."""
    async def scenario():
        async with ChaosCluster() as c:
            wedged = await c.add_miner("wedged")
            await c.add_miner("healthy")
            wedged.wedge()
            client = await new_async_client(c.hostport, c.params)
            client.write(new_request("race", 0, 599).to_json())
            reply = Message.from_json(await asyncio.wait_for(
                client.read(), 20))
            assert reply.type == MsgType.RESULT
            assert (reply.hash, reply.nonce) == expected("race", 599)
            wedged.unwedge()    # the loser now computes and answers
            # Poll until the loser's Result pops server-side (its FIFO
            # drains; the pop is identified as stale/duplicate and
            # dropped), keeping the client conn open the whole time...
            try:
                for _ in range(300):
                    ms = c.miner_state("wedged")
                    if ms is not None and not ms.pending:
                        break
                    await asyncio.sleep(0.02)
                assert ms is not None and not ms.pending
                # ...and assert NOTHING ELSE was delivered on this conn.
                with pytest.raises(asyncio.TimeoutError):
                    await asyncio.wait_for(client.read(), 0.7)
            finally:
                await client.close()
            assert await c.settle()
            assert c.scheduler.stats["results_sent"] == 1
    asyncio.run(scenario())


def test_one_sided_partition_declares_miner_lost_and_recovers():
    """Server goes deaf to one miner (inbound partition) while the miner
    still hears the server: the epoch timer declares it lost, its chunk is
    recovered, and the request completes."""
    async def scenario():
        async with ChaosCluster() as c:
            doomed = await c.add_miner("doomed", delay=0.5)
            await c.add_miner("healthy")
            pending = asyncio.create_task(
                submit(c.hostport, "split brain", 399, c.params))
            await asyncio.sleep(0.25)          # both miners hold chunks
            partition_conn(doomed.conn_id, inbound=True, outbound=False)
            result = await asyncio.wait_for(pending, 20)
            assert result == expected("split brain", 399)
            # The one-sided victim's conn was dropped server-side.
            assert c.miner_state("doomed") is None
            await doomed.close()
            del c.miners["doomed"]
            assert await c.settle()
    asyncio.run(scenario())


def test_client_retry_across_scheduler_restart():
    """submit_with_retry reconnects and resubmits after the scheduler dies
    mid-request and a fresh one takes over the same port: the restart
    degrades to latency, not a hang or Disconnected."""
    async def scenario():
        params = chaos_params()
        server1 = await new_async_server(0, params)
        port = server1.port
        sched1 = Scheduler(server1, lease=tight_lease())
        t1 = asyncio.create_task(sched1.run())
        m1 = chaos.ChaosMiner(f"127.0.0.1:{port}", params=params,
                              searcher_factory=oracle_factory(0.4),
                              name="m1")
        await m1.start()
        pending = asyncio.create_task(submit_with_retry(
            f"127.0.0.1:{port}", "nine lives", 499, params=params,
            retry=RetryParams(attempts=6, timeout_s=5.0, backoff_s=0.2,
                              backoff_cap_s=1.0)))
        await asyncio.sleep(0.25)              # request is in flight
        t1.cancel()
        await server1.close()                  # coordinator crash
        await m1.close()                       # its pool dies with it
        server2 = await new_async_server(port, params)   # same port
        sched2 = Scheduler(server2, lease=tight_lease())
        t2 = asyncio.create_task(sched2.run())
        m2 = chaos.ChaosMiner(f"127.0.0.1:{port}", params=params,
                              searcher_factory=oracle_factory(0.02),
                              name="m2")
        await m2.start()
        try:
            result = await asyncio.wait_for(pending, 30)
            assert result is not None
            h, n, found = result
            assert (h, n) == expected("nine lives", 499)
            assert not found                   # no target requested
            assert sched2.stats["results_sent"] == 1
        finally:
            await m2.close()
            t2.cancel()
            await server2.close()
    asyncio.run(scenario())


def test_client_retry_difficulty_target_mode():
    """Retry path preserves submit_until semantics: found iff the answer
    beats the target."""
    async def scenario():
        async with ChaosCluster() as c:
            await c.add_miner("solo")
            target = 1 << 60                   # loose: guaranteed hit
            got = await asyncio.wait_for(submit_with_retry(
                c.hostport, "difficulty", 2999, target, c.params,
                RetryParams(attempts=3, timeout_s=10.0)), 30)
            assert got is not None
            h, n, found = got
            assert found and h < target
            ref = await asyncio.wait_for(
                submit_until(c.hostport, "difficulty", 2999, target,
                             c.params), 30)
            assert ref is not None and ref[2]
            assert await c.settle()
    asyncio.run(scenario())


@pytest.mark.parametrize("seed", [5, 17])
def test_seeded_chaos_difficulty_storm_first_qualifying(seed):
    """Chaos coverage for difficulty mode (ROADMAP open item): a seeded
    self-healing storm (wedges -> lease blow + speculative re-issue,
    kills -> epoch drop + chunk recovery, packet delay) rides over an
    all-until pool while clients drive ``search_until`` requests through
    ``submit_with_retry``. Invariants, per request:

    - the answer is EXACTLY the host oracle's first-qualifying nonce over
      the scanned range [0, max+1] (or the exact arg-min fallback when
      the target is unreachable) — wedged stragglers, re-issued copies,
      prefix releases, and retry resubmissions never change the merge;
    - the pool converges to quiescent after the storm heals.

    Retried resubmissions of an already-answered request replay from the
    scheduler's result memo — the cache satellite under the same storm.
    """
    from distributed_bitcoinminer_tpu.bitcoin.hash import scan_until
    from tests.test_difficulty import until_factory

    async def scenario():
        chaos.seed_packet_faults(seed)
        async with ChaosCluster(lease=tight_lease(quarantine_after=3)) as c:
            for name in ("alpha", "beta", "gamma"):
                await c.add_miner(name, factory=until_factory(0.02))
            schedule = chaos.generate_schedule(
                seed, 3.0, list(c.miners), episodes=4, max_percent=20,
                kinds=("wedge", "kill", "delay"))
            storm = asyncio.create_task(
                chaos.run_schedule(schedule, c.miners))
            #              (data, max_nonce, target)
            jobs = [("until storm one", 1499, 1 << 59),   # quick hit
                    ("until storm two", 1999, 1 << 58),   # deeper hit
                    ("until storm three", 899, 1)]        # miss -> argmin
            retry = RetryParams(attempts=8, timeout_s=2.5, backoff_s=0.1,
                                backoff_cap_s=0.5)
            try:
                for data, max_nonce, target in jobs:
                    got = await asyncio.wait_for(submit_with_retry(
                        c.hostport, data, max_nonce, target, c.params,
                        retry), 40)
                    assert got is not None, f"{data} never answered"
                    want = scan_until(data, 0, max_nonce + 1, target)
                    assert got == want, (data, got, want)
            finally:
                await asyncio.wait_for(storm, 20)
            assert await c.settle(timeout=12.0)
            # All miners speak the extension: the merge was never weak.
            assert c.scheduler.current is None
    asyncio.run(scenario())


@pytest.mark.parametrize("seed", [11, 23])
def test_seeded_chaos_schedule_invariants(seed):
    """The headline property test: a seeded self-healing fault storm
    (kills, wedges, one-sided partitions, drop/delay knobs) rides over a
    3-miner pool while clients keep submitting; every request must come
    back with the oracle arg-min, exactly one Result per request, and the
    pool must converge to all-available after the storm."""
    async def scenario():
        chaos.seed_packet_faults(seed)
        async with ChaosCluster(lease=tight_lease(quarantine_after=3)) as c:
            for name in ("alpha", "beta", "gamma"):
                await c.add_miner(name, delay=0.02)
            schedule = chaos.generate_schedule(
                seed, 3.0, list(c.miners), episodes=5, max_percent=25)
            assert schedule == chaos.generate_schedule(
                seed, 3.0, list(c.miners), episodes=5,
                max_percent=25)        # determinism: same seed, same storm
            storm = asyncio.create_task(
                chaos.run_schedule(schedule, c.miners))
            jobs = [("storm one", 399), ("storm two", 499),
                    ("storm three", 299), ("storm four", 449)]
            retry = RetryParams(attempts=8, timeout_s=2.5, backoff_s=0.1,
                                backoff_cap_s=0.5)
            try:
                for data, max_nonce in jobs:
                    got = await asyncio.wait_for(submit_with_retry(
                        c.hostport, data, max_nonce, 0, c.params, retry),
                        40)
                    # Eventual answer, and the TRUE arg-min: re-issued and
                    # retried work never changes the merge.
                    assert got is not None, f"{data} never answered"
                    assert got[:2] == expected(data, max_nonce)
            finally:
                await asyncio.wait_for(storm, 20)
            # Post-storm convergence: all healed, nothing in flight.
            assert await c.settle(timeout=12.0)
            assert c.scheduler.queue == []
            assert c.scheduler.parked == []
    asyncio.run(scenario())


@pytest.mark.parametrize("seed", [29])
def test_seeded_byzantine_storm_mixed_with_faults(seed):
    """Verification tier under crash-fault pressure (ISSUE 16): a seeded
    storm draws from BYZ_EPISODES' byzantine turn-coat episode PLUS
    wedges and packet delay, over a pool where two miners carry lie
    modes (one fabricates hashes, one returns real-but-unscanned
    sentinels) and one is honest. Claim checks, reply-holding audits,
    and repair merges must keep every answer oracle-exact even while
    leases blow and audits expire on wedged auditors — then the pool
    converges once the schedule heals itself."""
    from distributed_bitcoinminer_tpu.utils.config import VerifyParams

    async def scenario():
        chaos.seed_packet_faults(seed)
        async with ChaosCluster(lease=tight_lease(quarantine_after=3)) as c:
            c.scheduler.verify = VerifyParams(
                enabled=True, audit_p=1.0, audit_max_nonces=1 << 20)
            await c.add_miner("alpha", byzantine="wrong_hash")
            await c.add_miner("beta", byzantine="sentinel")
            await c.add_miner("gamma")         # the honest floor
            schedule = chaos.generate_schedule(
                seed, 3.0, ["alpha", "beta"], episodes=5, max_percent=20,
                kinds=("byzantine", "wedge", "delay"))
            assert any(e.action == "byzantine" for e in schedule)
            storm = asyncio.create_task(
                chaos.run_schedule(schedule, c.miners))
            jobs = [("turncoat one", 399), ("turncoat two", 299),
                    ("turncoat three", 449)]
            retry = RetryParams(attempts=8, timeout_s=2.5, backoff_s=0.1,
                                backoff_cap_s=0.5)
            try:
                for data, max_nonce in jobs:
                    got = await asyncio.wait_for(submit_with_retry(
                        c.hostport, data, max_nonce, 0, c.params, retry),
                        40)
                    assert got is not None, f"{data} never answered"
                    # Never a wrong pair — not even mid-storm.
                    assert got[:2] == expected(data, max_nonce)
            finally:
                await asyncio.wait_for(storm, 20)
            assert await c.settle(timeout=12.0)
            assert c.scheduler.stats["claims_checked"] > 0
            assert c.scheduler.stats["audits_issued"] > 0
    asyncio.run(scenario())


# ------------------------------------------ process-level storms (ISSUE 12)
#
# The faults here are raw OS signals against REAL processes (router +
# replica schedulers on their own LSP sockets + a rejoining miner
# agent); failure detection is SOLELY the router's missed-beat watch —
# no test-hook kill path exists anywhere in the process topology (the
# acceptance criterion that separates this tier from PR 11's
# ReplicaSet.kill()).

PROC_ENV = {"DBM_HEALTH_BEAT_S": "0.15", "DBM_HEALTH_MISS_K": "3",
            "DBM_EPOCH_MILLIS": "200", "DBM_EPOCH_LIMIT": "4",
            "DBM_COMPUTE": "host"}


def proc_params():
    return Params(epoch_limit=4, epoch_millis=200, window_size=8,
                  max_backoff_interval=2)


def test_proc_storm_sigkill_twenty_seeds_exactly_once(tmp_path):
    """THE acceptance storm: >=20 seeded episodes, each SIGKILLing the
    replica that owns the in-flight request, with failover driven
    solely by missed health beats. Every request must complete exactly
    once (the retry plane's one-live-conn contract) and oracle-exact.
    One topology serves all episodes — each heals before the next."""
    from distributed_bitcoinminer_tpu.apps.procs import ProcCluster
    from distributed_bitcoinminer_tpu.lspnet.chaos import (
        generate_proc_storm, run_proc_episode)

    async def scenario():
        cluster = ProcCluster(str(tmp_path), replicas=2, miners=1,
                              env=PROC_ENV)
        cluster.start()
        records = []
        try:
            await cluster.wait_live(2, timeout_s=30.0, miners=1)
            for seed in range(20):
                (ep,) = generate_proc_storm(
                    seed, 1, kinds=("kill_replica",))
                assert generate_proc_storm(
                    seed, 1, kinds=("kill_replica",)) == [ep]  # seeded
                records.append(await run_proc_episode(
                    cluster, ep, proc_params()))
                await cluster.wait_live(2, timeout_s=30.0, miners=1)
        finally:
            cluster.close()
        assert len(records) == 20
        assert all(r["reply"] is not None for r in records)
        # Fence-push handoff (ISSUE 13 satellite): every episode's
        # displaced miner agent was back serving a survivor within the
        # beat-driven window (router detection 3x0.15s + one-beat
        # watcher poll + join), never parked on a long epoch wait —
        # the canary bound is generous for a loaded box, and the
        # discriminating slow-epoch proof lives in
        # test_proc_storm_fence_push_beats_epoch_detection.
        rejoins = [r["rejoin_s"] for r in records]
        assert all(rj is not None for rj in rejoins), rejoins
        assert statistics.median(rejoins) <= 1.5, rejoins
    asyncio.run(scenario())


def test_proc_storm_fence_push_beats_epoch_detection(tmp_path):
    """THE discriminating handoff proof (ISSUE 13 satellite): cluster
    processes run with SLOW LSP epochs (8 x 1s — conn-death detection
    alone would park the displaced agent for ~8s) but the normal fast
    beat cadence. A sub-2.5s rejoin is therefore only reachable
    through the fence-push channel: router fences at ~3 missed beats,
    the agent's membership watcher fires within one beat and closes
    its own transport instead of waiting out the epoch. TWO agents
    (thinnest-slice join puts one on each replica) make every seed
    displace an agent — measure_rejoin waits for the FULL population
    on survivors, so no seed can pass on router fence latency alone."""
    from distributed_bitcoinminer_tpu.apps.procs import ProcCluster
    from distributed_bitcoinminer_tpu.lspnet.chaos import (
        generate_proc_storm, run_proc_episode)
    env = dict(PROC_ENV, DBM_EPOCH_MILLIS="1000", DBM_EPOCH_LIMIT="8")

    async def scenario():
        cluster = ProcCluster(str(tmp_path), replicas=2, miners=2,
                              env=env)
        cluster.start()
        records = []
        try:
            await cluster.wait_live(2, timeout_s=30.0, miners=2)
            # One unasserted WARMUP episode: the very first kill can
            # race the agents' initial join/settle cycle (observed
            # once: an 8.8s first-episode rejoin that never recurs),
            # and this test is about the steady-state handoff path.
            (warm,) = generate_proc_storm(99, 1,
                                          kinds=("kill_replica",))
            await run_proc_episode(cluster, warm, proc_params())
            await cluster.wait_live(2, timeout_s=30.0, miners=2)
            for seed in range(100, 105):
                (ep,) = generate_proc_storm(
                    seed, 1, kinds=("kill_replica",))
                records.append(await run_proc_episode(
                    cluster, ep, proc_params()))
                await cluster.wait_live(2, timeout_s=30.0, miners=2)
        finally:
            cluster.close()
        rejoins = [r["rejoin_s"] for r in records]
        assert all(rj is not None for rj in rejoins), rejoins
        # A broken fence-push parks EVERY episode on the ~8s epoch
        # wait; tolerate at most one load-jitter outlier.
        fast = [rj for rj in rejoins if rj <= 2.5]
        assert len(fast) >= len(rejoins) - 1, rejoins
        assert all(r["reply"] is not None for r in records)
    asyncio.run(scenario())


def test_proc_storm_sigstop_fencing_and_router_kill(tmp_path):
    """The partitioned-but-alive fencing case at PROCESS level, plus a
    router kill mid-request: a SIGSTOPped serving replica is declared
    dead by its frozen beat seq, the reply re-routes to the survivor,
    and on SIGCONT the zombie observes its own fence and exits
    FENCED_EXIT (its late writes fenced everywhere); a killed router
    never interrupts the data path — clients ride the last advertised
    membership — and its restart resumes the SAME fencing epoch."""
    from distributed_bitcoinminer_tpu.apps.procs import (FENCED_EXIT,
                                                         ProcCluster)
    from distributed_bitcoinminer_tpu.lspnet.chaos import (
        generate_proc_storm, run_proc_episode)

    async def scenario():
        cluster = ProcCluster(str(tmp_path), replicas=2, miners=2,
                              env=PROC_ENV)
        cluster.start()
        try:
            await cluster.wait_live(2, timeout_s=30.0, miners=2)
            epoch_before = cluster.membership().epoch
            (stop_ep,) = generate_proc_storm(
                7, 1, kinds=("stop_replica",))
            rec = await run_proc_episode(cluster, stop_ep, proc_params())
            # The woken zombie observed its fence and exited for respawn.
            assert rec["fenced_exit"] == FENCED_EXIT, rec
            m = cluster.membership()
            assert m.fenced and m.epoch > epoch_before
            await cluster.wait_live(2, timeout_s=30.0, miners=2)
            # Router kill mid-request: reply arrives off the last
            # membership; the restarted router resumes the epoch.
            epoch_mid = cluster.membership().epoch
            (rt_ep,) = generate_proc_storm(11, 1, kinds=("kill_router",))
            rec2 = await run_proc_episode(cluster, rt_ep, proc_params())
            assert rec2["reply"] is not None
            for _ in range(100):
                m2 = cluster.membership()
                if m2 is not None and m2.epoch >= epoch_mid:
                    break
                await asyncio.sleep(0.1)
            assert m2.epoch >= epoch_mid     # fencing epoch never regresses
        finally:
            cluster.close()
    asyncio.run(scenario())
