"""Buffering across total network outages.

Port of the reference lsp4_test.go choreography: a "network master" toggles
the write-drop knob between 0%% and 100%% while both sides keep streaming;
every write issued during the outage must arrive after the network heals,
and Close called during an outage must still flush afterwards.
"""

import asyncio

from distributed_bitcoinminer_tpu import lspnet
from distributed_bitcoinminer_tpu.lsp import Params
from distributed_bitcoinminer_tpu.lsp.client import new_async_client
from distributed_bitcoinminer_tpu.lsp.server import new_async_server


def params_with(window=5, backoff=1, epoch_ms=50, limit=60):
    return Params(epoch_limit=limit, epoch_millis=epoch_ms,
                  window_size=window, max_backoff_interval=backoff)


class TestOutageBuffering:
    def test_client_to_server_through_outage(self):
        """Client streams during a dead network (ref TestClientToServer)."""
        async def scenario():
            params = params_with()
            server = await new_async_server(0, params)
            client = await new_async_client(f"127.0.0.1:{server.port}", params)
            n = 30
            lspnet.set_write_drop_percent(100)
            for i in range(n):
                client.write(f"m{i:02d}".encode())
            await asyncio.sleep(0.3)  # outage persists while writes queue
            lspnet.set_write_drop_percent(0)
            got = []
            while len(got) < n:
                _, payload = await asyncio.wait_for(server.read(), 10)
                if isinstance(payload, bytes):
                    got.append(payload)
            assert got == [f"m{i:02d}".encode() for i in range(n)]
            await client.close()
            await server.close()
        asyncio.run(scenario())

    def test_server_to_client_through_outage(self):
        """Server streams during a dead network (ref TestServerToClient)."""
        async def scenario():
            params = params_with()
            server = await new_async_server(0, params)
            client = await new_async_client(f"127.0.0.1:{server.port}", params)
            client.write(b"reg")
            conn_id, _ = await asyncio.wait_for(server.read(), 5)
            n = 30
            lspnet.set_write_drop_percent(100)
            for i in range(n):
                server.write(conn_id, f"s{i:02d}".encode())
            await asyncio.sleep(0.3)
            lspnet.set_write_drop_percent(0)
            got = [await asyncio.wait_for(client.read(), 10) for _ in range(n)]
            assert got == [f"s{i:02d}".encode() for i in range(n)]
            await client.close()
            await server.close()
        asyncio.run(scenario())

    def test_round_trip_through_toggling_network(self):
        """Echo stream while the network flaps (ref TestRoundTrip)."""
        async def scenario():
            params = params_with(window=8)
            server = await new_async_server(0, params)

            async def echo():
                while True:
                    conn_id, item = await server.read()
                    if isinstance(item, bytes):
                        server.write(conn_id, item)
            echo_task = asyncio.create_task(echo())

            async def flapper():
                for _ in range(4):
                    lspnet.set_write_drop_percent(100)
                    await asyncio.sleep(0.15)
                    lspnet.set_write_drop_percent(0)
                    await asyncio.sleep(0.25)
            flap_task = asyncio.create_task(flapper())

            client = await new_async_client(f"127.0.0.1:{server.port}", params)
            n = 40
            for i in range(n):
                client.write(f"rt{i:02d}".encode())
            got = [await asyncio.wait_for(client.read(), 15) for _ in range(n)]
            assert got == [f"rt{i:02d}".encode() for i in range(n)]
            await flap_task
            echo_task.cancel()
            await client.close()
            await server.close()
        asyncio.run(scenario())

    def test_fast_close_during_outage_still_flushes(self):
        """Close while the network is down: flush must complete once it
        heals (ref TestServerFastClose choreography)."""
        async def scenario():
            params = params_with()
            server = await new_async_server(0, params)
            client = await new_async_client(f"127.0.0.1:{server.port}", params)
            n = 10
            lspnet.set_write_drop_percent(100)
            for i in range(n):
                client.write(f"f{i}".encode())

            async def heal_later():
                await asyncio.sleep(0.4)
                lspnet.set_write_drop_percent(0)
            heal_task = asyncio.create_task(heal_later())
            await asyncio.wait_for(client.close(), 15)  # blocks through outage
            await heal_task
            got = []
            while len(got) < n:
                _, payload = await asyncio.wait_for(server.read(), 10)
                if isinstance(payload, bytes):
                    got.append(payload)
            assert got == [f"f{i}".encode() for i in range(n)]
            await server.close()
        asyncio.run(scenario())
