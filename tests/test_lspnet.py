"""Simulated-network tests: UDP endpoints, fault knobs, sniffer.

Covers the seven knob behaviors the 44-test LSP suite depends on
(ref: lspnet/staff.go, lspnet/conn.go, lspnet/sniff.go).
"""

import asyncio

from distributed_bitcoinminer_tpu import lspnet
from distributed_bitcoinminer_tpu.lsp.message import new_ack, new_data
from distributed_bitcoinminer_tpu.lsp.checksum import make_checksum


def _data_packet(payload=b"1234", conn_id=1, seq=1):
    return new_data(conn_id, seq, len(payload),
                    payload, make_checksum(conn_id, seq, len(payload), payload)).to_json()


async def _pair():
    server = await lspnet.listen_udp()
    client = await lspnet.dial_udp("127.0.0.1", server.sockname[1])
    return server, client


def test_basic_roundtrip():
    async def scenario():
        server, client = await _pair()
        client.send(_data_packet(b"ping"))
        raw, addr = await asyncio.wait_for(server.recv(), 2)
        assert b"ping" not in raw  # payload is base64 on the wire
        server.send(_data_packet(b"pong"), addr)
        raw2, _ = await asyncio.wait_for(client.recv(), 2)
        assert raw2 == _data_packet(b"pong")
        server.close()
        client.close()
    asyncio.run(scenario())


def test_write_drop_100_percent():
    async def scenario():
        server, client = await _pair()
        lspnet.set_client_write_drop_percent(100)
        client.send(_data_packet())
        with_timeout = asyncio.wait_for(server.recv(), 0.3)
        try:
            await with_timeout
            raise AssertionError("packet should have been dropped")
        except asyncio.TimeoutError:
            pass
        # Server side unaffected: client still receives.
        lspnet.set_client_write_drop_percent(0)
        client.send(_data_packet(b"probe"))
        _, addr = await asyncio.wait_for(server.recv(), 2)
        server.send(_data_packet(b"back"), addr)
        await asyncio.wait_for(client.recv(), 2)
        server.close()
        client.close()
    asyncio.run(scenario())


def test_read_drop_applies_per_side():
    async def scenario():
        server, client = await _pair()
        lspnet.set_server_read_drop_percent(100)
        client.send(_data_packet())
        try:
            await asyncio.wait_for(server.recv(), 0.3)
            raise AssertionError("server read should have dropped")
        except asyncio.TimeoutError:
            pass
        lspnet.set_server_read_drop_percent(0)
        client.send(_data_packet())
        await asyncio.wait_for(server.recv(), 2)
        server.close()
        client.close()
    asyncio.run(scenario())


def test_shortening_halves_payload_keeps_size():
    async def scenario():
        server, client = await _pair()
        lspnet.set_msg_shortening_percent(100)
        client.send(_data_packet(b"123456"))
        raw, _ = await asyncio.wait_for(server.recv(), 2)
        from distributed_bitcoinminer_tpu.lsp.message import Message
        msg = Message.from_json(raw)
        assert msg.size == 6          # header untouched
        assert len(msg.payload) == 3  # payload halved
        server.close()
        client.close()
    asyncio.run(scenario())


def test_lengthening_appends_bytes():
    async def scenario():
        server, client = await _pair()
        lspnet.set_msg_lengthening_percent(100)
        client.send(_data_packet(b"1234"))
        raw, _ = await asyncio.wait_for(server.recv(), 2)
        from distributed_bitcoinminer_tpu.lsp.message import Message
        msg = Message.from_json(raw)
        assert msg.size == 4
        assert len(msg.payload) == 7 and msg.payload[4:] == bytes([2, 3, 4])
        server.close()
        client.close()
    asyncio.run(scenario())


def test_corruption_flips_first_byte():
    async def scenario():
        server, client = await _pair()
        lspnet.set_msg_corrupted(True)
        client.send(_data_packet(b"1234"))
        raw, _ = await asyncio.wait_for(server.recv(), 2)
        from distributed_bitcoinminer_tpu.lsp.message import Message
        msg = Message.from_json(raw)
        assert msg.payload[0] == ord("1") ^ 0xFF
        assert msg.payload[1:] == b"234"
        server.close()
        client.close()
    asyncio.run(scenario())


def test_acks_never_mutated():
    async def scenario():
        server, client = await _pair()
        lspnet.set_msg_corrupted(True)
        lspnet.set_msg_shortening_percent(100)
        packet = new_ack(1, 5).to_json()
        client.send(packet)
        raw, _ = await asyncio.wait_for(server.recv(), 2)
        assert raw == packet
        server.close()
        client.close()
    asyncio.run(scenario())


def test_delay_defers_delivery():
    async def scenario():
        server, client = await _pair()
        lspnet.set_delay_message_percent(100)
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        client.send(_data_packet())
        await asyncio.wait_for(server.recv(), 2)
        assert loop.time() - t0 >= 0.45
        server.close()
        client.close()
    asyncio.run(scenario())


def test_sniffer_counts_sent_and_dropped():
    async def scenario():
        server, client = await _pair()
        lspnet.start_sniff()
        for _ in range(5):
            client.send(_data_packet())
        client.send(new_ack(1, 1).to_json())
        lspnet.set_client_write_drop_percent(100)
        for _ in range(3):
            client.send(_data_packet())
        await asyncio.sleep(0.1)
        result = lspnet.stop_sniff()
        assert result.num_sent_data == 5
        assert result.num_dropped_data == 3
        assert result.num_sent_acks == 1
        server.close()
        client.close()
    asyncio.run(scenario())
