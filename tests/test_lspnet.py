"""Simulated-network tests: UDP endpoints, fault knobs, sniffer.

Covers the seven knob behaviors the 44-test LSP suite depends on
(ref: lspnet/staff.go, lspnet/conn.go, lspnet/sniff.go).
"""

import asyncio

from distributed_bitcoinminer_tpu import lspnet
from distributed_bitcoinminer_tpu.lsp.message import new_ack, new_data
from distributed_bitcoinminer_tpu.lsp.checksum import make_checksum


def _data_packet(payload=b"1234", conn_id=1, seq=1):
    return new_data(conn_id, seq, len(payload),
                    payload, make_checksum(conn_id, seq, len(payload), payload)).to_json()


async def _pair():
    server = await lspnet.listen_udp()
    client = await lspnet.dial_udp("127.0.0.1", server.sockname[1])
    return server, client


def test_basic_roundtrip():
    async def scenario():
        server, client = await _pair()
        client.send(_data_packet(b"ping"))
        raw, addr = await asyncio.wait_for(server.recv(), 2)
        assert b"ping" not in raw  # payload is base64 on the wire
        server.send(_data_packet(b"pong"), addr)
        raw2, _ = await asyncio.wait_for(client.recv(), 2)
        assert raw2 == _data_packet(b"pong")
        server.close()
        client.close()
    asyncio.run(scenario())


def test_write_drop_100_percent():
    async def scenario():
        server, client = await _pair()
        lspnet.set_client_write_drop_percent(100)
        client.send(_data_packet())
        with_timeout = asyncio.wait_for(server.recv(), 0.3)
        try:
            await with_timeout
            raise AssertionError("packet should have been dropped")
        except asyncio.TimeoutError:
            pass
        # Server side unaffected: client still receives.
        lspnet.set_client_write_drop_percent(0)
        client.send(_data_packet(b"probe"))
        _, addr = await asyncio.wait_for(server.recv(), 2)
        server.send(_data_packet(b"back"), addr)
        await asyncio.wait_for(client.recv(), 2)
        server.close()
        client.close()
    asyncio.run(scenario())


def test_read_drop_applies_per_side():
    async def scenario():
        server, client = await _pair()
        lspnet.set_server_read_drop_percent(100)
        client.send(_data_packet())
        try:
            await asyncio.wait_for(server.recv(), 0.3)
            raise AssertionError("server read should have dropped")
        except asyncio.TimeoutError:
            pass
        lspnet.set_server_read_drop_percent(0)
        client.send(_data_packet())
        await asyncio.wait_for(server.recv(), 2)
        server.close()
        client.close()
    asyncio.run(scenario())


def test_shortening_halves_payload_keeps_size():
    async def scenario():
        server, client = await _pair()
        lspnet.set_msg_shortening_percent(100)
        client.send(_data_packet(b"123456"))
        raw, _ = await asyncio.wait_for(server.recv(), 2)
        from distributed_bitcoinminer_tpu.lsp.message import Message
        msg = Message.from_json(raw)
        assert msg.size == 6          # header untouched
        assert len(msg.payload) == 3  # payload halved
        server.close()
        client.close()
    asyncio.run(scenario())


def test_lengthening_appends_bytes():
    async def scenario():
        server, client = await _pair()
        lspnet.set_msg_lengthening_percent(100)
        client.send(_data_packet(b"1234"))
        raw, _ = await asyncio.wait_for(server.recv(), 2)
        from distributed_bitcoinminer_tpu.lsp.message import Message
        msg = Message.from_json(raw)
        assert msg.size == 4
        assert len(msg.payload) == 7 and msg.payload[4:] == bytes([2, 3, 4])
        server.close()
        client.close()
    asyncio.run(scenario())


def test_corruption_flips_first_byte():
    async def scenario():
        server, client = await _pair()
        lspnet.set_msg_corrupted(True)
        client.send(_data_packet(b"1234"))
        raw, _ = await asyncio.wait_for(server.recv(), 2)
        from distributed_bitcoinminer_tpu.lsp.message import Message
        msg = Message.from_json(raw)
        assert msg.payload[0] == ord("1") ^ 0xFF
        assert msg.payload[1:] == b"234"
        server.close()
        client.close()
    asyncio.run(scenario())


def test_acks_never_mutated():
    async def scenario():
        server, client = await _pair()
        lspnet.set_msg_corrupted(True)
        lspnet.set_msg_shortening_percent(100)
        packet = new_ack(1, 5).to_json()
        client.send(packet)
        raw, _ = await asyncio.wait_for(server.recv(), 2)
        assert raw == packet
        server.close()
        client.close()
    asyncio.run(scenario())


def test_delay_defers_delivery():
    async def scenario():
        server, client = await _pair()
        lspnet.set_delay_message_percent(100)
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        client.send(_data_packet())
        await asyncio.wait_for(server.recv(), 2)
        assert loop.time() - t0 >= 0.45
        server.close()
        client.close()
    asyncio.run(scenario())


def test_sniffer_counts_sent_and_dropped():
    async def scenario():
        server, client = await _pair()
        lspnet.start_sniff()
        for _ in range(5):
            client.send(_data_packet())
        client.send(new_ack(1, 1).to_json())
        lspnet.set_client_write_drop_percent(100)
        for _ in range(3):
            client.send(_data_packet())
        await asyncio.sleep(0.1)
        result = lspnet.stop_sniff()
        assert result.num_sent_data == 5
        assert result.num_dropped_data == 3
        assert result.num_sent_acks == 1
        server.close()
        client.close()
    asyncio.run(scenario())


def test_host_port_helpers_match_go_net_semantics():
    """join/split_host_port mirror Go's net.JoinHostPort/SplitHostPort
    (ref: lspnet/net.go:81-89), incl. bracketed IPv6 literals and Go's
    error phrasing for malformed addresses."""
    import pytest

    assert lspnet.join_host_port("localhost", 6060) == "localhost:6060"
    assert lspnet.join_host_port("::1", "80") == "[::1]:80"
    assert lspnet.split_host_port("localhost:6060") == ("localhost", "6060")
    assert lspnet.split_host_port(":6060") == ("", "6060")
    assert lspnet.split_host_port("[::1]:80") == ("::1", "80")
    # Round trip.
    for host, port in (("127.0.0.1", "9999"), ("fe80::2", "1")):
        assert lspnet.split_host_port(
            lspnet.join_host_port(host, port)) == (host, port)
    for bad, phrase in [
            ("localhost", "missing port"),
            ("[::1]", "missing port"),
            ("::1:80", "too many colons"),
            ("[::1:80", "missing ']'"),
            ("host]:1", "unexpected ']'"),
            ("[ho[st]:1", "unexpected '['")]:
        with pytest.raises(ValueError, match="address .*" + phrase.replace(
                "[", r"\[").replace("]", r"\]").replace("'", "'")):
            lspnet.split_host_port(bad)


def test_client_accepts_bracketed_and_plain_hostports():
    """new_async_client parses via split_host_port: a plain host:port
    connects; a malformed address raises ValueError immediately (not a
    connect timeout)."""
    import pytest
    from distributed_bitcoinminer_tpu.lsp.client import new_async_client
    from distributed_bitcoinminer_tpu.lsp.params import Params
    from distributed_bitcoinminer_tpu.lsp.server import new_async_server

    async def scenario():
        server = await new_async_server(0, Params(epoch_millis=100))
        client = await new_async_client(f"127.0.0.1:{server.port}",
                                        Params(epoch_millis=100))
        client.write(b"ping")
        conn_id, payload = await asyncio.wait_for(server.read(), 5)
        assert payload == b"ping"
        await client.close()
        await server.close()
        with pytest.raises(ValueError):
            await new_async_client("no-port-here", Params())
    asyncio.run(scenario())
