"""Wire-format tests: LSP Message JSON codec + checksum + bitcoin codec.

Goldens below were captured from the Go reference semantics
(encoding/json of lsp.Message / bitcoin.Message; lsp/checksum.go fold).
"""

import pytest

from distributed_bitcoinminer_tpu.lsp import (
    Message, MsgType, new_ack, new_connect, new_data,
    bytearray2checksum, int2checksum, make_checksum,
)
from distributed_bitcoinminer_tpu.lsp.params import Params
from distributed_bitcoinminer_tpu import bitcoin


class TestLspMessageCodec:
    def test_connect_golden(self):
        # Go: json.Marshal(NewConnect())
        assert new_connect().to_json() == (
            b'{"Type":0,"ConnID":0,"SeqNum":0,"Size":0,"Checksum":0,"Payload":null}')

    def test_ack_golden(self):
        assert new_ack(7, 3).to_json() == (
            b'{"Type":2,"ConnID":7,"SeqNum":3,"Size":0,"Checksum":0,"Payload":null}')

    def test_data_golden_base64(self):
        # Go base64-encodes []byte payloads: "abc" -> "YWJj".
        msg = new_data(1, 2, 3, b"abc", 99)
        assert msg.to_json() == (
            b'{"Type":1,"ConnID":1,"SeqNum":2,"Size":3,"Checksum":99,"Payload":"YWJj"}')

    def test_roundtrip(self):
        msg = new_data(12, 34, 5, b"hello", make_checksum(12, 34, 5, b"hello"))
        decoded = Message.from_json(msg.to_json())
        assert decoded == msg

    def test_decode_go_emitted(self):
        # As emitted by the Go reference client for Write([]byte("1234")).
        raw = b'{"Type":1,"ConnID":1,"SeqNum":1,"Size":4,"Checksum":26218,"Payload":"MTIzNA=="}'
        msg = Message.from_json(raw)
        assert msg.type == MsgType.DATA
        assert msg.payload == b"1234"
        assert msg.size == 4

    def test_malformed_raises(self):
        with pytest.raises(ValueError):
            Message.from_json(b"not json")
        with pytest.raises(ValueError):
            Message.from_json(b'[1,2,3]')
        with pytest.raises(ValueError):
            Message.from_json(b'{"Type":1,"Payload":"###"}')

    def test_str_matches_reference_format(self):
        assert str(new_connect()) == "[Connect 0 0]"
        assert str(new_ack(4, 9)) == "[Ack 4 9]"
        assert str(new_data(1, 2, 2, b"hi", 7)) == "[Data 1 2 7 hi]"


class TestChecksum:
    def test_int2checksum_splits_halves(self):
        assert int2checksum(0) == 0
        assert int2checksum(1) == 1
        assert int2checksum(0x10000) == 1          # upper half
        assert int2checksum(0x1_0001) == 2         # both halves
        assert int2checksum(0xFFFF_FFFF) == 0x1FFFE

    def test_bytearray_le_chunks(self):
        assert bytearray2checksum(b"") == 0
        assert bytearray2checksum(b"\x01\x02") == 0x0201
        # Odd length: trailing byte zero-padded (LE -> just the byte value).
        assert bytearray2checksum(b"\x01\x02\x03") == 0x0201 + 0x03

    def test_fold_carry(self):
        # Large sums fold 16-bit carries back in until <= 0xffff.
        payload = b"\xff\xff" * 40
        value = make_checksum(0, 0, 0, payload)
        assert 0 <= value <= 0xFFFF

    def test_known_value(self):
        # connID=1 seq=1 size=4 payload="1234":
        # 1 + 1 + 4 + (0x3231 + 0x3433) = 0x666a, fits in 16 bits unfolded.
        assert make_checksum(1, 1, 4, b"1234") == 0x666A

    def test_checksum_detects_corruption(self):
        good = make_checksum(3, 7, 5, b"hello")
        assert make_checksum(3, 7, 5, b"hellp") != good
        assert make_checksum(3, 8, 5, b"hello") != good


class TestParams:
    def test_defaults(self):
        p = Params()
        assert (p.epoch_limit, p.epoch_millis, p.window_size,
                p.max_backoff_interval) == (5, 2000, 1, 0)

    def test_str(self):
        assert str(Params()) == ("[EpochLimit: 5, EpochMillis: 2000, "
                                 "WindowSize: 1, MaxBackOffInterval: 0]")


class TestBitcoinCodec:
    def test_join_golden(self):
        assert bitcoin.new_join().to_json() == (
            b'{"Type":0,"Data":"","Lower":0,"Upper":0,"Hash":0,"Nonce":0}')

    def test_request_golden(self):
        assert bitcoin.new_request("cmu440", 0, 9999).to_json() == (
            b'{"Type":1,"Data":"cmu440","Lower":0,"Upper":9999,"Hash":0,"Nonce":0}')

    def test_result_uint64_range(self):
        h = (1 << 64) - 1
        msg = bitcoin.new_result(h, 123)
        decoded = bitcoin.Message.from_json(msg.to_json())
        assert decoded.hash == h and decoded.nonce == 123

    def test_go_html_escaping(self):
        # Go encoding/json escapes < > & and keeps non-ASCII as raw UTF-8.
        assert bitcoin.new_request("a<b&c>", 0, 1).to_json() == (
            b'{"Type":1,"Data":"a\\u003cb\\u0026c\\u003e",'
            b'"Lower":0,"Upper":1,"Hash":0,"Nonce":0}')
        assert b'h\xc3\xa9llo' in bitcoin.new_request("héllo", 0, 1).to_json()

    def test_str(self):
        assert str(bitcoin.new_join()) == "[Join]"
        assert str(bitcoin.new_request("m", 1, 2)) == "[Request m 1 2]"
        assert str(bitcoin.new_result(5, 6)) == "[Result 5 6]"

    def test_target_extension_absent_is_stock_bytes(self):
        # target=0 must serialize byte-identically to the reference layout:
        # a stock shell driver diffing wire captures sees no difference.
        assert bitcoin.new_request("cmu440", 0, 9999, target=0).to_json() == \
            bitcoin.new_request("cmu440", 0, 9999).to_json()
        assert b"Target" not in bitcoin.new_request("x", 0, 1).to_json()

    def test_target_extension_golden_and_roundtrip(self):
        msg = bitcoin.new_request("cmu440", 0, 9999, target=1 << 56)
        assert msg.to_json() == (
            b'{"Type":1,"Data":"cmu440","Lower":0,"Upper":9999,'
            b'"Hash":0,"Nonce":0,"Target":72057594037927936}')
        assert bitcoin.Message.from_json(msg.to_json()) == msg

    def test_stock_parser_shape_drops_unknown_target(self):
        # What a Go endpoint does with our extension: encoding/json ignores
        # keys with no struct field. Simulate by decoding into the stock
        # field set and re-encoding — the reference fields must survive
        # untouched and the re-encoded bytes be stock.
        raw = bitcoin.new_request("m", 3, 7, target=123).to_json()
        import json
        obj = json.loads(raw)
        stock = {k: obj[k] for k in
                 ("Type", "Data", "Lower", "Upper", "Hash", "Nonce")}
        assert stock == {"Type": 1, "Data": "m", "Lower": 3, "Upper": 7,
                         "Hash": 0, "Nonce": 0}
        # And OUR parser defaults a missing Target to 0 (stock messages).
        assert bitcoin.Message.from_json(
            bitcoin.new_request("m", 3, 7).to_json()).target == 0

    def test_out_of_uint64_range_fields_rejected(self):
        # Go json.Unmarshal errors on numbers that overflow uint64 and the
        # endpoints skip unparsable messages; a poison Target (or Upper)
        # must raise at the codec, not crash a miner's c_uint64 conversion.
        for key in ("Lower", "Upper", "Hash", "Nonce", "Target"):
            for bad in (1 << 64, -1):
                raw = ('{"Type":1,"Data":"x","Lower":0,"Upper":9,"Hash":0,'
                       '"Nonce":0,"%s":%d}' % (key, bad)).encode()
                with pytest.raises(ValueError):
                    bitcoin.Message.from_json(raw)
        # The extreme VALID value still parses.
        ok = bitcoin.new_request("x", 0, 9, target=(1 << 64) - 1)
        assert bitcoin.Message.from_json(ok.to_json()).target == (1 << 64) - 1

    def test_non_numeric_and_non_object_payloads_raise_valueerror(self):
        # TypeError/OverflowError from int() on null/[1]/Infinity — or
        # AttributeError on non-object JSON — would escape the recv loops'
        # `except ValueError: continue` and kill the endpoint; every
        # malformed shape must surface as ValueError.
        bads = [b'[1,2]', b'5', b'"x"', b'true',
                b'{"Type":1,"Data":7,"Lower":0,"Upper":9,"Hash":0,"Nonce":0}',
                b'{"Type":1,"Data":"x","Lower":null,"Upper":9,"Hash":0,"Nonce":0}',
                b'{"Type":1,"Data":"x","Lower":[1],"Upper":9,"Hash":0,"Nonce":0}',
                b'{"Type":1,"Data":"x","Lower":1.5,"Upper":9,"Hash":0,"Nonce":0}',
                b'{"Type":1,"Data":"x","Lower":Infinity,"Upper":9,"Hash":0,"Nonce":0}',
                b'{"Type":1,"Data":"x","Lower":true,"Upper":9,"Hash":0,"Nonce":0}',
                b'{"Type":true,"Data":"x","Lower":0,"Upper":9,"Hash":0,"Nonce":0}',
                b'{"Type":"1","Data":"x","Lower":0,"Upper":9,"Hash":0,"Nonce":0}']
        for raw in bads:
            with pytest.raises(ValueError):
                bitcoin.Message.from_json(raw)


class TestHashOracle:
    def test_known_sha256(self):
        # sha256("cmu440 0") computed with hashlib directly.
        import hashlib
        expected = int.from_bytes(
            hashlib.sha256(b"cmu440 0").digest()[:8], "big")
        assert bitcoin.hash_op("cmu440", 0) == expected

    def test_scan_min_earliest_tie(self):
        from distributed_bitcoinminer_tpu.bitcoin.hash import scan_min
        best, argmin = scan_min("cmu440", 0, 999)
        # Brute-force verify.
        import hashlib
        vals = [int.from_bytes(hashlib.sha256(f"cmu440 {i}".encode()).digest()[:8], "big")
                for i in range(1000)]
        assert best == min(vals)
        assert argmin == vals.index(min(vals))
