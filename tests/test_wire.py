"""Wire-format tests: LSP Message JSON codec + checksum + bitcoin codec.

Goldens below were captured from the Go reference semantics
(encoding/json of lsp.Message / bitcoin.Message; lsp/checksum.go fold).
"""

import pytest

from distributed_bitcoinminer_tpu.lsp import (
    Message, MsgType, new_ack, new_connect, new_data,
    bytearray2checksum, int2checksum, make_checksum,
)
from distributed_bitcoinminer_tpu.lsp.params import Params
from distributed_bitcoinminer_tpu import bitcoin


class TestLspMessageCodec:
    def test_connect_golden(self):
        # Go: json.Marshal(NewConnect())
        assert new_connect().to_json() == (
            b'{"Type":0,"ConnID":0,"SeqNum":0,"Size":0,"Checksum":0,"Payload":null}')

    def test_ack_golden(self):
        assert new_ack(7, 3).to_json() == (
            b'{"Type":2,"ConnID":7,"SeqNum":3,"Size":0,"Checksum":0,"Payload":null}')

    def test_data_golden_base64(self):
        # Go base64-encodes []byte payloads: "abc" -> "YWJj".
        msg = new_data(1, 2, 3, b"abc", 99)
        assert msg.to_json() == (
            b'{"Type":1,"ConnID":1,"SeqNum":2,"Size":3,"Checksum":99,"Payload":"YWJj"}')

    def test_roundtrip(self):
        msg = new_data(12, 34, 5, b"hello", make_checksum(12, 34, 5, b"hello"))
        decoded = Message.from_json(msg.to_json())
        assert decoded == msg

    def test_decode_go_emitted(self):
        # As emitted by the Go reference client for Write([]byte("1234")).
        raw = b'{"Type":1,"ConnID":1,"SeqNum":1,"Size":4,"Checksum":26218,"Payload":"MTIzNA=="}'
        msg = Message.from_json(raw)
        assert msg.type == MsgType.DATA
        assert msg.payload == b"1234"
        assert msg.size == 4

    def test_malformed_raises(self):
        with pytest.raises(ValueError):
            Message.from_json(b"not json")
        with pytest.raises(ValueError):
            Message.from_json(b'[1,2,3]')
        with pytest.raises(ValueError):
            Message.from_json(b'{"Type":1,"Payload":"###"}')

    def test_str_matches_reference_format(self):
        assert str(new_connect()) == "[Connect 0 0]"
        assert str(new_ack(4, 9)) == "[Ack 4 9]"
        assert str(new_data(1, 2, 2, b"hi", 7)) == "[Data 1 2 7 hi]"


class TestChecksum:
    def test_int2checksum_splits_halves(self):
        assert int2checksum(0) == 0
        assert int2checksum(1) == 1
        assert int2checksum(0x10000) == 1          # upper half
        assert int2checksum(0x1_0001) == 2         # both halves
        assert int2checksum(0xFFFF_FFFF) == 0x1FFFE

    def test_bytearray_le_chunks(self):
        assert bytearray2checksum(b"") == 0
        assert bytearray2checksum(b"\x01\x02") == 0x0201
        # Odd length: trailing byte zero-padded (LE -> just the byte value).
        assert bytearray2checksum(b"\x01\x02\x03") == 0x0201 + 0x03

    def test_fold_carry(self):
        # Large sums fold 16-bit carries back in until <= 0xffff.
        payload = b"\xff\xff" * 40
        value = make_checksum(0, 0, 0, payload)
        assert 0 <= value <= 0xFFFF

    def test_known_value(self):
        # connID=1 seq=1 size=4 payload="1234":
        # 1 + 1 + 4 + (0x3231 + 0x3433) = 0x666a, fits in 16 bits unfolded.
        assert make_checksum(1, 1, 4, b"1234") == 0x666A

    def test_checksum_detects_corruption(self):
        good = make_checksum(3, 7, 5, b"hello")
        assert make_checksum(3, 7, 5, b"hellp") != good
        assert make_checksum(3, 8, 5, b"hello") != good


class TestParams:
    def test_defaults(self):
        p = Params()
        assert (p.epoch_limit, p.epoch_millis, p.window_size,
                p.max_backoff_interval) == (5, 2000, 1, 0)

    def test_str(self):
        assert str(Params()) == ("[EpochLimit: 5, EpochMillis: 2000, "
                                 "WindowSize: 1, MaxBackOffInterval: 0]")


class TestBitcoinCodec:
    def test_join_golden(self):
        assert bitcoin.new_join().to_json() == (
            b'{"Type":0,"Data":"","Lower":0,"Upper":0,"Hash":0,"Nonce":0}')

    def test_request_golden(self):
        assert bitcoin.new_request("cmu440", 0, 9999).to_json() == (
            b'{"Type":1,"Data":"cmu440","Lower":0,"Upper":9999,"Hash":0,"Nonce":0}')

    def test_result_uint64_range(self):
        h = (1 << 64) - 1
        msg = bitcoin.new_result(h, 123)
        decoded = bitcoin.Message.from_json(msg.to_json())
        assert decoded.hash == h and decoded.nonce == 123

    def test_go_html_escaping(self):
        # Go encoding/json escapes < > & and keeps non-ASCII as raw UTF-8.
        assert bitcoin.new_request("a<b&c>", 0, 1).to_json() == (
            b'{"Type":1,"Data":"a\\u003cb\\u0026c\\u003e",'
            b'"Lower":0,"Upper":1,"Hash":0,"Nonce":0}')
        assert b'h\xc3\xa9llo' in bitcoin.new_request("héllo", 0, 1).to_json()

    def test_str(self):
        assert str(bitcoin.new_join()) == "[Join]"
        assert str(bitcoin.new_request("m", 1, 2)) == "[Request m 1 2]"
        assert str(bitcoin.new_result(5, 6)) == "[Result 5 6]"


class TestHashOracle:
    def test_known_sha256(self):
        # sha256("cmu440 0") computed with hashlib directly.
        import hashlib
        expected = int.from_bytes(
            hashlib.sha256(b"cmu440 0").digest()[:8], "big")
        assert bitcoin.hash_op("cmu440", 0) == expected

    def test_scan_min_earliest_tie(self):
        from distributed_bitcoinminer_tpu.bitcoin.hash import scan_min
        best, argmin = scan_min("cmu440", 0, 999)
        # Brute-force verify.
        import hashlib
        vals = [int.from_bytes(hashlib.sha256(f"cmu440 {i}".encode()).digest()[:8], "big")
                for i in range(1000)]
        assert best == min(vals)
        assert argmin == vals.index(min(vals))
