"""Config/logging/profiling utilities."""

import logging
import os

import pytest

from distributed_bitcoinminer_tpu.lsp.params import Params
from distributed_bitcoinminer_tpu.utils import (
    FrameworkConfig, Timer, configure_logging, from_env)


def test_from_env_defaults(monkeypatch):
    for var in ("DBM_COMPUTE", "DBM_BATCH", "DBM_EPOCH_LIMIT",
                "DBM_EPOCH_MILLIS", "DBM_WINDOW", "DBM_MAX_BACKOFF"):
        monkeypatch.delenv(var, raising=False)
    cfg = from_env()
    assert cfg.params == Params()
    assert cfg.compute == "auto" and cfg.batch is None


def test_from_env_overrides(monkeypatch):
    monkeypatch.setenv("DBM_COMPUTE", "host")
    monkeypatch.setenv("DBM_BATCH", "4096")
    monkeypatch.setenv("DBM_EPOCH_MILLIS", "250")
    monkeypatch.setenv("DBM_WINDOW", "7")
    cfg = from_env()
    assert cfg.compute == "host"
    assert cfg.batch == 4096
    assert cfg.params.epoch_millis == 250 and cfg.params.window_size == 7


def test_host_searcher_from_config():
    s = FrameworkConfig(compute="host").make_searcher("cfg test")
    from distributed_bitcoinminer_tpu.bitcoin.hash import scan_min
    assert s.search(0, 300) == scan_min("cfg test", 0, 300)


def test_configure_logging_and_timer(tmp_path):
    logfile = tmp_path / "log.txt"
    logger = configure_logging(logging.DEBUG, str(logfile))
    logger.info("hello structured world")
    logging.getLogger("dbm.scheduler").info("child propagates")
    for h in logger.handlers:
        h.flush()
    text = logfile.read_text()
    assert "hello structured world" in text and "child propagates" in text

    with Timer() as t:
        sum(range(1000))
    assert t.seconds >= 0
    assert Timer().rate(100) == 0.0


def test_device_trace_writes_profile(tmp_path):
    """The A2 profiler hook (bench's DBM_TRACE path) captures real trace
    artifacts; the None path is a no-op."""
    import jax.numpy as jnp

    from distributed_bitcoinminer_tpu.utils.profiling import device_trace
    with device_trace(None):
        pass
    logdir = tmp_path / "trace"
    with device_trace(str(logdir)):
        jnp.arange(16).sum().block_until_ready()
    dumped = list(logdir.rglob("*"))
    assert dumped, "profiler trace produced no files"


def test_apply_jax_platform_env_falls_back_on_bad_platform():
    """JAX_PLATFORMS naming a platform that cannot initialize in THIS
    process (e.g. the image-wide JAX_PLATFORMS=axon reaching a miner
    launched from a directory where the axon plugin registers under a
    different name — the round-3 e2e failure) must fall back to automatic
    selection instead of crashing every later jax.devices()."""
    import subprocess
    import sys

    from _env_detect import SKIP_REASON, tpu_plugin_without_device
    if tpu_plugin_without_device():
        # The fallback path this test exercises runs backend discovery
        # in a fresh child process, which is exactly the shape the
        # baked-in libtpu plugin wedges on a chip-less box.
        pytest.skip(SKIP_REASON)

    code = (
        "from distributed_bitcoinminer_tpu.utils.config import "
        "apply_jax_platform_env, jax_devices_robust\n"
        "apply_jax_platform_env()\n"
        "print('devices-ok', len(jax_devices_robust()) > 0)\n")
    env = {**os.environ, "JAX_PLATFORMS": "nonexistent_backend",
           "PYTHONPATH": os.path.dirname(os.path.dirname(
               os.path.abspath(__file__)))}
    # 75s covers the child's full jax init with margin even when the
    # fallback lands on a real accelerator; a box whose backend discovery
    # hangs (wedged device tunnel) burns the whole deadline, so a tighter
    # bound keeps the tier-1 suite inside its wall budget there.
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=75)
    assert proc.returncode == 0, proc.stderr[-500:]
    assert "devices-ok True" in proc.stdout
