"""Native C++ scan: bit-exact vs the Python oracle, incl. digit rollovers."""

import pytest

from distributed_bitcoinminer_tpu import native
from distributed_bitcoinminer_tpu.bitcoin.hash import hash_op, scan_min

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="no C++ toolchain")


@pytest.mark.parametrize("lower,upper", [
    (0, 500),
    (95, 105),          # 1->2-digit rollover
    (9_990, 10_010),    # 4->5-digit rollover
    (99_999, 100_002),  # 5->6-digit rollover
    (123_456, 124_000),
])
def test_scan_matches_oracle(lower, upper):
    # Data lengths chosen to cover every tail-block shape of the pair
    # scan: short, empty, multi-block prefix, and the 52-56 band where
    # rem + nd straddles the 64-byte pad boundary — there a digit
    # rollover INSIDE a pair makes one message need two padded blocks
    # and its partner one, exercising finish2's two-block loop and its
    # unequal-block scalar fallback (code-review r4: previously no test
    # reached either path).
    for data in ("cmu440", "", "x" * 70, "x" * 52, "x" * 53, "x" * 54,
                 "x" * 55, "x" * 56):
        assert native.scan_min_native(data, lower, upper) == \
            scan_min(data, lower, upper)


def test_single_hash_matches():
    for nonce in (0, 7, 99, 1234, 10**12):
        assert native.hash_native("msg", nonce) == hash_op("msg", nonce)


def test_empty_range_raises():
    with pytest.raises(ValueError):
        native.scan_min_native("x", 5, 4)


def test_mt_until_preserves_first_qualifying_nonce():
    """The MT difficulty scan (ascending shards, lowest hitting shard wins,
    higher shards cooperatively aborted) must agree bit-for-bit with the
    single-threaded scan on the FIRST qualifying nonce — including when the
    hit sits deep in a later shard — and on the arg-min miss fallback."""
    from distributed_bitcoinminer_tpu.bitcoin.hash import scan_until
    cases = [
        ("mt until", 0, 70_000, 1 << 57),     # hit early, many shards
        ("mt until", 0, 70_000, 1 << 50),     # hit deep or miss
        ("deep hit", 1_000, 180_000, 1 << 53),
        ("no luck", 0, 3_000, 1),             # miss -> exact arg-min merge
    ]
    for data, lo, hi, target in cases:
        st = native.scan_until_native(data, lo, hi, target, threads=1)
        assert st == scan_until(data, lo, hi, target)
        for threads in (2, 3, 8):
            assert native.scan_until_native(
                data, lo, hi, target, threads=threads) == st
    # More threads than nonces.
    assert native.scan_until_native("mt", 7, 9, 1 << 62, threads=8) == \
        scan_until("mt", 7, 9, 1 << 62)


def test_mt_scan_matches_single_threaded():
    """The threaded fan-out (contiguous ascending sub-ranges, merged in
    index order) must preserve the strict-'<' earliest-nonce tie rule
    bit-for-bit — including ranges that straddle digit rollovers and
    ranges shorter than the thread count."""
    for lo, hi in ((0, 70_000), (99_990, 163_000)):
        st = native.scan_min_native("mt", lo, hi, threads=1)
        for threads in (2, 3, 8):
            assert native.scan_min_native("mt", lo, hi,
                                          threads=threads) == st
    # More threads than nonces degrades to one nonce per thread.
    assert native.scan_min_native("mt", 7, 9, threads=8) == \
        scan_min("mt", 7, 9)
