"""Native C++ scan: bit-exact vs the Python oracle, incl. digit rollovers."""

import pytest

from distributed_bitcoinminer_tpu import native
from distributed_bitcoinminer_tpu.bitcoin.hash import hash_op, scan_min

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="no C++ toolchain")


@pytest.mark.parametrize("lower,upper", [
    (0, 500),
    (95, 105),          # 1->2-digit rollover
    (9_990, 10_010),    # 4->5-digit rollover
    (99_999, 100_002),  # 5->6-digit rollover
    (123_456, 124_000),
])
def test_scan_matches_oracle(lower, upper):
    for data in ("cmu440", "", "x" * 70):  # incl. multi-block prefixes
        assert native.scan_min_native(data, lower, upper) == \
            scan_min(data, lower, upper)


def test_single_hash_matches():
    for nonce in (0, 7, 99, 1234, 10**12):
        assert native.hash_native("msg", nonce) == hash_op("msg", nonce)


def test_empty_range_raises():
    with pytest.raises(ValueError):
        native.scan_min_native("x", 5, 4)


def test_mt_scan_matches_single_threaded():
    """The threaded fan-out (contiguous ascending sub-ranges, merged in
    index order) must preserve the strict-'<' earliest-nonce tie rule
    bit-for-bit — including ranges that straddle digit rollovers and
    ranges shorter than the thread count."""
    for lo, hi in ((0, 70_000), (99_990, 163_000)):
        st = native.scan_min_native("mt", lo, hi, threads=1)
        for threads in (2, 3, 8):
            assert native.scan_min_native("mt", lo, hi,
                                          threads=threads) == st
    # More threads than nonces degrades to one nonce per thread.
    assert native.scan_min_native("mt", 7, 9, threads=8) == \
        scan_min("mt", 7, 9)
