"""Native C++ scan: bit-exact vs the Python oracle, incl. digit rollovers."""

import pytest

from distributed_bitcoinminer_tpu import native
from distributed_bitcoinminer_tpu.bitcoin.hash import hash_op, scan_min

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="no C++ toolchain")


@pytest.mark.parametrize("lower,upper", [
    (0, 500),
    (95, 105),          # 1->2-digit rollover
    (9_990, 10_010),    # 4->5-digit rollover
    (99_999, 100_002),  # 5->6-digit rollover
    (123_456, 124_000),
])
def test_scan_matches_oracle(lower, upper):
    for data in ("cmu440", "", "x" * 70):  # incl. multi-block prefixes
        assert native.scan_min_native(data, lower, upper) == \
            scan_min(data, lower, upper)


def test_single_hash_matches():
    for nonce in (0, 7, 99, 1234, 10**12):
        assert native.hash_native("msg", nonce) == hash_op("msg", nonce)


def test_empty_range_raises():
    with pytest.raises(ValueError):
        native.scan_min_native("x", 5, 4)
