"""The Go-style blocking facade (threads + shared background loop)."""

import threading

from distributed_bitcoinminer_tpu.lsp import Params, new_client, new_server


def test_sync_echo_roundtrip():
    params = Params(epoch_limit=10, epoch_millis=100, window_size=5,
                    max_backoff_interval=1)
    server = new_server(0, params)

    def echo():
        while True:
            try:
                conn_id, item = server.read()
            except Exception:  # noqa: BLE001 — server closed
                return
            if isinstance(item, bytes):
                try:
                    server.write(conn_id, item)
                except Exception:  # noqa: BLE001
                    return
    thread = threading.Thread(target=echo, daemon=True)
    thread.start()

    client = new_client(f"127.0.0.1:{server.port}", params)
    assert client.conn_id() > 0
    for i in range(10):
        payload = f"sync{i}".encode()
        client.write(payload)
        assert client.read() == payload
    client.close()
    server.close()
    thread.join(timeout=5)
    assert not thread.is_alive()
