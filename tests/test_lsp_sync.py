"""The Go-style blocking facade (threads + shared background loop)."""

import threading

from distributed_bitcoinminer_tpu.lsp import Params, new_client, new_server


def test_sync_echo_roundtrip():
    params = Params(epoch_limit=10, epoch_millis=100, window_size=5,
                    max_backoff_interval=1)
    server = new_server(0, params)

    def echo():
        while True:
            try:
                conn_id, item = server.read()
            except Exception:  # noqa: BLE001 — server closed
                return
            if isinstance(item, bytes):
                try:
                    server.write(conn_id, item)
                except Exception:  # noqa: BLE001
                    return
    thread = threading.Thread(target=echo, daemon=True)
    thread.start()

    client = new_client(f"127.0.0.1:{server.port}", params)
    assert client.conn_id() > 0
    for i in range(10):
        payload = f"sync{i}".encode()
        client.write(payload)
        assert client.read() == payload
    client.close()
    server.close()
    thread.join(timeout=5)
    assert not thread.is_alive()


def test_sync_facade_under_threaded_load():
    """Round-1 backlog item "sync-facade load": the Go deployment shape is
    one OS thread per blocking client over ONE shared background loop; 5
    client threads x 100 in-order round-trips must hold w=10 discipline
    with no cross-talk, and every thread (incl. the server's) must be
    joinable afterwards — the no-goroutine-outlives-Close rule for the
    facade layer."""
    params = Params(epoch_limit=20, epoch_millis=100, window_size=10,
                    max_backoff_interval=1)
    server = new_server(0, params)

    def echo():
        while True:
            try:
                conn_id, item = server.read()
            except Exception:  # noqa: BLE001 — server closed
                return
            if isinstance(item, bytes):
                try:
                    server.write(conn_id, item)
                except Exception:  # noqa: BLE001
                    return

    echo_thread = threading.Thread(target=echo, daemon=True)
    echo_thread.start()

    errors: list[str] = []

    def one_client(idx: int):
        try:
            c = new_client(f"127.0.0.1:{server.port}", params)
            for i in range(100):
                payload = f"t{idx}m{i:03d}".encode()
                c.write(payload)
                got = c.read()
                if got != payload:
                    errors.append(f"thread {idx} msg {i}: {got!r}")
                    return
            c.close()
        except Exception as exc:  # noqa: BLE001
            errors.append(f"thread {idx}: {exc!r}")

    threads = [threading.Thread(target=one_client, args=(i,))
               for i in range(5)]
    try:
        for t in threads:
            t.start()
        wedged = []
        for t in threads:
            t.join(timeout=30)
            if t.is_alive():
                wedged.append(t.name)
    finally:
        # Close BEFORE asserting: a failed assertion must not leak the
        # bound socket + a thread parked in server.read() into the rest
        # of the pytest session (review r3).
        server.close()
        echo_thread.join(timeout=5)
    assert not wedged, f"client threads wedged: {wedged}"
    assert not errors, errors
    assert not echo_thread.is_alive()
