"""Cluster observability plane (ISSUE 18): rollup, SLO, dbmtop.

Pins the tentpole contracts: cross-process snapshot merge exactness
(counters sum to exactly the sum of parts, cumulative-``le`` histogram
buckets merge elementwise, EWMAs combine sample-weighted), aggregate
idempotence under re-read, fenced/stale-source exclusion from cluster
totals, the ``proc``-label cardinality bound under miner-agent churn,
process-identity stamps on emitter/flight-recorder lines, the
multi-window SLO burn alert, and the one-attribute-per-hook knob-off
shape (``DBM_ROLLUP=0`` constructs no publisher anywhere — the matrix
leg runs this module with the knob off).
"""

from __future__ import annotations

import json
import logging
import os

import pytest

from distributed_bitcoinminer_tpu.apps.health import (Beat, BeatMonitor,
                                                      Membership,
                                                      SeqFreshness)
from distributed_bitcoinminer_tpu.apps.rollup import (RollupPublisher,
                                                      RollupState,
                                                      SourceSet,
                                                      aggregate,
                                                      gc_stale_blobs,
                                                      hist_quantile,
                                                      merge_snapshots,
                                                      read_blobs,
                                                      rollup_enabled)
from distributed_bitcoinminer_tpu.apps.slo import (SloTracker,
                                                   default_objectives)
from distributed_bitcoinminer_tpu.utils.metrics import (Emitter, Registry,
                                                        proc_identity,
                                                        set_proc_identity)

T0 = 1_000_000.0


def _hist(le, counts, total, s):
    return {"le": list(le), "counts": list(counts), "count": total,
            "sum": s}


def _snap(counters=None, gauges=None, histograms=None, ewmas=None,
          overflow=0):
    return {"counters": dict(counters or {}), "gauges": dict(gauges or {}),
            "histograms": dict(histograms or {}),
            "ewmas": dict(ewmas or {}), "series_overflow": overflow}


# ------------------------------------------------------------------- merge


class TestMergeSnapshots:
    def test_counter_sum_equals_parts(self):
        r1, r2 = Registry(), Registry()
        r1.counter("sched.results_sent").inc(10)
        r2.counter("sched.results_sent").inc(32)
        r1.counter("sched.qos_shed", tenant="a").inc(3)
        r2.counter("sched.qos_shed", tenant="a").inc(4)
        merged = merge_snapshots([("replica0", r1.snapshot()),
                                  ("replica1", r2.snapshot())])
        assert merged["counters"]["sched.results_sent"] == 42
        assert merged["counters"]["sched.qos_shed{tenant=a}"] == 7
        # Exactly the sum of the per-process registries, nothing else.
        parts = sum(r.snapshot()["counters"]["sched.results_sent"]
                    for r in (r1, r2))
        assert merged["counters"]["sched.results_sent"] == parts

    def test_histogram_bucket_merge_exact(self):
        h1 = _hist([0.1, 1.0, 10.0], [1, 3, 5], 6, 7.5)
        h2 = _hist([0.1, 1.0, 10.0], [2, 2, 4], 4, 2.5)
        merged = merge_snapshots(
            [("a", _snap(histograms={"w": h1})),
             ("b", _snap(histograms={"w": h2}))])
        got = merged["histograms"]["w"]
        assert got["le"] == [0.1, 1.0, 10.0]
        assert got["counts"] == [3, 5, 9]        # elementwise, exact
        assert got["count"] == 10
        assert got["sum"] == 10.0
        # Inputs are never mutated (fresh dict copies).
        assert h1["counts"] == [1, 3, 5]

    def test_histogram_bound_mismatch_falls_back_per_source(self):
        h1 = _hist([0.1, 1.0], [1, 2], 2, 1.0)
        h2 = _hist([0.5, 5.0], [1, 1], 1, 0.4)
        merged = merge_snapshots(
            [("a", _snap(histograms={"w": h1})),
             ("b", _snap(histograms={"w": h2}))])
        assert merged["histograms"]["w"]["counts"] == [1, 2]
        assert merged["histograms"]["w{proc=b}"]["le"] == [0.5, 5.0]

    def test_ewma_sample_weighted(self):
        merged = merge_snapshots(
            [("a", _snap(ewmas={"nps": {"value": 100.0, "samples": 1}})),
             ("b", _snap(ewmas={"nps": {"value": 200.0, "samples": 3}}))])
        assert merged["ewmas"]["nps"] == {"value": 175.0, "samples": 4}

    def test_ewma_empty_sources(self):
        merged = merge_snapshots(
            [("a", _snap(ewmas={"nps": {"value": None, "samples": 0}}))])
        assert merged["ewmas"]["nps"] == {"value": None, "samples": 0}

    def test_gauges_kept_per_source_under_proc_label(self):
        merged = merge_snapshots(
            [("replica0", _snap(gauges={"sched.queue_depth": 3})),
             ("replica1", _snap(gauges={"sched.queue_depth": 5,
                                        "t{m=x}": 1.0}))])
        assert merged["gauges"]["sched.queue_depth{proc=replica0}"] == 3
        assert merged["gauges"]["sched.queue_depth{proc=replica1}"] == 5
        # Existing label sets gain proc INSIDE the braces.
        assert merged["gauges"]["t{m=x,proc=replica1}"] == 1.0

    def test_merge_is_pure_and_idempotent(self):
        pairs = [("a", _snap(counters={"c": 1},
                             histograms={"w": _hist([1.0], [1], 1, 0.5)},
                             ewmas={"e": {"value": 2.0, "samples": 2}})),
                 ("b", _snap(counters={"c": 2}, gauges={"g": 9}))]
        assert merge_snapshots(pairs) == merge_snapshots(pairs)

    def test_overflow_sums_input_overflows(self):
        merged = merge_snapshots([("a", _snap(overflow=2)),
                                  ("b", _snap(overflow=3))])
        assert merged["series_overflow"] == 5


class TestSourceSetCardinality:
    def test_bound_under_miner_churn(self):
        ss = SourceSet(max_series=4)
        pairs = [(f"miner{pid}", _snap(gauges={"g": pid}))
                 for pid in range(10)]
        merged = merge_snapshots(pairs, source_set=ss)
        # Only the admitted sources keep per-proc gauges; the rest are
        # refused and COUNTED, not silently folded in.
        assert len(merged["gauges"]) == 4
        assert merged["series_overflow"] == 6
        assert ss.overflows == 6
        # Counters still sum over every source — the bound only guards
        # the per-source (proc-labeled) series space.
        assert merged["sources"] == 10

    def test_retire_frees_slot(self):
        ss = SourceSet(max_series=1)
        assert ss.proc_series("rollup_sources", proc="miner1")
        assert not ss.proc_series("rollup_sources", proc="miner2")
        ss.retire_proc("rollup_sources", proc="miner1")
        assert ss.proc_series("rollup_sources", proc="miner2")
        assert ss.sources("rollup_sources") == [(("proc", "miner2"),)]

    def test_readmission_is_free(self):
        ss = SourceSet(max_series=1)
        assert ss.proc_series("rollup_sources", proc="a")
        assert ss.proc_series("rollup_sources", proc="a")
        assert ss.overflows == 0


class TestHistQuantile:
    def test_quantiles(self):
        h = _hist([0.1, 1.0, 10.0], [50, 90, 100], 100, 55.0)
        assert hist_quantile(h, 0.5) == 0.1
        assert hist_quantile(h, 0.9) == 1.0
        assert hist_quantile(h, 0.99) == 10.0

    def test_empty_and_inf_bucket(self):
        assert hist_quantile(None, 0.5) is None
        assert hist_quantile(_hist([1.0], [0], 0, 0.0), 0.5) is None
        # All mass past the largest finite bound: unbounded quantile.
        assert hist_quantile(_hist([1.0], [0], 5, 50.0), 0.5) is None


# --------------------------------------------------------- publish/aggregate


def _publish(statedir, role, rid, inc, registry, *, beat_s=0.5,
             epoch_seen=0):
    pub = RollupPublisher(statedir, role, rid, inc, registry=registry,
                          beat_s=beat_s)
    assert pub.publish(epoch_seen=epoch_seen)
    return pub


class TestPublishAggregate:
    def test_blob_shape_and_atomic_discipline(self, tmp_path):
        d = str(tmp_path)
        r = Registry()
        r.counter("sched.results_sent").inc(7)
        _publish(d, "replica", 0, "i0", r)
        blobs = read_blobs(d)
        assert len(blobs) == 1
        b = blobs[0]
        assert (b["role"], b["rid"], b["inc"], b["seq"]) == \
            ("replica", 0, "i0", 1)
        assert b["snapshot"]["counters"]["sched.results_sent"] == 7
        # No tmp litter: the writer goes through tmp+rename.
        assert all(not f.startswith(".") and ".tmp" not in f
                   for f in os.listdir(d))

    def test_aggregate_idempotent_under_reread(self, tmp_path):
        d = str(tmp_path)
        for rid in (0, 1):
            r = Registry()
            r.counter("sched.results_sent").inc(10 + rid)
            r.histogram("sched.queue_wait_s").observe(0.01)
            _publish(d, "replica", rid, f"i{rid}", r)
        now = read_blobs(d)[0]["wall"] + 0.1
        doc1 = aggregate(d, now=now)
        doc2 = aggregate(d, now=now)
        assert doc1 == doc2
        assert json.dumps(doc1, sort_keys=True) == \
            json.dumps(doc2, sort_keys=True)

    def test_totals_equal_sum_of_parts(self, tmp_path):
        d = str(tmp_path)
        want = 0
        for rid in range(3):
            r = Registry()
            r.counter("sched.results_sent").inc(5 * (rid + 1))
            want += 5 * (rid + 1)
            _publish(d, "replica", rid, f"i{rid}", r)
        doc = aggregate(d)
        assert doc["cluster"]["counters"]["sched.results_sent"] == want
        assert [p["status"] for p in doc["procs"]] == ["fresh"] * 3

    def test_stale_source_flagged_and_excluded(self, tmp_path):
        d = str(tmp_path)
        for rid in (0, 1):
            r = Registry()
            r.counter("sched.results_sent").inc(10)
            _publish(d, "replica", rid, f"i{rid}", r, beat_s=0.5)
        # Freeze replica 1 by aggregating far past its window: its
        # numbers drop out of totals, but the row stays VISIBLE.
        path = os.path.join(d, "metrics_replica_1.json")
        blob = json.load(open(path))
        blob["wall"] -= 60.0
        json.dump(blob, open(path, "w"))
        doc = aggregate(d)
        by = {p["proc"]: p for p in doc["procs"]}
        assert by["replica0"]["status"] == "fresh"
        assert by["replica1"]["status"] == "stale"
        assert doc["cluster"]["counters"]["sched.results_sent"] == 10
        assert by["replica1"]["age_s"] > by["replica1"]["window_s"]

    def test_fenced_source_excluded_like_cache_spools(self, tmp_path):
        d = str(tmp_path)
        for rid in (0, 1):
            r = Registry()
            r.counter("sched.results_sent").inc(10)
            _publish(d, "replica", rid, f"i{rid}", r)
        m = Membership()
        m.admit(Beat(rid=0, incarnation="i0", seq=1))
        m.admit(Beat(rid=1, incarnation="i1", seq=1))
        m.declare_dead(1)
        doc = aggregate(d, membership=m)
        by = {p["proc"]: p for p in doc["procs"]}
        assert by["replica1"]["status"] == "fenced"
        assert doc["cluster"]["counters"]["sched.results_sent"] == 10
        # A NEW incarnation of the same rid is not fenced.
        r = Registry()
        r.counter("sched.results_sent").inc(1)
        _publish(d, "replica", 1, "i1b", r)
        doc = aggregate(d, membership=m)
        assert {p["proc"]: p["status"] for p in doc["procs"]} == \
            {"replica0": "fresh", "replica1": "fresh"}

    def test_proc_detail_rows(self, tmp_path):
        d = str(tmp_path)
        r = Registry()
        r.counter("sched.results_sent").inc(4)
        r.counter("sched.qos_shed").inc(1)
        r.gauge("sched.queue_depth").set(7)
        r.gauge("sched.miner_trust", miner="m1").set(0.5)
        r.gauge("sched.miner_trust", miner="m2").set(0.9)
        r.histogram("sched.queue_wait_s").observe(0.02)
        r.ewma("miner.nonces_per_s").observe(1234.5)
        _publish(d, "miner", 99, "i", r)
        detail = aggregate(d)["procs"][0]["detail"]
        assert detail["results"] == 4 and detail["shed"] == 1
        assert detail["queue"] == 7
        assert detail["trust_min"] == 0.5
        assert detail["queue_wait_p99_s"] is not None
        assert detail["nps"] == 1234.5

    def test_gc_sweeps_only_long_dead(self, tmp_path):
        d = str(tmp_path)
        r = Registry()
        _publish(d, "miner", 1, "i", r, beat_s=0.5)
        _publish(d, "miner", 2, "i", r, beat_s=0.5)
        wall = read_blobs(d)[0]["wall"]
        window = 0.5 * 3
        # Freshly dead: visible, NOT swept (the operator must see it).
        assert gc_stale_blobs(d, now=wall + window * 2) == 0
        assert len(read_blobs(d)) == 2
        # Long dead: litter from churned pids, swept.
        assert gc_stale_blobs(d, now=wall + window * 50) == 2
        assert read_blobs(d) == []


class TestRollupState:
    def test_frozen_seq_downgrades_fresh_wall(self, tmp_path):
        d = str(tmp_path)
        r = Registry()
        pub = _publish(d, "replica", 0, "i0", r, beat_s=0.5)
        state = RollupState(d)
        t0 = read_blobs(d)[0]["wall"]
        assert state.refresh(now=t0)["procs"][0]["status"] == "fresh"
        # A cloned/replayed blob: wall advances, seq does not. The seq
        # rule wins — exactly the BeatMonitor's SIGSTOP discipline.
        path = os.path.join(d, "metrics_replica_0.json")
        blob = json.load(open(path))
        blob["wall"] = t0 + 10.0
        json.dump(blob, open(path, "w"))
        doc = state.refresh(now=t0 + 10.0)
        assert doc["procs"][0]["status"] == "stale"
        # A real publish (seq advances) restores freshness.
        assert pub.publish()
        blob = json.load(open(path))
        blob["wall"] = t0 + 10.5
        json.dump(blob, open(path, "w"))
        doc = state.refresh(now=t0 + 10.6)
        assert doc["procs"][0]["status"] == "fresh"

    def test_long_stale_source_retired_from_bound(self, tmp_path):
        d = str(tmp_path)
        r = Registry()
        r.gauge("g").set(1)
        _publish(d, "replica", 0, "i0", r, beat_s=0.5)
        state = RollupState(d)
        t0 = read_blobs(d)[0]["wall"]
        state.refresh(now=t0)
        assert state.sources.sources("rollup_sources")
        state.refresh(now=t0 + 0.5 * 3 * (RollupState.RETIRE_K + 5))
        assert state.sources.sources("rollup_sources") == []

    def test_epoch_timeline(self, tmp_path):
        d = str(tmp_path)
        from distributed_bitcoinminer_tpu.apps.procs import \
            write_json_atomic
        r = Registry()
        _publish(d, "replica", 0, "i0", r)
        m = Membership()
        m.admit(Beat(rid=0, incarnation="i0", seq=1))
        write_json_atomic(os.path.join(d, "membership.json"), m.to_dict())
        state = RollupState(d)
        t0 = read_blobs(d)[0]["wall"]
        state.refresh(now=t0)
        m.admit(Beat(rid=1, incarnation="i1", seq=1))
        write_json_atomic(os.path.join(d, "membership.json"), m.to_dict())
        state.refresh(now=t0 + 0.1)
        assert [e for _, e in state.epochs()] == [1, 2]


# ------------------------------------------------------------- seq freshness


class TestSeqFreshness:
    def test_advance_and_stale(self):
        f = SeqFreshness(window_s=1.0)
        assert f.observe("a", "g1", 1, T0)
        assert not f.observe("a", "g1", 1, T0 + 0.5)   # replay: no life
        assert f.stale(T0 + 0.5) == []
        assert f.stale(T0 + 1.5) == ["a"]
        assert f.observe("a", "g1", 2, T0 + 2.0)       # seq advanced
        assert f.stale(T0 + 2.5) == []

    def test_generation_change_counts_as_advance(self):
        f = SeqFreshness(window_s=1.0)
        f.observe("a", "g1", 5, T0)
        # A restarted source resets its seq under a NEW generation.
        assert f.observe("a", "g2", 1, T0 + 0.5)
        assert f.age_s("a", T0 + 0.6) == pytest.approx(0.1)

    def test_forget(self):
        f = SeqFreshness(window_s=1.0)
        f.observe("a", "g", 1, T0)
        f.forget("a")
        assert f.keys() == [] and f.stale(T0 + 10) == []

    def test_beat_monitor_delegates_same_rules(self):
        mon = BeatMonitor(beat_s=0.1, miss_k=3)
        mon.observe(Beat(rid=0, incarnation="i", seq=1), T0)
        # Replayed blob (same seq) is not life: dead after the window.
        mon.observe(Beat(rid=0, incarnation="i", seq=1), T0 + 0.25)
        assert mon.dead(T0 + 0.35) == [0]
        mon.observe(Beat(rid=0, incarnation="i", seq=2), T0 + 0.4)
        assert mon.dead(T0 + 0.5) == []
        mon.forget(0)
        assert mon.dead(T0 + 10.0) == []


# ------------------------------------------------------------ identity stamp


class TestIdentityStamp:
    @pytest.fixture(autouse=True)
    def _clear(self):
        yield
        set_proc_identity(None)

    def _emit_doc(self):
        records = []

        class _H(logging.Handler):
            def emit(self, record):
                records.append(record.getMessage())

        log = logging.getLogger("test.rollup.emit")
        log.addHandler(_H())
        log.setLevel(logging.INFO)
        try:
            Emitter(Registry(), interval_s=60, logger=log).emit()
        finally:
            log.handlers.clear()
        return json.loads(records[-1])

    def test_emitter_lines_stamped(self):
        set_proc_identity("replica", 3, "pid-123")
        doc = self._emit_doc()
        assert doc["identity"] == {"role": "replica", "rid": 3,
                                   "inc": "pid-123"}

    def test_no_identity_no_stamp(self):
        set_proc_identity(None)
        assert "identity" not in self._emit_doc()
        assert proc_identity() is None

    def test_flight_recorder_dump_stamped(self, caplog):
        from distributed_bitcoinminer_tpu.utils.trace import FlightRecorder
        set_proc_identity("miner", 42, "pid-9")
        fr = FlightRecorder(cap=8)
        fr.record("x", k=1)
        with caplog.at_level(logging.WARNING):
            fr.dump("test")
        line = next(m for m in caplog.messages
                    if "flight recorder dump" in m)
        doc = json.loads(line[line.index("{"):])
        assert doc["identity"] == {"role": "miner", "rid": 42,
                                   "inc": "pid-9"}


# ------------------------------------------------------------------ knob off


class TestKnobOff:
    """One attribute test per hook: DBM_ROLLUP=0 constructs NOTHING."""

    def test_enabled_default_on(self, monkeypatch):
        monkeypatch.delenv("DBM_ROLLUP", raising=False)
        assert rollup_enabled()
        monkeypatch.setenv("DBM_ROLLUP", "0")
        assert not rollup_enabled()

    def test_replica_hook(self, tmp_path, monkeypatch):
        from distributed_bitcoinminer_tpu.apps.procs import ReplicaProcess
        monkeypatch.setenv("DBM_ROLLUP", "0")
        assert ReplicaProcess(str(tmp_path), 0)._rollup is None
        monkeypatch.delenv("DBM_ROLLUP")
        assert ReplicaProcess(str(tmp_path), 0)._rollup is not None

    def test_router_hook(self, tmp_path, monkeypatch):
        from distributed_bitcoinminer_tpu.apps.procs import Router
        monkeypatch.setenv("DBM_ROLLUP", "0")
        assert Router(str(tmp_path))._rollup is None
        monkeypatch.delenv("DBM_ROLLUP")
        assert Router(str(tmp_path))._rollup is not None

    def test_miner_agent_hook(self, tmp_path, monkeypatch):
        from distributed_bitcoinminer_tpu.apps.procs import MinerAgent
        monkeypatch.setenv("DBM_ROLLUP", "0")
        assert MinerAgent(str(tmp_path))._rollup is None
        monkeypatch.delenv("DBM_ROLLUP")
        assert MinerAgent(str(tmp_path))._rollup is not None

    def test_off_writes_no_blobs(self, tmp_path, monkeypatch):
        from distributed_bitcoinminer_tpu.apps.procs import ReplicaProcess
        monkeypatch.setenv("DBM_ROLLUP", "0")
        ReplicaProcess(str(tmp_path), 0)
        assert read_blobs(str(tmp_path)) == []


# ----------------------------------------------------------------------- slo


def _slo_doc(shed, sent, procs=None):
    return {"cluster": {"counters": {"sched.qos_shed": shed,
                                     "sched.results_sent": sent,
                                     "sched.qos_grants": sent}},
            "procs": procs if procs is not None else [
                {"proc": "replica0", "status": "fresh",
                 "detail": {"shed": shed, "results": 0}},
                {"proc": "replica1", "status": "fresh",
                 "detail": {"shed": 0, "results": sent}}]}


class _Recorder:
    def __init__(self):
        self.events = []

    def record(self, event, **detail):
        self.events.append((event, detail))


class TestSlo:
    def test_burn_alert_fires_on_transition_naming_offender(self):
        rec = _Recorder()
        tracker = SloTracker(window_s=12.0, burn=4.0, recorder=rec)
        # Overload storm: half of everything decided is shed — error
        # fraction 0.5 >> 4x the 1% availability budget.
        alerts, fired_at = [], None
        for i in range(14):
            alerts = tracker.observe(_slo_doc(shed=10 * i, sent=10 * i),
                                     now=T0 + i)
            if alerts:
                fired_at = i
                break
        assert alerts, "burn alert never fired"
        assert alerts[0]["objective"] == "reply_availability"
        assert alerts[0]["worst"] == "replica0"
        assert alerts[0]["event"] == "slo_burn"
        assert rec.events and rec.events[0][0] == "slo_burn"
        assert rec.events[0][1]["objective"] == "reply_availability"
        # Transition-only: the storm keeps burning across further
        # observations at the beat cadence — no NEW alert fires.
        for i in range(fired_at + 1, fired_at + 4):
            assert tracker.observe(
                _slo_doc(shed=10 * i, sent=10 * i), now=T0 + i) == []
        st = {e["objective"]: e for e in tracker.status()}
        assert st["reply_availability"]["burning"]

    def test_recovery_clears_burning(self):
        tracker = SloTracker(window_s=12.0, burn=4.0,
                             recorder=_Recorder())
        for i in range(14):
            tracker.observe(_slo_doc(shed=10 * i, sent=10 * i),
                            now=T0 + i)
        # Flat counters: no new decisions, no windowed error, no burn.
        for i in range(14, 30):
            tracker.observe(_slo_doc(shed=130, sent=130), now=T0 + i)
        st = {e["objective"]: e for e in tracker.status()}
        assert not st["reply_availability"]["burning"]
        # Recovery re-arms the transition: a second storm re-fires.
        fired = []
        for i in range(30, 48):
            fired = tracker.observe(
                _slo_doc(shed=130 + 10 * (i - 29), sent=130), now=T0 + i)
            if fired:
                break
        assert fired and fired[0]["objective"] in ("reply_availability",
                                                   "shed_rate")

    def test_no_alert_without_traffic(self):
        tracker = SloTracker(window_s=12.0, recorder=_Recorder())
        for i in range(20):
            assert tracker.observe(_slo_doc(shed=0, sent=0),
                                   now=T0 + i) == []
        for e in tracker.status():
            assert not e["burning"]

    def test_fenced_procs_never_rank_as_offender(self):
        procs = [{"proc": "replica0", "status": "fenced",
                  "detail": {"shed": 100, "results": 0}},
                 {"proc": "replica1", "status": "fresh",
                  "detail": {"shed": 1, "results": 9}}]
        tracker = SloTracker(window_s=12.0, recorder=_Recorder())
        alert = None
        for i in range(14):
            got = tracker.observe(
                _slo_doc(shed=10 * i, sent=10 * i, procs=procs),
                now=T0 + i)
            if got:
                alert = got[0]
                break
        assert alert is not None and alert["worst"] == "replica1"

    def test_default_objectives_mirror_gates(self, monkeypatch):
        monkeypatch.delenv("DBM_SLO_AVAIL", raising=False)
        objs = {o.name: o for o in default_objectives()}
        assert objs["reply_availability"].budget == pytest.approx(0.01)
        assert objs["shed_rate"].budget == pytest.approx(0.25)
        monkeypatch.setenv("DBM_SLO_AVAIL", "0.999")
        objs = {o.name: o for o in default_objectives()}
        assert objs["reply_availability"].budget == pytest.approx(0.001)

    def test_queue_wait_objective_reads_buckets(self):
        objs = {o.name: o for o in default_objectives()}
        doc = {"cluster": {"histograms": {"sched.queue_wait_s": _hist(
            [1.0, 30.0, 60.0, 120.0], [50, 80, 90, 100], 100, 0.0)}},
            "procs": []}
        bad, total = objs["queue_wait_p99"].cumulative(doc)
        assert (bad, total) == (10.0, 100.0)   # 10 waits over 60s


# -------------------------------------------------------------------- dbmtop


def _load_dbmtop():
    import importlib.util
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "dbmtop.py")
    spec = importlib.util.spec_from_file_location("_dbmtop_under_test",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestDbmtop:
    def _statedir(self, tmp_path):
        d = str(tmp_path)
        for rid in (0, 1):
            r = Registry()
            r.counter("sched.results_sent").inc(20 + rid)
            r.gauge("sched.queue_depth").set(rid)
            r.histogram("sched.queue_wait_s").observe(0.01)
            _publish(d, "replica", rid, f"i{rid}", r)
        r = Registry()
        r.ewma("miner.nonces_per_s").observe(5000.0)
        _publish(d, "miner", 77, "im", r)
        return d

    def test_render_rows_and_slo_bars(self, tmp_path):
        top = _load_dbmtop()
        doc = top.one_doc(self._statedir(tmp_path))
        lines = top.render(doc)
        text = "\n".join(lines)
        assert "replica0" in text and "replica1" in text
        assert "miner77" in text
        assert "slo reply_availability" in text
        assert "3/3 fresh" in text
        # Cluster totals line carries the exact counter sum.
        assert "results 41" in text

    def test_once_json_mode(self, tmp_path, capsys):
        top = _load_dbmtop()
        d = self._statedir(tmp_path)
        assert top.main([d, "--once", "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert {p["proc"] for p in doc["procs"]} == \
            {"replica0", "replica1", "miner77"}
        assert doc["cluster"]["counters"]["sched.results_sent"] == 41
        assert {e["objective"] for e in doc["slo"]} == \
            {"reply_availability", "queue_wait_p99", "shed_rate"}

    def test_missing_statedir(self, tmp_path, capsys):
        top = _load_dbmtop()
        assert top.main([str(tmp_path / "nope"), "--once"]) == 2
        capsys.readouterr()
