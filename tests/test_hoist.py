"""Oracle equivalence of the hoisted SHA-256 entry paths (ISSUE 2).

The hoist (ops/sha256_jnp.build_hoist) precomputes lane-invariant work on
the host: the deep midstate after the first ``rem // 4`` rounds of block
0, K[t]+W[t] precombinations, the constant terms of the rounds-16..31
schedule window, and — for digit-free blocks — the entire schedule. Every
one of those cuts is only legal if the device output stays BIT-IDENTICAL
to ``bitcoin.hash.hash_op`` for every lane, for every placement of the
digit bytes. This suite sweeps ``rem`` across word and block boundaries
(word-aligned and straddling digit bytes, 1- and 2-block tails, the
digit-spill-into-block-1 and fully-constant-block-1 shapes) crossed with
k in {1, 5, 9}:

- per-LANE bit-exactness of the jnp tier against hash_op (eager, no jit
  cache pressure), hoisted vs plain vs oracle;
- searcher-level argmin + difficulty early-exit equivalence on both
  device tiers (the pallas tier runs its peeled+hoisted Mosaic kernel
  under the simulator). The tier-1 subsets cover every structural class
  at jit-signature cost the 870 s gate absorbs; the full cross products
  ride the ``slow`` mark (``pytest -m slow tests/test_hoist.py``).

The host-side primitives double as the oracle for the hoist itself:
``compress_rounds`` + ``schedule_words`` must reproduce ``compress_host``
exactly, so a failure localizes to host-builder vs device-consumer.
"""

import numpy as np
import pytest

from distributed_bitcoinminer_tpu.bitcoin.hash import (hash_op, scan_min,
                                                       scan_until)
from distributed_bitcoinminer_tpu.models import NonceSearcher
from distributed_bitcoinminer_tpu.ops.sha256_host import (
    SHA256_H0, compress_host, compress_rounds, schedule_words,
    sha256_midstate)
from distributed_bitcoinminer_tpu.ops.sha256_jnp import (
    build_hoist, build_tail_template, hoist_structure)

#: Word/block-boundary sweep: digit bytes word-aligned (0, 4, 32, 56) and
#: straddling (1, 3, 31, 55, 62, 63); 1-block (rem <= ~46) and 2-block
#: tails; rem 55/56 put the digits at the pad boundary, 62/63 spill them
#: into block 1 for k > 1.
REMS = (0, 1, 3, 4, 31, 32, 55, 56, 62, 63)
KS = (1, 5, 9)


def _mk(rem: int, k: int):
    """(data, midstate, template, hoist) with len(prefix) % 64 == rem."""
    data = "a" * (rem - 1) if rem >= 1 else "a" * 63
    prefix = data.encode() + b" "
    midstate, tail = sha256_midstate(prefix)
    assert len(tail) == rem
    template = build_tail_template(tail, k, len(prefix) + k)
    return data, midstate, template, build_hoist(midstate, template, rem, k)


def _class_range(k: int, span: int = 200):
    lo = 10 ** (k - 1) if k > 1 else 0
    return lo, min(lo + span - 1, 10 ** k - 1)


class TestHostOracle:
    """compress_rounds/schedule_words ARE the hoist's bit-exactness
    oracle; pin them against the reference host compression first."""

    @pytest.mark.parametrize("rem", REMS)
    def test_round_extension_reproduces_compress_host(self, rem):
        _, midstate, template, _ = _mk(rem, 5)
        block = template[0]
        w = schedule_words([int(x) for x in block])
        st = compress_rounds(midstate, w, 0, 64)
        want = compress_host(
            midstate, b"".join(int(x).to_bytes(4, "big") for x in block))
        assert tuple((m + s) & 0xFFFFFFFF
                     for m, s in zip(midstate, st)) == want

    def test_partial_then_rest_equals_whole(self):
        # The deep-midstate split point: rounds [0, wd0) + [wd0, 64) must
        # compose to the full compression for every split.
        msg = b"x" * 64
        w = schedule_words(list(np.frombuffer(msg, dtype=">u4")))
        whole = compress_rounds(SHA256_H0, w, 0, 64)
        for wd0 in (0, 1, 7, 13, 15):
            deep = compress_rounds(SHA256_H0, w, 0, wd0)
            assert compress_rounds(deep, w, wd0, 64) == whole

    @pytest.mark.parametrize("rem,k", [(0, 9), (7, 5), (31, 1), (55, 9),
                                       (62, 5), (63, 1)])
    def test_structure_marks_exactly_the_digit_words(self, rem, k):
        _, _, template, hoist = _mk(rem, k)
        struct = hoist_structure(rem, k, template.shape[0])
        # Block 0's first varying word is the hoist depth.
        assert struct[0][0][0] == rem // 4 == hoist.wd0
        # A block is full-const iff it has no digit bytes.
        for b, (varying, _taps, full) in enumerate(struct):
            assert full == (not varying)
        # 2-block tails without digit spill hoist the whole 48-round
        # expansion of block 1 (4 taps x 48 words).
        if template.shape[0] == 2 and rem + k <= 64:
            assert hoist.full_const[1]
            assert "ckw" in hoist.ops
            assert hoist.schedule_terms_hoisted >= 4 * 48


class TestEveryLaneBitExact:
    """The strongest form of the acceptance sweep: per-LANE digest words
    of the hoisted jnp compression vs hash_op (eager execution — no jit
    signatures, no cache pressure). Tier-1 runs k in {1, 9} for every
    rem plus k=5 at the pad/spill boundaries (the only rems where the
    middle k changes the block structure); the full product rides the
    ``slow`` variant below. The hoisted-vs-plain full-lane comparison
    (which also covers the out-of-class lanes every caller masks) runs
    at the middle k of the boundary rems — the plain path's own oracle
    coverage is the rest of the suite."""

    def _sweep(self, rem, ks):
        import jax.numpy as jnp

        from distributed_bitcoinminer_tpu.ops.search import _hash_lanes
        for k in ks:
            data, midstate, template, hoist = _mk(rem, k)
            lo, _hi = _class_range(k)
            base = max(lo - 13, 0)         # straddle the class floor
            i = np.uint32(base) + jnp.arange(64, dtype=jnp.uint32)
            mid32 = np.asarray(midstate, np.uint32)
            hi_h, lo_h = _hash_lanes(mid32, jnp.asarray(template), i,
                                     rem, k, hoist=hoist.ops)
            if k == 5 and rem in (0, 4, 55, 62):
                hi_p, lo_p = _hash_lanes(mid32, jnp.asarray(template), i,
                                         rem, k)
                # Hoisted == plain on EVERY lane (even out-of-class
                # lanes, which callers mask — the two entry paths must
                # still agree).
                assert bool(jnp.all(hi_h == hi_p)
                            & jnp.all(lo_h == lo_p)), (rem, k)
            # In-class lanes == the reference oracle, lane by lane.
            hi_np, lo_np = np.asarray(hi_h), np.asarray(lo_h)
            for j, n in enumerate(range(base, base + 64)):
                if len(str(n)) != k:
                    continue
                want = hash_op(data, n)
                assert (int(hi_np[j]), int(lo_np[j])) == \
                    (want >> 32, want & 0xFFFFFFFF), (rem, k, n)

    @pytest.mark.parametrize("rem", REMS)
    def test_lanes_match_oracle_and_plain(self, rem):
        self._sweep(rem, (1, 9) if rem not in (55, 56, 62, 63) else KS)

    @pytest.mark.slow
    @pytest.mark.parametrize("rem", REMS)
    def test_lanes_match_oracle_full(self, rem):
        self._sweep(rem, KS)


def _searcher_sweep(rem: int, k: int, tier: str):
    """Argmin + difficulty early-exit of one (rem, k) on one tier, vs the
    sequential host oracle. Ranges are offset so batch boundaries fall
    inside (merge/tie rule in play) and the class floor is straddled."""
    data, *_ = _mk(rem, k)
    lo, hi = _class_range(k)
    s = NonceSearcher(data, batch=64, tier=tier)
    assert s.search(lo, hi) == scan_min(data, lo, hi), (rem, k, tier)
    # Difficulty: a target that first hits mid-range (the argmin + 1
    # always hits AT the argmin — early-exit path, exact first index).
    want = scan_until(data, lo, hi, scan_min(data, lo, hi)[0] + 1)
    assert want[2]
    got = s.search_until(lo, hi, scan_min(data, lo, hi)[0] + 1)
    assert got == want, (rem, k, tier)
    # Miss path: impossible target falls back to the exact argmin.
    assert s.search_until(lo, hi, 1) == (*scan_min(data, lo, hi), False), \
        (rem, k, tier)


#: Tier-1 searcher-level subsets (the per-lane sweep above already covers
#: the FULL rem x k product): every structural class — word-aligned digit
#: start, wd0=1 straddle, deep 1-block hoist, 2-block const-schedule
#: block 1, 2-block digit spill — at jit-signature cost the 870 s tier-1
#: budget absorbs on a cold cache. The full cross products ride the
#: ``slow`` mark (run explicitly: pytest -m slow tests/test_hoist.py).
JNP_TIER1 = (55, 62)
PALLAS_TIER1 = [(0, 9), (55, 9), (62, 5)]


@pytest.mark.parametrize("rem", JNP_TIER1)
def test_searcher_oracle_equivalence_jnp(rem):
    for k in KS:
        _searcher_sweep(rem, k, "jnp")


@pytest.mark.slow
@pytest.mark.parametrize("rem", [r for r in REMS if r not in JNP_TIER1])
def test_searcher_oracle_equivalence_jnp_full(rem):
    for k in KS:
        _searcher_sweep(rem, k, "jnp")


@pytest.mark.parametrize("rem,k", PALLAS_TIER1)
def test_searcher_oracle_equivalence_pallas(rem, k, monkeypatch):
    # The peeled kernel is where the hoist lives (DBM_PEEL gates the
    # chip-default; correctness runs it under the Mosaic simulator).
    monkeypatch.setenv("DBM_PEEL", "1")
    _searcher_sweep(rem, k, "pallas")


@pytest.mark.slow
@pytest.mark.parametrize("rem", REMS)
def test_searcher_oracle_equivalence_pallas_full(rem, monkeypatch):
    monkeypatch.setenv("DBM_PEEL", "1")
    for k in KS:
        _searcher_sweep(rem, k, "pallas")


class TestDeepStaticWindow:
    """ISSUE 4 satellite: the rounds-16..47 static window (DBM_HOIST_DEEP;
    CPU default on). The structure analysis must stay consistent between
    build and trace (keyed off the ``cw2`` operand), and results must be
    bit-identical to the default window for every structural rem class."""

    @pytest.mark.parametrize("rem", (0, 4, 31, 55, 60, 62))
    def test_deep_window_lanes_match_default_window(self, rem):
        import jax.numpy as jnp

        from distributed_bitcoinminer_tpu.ops.search import _hash_lanes
        k = 5
        data, midstate, template, _ = _mk(rem, k)
        deep = build_hoist(midstate, template, rem, k, deep_window=True)
        std = build_hoist(midstate, template, rem, k, deep_window=False)
        assert "cw2" in deep.ops and "cw2" not in std.ops
        # Residual constant taps past round 31 exist for large rem — the
        # taps the deep window is for (e.g. rem=60: w16/w18/w20 const).
        if rem >= 55:
            assert deep.schedule_terms_hoisted > std.schedule_terms_hoisted
        lo, _hi = _class_range(k)
        i = np.uint32(max(lo - 13, 0)) + jnp.arange(64, dtype=jnp.uint32)
        mid32 = np.asarray(midstate, np.uint32)
        hi_d, lo_d = _hash_lanes(mid32, jnp.asarray(template), i, rem, k,
                                 hoist=deep.ops)
        hi_s, lo_s = _hash_lanes(mid32, jnp.asarray(template), i, rem, k,
                                 hoist=std.ops)
        assert bool(jnp.all(hi_d == hi_s) & jnp.all(lo_d == lo_s)), rem

    def test_deep_window_searcher_equivalence(self, monkeypatch):
        """Searcher-level argmin/until equivalence deep vs default window
        at a boundary rem (the env knob drives build_hoist's default)."""
        data = "d" * 59                      # rem = 60: 2-block digit spill
        lo, hi = 10_000, 11_000
        monkeypatch.setenv("DBM_HOIST_DEEP", "1")
        s_deep = NonceSearcher(data, batch=64, tier="jnp")
        assert "cw2" in next(s_deep.plan(lo, hi)).hoist.ops
        monkeypatch.setenv("DBM_HOIST_DEEP", "0")
        s_std = NonceSearcher(data, batch=64, tier="jnp")
        assert "cw2" not in next(s_std.plan(lo, hi)).hoist.ops
        want = scan_min(data, lo, hi)
        assert s_deep.search(lo, hi) == s_std.search(lo, hi) == want
        t = want[0] + 1
        assert s_deep.search_until(lo, hi, t) == \
            s_std.search_until(lo, hi, t) == scan_until(data, lo, hi, t)

    def test_pallas_peel_ignores_deep_operands(self, monkeypatch):
        """The pallas peel kernel's chip-validated SMEM layout reads only
        deep/kw/cw/ckw — a deep-window plan (cw2 present) must lower and
        answer exactly under the simulator."""
        monkeypatch.setenv("DBM_PEEL", "1")
        monkeypatch.setenv("DBM_HOIST_DEEP", "1")
        data, lo, hi = "peeldeep", 100, 499
        s = NonceSearcher(data, batch=64, tier="pallas")
        assert "cw2" in next(s.plan(lo, hi)).hoist.ops
        assert s.search(lo, hi) == scan_min(data, lo, hi)


def test_hoist_off_knob_restores_plain_path():
    s_on = NonceSearcher("cmu440", batch=64, tier="jnp")
    s_off = NonceSearcher("cmu440", batch=64, tier="jnp", hoist=False)
    plan_on = next(s_on.plan(100, 999))
    plan_off = next(s_off.plan(100, 999))
    assert plan_on.hoist is not None and plan_on.hoist_ops is not None
    assert plan_off.hoist is None and plan_off.hoist_ops is None
    assert s_on.search(100, 999) == s_off.search(100, 999) == \
        scan_min("cmu440", 100, 999)


def test_sharded_mesh_takes_hoist_operands():
    """The shard_map body accepts the new hoist operands and the 8-device
    CPU mesh merge stays exact, argmin and difficulty both."""
    from distributed_bitcoinminer_tpu.models import ShardedNonceSearcher
    data = "mesh hoist"
    s = ShardedNonceSearcher(data, batch=64, tier="jnp")
    assert s.n_devices == 8
    assert next(s.plan(0, 4095)).hoist is not None
    assert s.search(0, 4095) == scan_min(data, 0, 4095)
    target = 1 << 59
    assert s.search_until(0, 4095, target) == \
        scan_until(data, 0, 4095, target)


def test_pallas_runtime_fault_on_pipelined_handle_degrades(monkeypatch):
    """A pallas RUNTIME fault (surfacing at device_get, not at dispatch)
    must degrade to jnp for EVERY already-pipelined pallas handle: with
    lookahead, sub k+1 was dispatched as pallas before sub k's fault
    latched the sticky flag, and its force must fall back too instead of
    re-raising (code-review finding on the dispatch/force split)."""
    import jax

    from distributed_bitcoinminer_tpu.bitcoin.hash import scan_min
    from distributed_bitcoinminer_tpu.ops import sha256_pallas

    data, lo, hi = "forcefault", 128, 999   # one 3-digit block
    s = NonceSearcher(data, batch=128, tier="pallas")
    assert s._until_lookahead == 1
    # 7 batches -> subs [4, 2, 1]: two pallas handles in flight at the
    # first fault, plus a post-degradation jnp sub.
    assert [n for _, n in s._sub_dispatches(next(s.plan(lo, hi)))] == \
        [4, 2, 1]
    poison = ("pallas-lazy-result",)
    monkeypatch.setattr(sha256_pallas, "pallas_until",
                        lambda *a, **k: poison)
    real_get = jax.device_get

    def fake_get(x):
        if x is poison:
            raise RuntimeError("synthetic runtime kernel fault")
        return real_get(x)
    monkeypatch.setattr(jax, "device_get", fake_get)
    target = scan_min(data, lo, hi)[0] + 1
    assert s.search_until(lo, hi, target) == scan_until(data, lo, hi, target)
    assert s._until_degraded
    # Argmin path untouched by the degradation flag (still pallas-able,
    # but patched pallas_until only affects the until tier).
    assert s.search_until(lo, hi, 1) == (*scan_min(data, lo, hi), False)


def test_until_pipeline_matches_serial():
    """The pipelined difficulty sub-dispatch (lookahead 1) must return
    byte-identical results to the strictly serial order, hit and miss,
    across a multi-sub pow2 decomposition."""
    data = "pipelined"
    s_pipe = NonceSearcher(data, batch=128, tier="jnp")
    s_ser = NonceSearcher(data, batch=128, tier="jnp")
    s_ser._until_lookahead = 0
    assert s_pipe._until_lookahead == 1
    lo, hi = 128, 895     # 6 batches -> subs [4, 2]: real lookahead
    assert [n for _, n in s_pipe._sub_dispatches(next(s_pipe.plan(lo, hi)))] \
        == [4, 2]
    for target in (1 << 58, scan_min(data, lo, hi)[0] + 1, 1):
        assert s_pipe.search_until(lo, hi, target) == \
            s_ser.search_until(lo, hi, target) == \
            scan_until(data, lo, hi, target)
