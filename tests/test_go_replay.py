"""Byte-level endpoint conformance vs a scripted Go counterparty.

VERDICT r2 "What's missing" #4: no Go toolchain exists in this image and
the reference binaries are darwin-only, so wire compatibility cannot be
proven against a LIVE Go process. This harness is the next-strongest
evidence: a raw UDP socket replays the EXACT bytes the Go reference puts
on the wire (encoding/json marshals struct fields in declaration order —
Type, ConnID, SeqNum, Size, Checksum, Payload — so the byte stream is
deterministic; constructors per lsp/message.go:29-55, connect/ack carry a
zero checksum) and asserts our endpoints' responses byte-for-byte.

Covers, against BOTH our server and our client:
- connect handshake bytes (Connect -> Ack(id, 0));
- data with the Go-computed checksum -> byte-exact Ack, in-order delivery;
- out-of-order raw injection (seq 2 before seq 1) -> buffered, in-order
  release, both acked;
- duplicate Connect dedup (same addr re-acked with the same conn id);
- our client's outbound Data bytes match the Go marshal byte-for-byte
  (including the base64 payload and checksum value).
"""

import asyncio
import json
import socket

from distributed_bitcoinminer_tpu.lsp import make_checksum
from distributed_bitcoinminer_tpu.lsp.client import new_async_client
from distributed_bitcoinminer_tpu.lsp.params import Params
from distributed_bitcoinminer_tpu.lsp.server import new_async_server


def fast_params():
    return Params(epoch_limit=30, epoch_millis=100, window_size=5,
                  max_backoff_interval=1)


def go_connect() -> bytes:
    """json.Marshal(NewConnect()) — ref lsp/message.go:29-31."""
    return (b'{"Type":0,"ConnID":0,"SeqNum":0,"Size":0,"Checksum":0,'
            b'"Payload":null}')


def go_ack(conn_id: int, seq: int) -> bytes:
    """json.Marshal(NewAck(id, seq)) — ref lsp/message.go:47-54."""
    return (f'{{"Type":2,"ConnID":{conn_id},"SeqNum":{seq},"Size":0,'
            f'"Checksum":0,"Payload":null}}').encode()


def go_data(conn_id: int, seq: int, payload: bytes) -> bytes:
    """json.Marshal(NewData(...)) with the reference checksum — ref
    lsp/message.go:33-45, client_impl.go:183-198."""
    import base64
    ck = make_checksum(conn_id, seq, len(payload), payload)
    b64 = base64.b64encode(payload).decode()
    return (f'{{"Type":1,"ConnID":{conn_id},"SeqNum":{seq},'
            f'"Size":{len(payload)},"Checksum":{ck},'
            f'"Payload":"{b64}"}}').encode()


class GoPeer:
    """A raw UDP socket playing the Go side, byte for byte."""

    def __init__(self, target=None):
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.bind(("127.0.0.1", 0))
        self.sock.settimeout(5.0)
        self.target = target
        self.peer_addr = None

    @property
    def port(self):
        return self.sock.getsockname()[1]

    def send(self, raw: bytes, addr=None):
        self.sock.sendto(raw, addr or self.peer_addr or self.target)

    def recv(self) -> bytes:
        raw, addr = self.sock.recvfrom(2000)
        self.peer_addr = addr
        return raw

    def recv_until(self, pred, tries=20) -> bytes:
        """Skip heartbeat re-acks etc. until ``pred(raw)`` matches."""
        for _ in range(tries):
            raw = self.recv()
            if pred(raw):
                return raw
        raise AssertionError("expected packet never arrived")

    def close(self):
        self.sock.close()


def test_go_client_replay_against_our_server():
    async def scenario():
        server = await new_async_server(0, fast_params())
        peer = GoPeer(("127.0.0.1", server.port))
        try:
            # Handshake: Connect -> byte-exact Ack(id, 0).
            peer.send(go_connect())
            ack = await asyncio.to_thread(peer.recv)
            assert ack == go_ack(1, 0), ack
            # Duplicate Connect from the same addr: same id re-acked
            # (ref server_impl.go:327-332).
            peer.send(go_connect())
            ack2 = await asyncio.to_thread(peer.recv)
            assert ack2 == go_ack(1, 0), ack2

            # Out-of-order raw injection: seq 2 lands before seq 1.
            peer.send(go_data(1, 2, b"second"))
            peer.send(go_data(1, 1, b"first"))
            got1 = await asyncio.wait_for(server.read(), 5)
            got2 = await asyncio.wait_for(server.read(), 5)
            assert (got1, got2) == ((1, b"first"), (1, b"second"))
            # Both data messages acked with byte-exact Go acks (order of
            # the two acks is not pinned; heartbeats may interleave).
            want = {go_ack(1, 1), go_ack(1, 2)}
            seen = set()
            while want - seen:
                raw = await asyncio.to_thread(
                    peer.recv_until, lambda r: r in want)
                seen.add(raw)

            # Server-side write reaches the wire as byte-exact Go Data.
            server.write(1, b"reply")
            expect = go_data(1, 1, b"reply")
            raw = await asyncio.to_thread(
                peer.recv_until, lambda r: json.loads(r)["Type"] == 1)
            assert raw == expect, (raw, expect)
            peer.send(go_ack(1, 1))   # ack it so close() flushes cleanly
        finally:
            peer.close()
            await server.close()
    asyncio.run(scenario())


def load_golden(name):
    """(golden dict, label -> bytes) from a checked-in transcript file."""
    import os
    with open(os.path.join(os.path.dirname(__file__), "goldens",
                           name)) as f:
        golden = json.load(f)
    by_label = {e["label"]: e["bytes"].encode()
                for e in golden["transcript"]}
    return golden, by_label


def golden_payload(by_label, label) -> bytes:
    """App payload reconstructed from the golden bytes themselves."""
    import base64
    return base64.b64decode(json.loads(by_label[label])["Payload"])


class TranscriptRecorder:
    """Drift detector shared by the client/server transcript tests: every
    observed packet must byte-equal SOME golden entry; first-occurrence
    order and per-packet counts are kept for the scenario assertions."""

    def __init__(self, peer: GoPeer, byte_set: set):
        self.peer = peer
        self.byte_set = byte_set
        self.seen: list[bytes] = []
        self.counts: dict[bytes, int] = {}

    def record(self, raw: bytes) -> bytes:
        assert raw in self.byte_set, f"unknown packet (drift): {raw!r}"
        if raw not in self.counts:
            self.seen.append(raw)
        self.counts[raw] = self.counts.get(raw, 0) + 1
        return raw

    async def collect_until(self, pred, timeout=4.0):
        deadline = asyncio.get_running_loop().time() + timeout
        while not pred():
            assert asyncio.get_running_loop().time() < deadline, \
                (self.seen, self.counts)
            self.record(await asyncio.to_thread(self.peer.recv))


def test_client_transcript_matches_golden_corpus():
    """VERDICT r3 task 8: the FULL byte stream of a scripted scenario —
    connect -> window-gated writes -> backoff retransmits -> ack of server
    data -> close — frozen against tests/goldens/wire_transcript.json.

    Every packet our client emits must byte-equal a golden entry (drift in
    the codec, checksum, or retransmit path = an unknown packet = fail),
    first occurrences of the window stream must be ordered, retransmits
    must be byte-identical to the original send, and packets beyond the
    window must never appear before their admission acks (C1/C2/C8/C9/C10
    observables in one artifact).
    """
    golden, by_label = load_golden("wire_transcript.json")
    params = Params(**golden["params"])

    async def scenario():
        peer = GoPeer()
        rec = TranscriptRecorder(peer, set(by_label.values()))

        async def fake_go_server():
            raw = rec.record(await asyncio.to_thread(peer.recv))
            assert raw == by_label["connect"]
            peer.send(go_ack(1, 0))
            # Window 2 of 4 queued writes: data1+data2 flow (in order) and
            # retransmit byte-identically; data3/data4 must stay gated.
            await rec.collect_until(
                lambda: rec.counts.get(by_label["data1"], 0) >= 2
                and rec.counts.get(by_label["data2"], 0) >= 2)
            assert rec.seen.index(by_label["data1"]) < \
                rec.seen.index(by_label["data2"])
            assert by_label["data3"] not in rec.counts
            assert by_label["data4"] not in rec.counts
            # Admission acks open the window for data3/data4.
            peer.send(go_ack(1, 1))
            peer.send(go_ack(1, 2))
            await rec.collect_until(lambda: by_label["data3"] in rec.counts
                                    and by_label["data4"] in rec.counts)
            # Server-side data is acked with the exact golden ack bytes.
            peer.send(go_data(1, 1, b"pong"))
            await rec.collect_until(
                lambda: by_label["ack_of_server_data1"] in rec.counts)
            peer.send(go_ack(1, 3))
            peer.send(go_ack(1, 4))

        server_task = asyncio.create_task(fake_go_server())
        client = await new_async_client(f"127.0.0.1:{peer.port}", params)
        try:
            for label in ("data1", "data2", "data3", "data4"):
                client.write(golden_payload(by_label, label))
            got = await asyncio.wait_for(client.read(), 5)
            assert got == b"pong"
            await asyncio.wait_for(server_task, 15)
            # Everything acked; close flushes without new unknown packets.
            await client.close()
            # All golden entries were exercised.
            assert set(by_label.values()) <= set(rec.counts)
        finally:
            if not server_task.done():
                server_task.cancel()
            client._conn.abort()
            client._ep.close()
            peer.close()
    asyncio.run(scenario())


def test_server_transcript_matches_golden_corpus():
    """Server-side sibling of the client transcript test: every byte OUR
    SERVER emits against a scripted Go client — connect grant, epoch
    re-acks, the ack of inbound data, window-gated writes and their
    byte-identical backoff retransmits — frozen against
    tests/goldens/wire_transcript_server.json."""
    golden, by_label = load_golden("wire_transcript_server.json")
    params = Params(**golden["params"])

    async def scenario():
        server = await new_async_server(0, params)
        peer = GoPeer(("127.0.0.1", server.port))
        rec = TranscriptRecorder(peer, set(by_label.values()))
        try:
            peer.send(go_connect())
            raw = rec.record(await asyncio.to_thread(peer.recv))
            assert raw == by_label["grant_ack"]
            # Inbound data is acked with the exact golden bytes.
            peer.send(go_data(1, 1, b"ping"))
            got = await asyncio.wait_for(server.read(), 5)
            assert got == (1, b"ping")
            await rec.collect_until(
                lambda: by_label["ack_of_client_data1"] in rec.counts)
            # Window 2 of 4 queued writes: data1+data2 flow in order and
            # retransmit byte-identically; data3/data4 stay gated.
            for label in ("data1", "data2", "data3", "data4"):
                server.write(1, golden_payload(by_label, label))
            await rec.collect_until(
                lambda: rec.counts.get(by_label["data1"], 0) >= 2
                and rec.counts.get(by_label["data2"], 0) >= 2, timeout=5.0)
            assert rec.seen.index(by_label["data1"]) < \
                rec.seen.index(by_label["data2"])
            assert by_label["data3"] not in rec.counts
            assert by_label["data4"] not in rec.counts
            peer.send(go_ack(1, 1))
            peer.send(go_ack(1, 2))
            await rec.collect_until(lambda: by_label["data3"] in rec.counts
                                    and by_label["data4"] in rec.counts)
            peer.send(go_ack(1, 3))
            peer.send(go_ack(1, 4))
            assert set(by_label.values()) <= set(rec.counts)
            # The heartbeat claim must be non-vacuous: grant_ack and the
            # epoch re-ack share bytes, so require MULTIPLE sightings
            # during an explicitly receive-idle stretch — reminder acks are
            # idle-only (ref timeRoutine, client_impl.go:266-281: the timer
            # re-arms on every receive), so the peer now goes silent and
            # Ack(1, 0) must tick once per epoch.
            base = rec.counts.get(by_label["heartbeat_ack0"], 0)
            await rec.collect_until(
                lambda: rec.counts.get(by_label["heartbeat_ack0"], 0)
                >= base + 3, timeout=10 * params.epoch_millis / 1000.0)
        finally:
            peer.close()
            await server.close()
    asyncio.run(scenario())


def test_our_client_bytes_against_go_server_replay():
    async def scenario():
        peer = GoPeer()

        async def fake_go_server():
            # Expect Connect bytes, grant conn id 42.
            raw = await asyncio.to_thread(peer.recv)
            assert raw == go_connect(), raw
            peer.send(go_ack(42, 0))
            # Expect the client's Data marshal byte-for-byte, then ack.
            raw = await asyncio.to_thread(
                peer.recv_until, lambda r: json.loads(r)["Type"] == 1)
            assert raw == go_data(42, 1, b"1234"), raw
            peer.send(go_ack(42, 1))
            # Push one data message back; expect OUR byte-exact ack.
            peer.send(go_data(42, 1, b"pong"))
            raw = await asyncio.to_thread(
                peer.recv_until, lambda r: r == go_ack(42, 1))
            assert raw == go_ack(42, 1)

        server_task = asyncio.create_task(fake_go_server())
        client = await new_async_client(f"127.0.0.1:{peer.port}",
                                        fast_params())
        try:
            assert client.conn_id() == 42
            client.write(b"1234")
            got = await asyncio.wait_for(client.read(), 5)
            assert got == b"pong"
            await asyncio.wait_for(server_task, 10)
        finally:
            # The scripted peer cannot ack a close flush; abort the engine.
            client._conn.abort()
            client._ep.close()
            peer.close()
    asyncio.run(scenario())
