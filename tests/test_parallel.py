"""Mesh-sharded search: exactness on the virtual 8-device CPU mesh.

Sharded results must be bit-identical to the host oracle — including ties
across device-span boundaries (ref tie rule: bitcoin/miner/miner.go:54-58).
"""

import jax
import numpy as np
import pytest

from distributed_bitcoinminer_tpu.bitcoin.hash import scan_min
from distributed_bitcoinminer_tpu.models import NonceSearcher, ShardedNonceSearcher
from distributed_bitcoinminer_tpu.ops.sha256_host import sha256_midstate
from distributed_bitcoinminer_tpu.ops.sha256_jnp import build_tail_template
from distributed_bitcoinminer_tpu.parallel import (
    device_spans, make_mesh, sharded_search_span)


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "conftest must force 8 virtual devices"
    return make_mesh()


def test_sharded_span_matches_oracle(mesh):
    data = "cmu440"
    prefix = data.encode() + b" "
    midstate, tail = sha256_midstate(prefix)
    k = 4  # lanes are 4-digit nonces within the aligned block [0, 10^4)
    template = build_tail_template(tail, k, len(prefix) + k)
    batch, nbatches = 128, 2
    i0_d = device_spans(1000, 8, batch, nbatches)
    hi, lo, idx = sharded_search_span(
        np.asarray(midstate, np.uint32), template, i0_d,
        np.uint32(1000), np.uint32(2999),
        mesh=mesh, rem=len(tail), k=k, batch=batch, nbatches=nbatches)
    got = (int(hi) << 32) | int(lo)
    want_hash, want_nonce = scan_min(data, 1000, 2999)
    assert got == want_hash
    assert int(idx) == want_nonce


@pytest.mark.parametrize("lower,upper", [
    (0, 4095),            # crosses digit classes 1..4
    (990, 10350),         # crosses a 10^k block boundary
    (123456, 131071),     # single digit class, unaligned
])
def test_sharded_searcher_matches_single_device(mesh, lower, upper):
    data = "distributed"
    sharded = ShardedNonceSearcher(data, batch=256, mesh=mesh)
    single = NonceSearcher(data, batch=256)
    assert sharded.search(lower, upper) == single.search(lower, upper)


def test_sharded_searcher_matches_cpu_oracle(mesh):
    data = "tie hunt"
    sharded = ShardedNonceSearcher(data, batch=64, mesh=mesh)
    assert sharded.search(50, 2049) == scan_min(data, 50, 2049)


def test_sharded_pallas_tier_matches_jnp_tier(mesh):
    """VERDICT r2 task 4: the sharded pallas path must actually execute.
    On the CPU mesh the kernel rides the Mosaic TPU simulator inside the
    shard_map body (vma-typed outputs); the collective merge semantics are
    pinned by equality with the jnp tier and the oracle. One small block
    keeps the simulator cost down (~1 grid step per device)."""
    data = "cmu440"
    prefix = data.encode() + b" "
    midstate, tail = sha256_midstate(prefix)
    k = 4
    template = build_tail_template(tail, k, len(prefix) + k)
    batch, nbatches = 128, 1
    i0_d = device_spans(1000, 8, batch, nbatches)
    args = (np.asarray(midstate, np.uint32), template, i0_d,
            np.uint32(1100), np.uint32(1987))
    kw = dict(mesh=mesh, rem=len(tail), k=k, batch=batch, nbatches=nbatches)
    got_p = [int(x) for x in sharded_search_span(*args, tier="pallas", **kw)]
    got_j = [int(x) for x in sharded_search_span(*args, tier="jnp", **kw)]
    assert got_p == got_j
    want_hash, want_nonce = scan_min(data, 1100, 1987)
    assert ((got_p[0] << 32) | got_p[1], got_p[2]) == (want_hash, want_nonce)


def test_unaligned_window_top_lanes_covered(mesh):
    """Regression: nbatches sized from lo_i (not the aligned scan start i0)
    left up to batch-1 top lanes unscanned when the window filled a whole
    number of per-step spans. Repro range from the code-review finding."""
    data = "cmu440"
    sharded = ShardedNonceSearcher(data, batch=64, mesh=mesh)
    assert sharded.search(1357, 1868) == scan_min(data, 1357, 1868)
    single = NonceSearcher(data, batch=64)
    assert single.search(1001, 1064) == scan_min(data, 1001, 1064)


def test_sharded_until_pallas_tier_matches_oracle():
    """Sharded difficulty mode through the Mosaic kernel (simulator on the
    CPU mesh): first-qualifying merge = pmin of per-device hit indices."""
    import jax

    from distributed_bitcoinminer_tpu.bitcoin.hash import hash_op, scan_min
    from distributed_bitcoinminer_tpu.models import ShardedNonceSearcher
    from distributed_bitcoinminer_tpu.parallel import make_mesh

    mesh = make_mesh(4, jax.devices("cpu"))
    data = "shardun"
    s = ShardedNonceSearcher(data, batch=128, mesh=mesh, tier="pallas")
    lo, hi = 1000, 1000 + 128 * 4 - 1
    hashes = {n: hash_op(data, n) for n in range(lo, hi + 1)}
    # hit only on the LAST device's span
    target = min(h for n, h in hashes.items() if n >= lo + 128 * 3) + 1
    first = next(n for n in range(lo, hi + 1) if hashes[n] < target)
    assert s.search_until(lo, hi, target) == (hashes[first], first, True)
    wh, wn = scan_min(data, lo, hi)
    assert s.search_until(lo, hi, min(hashes.values())) == (wh, wn, False)
