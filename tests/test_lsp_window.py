"""Sliding-window + exponential-backoff semantics.

Port of the reference lsp2_test.go scenarios: max-capacity windows (acks
blackholed => exactly the first W messages cross), out-of-order release, and
the graded retransmit-counting law (sniff N epochs with acks dropped and
assert the on-wire send count matches the XXOXOOX0000X backoff pattern;
ref: lsp2_test.go:503-533).
"""

import asyncio

from distributed_bitcoinminer_tpu import lspnet
from distributed_bitcoinminer_tpu.lsp import Params
from distributed_bitcoinminer_tpu.lsp.client import new_async_client
from distributed_bitcoinminer_tpu.lsp.server import new_async_server


def params_with(window=1, backoff=0, epoch_ms=50, limit=5):
    return Params(epoch_limit=limit, epoch_millis=epoch_ms,
                  window_size=window, max_backoff_interval=backoff)


class TestWindowMaxCapacity:
    def test_only_window_size_messages_cross_without_acks(self):
        """Blackhole server->client acks; client must stop at W unacked
        (ref runMaxCapacityTest, lsp2_test.go:335-400)."""
        async def scenario():
            window = 3
            params = params_with(window=window, backoff=1, epoch_ms=50, limit=60)
            server = await new_async_server(0, params)
            client = await new_async_client(f"127.0.0.1:{server.port}", params)

            # Server writes nothing; acks from the server are dropped.
            lspnet.set_server_write_drop_percent(100)
            for i in range(10):
                client.write(f"m{i}".encode())

            received = []
            async def reader():
                while True:
                    _, payload = await server.read()
                    if isinstance(payload, bytes):
                        received.append(payload)
            reader_task = asyncio.create_task(reader())
            await asyncio.sleep(0.6)  # several epochs of retransmits
            assert sorted(received) == [f"m{i}".encode() for i in range(window)], \
                f"window overflow: {received}"

            # Heal the network: the rest must flow.
            lspnet.set_server_write_drop_percent(0)
            await asyncio.sleep(1.0)
            assert sorted(received) == sorted(f"m{i}".encode() for i in range(10))
            reader_task.cancel()
            await client.close()
            await server.close()
        asyncio.run(scenario())


class TestOutOfOrder:
    def test_in_order_release_with_delays(self):
        """50% of packets delayed 500 ms; receiver must still see order
        (ref runMessageOrderTest, lsp2_test.go:481-501)."""
        async def scenario():
            params = params_with(window=20, backoff=1, epoch_ms=300, limit=10)
            server = await new_async_server(0, params)
            client = await new_async_client(f"127.0.0.1:{server.port}", params)
            lspnet.set_delay_message_percent(50)
            n = 30
            for i in range(n):
                client.write(f"m{i:03d}".encode())
            got = []
            while len(got) < n:
                _, payload = await asyncio.wait_for(server.read(), 10)
                if isinstance(payload, bytes):
                    got.append(payload)
            assert got == [f"m{i:03d}".encode() for i in range(n)]
            lspnet.set_delay_message_percent(0)
            await client.close()
            await server.close()
        asyncio.run(scenario())


class TestExpBackOff:
    def test_retransmit_count_matches_backoff_law(self):
        """Unbounded backoff: ~5 sends per message in 14 epochs, graded as
        4-6x window x messages (ref lsp2_test.go:503-533)."""
        async def scenario():
            window = 2
            epochs = 14
            epoch_ms = 60
            params = params_with(window=window, backoff=1000,
                                 epoch_ms=epoch_ms, limit=epochs + 6)
            server = await new_async_server(0, params)
            client = await new_async_client(f"127.0.0.1:{server.port}", params)
            # Blackhole everything the server sends: no acks ever arrive.
            lspnet.set_server_write_drop_percent(100)
            lspnet.start_sniff()
            for i in range(window):
                client.write(f"m{i}".encode())
            await asyncio.sleep(epochs * epoch_ms / 1000.0)
            result = lspnet.stop_sniff()
            lspnet.set_server_write_drop_percent(0)
            total = result.num_sent_data
            low, high = 4 * window, 6 * window
            assert low <= total <= high, \
                f"sent {total} data packets; expected [{low}, {high}]"
            await client.close()
            await server.close()
        asyncio.run(scenario())

    def test_retransmit_law_ten_clients(self):
        """TestExpBackOff2 analog (ref lsp2_test.go:542-547): the sniffer-
        counted 4-6 sends-per-window law must hold aggregated over 10
        concurrent clients each streaming into blackholed acks."""
        async def scenario():
            window, nclients = 5, 10
            epochs, epoch_ms = 14, 60
            params = params_with(window=window, backoff=1000,
                                 epoch_ms=epoch_ms, limit=epochs + 10)
            server = await new_async_server(0, params)
            clients = [await new_async_client(f"127.0.0.1:{server.port}",
                                              params)
                       for _ in range(nclients)]
            lspnet.set_server_write_drop_percent(100)
            lspnet.start_sniff()
            try:
                for c in clients:
                    for i in range(15):  # > window: only 5 reach the wire
                        c.write(f"m{i}".encode())
                await asyncio.sleep(epochs * epoch_ms / 1000.0)
                result = lspnet.stop_sniff()
                lspnet.set_server_write_drop_percent(0)
                total = result.num_sent_data
                low, high = 4 * window * nclients, 6 * window * nclients
                assert low <= total <= high, \
                    f"sent {total} data packets; expected [{low}, {high}]"
            finally:
                # Close before a failed assertion can leak 11 endpoints
                # mid-retransmit into the loop teardown (review r3).
                lspnet.set_server_write_drop_percent(0)
                for c in clients:
                    await c.close()
                await server.close()
        asyncio.run(scenario())

    def test_capped_backoff_resends_regularly(self):
        """max_backoff=1 => a resend at least every 2 epochs."""
        async def scenario():
            epochs = 10
            epoch_ms = 60
            params = params_with(window=1, backoff=1, epoch_ms=epoch_ms,
                                 limit=epochs + 6)
            server = await new_async_server(0, params)
            client = await new_async_client(f"127.0.0.1:{server.port}", params)
            lspnet.set_server_write_drop_percent(100)
            lspnet.start_sniff()
            client.write(b"x")
            await asyncio.sleep(epochs * epoch_ms / 1000.0)
            result = lspnet.stop_sniff()
            lspnet.set_server_write_drop_percent(0)
            # send pattern with cap 1: X X O X O X O X ... ~ 1 + ceil(epochs/2)
            assert result.num_sent_data >= 1 + (epochs - 2) // 2, \
                f"too few sends: {result.num_sent_data}"
            await client.close()
            await server.close()
        asyncio.run(scenario())


class TestReadQueueBound:
    def test_never_reading_server_backpressures_at_cap(self):
        """VERDICT r4: the server's delivery queue is bounded at the
        reference's 500 (ref server_impl.go:112). A client streaming into a
        never-reading server must see its window stall — the queue settles
        at exactly the cap — and once the app starts reading, every message
        still arrives exactly once, in order."""
        async def scenario():
            from distributed_bitcoinminer_tpu.lsp.server import READ_QUEUE_CAP
            n_msgs = READ_QUEUE_CAP + 100
            params = params_with(window=20, backoff=1, epoch_ms=40,
                                 limit=1000)
            server = await new_async_server(0, params)
            client = await new_async_client(f"127.0.0.1:{server.port}", params)
            for i in range(n_msgs):
                client.write(b"%d" % i)
            # Let deliveries run to the cap and the stall settle (a few
            # retransmit rounds of the withheld oldest-unacked message).
            for _ in range(100):
                await asyncio.sleep(0.05)
                if server._read_queue.qsize() >= READ_QUEUE_CAP:
                    break
            await asyncio.sleep(0.3)
            assert server._read_queue.qsize() == READ_QUEUE_CAP
            # Draining the app side releases the back-pressure: each read
            # at the cap wakes the connections, so the parked backlog
            # delivers immediately — exactly-once, in order, with no
            # retransmit-latency dependence.
            for i in range(n_msgs):
                _, payload = await asyncio.wait_for(server.read(), 15)
                assert payload == b"%d" % i
            await client.close()
            await server.close()
        asyncio.run(scenario())


class TestBackPressureEngine:
    def test_parked_acked_backlog_drains_without_retransmits(self):
        """Regression (code-review r5): an out-of-order message acked
        BEFORE the cap hit must not strand — once it is acked the peer
        never retransmits it, so resume_delivery() is the only path that
        can ever deliver it. The head parks unacked (and its retransmit
        must not be re-acked as a duplicate) until delivery."""
        async def scenario():
            from distributed_bitcoinminer_tpu.lsp._engine import Conn
            from distributed_bitcoinminer_tpu.lsp.checksum import make_checksum
            from distributed_bitcoinminer_tpu.lsp.message import new_data

            sent, delivered, ready = [], [], [True]
            conn = Conn(params=params_with(epoch_ms=10_000),
                        conn_id=7, send_raw=sent.append,
                        deliver=delivered.append, broken=lambda e: None,
                        deliver_ready=lambda: ready[0])

            def data(seq, payload):
                return new_data(7, seq, len(payload), payload,
                                make_checksum(7, seq, len(payload), payload))

            conn.on_message(data(2, b"second"))   # out of order: acked, parked
            acks_after_ooo = len(sent)
            assert acks_after_ooo == 1
            ready[0] = False                      # queue hits the cap
            conn.on_message(data(1, b"first"))    # head: parked, NOT acked
            assert len(sent) == acks_after_ooo and delivered == []
            conn.on_message(data(1, b"first"))    # head retransmit: still unacked
            assert len(sent) == acks_after_ooo
            ready[0] = True                       # app read; owner wakes us
            conn.resume_delivery()
            assert delivered == [b"first", b"second"]
            assert len(sent) == acks_after_ooo + 1  # head acked at delivery
            conn.on_message(data(1, b"first"))    # late dup: normal re-ack
            assert len(sent) == acks_after_ooo + 2
            assert delivered == [b"first", b"second"]
            conn.abort()
        asyncio.run(scenario())


class TestHeartbeat:
    def test_busy_link_sends_no_reminder_acks(self):
        """Idle-only heartbeat fidelity (VERDICT r4): the reference re-arms
        its reminder timer on every receive, so a busy connection emits ONLY
        per-message data acks — with the old every-epoch heartbeat this
        wire would carry ~2 extra acks per epoch (both endpoints)."""
        async def scenario():
            epochs, epoch_ms = 12, 60
            params = params_with(window=8, epoch_ms=epoch_ms,
                                 limit=epochs + 6)
            server = await new_async_server(0, params)
            client = await new_async_client(f"127.0.0.1:{server.port}", params)
            n_msgs = epochs * 3   # one write per epoch/3: no silent epochs
            lspnet.start_sniff()
            for i in range(n_msgs):
                client.write(f"m{i}".encode())
                await server.read()
                await asyncio.sleep(epoch_ms / 3000.0)
            result = lspnet.stop_sniff()
            # One data ack per message; a few strays allowed for event-loop
            # stalls. Every-epoch heartbeats (2 * epochs more) must fail.
            assert result.num_sent_acks <= n_msgs + epochs // 2, \
                f"{result.num_sent_acks} acks for {n_msgs} messages"
            assert result.num_sent_data >= n_msgs
            await client.close()
            await server.close()
        asyncio.run(scenario())

    def test_quiet_link_heartbeats_every_idle_epoch(self):
        """On a mutually idle link BOTH sides must keep heartbeating every
        epoch — a peer's reminder ack is not substantive traffic and must
        not suppress ours, or its loss detector (fed only by our sends)
        would starve and drop a live link (the reference's reminder race
        reliably fires: heartbeats arrive one epoch + latency apart)."""
        async def scenario():
            epochs, epoch_ms = 12, 60
            params = params_with(window=1, epoch_ms=epoch_ms,
                                 limit=epochs + 6)
            server = await new_async_server(0, params)
            client = await new_async_client(f"127.0.0.1:{server.port}", params)
            await asyncio.sleep(0.05)  # let the connect exchange drain
            lspnet.start_sniff()
            await asyncio.sleep(epochs * epoch_ms / 1000.0)
            result = lspnet.stop_sniff()
            # ~1 reminder per side per epoch; suppression-on-heartbeat
            # (alternation, ~epochs total) must fail the lower bound.
            assert 2 * epochs - 4 <= result.num_sent_acks <= 2 * epochs + 6, \
                f"{result.num_sent_acks} reminder acks in {epochs} epochs"
            await client.close()
            await server.close()
        asyncio.run(scenario())

    def test_idle_connection_stays_alive(self):
        """No data for >> epoch_limit epochs; heartbeats keep the link up."""
        async def scenario():
            params = params_with(window=1, epoch_ms=40, limit=3)
            server = await new_async_server(0, params)
            client = await new_async_client(f"127.0.0.1:{server.port}", params)
            await asyncio.sleep(0.5)  # ~12 epochs of silence
            client.write(b"still here")
            conn_id, payload = await asyncio.wait_for(server.read(), 5)
            assert payload == b"still here"
            await client.close()
            await server.close()
        asyncio.run(scenario())
