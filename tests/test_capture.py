"""Workload capture & replay plane (ISSUE 15, ``apps/capture.py``).

Covers the capture file format (versioned header, refusal of unknown
versions, torn-tail tolerance, the rotation disk bound), the scheduler
hooks (req/rep/shed/cancel records through a scripted drive, the
``DBM_CAPTURE=0`` byte-for-byte parity pin the tier-1 knob-off matrix
leg re-runs), the deterministic replay plan, the capture→replay round
trip on the detnet harness (shape-equal reports, fidelity inside the
stated bounds), the fidelity verdict arithmetic (speed rescale, None
bounds, request-count mismatch), crash-artifact naming (flight dump +
metrics emitter embed the active capture), the dbmcheck
``replayed_storm`` scenario, and the ``benchdiff`` / ``dbmtrace
summarize`` satellites.
"""

from __future__ import annotations

import json
import logging
import os
import sys

import pytest

from distributed_bitcoinminer_tpu.apps import capture as capmod
from distributed_bitcoinminer_tpu.apps.capture import (
    CAPTURE_VERSION, WorkloadCapture, capture_baseline, fidelity,
    load_capture, replay_plan)
from distributed_bitcoinminer_tpu.apps.scheduler import Scheduler
from distributed_bitcoinminer_tpu.bitcoin.message import (Message,
                                                          new_request,
                                                          new_result)
from distributed_bitcoinminer_tpu.utils import metrics as umetrics
from distributed_bitcoinminer_tpu.utils.config import (CacheParams,
                                                       LeaseParams,
                                                       QosParams,
                                                       VerifyParams)

MINER_A, MINER_B = 1, 2
TEN_X, TEN_Y = 10, 11

_SCRIPTS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts")


def _cap(tmp_path, **kw):
    kw.setdefault("snap_s", 0.0)
    return WorkloadCapture(path=str(tmp_path / "cap.jsonl"), **kw)


# ---------------------------------------------------------- file format


def test_records_round_trip_through_loader(tmp_path):
    cap = _cap(tmp_path)
    cap.config(max_queued=64, qos=True)
    cap.request(5, 8, 256, False)
    cap.request(6, 8, 4096, True)
    cap.reply(5, 0.25)
    cap.reply(6, 0.0, cached=True)
    cap.shed(7, "overload")
    cap.cancel(7, 2)
    cap.reissue()
    cap.span({"queue_s": 0.1, "force_s": 0.2, "bogus": "dropped",
              "lanes": 4})
    cap.maybe_snapshot(miners=2, rates=[1000.0, 2000.0], queued=3,
                       inflight=1)
    cap.close()
    c = load_capture(cap.path)
    assert c.header["v"] == CAPTURE_VERSION
    assert c.cfg == {"max_queued": 64, "qos": True}
    assert [r["mode"] for r in c.reqs] == ["argmin", "diff"]
    assert [r["n"] for r in c.reqs] == [256, 4096]
    # Hashed tenant keys: distinct per conn, stable within the capture,
    # and never the raw conn id.
    assert c.reqs[0]["ten"] != c.reqs[1]["ten"]
    assert c.reqs[0]["ten"] == c.reps[0]["ten"]
    assert "5" != c.reqs[0]["ten"]
    assert c.reps[1]["cached"] is True
    assert c.sheds[0]["why"] == "overload"
    assert c.cancels[0]["n"] == 2
    assert c.reissues == 1
    assert c.spans[0]["force_s"] == 0.2
    assert "bogus" not in c.spans[0]       # whitelist held
    assert c.pools[0]["rates"] == [1000.0, 2000.0]


def test_unknown_version_refused(tmp_path):
    path = tmp_path / "v99.jsonl"
    path.write_text(json.dumps({"k": "hdr", "v": 99, "t0": 0}) + "\n")
    with pytest.raises(ValueError, match="unsupported capture version"):
        load_capture(str(path))


def test_headerless_file_refused(tmp_path):
    path = tmp_path / "nohdr.jsonl"
    path.write_text(json.dumps({"k": "req", "t": 0.0, "ten": "x",
                                "n": 1}) + "\n")
    with pytest.raises(ValueError, match="not a workload capture"):
        load_capture(str(path))
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    with pytest.raises(ValueError, match="empty capture"):
        load_capture(str(empty))


def test_torn_tail_line_skipped(tmp_path):
    cap = _cap(tmp_path)
    cap.request(1, 8, 64, False)
    cap.reply(1, 0.1)
    cap.close()
    with open(cap.path, "a", encoding="utf-8") as fh:
        fh.write('{"k": "rep", "t": 9.9, "ten": "torn')   # crash mid-write
    c = load_capture(cap.path)
    assert len(c.reqs) == 1 and len(c.reps) == 1


def test_records_are_line_durable_without_close(tmp_path):
    """Every record reaches the OS as it is written (line buffering):
    a SIGTERM'd/killed process must lose nothing already recorded —
    atexit does not run on SIGTERM, and a live 3-process drive lost
    every record between the last snapshot flush and the kill before
    this was pinned."""
    cap = _cap(tmp_path)
    cap.request(1, 8, 64, False)
    cap.reply(1, 0.1)
    # No close(), no flush(): read what is durably visible NOW.
    with open(cap.path, encoding="utf-8") as fh:
        lines = fh.read().splitlines()
    assert len(lines) == 3            # header + req + rep
    cap.close()


def test_rotation_bounds_disk(tmp_path):
    cap = _cap(tmp_path, max_lines=1024)   # ctor floor
    cap.max_lines = 50                     # tighten for the test
    for i in range(400):
        cap.request(i, 8, 64, False)
    cap.close()
    assert cap._rotations >= 1
    # At most ~two windows on disk, nothing else.
    n_current = sum(1 for _ in open(cap.path, encoding="utf-8"))
    n_rotated = sum(1 for _ in open(cap.path + ".1", encoding="utf-8"))
    assert n_current <= 50 and n_rotated <= 50
    assert not os.path.exists(cap.path + ".2")
    # Each window restarts with its own header: both load alone.
    for p in (cap.path, cap.path + ".1"):
        c = load_capture(p)
        assert c.header["v"] == CAPTURE_VERSION
        assert c.reqs


def test_rotation_reemits_config(tmp_path):
    """A rotated-in window keeps the attach config — especially the
    transport tag the replay side's cross-transport gating needs
    (code review)."""
    cap = _cap(tmp_path, max_lines=1024)
    cap.max_lines = 20
    cap.config(max_queued=7, transport="AsyncServer")
    for i in range(60):
        cap.request(i, 8, 64, False)
    cap.close()
    assert cap._rotations >= 1
    current = load_capture(cap.path)
    assert current.cfg["max_queued"] == 7
    assert current.cfg["transport"] == "AsyncServer"


# ------------------------------------------------------ scheduler hooks


class FakeServer:
    def __init__(self):
        self.writes = []
        self.closed = []

    def write(self, conn_id, payload):
        self.writes.append((conn_id, Message.from_json(payload)))

    def close_conn(self, conn_id):
        self.closed.append(conn_id)


def _drive(sched):
    """Scripted storm: two miners, three requests, one tenant flood
    that trips the max_queued=2 overload shed."""
    sched._on_join(MINER_A)
    sched._on_join(MINER_B)
    sched._pool_rate = 100.0
    sched._on_request(TEN_X, new_request("alpha", 0, 999))
    sched._on_request(TEN_Y, new_request("beta", 0, 499))
    sched._on_request(TEN_X, new_request("gamma", 0, 99))
    sched._on_request(TEN_Y, new_request("delta", 0, 99))
    for _ in range(400):
        popped = None
        for m in sched.miners:
            if m.pending:
                popped = m.pending[0]
                sched._on_result(m.conn_id,
                                 new_result(1_000_000 + popped.lower,
                                            popped.lower))
                break
        if popped is None:
            break


def _sched(capture=None, max_queued=0):
    # _drive feeds synthetic hashes the claim check would reject;
    # verification has its own suite (test_verify.py), so pin it off.
    return Scheduler(FakeServer(), lease=LeaseParams(),
                     cache=CacheParams(enabled=False),
                     qos=QosParams(enabled=True, max_queued=max_queued),
                     verify=VerifyParams(enabled=False),
                     capture=capture)


def test_scheduler_hooks_record_the_drive(tmp_path):
    cap = _cap(tmp_path)
    sched = _sched(capture=cap, max_queued=2)
    _drive(sched)
    cap.close()
    c = load_capture(cap.path)
    assert len(c.reqs) == 4                   # every arrival, shed or not
    assert c.cfg["max_queued"] == 2
    # max_queued=2 sheds oldest queued work as the flood lands; sheds +
    # replies + cancels cover what the drive produced.
    assert len(c.sheds) == sched.stats["qos_shed"] > 0
    assert len(c.reps) == sched.stats["results_sent"] > 0
    # Distinct tenants stayed distinct through the hash.
    assert len({r["ten"] for r in c.reqs}) == 2


def test_capture_off_is_bit_for_bit_stock(monkeypatch, tmp_path):
    """The tier-1 matrix-leg pin: DBM_CAPTURE=0 (and unset — the
    default) builds NO capture, and every write a capture-armed
    scheduler emits is byte-identical to the stock one's — the plane
    is observability-only by construction."""
    monkeypatch.delenv("DBM_CAPTURE", raising=False)
    assert _sched().capture is None            # default off
    monkeypatch.setenv("DBM_CAPTURE", "0")
    assert _sched().capture is None
    cap = _cap(tmp_path)
    on = _sched(capture=cap, max_queued=2)
    off = _sched(max_queued=2)
    _drive(on)
    _drive(off)
    cap.close()
    assert [(c, m.to_json()) for c, m in on.server.writes] == \
        [(c, m.to_json()) for c, m in off.server.writes]
    assert on.server.closed == off.server.closed


def test_capture_false_refuses_env_arming(monkeypatch, tmp_path):
    """The replay-side guard (code review): ``capture=False`` must not
    let a lingering DBM_CAPTURE=1 open — and truncate — the capture
    file, which may be the very file being replayed."""
    path = tmp_path / "precious.jsonl"
    cap = WorkloadCapture(path=str(path), snap_s=0.0)
    cap.request(1, 8, 64, False)
    cap.close()
    before = path.read_text()
    monkeypatch.setenv("DBM_CAPTURE", "1")
    monkeypatch.setenv("DBM_CAPTURE_PATH", str(path))
    try:
        sched = Scheduler(FakeServer(), lease=LeaseParams(),
                          cache=CacheParams(enabled=False),
                          qos=QosParams(), capture=False)
        assert sched.capture is None
        assert path.read_text() == before      # not truncated
    finally:
        capmod.close_active()


def test_replay_does_not_truncate_source_under_env_capture(
        monkeypatch, tmp_path):
    from distributed_bitcoinminer_tpu.apps.loadharness import (
        run_load, run_replay)
    path = str(tmp_path / "storm.jsonl")
    run_load(tenants=20, replicas=1, miners=2, req_nonces=128,
             capture_path=path, timeout_s=30.0)
    monkeypatch.setenv("DBM_CAPTURE", "1")
    monkeypatch.setenv("DBM_CAPTURE_PATH", path)
    try:
        rep = run_replay(path, timeout_s=30.0)
    finally:
        capmod.close_active()
    assert rep["completed"] == 20
    # The source survived the replay and still loads.
    assert len(load_capture(path).reqs) == 20


def test_env_armed_capture_is_process_shared(monkeypatch, tmp_path):
    path = str(tmp_path / "env_cap.jsonl")
    monkeypatch.setenv("DBM_CAPTURE", "1")
    monkeypatch.setenv("DBM_CAPTURE_PATH", path)
    try:
        a = _sched()
        b = _sched()
        assert a.capture is b.capture          # one trace per process
        assert a.capture.path == path
    finally:
        capmod.close_active()
    assert capmod.ensure_from_env() is not None
    capmod.close_active()
    monkeypatch.setenv("DBM_CAPTURE", "0")
    assert capmod.ensure_from_env() is None


# --------------------------------------------------- plan + round trip


def test_replay_plan_is_deterministic(tmp_path):
    cap = _cap(tmp_path)
    for i in range(20):
        cap.request(i % 7, 8, 128 + i, i % 3 == 0)
    cap.close()
    c1, c2 = load_capture(cap.path), load_capture(cap.path)
    assert replay_plan(c1) == replay_plan(c2)
    plan = replay_plan(c1)
    assert len(plan) == 7
    assert [p["name"] for p in plan] == [f"r{i}" for i in range(7)]
    assert sum(len(p["reqs"]) for p in plan) == 20
    assert replay_plan(c1, max_tenants=3) == plan[:3]
    # Offsets are relative and non-negative.
    assert plan[0]["start"] == 0.0
    for p in plan:
        assert p["reqs"][0][0] == 0.0
        assert all(dt >= 0 for dt, _n, _m, _d in p["reqs"])


def test_capture_replay_round_trip_shape_equal(tmp_path):
    """The acceptance round trip: a captured synthesized storm replays
    with the same request population — and twice in a row with
    shape-equal reports — inside the stated fidelity bounds."""
    from distributed_bitcoinminer_tpu.apps.loadharness import (
        run_load, run_replay)
    path = str(tmp_path / "storm.jsonl")
    leg = run_load(tenants=120, replicas=1, miners=3, req_nonces=256,
                   capture_path=path, timeout_s=60.0)
    assert leg["completed"] == 120
    reps = [run_replay(path, timeout_s=60.0) for _ in range(2)]
    for rep in reps:
        assert rep["requests"] == 120          # every captured arrival
        assert rep["completed"] == 120         # instant pool: all served
        assert rep["shed_requests"] == 0
        assert rep["capture"]["requests"] == 120
        assert rep["fidelity"]["within"], rep["fidelity"]
    # Shape-equal across replays: same population, same outcome set.
    assert reps[0]["requests"] == reps[1]["requests"]
    assert reps[0]["completed"] == reps[1]["completed"]
    assert reps[0]["tenants"] == reps[1]["tenants"]


def test_replay_max_tenants_compares_against_window_baseline(tmp_path):
    """A max_tenants-truncated replay gates against the SAME tenant
    window's baseline — comparing against the full capture guaranteed
    a request-count violation (code review)."""
    from distributed_bitcoinminer_tpu.apps.loadharness import (
        run_load, run_replay)
    path = str(tmp_path / "storm.jsonl")
    run_load(tenants=40, replicas=1, miners=2, req_nonces=128,
             capture_path=path, timeout_s=30.0)
    rep = run_replay(path, max_tenants=10, timeout_s=30.0)
    assert rep["requests"] == 10
    assert rep["capture"]["requests"] == 10     # windowed baseline
    assert rep["completed"] == 10
    assert rep["fidelity"]["within"], rep["fidelity"]


def test_replay_preserves_geometry_mix(tmp_path):
    """Difficulty mode and range sizes survive the round trip: the
    replayed scheduler sees the captured geometry, not a homogenized
    one."""
    cap = _cap(tmp_path)
    cap.config(max_queued=0, qos=True, wholesale_s=5.0)
    cap.request(1, 8, 512, False)
    cap.request(2, 8, 2048, True)
    cap.reply(1, 0.01)
    cap.reply(2, 0.01)
    cap.close()
    from distributed_bitcoinminer_tpu.apps.loadharness import run_replay
    rep = run_replay(cap.path, timeout_s=30.0)
    assert rep["completed"] == rep["requests"] == 2


# ------------------------------------------------------------- fidelity


def test_fidelity_speed_rescale_and_bounds():
    base = {"requests": 100, "admitted_per_s": 100.0, "p99_s": 1.0,
            "shed_rate": 0.1}
    rep = {"requests": 100, "admitted_per_s": 400.0, "p99_s": 3.0,
           "shed_rate": 0.15}
    out = fidelity(base, rep, speed=4.0)
    assert out["admitted_ratio"] == 1.0        # rescaled by the warp
    assert out["within"], out                  # p99 ungated off 1.0 speed
    out1 = fidelity(base, rep, speed=1.0)
    assert out1["admitted_ratio"] == 4.0
    assert not out1["within"]
    assert any("admitted" in v for v in out1["violations"])


def test_fidelity_zero_replay_rate_still_gates():
    """A near-dead replay's admitted/s rounds to 0.0; truthiness would
    skip the ratio gate exactly then (code review)."""
    base = {"requests": 3000, "admitted_per_s": 50.0, "p99_s": 1.0,
            "shed_rate": 0.0}
    rep = {"requests": 3000, "admitted_per_s": 0.0, "p99_s": 0.0,
           "shed_rate": 0.0}
    out = fidelity(base, rep)
    assert not out["within"]
    assert any("admitted" in v for v in out["violations"])
    assert any("p99" in v for v in out["violations"])


def test_baseline_excludes_cached_replies_from_percentiles(tmp_path):
    cap = _cap(tmp_path)
    cap.request(1, 8, 64, False)
    cap.request(2, 8, 64, False)
    cap.reply(1, 2.0)
    cap.reply(2, 0.0, cached=True)
    cap.close()
    base = capture_baseline(load_capture(cap.path))
    assert base["completed"] == 2          # cached replies still served
    assert base["p50_s"] == 2.0            # but never deflate latency


def test_fidelity_none_bound_reports_without_gating():
    base = {"requests": 10, "admitted_per_s": 100.0, "p99_s": 1.0,
            "shed_rate": 0.0}
    rep = {"requests": 10, "admitted_per_s": 5.0, "p99_s": 9.0,
           "shed_rate": 0.0}
    out = fidelity(base, rep, bounds={"admitted_ratio": None,
                                     "p99_ratio": None})
    assert out["admitted_ratio"] == 0.05       # still reported
    assert out["within"], out                  # but not gated


def test_fidelity_request_count_mismatch_fails():
    base = {"requests": 100, "shed_rate": 0.0}
    rep = {"requests": 60, "shed_rate": 0.0}
    out = fidelity(base, rep)
    assert not out["within"]
    assert any("60 requests for 100" in v for v in out["violations"])


# -------------------------------------------- crash artifacts name it


def test_flight_dump_names_active_capture(tmp_path, caplog):
    from distributed_bitcoinminer_tpu.utils.trace import FlightRecorder
    cap = _cap(tmp_path)
    cap.request(1, 8, 64, False)
    try:
        ring = FlightRecorder(cap=16)
        ring.record("dispatch", job=1)
        with caplog.at_level(logging.WARNING, logger="dbm.trace"):
            ring.dump("test alarm")
    finally:
        cap.close()
    dumped = [r.getMessage() for r in caplog.records
              if "flight recorder dump" in r.getMessage()]
    assert dumped
    doc = json.loads(dumped[-1].split(": ", 1)[1])
    assert doc["capture"]["path"] == cap.path
    assert doc["capture"]["lines"] >= 2        # header + one record
    # After close the slot clears: no stale pointer in later dumps.
    with caplog.at_level(logging.WARNING, logger="dbm.trace"):
        ring.dump("after close")
    doc2 = json.loads(
        [r.getMessage() for r in caplog.records
         if "after close" in r.getMessage()][-1].split(": ", 1)[1])
    assert "capture" not in doc2


def test_metrics_emitter_final_dump_names_capture(tmp_path, caplog):
    cap = _cap(tmp_path)
    try:
        emitter = umetrics.Emitter(umetrics.Registry(), 1000.0)
        with caplog.at_level(logging.INFO, logger="dbm.metrics"):
            emitter.emit(final=True)
    finally:
        cap.close()
    lines = [r.getMessage() for r in caplog.records
             if '"event": "metrics"' in r.getMessage()]
    assert lines
    doc = json.loads(lines[-1])
    assert doc["final"] is True
    assert doc["capture"]["path"] == cap.path


# ------------------------------------------------- replayed_storm


def test_replayed_storm_scenario_clean_sweep():
    """The measured-traffic scenario holds the full invariant pack over
    a seeded sweep of the checked-in fixture (the tier-1 replay leg
    explores >=500 distinct schedules over a FRESH capture)."""
    from distributed_bitcoinminer_tpu.analysis.schedcheck.scenario \
        import execute
    from distributed_bitcoinminer_tpu.analysis.schedcheck.scenarios \
        import ReplayedStorm
    for seed in range(15):
        result = execute(ReplayedStorm(), seed)
        assert not result.failed, \
            f"seed {seed}: {result.violations}"


def test_replayed_storm_reads_dbm_check_capture(monkeypatch, tmp_path):
    cap = _cap(tmp_path)
    for i in range(12):
        cap.request(i % 5, 8, 200, False)
    cap.maybe_snapshot(miners=2, rates=[800.0, 3200.0], queued=0,
                       inflight=0)
    cap.close()
    monkeypatch.setenv("DBM_CHECK_CAPTURE", cap.path)
    from distributed_bitcoinminer_tpu.analysis.schedcheck.scenario \
        import execute
    from distributed_bitcoinminer_tpu.analysis.schedcheck.scenarios \
        import ReplayedStorm
    result = execute(ReplayedStorm(), 3)
    assert not result.failed, result.violations


# ------------------------------------------------------ CLI satellites


def _load_script(name):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        f"_cli_{name}", os.path.join(_SCRIPTS, f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_benchdiff_flags_regressions_and_exits_nonzero(tmp_path):
    benchdiff = _load_script("benchdiff")
    old = {"value": 100.0, "detail": {"qos": {"p99_s": 1.0,
                                              "rounds": 3},
                                      "load": {"admitted_per_s": 50.0}}}
    new = json.loads(json.dumps(old))
    new["detail"]["qos"]["p99_s"] = 2.0        # 2x worse, lower-better
    a, b = tmp_path / "old.json", tmp_path / "new.json"
    a.write_text(json.dumps(old))
    b.write_text(json.dumps(new))
    assert benchdiff.main([str(a), str(b)]) == 1
    result = benchdiff.diff(old, new, 0.2)
    rows = {r["path"]: r for r in result["rows"]}
    assert rows["detail/qos/p99_s"]["verdict"] == "REGRESSED"
    assert rows["value"]["verdict"] == "ok"
    assert "detail/qos/rounds" not in rows     # config, never gated
    # Identical artifacts: clean exit.
    assert benchdiff.main([str(a), str(a)]) == 0
    # Improvement is not a regression.
    better = json.loads(json.dumps(old))
    better["detail"]["qos"]["p99_s"] = 0.4
    c = tmp_path / "better.json"
    c.write_text(json.dumps(better))
    assert benchdiff.main([str(a), str(c)]) == 0


def test_benchdiff_added_removed_not_gated(tmp_path):
    benchdiff = _load_script("benchdiff")
    old = {"value": 1.0}
    new = {"value": 1.0, "detail": {"replay": {"p99_s": 9.0}}}
    a, b = tmp_path / "o.json", tmp_path / "n.json"
    a.write_text(json.dumps(old))
    b.write_text(json.dumps(new))
    assert benchdiff.main([str(a), str(b)]) == 0
    result = benchdiff.diff(old, new, 0.2)
    assert "detail/replay/p99_s" in result["added"]


def test_dbmtrace_summarize_reads_captures_and_dumps(tmp_path, capsys):
    dbmtrace = _load_script("dbmtrace")
    cap = _cap(tmp_path)
    cap.span({"queue_s": 0.1, "force_s": 0.4})
    cap.span({"queue_s": 0.2, "force_s": 0.6})
    cap.reply(1, 1.25)
    cap.reply(2, 0.75)
    cap.close()
    trace_dump = tmp_path / "dump.jsonl"
    trace_dump.write_text(json.dumps({
        "key": 7, "meta": {"client": 42},
        "events": [
            {"t": 0.0, "event": "enqueue"},
            {"t": 0.1, "event": "miner_span", "miner": 1,
             "queue_s": 0.05, "force_s": 0.3},
            {"t": 0.5, "event": "reply", "elapsed_s": 0.5},
        ]}) + "\n")
    rc = dbmtrace.summarize([str(cap.path), str(trace_dump)], top=5)
    out = capsys.readouterr().out
    assert rc == 0
    assert "force" in out and "queue" in out
    assert "slowest" in out
    assert "tenant" in out
    # Empty input: loud nonzero, not a silent success.
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert dbmtrace.summarize([str(empty)], top=5) == 1


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-v"]))
