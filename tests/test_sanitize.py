"""Runtime sanitizer tests (ISSUE 7, ``DBM_SANITIZE``).

The acceptance case: the slow-callback watchdog must flag an injected
100ms synchronous stall on the scheduler's event loop, NAMING the
offending callback. Plus: threshold respected, thread-ownership
violations on the scheduler's hot state, off-loop assertions on the
miner compute entry points, disabled-by-default no-op, and the
regression pin for the `_run_miner` loop-block fix (the deadlined
accelerator probe now runs on a worker thread, so the loop stays
responsive through it).
"""

import asyncio
import logging
import threading
import time

import pytest

from distributed_bitcoinminer_tpu.apps.scheduler import Scheduler
from distributed_bitcoinminer_tpu.bitcoin.message import Message, new_join
from distributed_bitcoinminer_tpu.utils import sanitize
from distributed_bitcoinminer_tpu.utils.metrics import registry


@pytest.fixture(autouse=True)
def _watchdog_isolation():
    yield
    sanitize.uninstall_watchdog()


def _counter(name):
    return registry().counter(name).value


class FakeServer:
    """Recording write-only server (the scripted-scheduler harness)."""

    def __init__(self):
        self.writes = []

    def write(self, conn_id, payload):
        self.writes.append((conn_id, Message.from_json(payload)))


class AsyncFakeServer(FakeServer):
    """Adds an awaitable read() so Scheduler.run() serves on a real loop."""

    def __init__(self):
        super().__init__()
        self.q = asyncio.Queue()

    async def read(self):
        return await self.q.get()


def _injected_stall_100ms():
    time.sleep(0.1)


def test_watchdog_flags_injected_stall_on_scheduler_loop(monkeypatch,
                                                         caplog):
    """Acceptance: a 100ms synchronous stall on the serving scheduler's
    event loop is flagged by name in dbm.sanitize and counted."""
    monkeypatch.setenv("DBM_SANITIZE", "1")
    monkeypatch.setenv("DBM_SANITIZE_SLOW_S", "0.05")
    before = _counter("sanitize.slow_callbacks")

    async def drive():
        server = AsyncFakeServer()
        sched = Scheduler(server)           # installs the watchdog
        assert sched._owner is not None
        task = asyncio.get_running_loop().create_task(sched.run())
        await server.q.put((1, new_join().to_json()))   # serve something
        await asyncio.sleep(0.01)
        asyncio.get_running_loop().call_soon(_injected_stall_100ms)
        await asyncio.sleep(0.05)
        task.cancel()
        with pytest.raises(asyncio.CancelledError):
            await task
        return sched

    with caplog.at_level(logging.WARNING, logger="dbm.sanitize"):
        sched = asyncio.run(drive())
    assert sched.miners and sched.miners[0].conn_id == 1   # it served
    assert _counter("sanitize.slow_callbacks") >= before + 1
    joined = " ".join(r.getMessage() for r in caplog.records)
    assert "_injected_stall_100ms" in joined, joined
    assert "event-loop stall" in joined


def test_watchdog_names_coroutine_stalls(monkeypatch, caplog):
    """A stall INSIDE an async def (the PR-4 wedged-probe shape) must be
    attributed to the coroutine's qualname, not an anonymous Task step
    wrapper (code-review finding on the first cut)."""
    monkeypatch.setenv("DBM_SANITIZE", "1")
    monkeypatch.setenv("DBM_SANITIZE_SLOW_S", "0.05")

    async def wedged_probe_coro():
        time.sleep(0.1)        # sync stall inside the coroutine step

    async def drive():
        Scheduler(AsyncFakeServer())        # installs the watchdog
        # Its own task: the stall lands in wedged_probe_coro's OWN step
        # (awaiting the bare coroutine would charge the stall to this
        # test harness's wrapper coroutine instead).
        await asyncio.get_running_loop().create_task(wedged_probe_coro())

    with caplog.at_level(logging.WARNING, logger="dbm.sanitize"):
        asyncio.run(drive())
    joined = " ".join(r.getMessage() for r in caplog.records)
    assert "coroutine" in joined, joined
    assert "wedged_probe_coro" in joined or "drive" in joined, joined
    assert "TaskStepMethWrapper" not in joined


def test_watchdog_threshold_respected(monkeypatch, caplog):
    monkeypatch.setenv("DBM_SANITIZE", "1")
    monkeypatch.setenv("DBM_SANITIZE_SLOW_S", "0.5")
    before = _counter("sanitize.slow_callbacks")

    async def drive():
        Scheduler(AsyncFakeServer())
        asyncio.get_running_loop().call_soon(_injected_stall_100ms)
        await asyncio.sleep(0.02)

    with caplog.at_level(logging.WARNING, logger="dbm.sanitize"):
        asyncio.run(drive())
    # 100ms < the 500ms bound: nothing flagged.
    assert _counter("sanitize.slow_callbacks") == before


def test_sanitizer_disabled_by_default(monkeypatch):
    monkeypatch.delenv("DBM_SANITIZE", raising=False)
    sched = Scheduler(FakeServer())
    assert sched._owner is None
    assert sanitize.ensure_sanitizer() is False
    assert sanitize._orig_handle_run is None     # nothing installed


def test_ownership_violation_counted_and_logged(monkeypatch, caplog):
    monkeypatch.setenv("DBM_SANITIZE", "1")
    sched = Scheduler(FakeServer())
    sched._on_join(1)                        # main thread becomes owner
    before = _counter("sanitize.ownership_violations")
    with caplog.at_level(logging.WARNING, logger="dbm.sanitize"):
        t = threading.Thread(target=sched._on_join, args=(2,),
                             name="rogue-worker")
        t.start()
        t.join()
    # _on_join cascades into _maybe_dispatch (both guarded), so one
    # rogue call may count more than one violation — at least one.
    assert _counter("sanitize.ownership_violations") > before
    joined = " ".join(r.getMessage() for r in caplog.records)
    assert "rogue-worker" in joined and "Scheduler hot state" in joined


def test_ownership_same_thread_is_quiet(monkeypatch):
    monkeypatch.setenv("DBM_SANITIZE", "1")
    sched = Scheduler(FakeServer())
    before = _counter("sanitize.ownership_violations")
    sched._on_join(1)
    sched._on_join(2)
    assert _counter("sanitize.ownership_violations") == before


def test_assert_off_loop_detects_loop_thread():
    before = _counter("sanitize.loop_blocking")

    async def on_loop():
        return sanitize.assert_off_loop("test compute")

    assert asyncio.run(on_loop()) is False
    assert _counter("sanitize.loop_blocking") == before + 1
    # Off the loop (plain thread): fine.
    assert sanitize.assert_off_loop("test compute") is True
    assert _counter("sanitize.loop_blocking") == before + 1


def test_miner_compute_entry_points_assert_off_loop(monkeypatch):
    """The miner's blocking search warns when (hypothetically) invoked on
    the event loop — the runtime complement of the loop-block analyzer."""
    from distributed_bitcoinminer_tpu.apps.miner import MinerWorker
    monkeypatch.setenv("DBM_SANITIZE", "1")
    worker = MinerWorker.__new__(MinerWorker)
    worker._sanitize = sanitize.enabled()
    worker._searchers = {}
    before = _counter("sanitize.loop_blocking")

    async def on_loop():
        # Inverted range returns before any searcher work, but the
        # off-loop assertion has already fired by then.
        return worker._search("m", 5, 4)

    assert asyncio.run(on_loop()) == (2 ** 64 - 1, 0, 0)
    assert _counter("sanitize.loop_blocking") == before + 1


def test_miner_probe_runs_off_loop_keeping_heartbeats_alive(monkeypatch):
    """Regression for the _run_miner loop-block fix: the deadlined
    accelerator probe (a blocking subprocess join of up to 120s) must not
    hold the event loop. Drives the extracted _probe_and_pin through the
    same asyncio.to_thread hop _run_miner now uses, with a stand-in probe
    that blocks 0.25s, and counts loop heartbeats meanwhile."""
    from distributed_bitcoinminer_tpu.apps import miner
    from distributed_bitcoinminer_tpu.utils import config
    from distributed_bitcoinminer_tpu.utils.config import FrameworkConfig

    monkeypatch.setenv("JAX_PLATFORMS", "")       # don't short-circuit
    monkeypatch.delenv("DBM_COORDINATOR", raising=False)

    def slow_probe(timeout_s, repo_dir=None, refresh=False):
        time.sleep(0.25)
        return {"error": "stand-in: tunnel wedged"}

    monkeypatch.setattr(config, "probe_backend", slow_probe)
    cfg = FrameworkConfig(compute="jnp")          # non-auto: no native build

    async def drive():
        ticks = 0
        done = asyncio.Event()

        async def heartbeat():
            nonlocal ticks
            while not done.is_set():
                ticks += 1
                await asyncio.sleep(0.01)

        hb = asyncio.get_running_loop().create_task(heartbeat())
        out = await asyncio.to_thread(miner._probe_and_pin, cfg)
        done.set()
        await hb
        return out, ticks

    out, ticks = asyncio.run(drive())
    assert out.compute == "jnp"                   # explicit tier respected
    # The probe blocked a worker thread for 0.25s; a responsive loop
    # ticks ~25x. Inline (the old bug) it would tick ~once. Generous
    # bound for a loaded CI box:
    assert ticks >= 5, f"event loop starved during probe ({ticks} ticks)"


def test_run_miner_uses_thread_hop_for_probe():
    """Static pin of the same fix: _run_miner must not call the probe
    path synchronously (the dbmlint loop-block gate enforces this
    repo-wide; this is the targeted regression guard)."""
    import ast
    import inspect

    from distributed_bitcoinminer_tpu.apps import miner
    tree = ast.parse(inspect.getsource(miner._run_miner))
    calls = [n for n in ast.walk(tree) if isinstance(n, ast.Call)]
    direct = [n for n in calls
              if getattr(n.func, "id", "") == "_probe_and_pin"]
    assert not direct, "_probe_and_pin called inline on the event loop"
    hops = [n for n in calls
            if getattr(n.func, "attr", "") == "to_thread"
            and any(getattr(a, "id", "") == "_probe_and_pin"
                    for a in n.args)]
    assert hops, "_run_miner no longer hops the probe to a worker thread"
