"""Scheduler/miner/client end-to-end tests over real localhost UDP.

The reference repo ships no Part B test sources (only staff binaries
ctest/mtest, p1/README.md:137-141); these scenarios cover the scheduler state
machine from SURVEY §3.3-3.4: happy path, FIFO queueing, elastic join,
miner-failure reassignment, and client-failure cancellation.

Most tests plug a pure-Python oracle searcher into MinerWorker so they
exercise distributed logic, not device compute; one smoke test runs the real
JAX searcher end to end.
"""

import asyncio
import time

import pytest

from distributed_bitcoinminer_tpu.apps.client import printable_result, submit
from distributed_bitcoinminer_tpu.apps.miner import MinerWorker
from distributed_bitcoinminer_tpu.apps.scheduler import Scheduler
from distributed_bitcoinminer_tpu.bitcoin.hash import scan_min
from distributed_bitcoinminer_tpu.lsp import Params
from distributed_bitcoinminer_tpu.lsp.server import new_async_server


def fast_params(epoch_ms=50, limit=5, window=5):
    return Params(epoch_limit=limit, epoch_millis=epoch_ms,
                  window_size=window, max_backoff_interval=2)


class OracleSearcher:
    """Host-oracle stand-in for the device searcher (optionally slow)."""

    def __init__(self, data: str, delay: float = 0.0):
        self.data = data
        self.delay = delay

    def search(self, lower: int, upper: int):
        if self.delay:
            time.sleep(self.delay)
        return scan_min(self.data, lower, upper)


def oracle_factory(delay: float = 0.0):
    return lambda data, batch: OracleSearcher(data, delay)


class Cluster:
    """A scheduler plus helpers to spawn miners against it."""

    def __init__(self, params):
        self.params = params
        self.server = None
        self.tasks = []
        self.miners = []

    async def __aenter__(self):
        self.server = await new_async_server(0, self.params)
        self.scheduler = Scheduler(self.server)
        self.tasks.append(asyncio.create_task(self.scheduler.run()))
        return self

    async def __aexit__(self, *exc):
        for task in self.tasks:
            task.cancel()
        for worker in self.miners:
            await worker.close()
        await self.server.close()

    @property
    def hostport(self):
        return f"127.0.0.1:{self.server.port}"

    async def start_miner(self, factory=None, delay=0.0):
        worker = MinerWorker(self.hostport, params=self.params,
                             searcher_factory=factory or oracle_factory(delay))
        await worker.join()
        self.tasks.append(asyncio.create_task(worker.run()))
        self.miners.append(worker)
        return worker


# The system scans [0, maxNonce+1]: the scheduler hands out exclusive upper
# bounds but miners read them as inclusive (ref quirk, see scheduler.py).
def expected(data, max_nonce):
    return scan_min(data, 0, max_nonce + 1)


def test_end_to_end_single_miner():
    async def scenario():
        async with Cluster(fast_params()) as c:
            await c.start_miner()
            result = await asyncio.wait_for(
                submit(c.hostport, "cmu440", 999, c.params), 10)
            assert result == expected("cmu440", 999)
    asyncio.run(scenario())


def test_end_to_end_multi_miner_and_fifo_queue():
    async def scenario():
        async with Cluster(fast_params()) as c:
            for _ in range(3):
                await c.start_miner()
            results = await asyncio.wait_for(asyncio.gather(
                submit(c.hostport, "msg one", 500, c.params),
                submit(c.hostport, "msg two", 700, c.params),
                submit(c.hostport, "msg three", 900, c.params)), 20)
            assert results[0] == expected("msg one", 500)
            assert results[1] == expected("msg two", 700)
            assert results[2] == expected("msg three", 900)
    asyncio.run(scenario())


def test_request_queued_until_miner_joins():
    async def scenario():
        async with Cluster(fast_params()) as c:
            pending = asyncio.create_task(
                submit(c.hostport, "late pool", 300, c.params))
            await asyncio.sleep(0.3)
            assert not pending.done()
            await c.start_miner()
            assert await asyncio.wait_for(pending, 10) == \
                expected("late pool", 300)
    asyncio.run(scenario())


def test_miner_drop_reassigns_chunk():
    async def scenario():
        params = fast_params(epoch_ms=40, limit=3)
        async with Cluster(params) as c:
            victim = await c.start_miner(delay=1.5)   # slow: dies mid-chunk
            await c.start_miner()                     # fast survivor
            pending = asyncio.create_task(
                submit(c.hostport, "fault tolerant", 400, params))
            await asyncio.sleep(0.3)  # both miners now hold chunks
            # Crash the slow miner without a graceful close: silence makes
            # the server's epoch timer declare it lost (SURVEY §3.4).
            victim.client._conn.abort()
            victim.client._ep.close()
            assert await asyncio.wait_for(pending, 15) == \
                expected("fault tolerant", 400)
    asyncio.run(scenario())


def test_miner_drop_with_no_spare_parks_chunk_until_join():
    async def scenario():
        params = fast_params(epoch_ms=40, limit=3)
        async with Cluster(params) as c:
            victim = await c.start_miner(delay=2.0)
            pending = asyncio.create_task(
                submit(c.hostport, "parked chunk", 200, params))
            await asyncio.sleep(0.3)
            victim.client._conn.abort()
            victim.client._ep.close()
            await asyncio.sleep(0.5)   # chunk parks; pool is empty
            await c.start_miner()      # joiner absorbs the parked chunk
            assert await asyncio.wait_for(pending, 15) == \
                expected("parked chunk", 200)
    asyncio.run(scenario())


def test_client_drop_cancels_and_frees_pool():
    async def scenario():
        params = fast_params(epoch_ms=40, limit=3)
        async with Cluster(params) as c:
            await c.start_miner(delay=1.0)
            from distributed_bitcoinminer_tpu.bitcoin.message import new_request
            from distributed_bitcoinminer_tpu.lsp.client import new_async_client
            doomed = await new_async_client(c.hostport, params)
            doomed.write(new_request("abandoned", 0, 300).to_json())
            await asyncio.sleep(0.3)
            doomed._conn.abort()   # crash the client mid-request
            doomed._ep.close()
            # The pool must recover and serve the next client.
            result = await asyncio.wait_for(
                submit(c.hostport, "next in line", 250, params), 15)
            assert result == expected("next in line", 250)
    asyncio.run(scenario())


def test_client_drop_with_parked_chunk_does_not_deadlock():
    """Regression: a responsible miner's chunk parks (miner died, no spare),
    then the client drops. The reference's state machine would wait forever
    for the parked chunk's Result; the scheduler must instead cancel the
    request and keep serving (see scheduler.py module docstring)."""
    async def scenario():
        params = fast_params(epoch_ms=40, limit=3)
        async with Cluster(params) as c:
            survivor = await c.start_miner(delay=1.0)   # busy when B dies
            victim = await c.start_miner(delay=1.0)
            from distributed_bitcoinminer_tpu.bitcoin.message import new_request
            from distributed_bitcoinminer_tpu.lsp.client import new_async_client
            doomed = await new_async_client(c.hostport, params)
            doomed.write(new_request("doomed job", 0, 400).to_json())
            await asyncio.sleep(0.3)    # both miners hold chunks
            victim.client._conn.abort() # dies; survivor busy -> chunk parks
            victim.client._ep.close()
            await asyncio.sleep(0.4)
            doomed._conn.abort()        # client dies too
            doomed._ep.close()
            result = await asyncio.wait_for(
                submit(c.hostport, "after the storm", 300, params), 15)
            assert result == expected("after the storm", 300)
    asyncio.run(scenario())


def test_end_to_end_with_real_jax_searcher():
    from distributed_bitcoinminer_tpu.apps.miner import default_searcher_factory

    # Precompile OUTSIDE the wire deadline: on slow CPU boxes the first
    # XLA compile alone ate the whole 120 s budget (flaked on the seed
    # too). This searcher scans the exact range the one chunk below will
    # cover, so every (rem, k, nbatches) signature—and the until/argmin
    # graphs behind it—is warm in the in-process jit cache (and the
    # persistent cache) before the clock starts; the timed wait then
    # covers wire + execution only.
    default_searcher_factory("cmu440", 1 << 10).search(0, 3000)

    async def scenario():
        async with Cluster(fast_params()) as c:
            await c.start_miner(
                factory=lambda data, batch: default_searcher_factory(data, 1 << 10))
            result = await asyncio.wait_for(
                submit(c.hostport, "cmu440", 2999, c.params), 120)
            assert result == expected("cmu440", 2999)
    asyncio.run(scenario())


def test_empty_range_request_does_not_wedge_scheduler():
    """Regression: Request(0, maxNonce=-1) made num_chunks 0 and left the
    barrier permanently unreleasable; it must answer with the empty-scan
    sentinel and keep serving."""
    from distributed_bitcoinminer_tpu.bitcoin.hash import MAX_U64
    from distributed_bitcoinminer_tpu.bitcoin.message import Message, MsgType

    async def scenario():
        async with Cluster(fast_params()) as c:
            await c.start_miner()
            bad = Message(type=MsgType.REQUEST, data="void", lower=5, upper=3)
            from distributed_bitcoinminer_tpu.lsp.client import new_async_client
            sender = await new_async_client(c.hostport, c.params)
            sender.write(bad.to_json())
            reply = Message.from_json(await asyncio.wait_for(sender.read(), 10))
            assert (reply.hash, reply.nonce) == (MAX_U64, 0)
            await sender.close()
            # Scheduler must still serve normal traffic afterwards.
            result = await asyncio.wait_for(
                submit(c.hostport, "alive", 200, c.params), 10)
            assert result == expected("alive", 200)
    asyncio.run(scenario())


def test_printable_result_contract():
    assert printable_result((123, 45)) == "Result 123 45"
    assert printable_result(None) == "Disconnected"


def test_broken_miner_exits_and_chunk_is_reassigned():
    """A compute failure must REMOVE the worker from the pool — never
    fabricate a Result: round 3's on-chip e2e caught a miner whose device
    backend failed to init answering with the (MAX_U64, 0) sentinel,
    handing a single-miner client garbage. The failing miner exits (ref:
    the Go miner exits silently on any failure, miner.go:44-50), the
    scheduler detects the drop, and the chunk re-executes on a healthy
    miner."""
    class Poisoned:
        def __init__(self, data):
            self.data = data

        def search(self, lower, upper):
            raise RuntimeError("device backend failed to init")

    async def scenario():
        async with Cluster(fast_params()) as c:
            await c.start_miner(factory=lambda data, batch: Poisoned(data))
            await c.start_miner()   # healthy oracle miner
            result = await asyncio.wait_for(
                submit(c.hostport, "poison", 900, c.params), 30)
            assert result == expected("poison", 900)
    asyncio.run(scenario())
