"""Dispatch-pipeline property suite (ISSUE 4).

Covers both sides of the overlapped dispatch pipeline:

- **Scheduler striping** (``DBM_STRIPE``): unit-level chunk-plan shape —
  cold-pool parity with the reference even split, EWMA-sized stripe chunks
  that stay contiguous/ascending and merge exactly, stripe-chunk recovery
  on miner drop.
- **Miner pipeline** (``DBM_PIPELINE``): two-phase dispatch/finalize
  equivalence across compute tiers (host native, jnp, mesh-sharded),
  strictly in-order Result writes under a slow-chunk shuffle, and
  end-to-end bit-equivalence of arg-min and difficulty first-hit answers
  with the knobs on vs off.
- **Chaos leg**: wedge and kill mid-pipeline over striped chunks — blown
  leases re-issue single stripe chunks and the merge stays exact and
  idempotent.

The tier-1 knob-off matrix leg (scripts/tier1.sh) re-runs the scheduler
recovery + chaos + conformance modules with ``DBM_PIPELINE=0 DBM_STRIPE=0``
so the stock serial/even-split path stays exercised in CI; the tests here
that force striping pass explicit params and are knob-independent.
"""

import asyncio
import time

import pytest

from distributed_bitcoinminer_tpu.apps.client import submit, submit_until
from distributed_bitcoinminer_tpu.apps.miner import HostSearcher, MinerWorker
from distributed_bitcoinminer_tpu.apps.scheduler import Scheduler
from distributed_bitcoinminer_tpu.bitcoin.hash import scan_min, scan_until
from distributed_bitcoinminer_tpu.bitcoin.message import MsgType, new_request
from distributed_bitcoinminer_tpu.lsp.server import new_async_server
from distributed_bitcoinminer_tpu.utils.config import (LeaseParams,
                                                       StripeParams,
                                                       VerifyParams)

from tests.test_apps import Cluster, fast_params
from tests.test_scheduler_recovery import (CLIENT_X, MINER_A, MINER_B,
                                           FakeServer, join, request, result)

#: Forces striping regardless of rate magnitude: the per-chunk target size
#: collapses to ~rate*1ms nonces, so any observed EWMA splits a share into
#: the depth cap. Tests that need the split deterministic use this.
FORCED_STRIPE = StripeParams(enabled=True, chunk_s=0.001, depth=3)


def make_striped_scheduler(stripe=FORCED_STRIPE, **lease_kw):
    # Scripted result() answers carry synthetic hashes the claim check
    # would reject; verification has its own suite, so pin it off.
    lease = LeaseParams(**lease_kw) if lease_kw else LeaseParams()
    server = FakeServer()
    return Scheduler(server, lease=lease, stripe=stripe,
                     verify=VerifyParams(enabled=False)), server


def seed_rate(sched, conn_id, rate=1_000_000.0):
    """Pretend the miner has an observed throughput EWMA."""
    sched._find_miner(conn_id).rate_ewma = rate


# ---------------------------------------------------------- scheduler stripes


def test_cold_pool_falls_back_to_even_split():
    """Before any throughput is observed, the chunk plan is bit-identical
    to the reference even split — the conformance/parity shape needs no
    knob for first requests."""
    sched, server = make_striped_scheduler()
    join(sched, MINER_A)
    join(sched, MINER_B)
    request(sched, CLIENT_X, "cold", 199)
    assert sched.current.num_chunks == 2
    reqs = server.sent_to(MINER_A, MsgType.REQUEST)
    assert [(m.lower, m.upper) for m in reqs] == [(0, 100)]
    reqs = server.sent_to(MINER_B, MsgType.REQUEST)
    assert [(m.lower, m.upper) for m in reqs] == [(100, 200)]
    assert sched.stats["chunks_striped"] == 0


def test_stripe_plan_contiguous_ascending_and_merges_exactly():
    """With an observed EWMA the share splits into depth-capped contiguous
    chunks, indices ascend with nonce range globally, and the barrier
    merge over all stripe chunks is exact."""
    sched, server = make_striped_scheduler()
    join(sched, MINER_A)
    join(sched, MINER_B)
    seed_rate(sched, MINER_A)
    seed_rate(sched, MINER_B)
    request(sched, CLIENT_X, "striped", 199_999)
    assert sched.current.num_chunks == 6      # 2 miners x depth 3
    assert sched.stats["chunks_striped"] == 4
    a = [(m.lower, m.upper)
         for m in server.sent_to(MINER_A, MsgType.REQUEST)]
    b = [(m.lower, m.upper)
         for m in server.sent_to(MINER_B, MsgType.REQUEST)]
    bounds = a + b
    # Contiguous cover of [0, 200000) in ascending order.
    assert bounds[0][0] == 0 and bounds[-1][1] == 200_000
    for (lo1, up1), (lo2, up2) in zip(bounds, bounds[1:]):
        assert up1 == lo2 and lo1 < up1
    # FIFO pops answer in stripe order; the merged min is exact.
    for i, _ in enumerate(a):
        result(sched, MINER_A, h=100 + i, nonce=10 + i)
    for i, _ in enumerate(b):
        result(sched, MINER_B, h=50 - i, nonce=20 + i)
    replies = server.sent_to(CLIENT_X, MsgType.RESULT)
    assert [(m.hash, m.nonce) for m in replies] == [(48, 22)]


def test_stripe_chunk_count_tracks_rate_and_chunk_s():
    """The sizing rule: ceil(share / (rate * chunk_s)), depth-capped."""
    sched, _server = make_striped_scheduler(
        stripe=StripeParams(enabled=True, chunk_s=1.0, depth=8))
    join(sched, MINER_A)
    m = sched._find_miner(MINER_A)
    assert sched._stripe_chunks(m, 10_000) == 1          # cold: parity
    m.rate_ewma = 1000.0
    assert sched._stripe_chunks(m, 10_000) == 8          # capped at depth
    assert sched._stripe_chunks(m, 2_500) == 3           # ceil(2.5)
    assert sched._stripe_chunks(m, 1_000) == 1           # exactly chunk_s
    assert sched._stripe_chunks(m, 1) == 1               # trivial share
    off = Scheduler(FakeServer(),
                    stripe=StripeParams(enabled=False))
    off._on_join(MINER_A)
    off_m = off._find_miner(MINER_A)
    off_m.rate_ewma = 1000.0
    assert off._stripe_chunks(off_m, 10_000) == 1        # knob off


def test_striped_chunks_recover_individually_on_miner_drop():
    """A dead miner forfeits its stripe chunks one by one: each unanswered
    stripe chunk is reassigned/parked individually, and the merge stays
    exact — the shrunken blast radius the striping buys."""
    sched, server = make_striped_scheduler()
    join(sched, MINER_A)
    join(sched, MINER_B)
    seed_rate(sched, MINER_A)
    seed_rate(sched, MINER_B)
    request(sched, CLIENT_X, "blast radius", 119_999)
    assert sched.current.num_chunks == 6
    # B answers its first stripe chunk, then dies: its 2 remaining chunks
    # must be recovered (A busy -> parked), not lost with the share.
    result(sched, MINER_B, h=70, nonce=3)
    sched._on_drop(MINER_B)
    assert len(sched.parked) == 2
    # A drains its own 3 chunks, absorbing parked chunks as it frees.
    for h in (60, 61, 62, 63, 64):
        result(sched, MINER_A, h=h, nonce=h)
    replies = server.sent_to(CLIENT_X, MsgType.RESULT)
    assert [(m.hash, m.nonce) for m in replies] == [(60, 60)]
    assert sched.parked == []


# ------------------------------------------------------- two-phase searchers


def test_host_searcher_two_phase_matches_blocking():
    s = HostSearcher("two phase")
    want = s.search(0, 5000)
    handles = [s.dispatch(0, 2500), s.dispatch(2501, 5000)]
    got = [s.finalize(h, lo) for h, lo in zip(handles, (0, 2501))]
    assert min(got) == want
    with pytest.raises(ValueError):
        s.dispatch(5, 3)


def test_sharded_dispatch_finalize_equivalence():
    """The mesh-sharded searcher pipelines through the SAME inherited
    dispatch/finalize contract: overlapped handles force to the exact
    sequential results (8-device virtual CPU mesh)."""
    from distributed_bitcoinminer_tpu.models import ShardedNonceSearcher

    s = ShardedNonceSearcher("sharded pipe", batch=256)
    ranges = [(0, 2999), (3000, 5999), (6000, 8999)]
    handles = [(s.dispatch(lo, hi), lo) for lo, hi in ranges]
    got = [s.finalize(h, lo) for h, lo in handles]
    for (lo, hi), g in zip(ranges, got):
        assert g == scan_min("sharded pipe", lo, hi)


# ----------------------------------------------------- miner executor order


class _ShuffleSearcher:
    """Two-phase searcher whose finalize times vary per chunk (earlier
    chunks slower), so an executor that wrote Results as they finish —
    instead of in request order — would be caught."""

    def __init__(self, data: str, delays):
        self.data = data
        self.delays = list(delays)
        self.finalized = []

    def dispatch(self, lower, upper):
        return (lower, upper)

    def finalize(self, handle, lower):
        delay = self.delays.pop(0) if self.delays else 0.0
        time.sleep(delay)
        self.finalized.append(handle)
        return scan_min(self.data, handle[0], handle[1])


class _ScriptClient:
    """Fake AsyncClient: serves a scripted list of Requests, records
    writes, then blocks forever (the test cancels the worker)."""

    def __init__(self, payloads):
        self._payloads = list(payloads)
        self.writes = []
        self._forever = asyncio.get_running_loop().create_future()

    async def read(self):
        if self._payloads:
            return self._payloads.pop(0)
        await self._forever            # park: transport stays "alive"

    def write(self, payload):
        self.writes.append(payload)

    async def close(self):
        pass


def test_results_written_in_request_order_under_slow_chunk_shuffle():
    """In-order Result writes (the scheduler's FIFO pop contract): chunk
    0's finalize is slowest, later chunks are instant — the pipelined
    executor must still write 0, 1, 2, 3."""
    from distributed_bitcoinminer_tpu.bitcoin.message import Message

    async def scenario():
        searcher = _ShuffleSearcher("order", [0.3, 0.0, 0.0, 0.0])
        worker = MinerWorker("unused:0",
                             searcher_factory=lambda d, b: searcher,
                             pipeline=True, pipeline_depth=4)
        ranges = [(0, 999), (1000, 1999), (2000, 2999), (3000, 3999)]
        worker.client = _ScriptClient(
            [new_request("order", lo, up).to_json() for lo, up in ranges])
        task = asyncio.create_task(worker.run())
        for _ in range(400):
            if len(worker.client.writes) == 4:
                break
            await asyncio.sleep(0.01)
        task.cancel()
        assert len(worker.client.writes) == 4
        replies = [Message.from_json(w) for w in worker.client.writes]
        # Each reply is the exact answer of ITS request, in request order.
        for (lo, up), m in zip(ranges, replies):
            want_h, want_n = scan_min("order", lo, up)
            assert (m.hash, m.nonce) == (want_h, want_n), (lo, up)
        # And the pipeline really dispatched ahead: chunk 1 finished
        # finalize after chunk 0 (order list), but all were dispatched.
        assert [h[0] for h in searcher.finalized] == [lo for lo, _ in ranges]
    asyncio.run(scenario())


def test_slow_dispatch_does_not_hold_inflight_result():
    """A dispatch stuck in jit trace+compile (fresh signature — chunk
    sizes drift with the rate EWMA, so this happens in steady state) must
    not delay the in-flight chunk's already-computed Result write: the
    Result would otherwise wait out its head-of-FIFO lease behind a
    multi-second compile and be spuriously re-issued. Pinned: the first
    chunk's write lands BEFORE the second chunk's slow dispatch
    completes."""
    from distributed_bitcoinminer_tpu.bitcoin.message import Message

    events = []

    class _Searcher:
        def __init__(self, data):
            self.data = data

        def dispatch(self, lower, upper):
            if self.data == "cold":
                time.sleep(0.4)        # the trace+compile stand-in
            events.append(("dispatch_done", self.data))
            return (lower, upper)

        def finalize(self, handle, lower):
            return scan_min(self.data, handle[0], handle[1])

    class _Client(_ScriptClient):
        def write(self, payload):
            events.append(("write", Message.from_json(payload).nonce))
            super().write(payload)

    async def scenario():
        worker = MinerWorker("unused:0",
                             searcher_factory=lambda d, b: _Searcher(d),
                             pipeline=True, pipeline_depth=4)
        worker.client = _Client(
            [new_request("warm", 0, 999).to_json(),
             new_request("cold", 0, 999).to_json()])
        task = asyncio.create_task(worker.run())
        for _ in range(300):
            if len(worker.client.writes) == 2:
                break
            await asyncio.sleep(0.01)
        task.cancel()
        assert len(worker.client.writes) == 2
        # Replies are exact and in request order…
        for data, m in zip(("warm", "cold"),
                           (Message.from_json(w)
                            for w in worker.client.writes)):
            assert (m.hash, m.nonce) == scan_min(data, 0, 999)
        # …and the warm Result was written while "cold" still compiled.
        d_cold = events.index(("dispatch_done", "cold"))
        w_warm = next(i for i, e in enumerate(events) if e[0] == "write")
        assert w_warm < d_cold, events
    asyncio.run(scenario())


# -------------------------------------------------------- e2e equivalence


def _e2e_cluster_answers(pipeline: bool, stripe: StripeParams,
                         factory=None):
    """Drive arg-min + difficulty requests through a 2-miner cluster with
    the given knob settings; returns the (argmin, until) answers."""
    async def scenario():
        params = fast_params()
        async with Cluster(params) as c:
            c.scheduler.stripe = stripe
            for _ in range(2):
                worker = MinerWorker(
                    c.hostport, params=params,
                    searcher_factory=factory or
                    (lambda d, b: HostSearcher(d)),
                    pipeline=pipeline)
                await worker.join()
                c.tasks.append(asyncio.create_task(worker.run()))
                c.miners.append(worker)
            # Request 1 warms the pool; the EWMA is then pinned directly
            # (the windowed rate sampler ignores sub-window warm
            # requests by design) so request 2 stripes (when on). On a
            # loaded box the warm request CAN outlast RATE_WINDOW_S and
            # publish a real pool rate, which flips the QoS gate to a
            # chunked incremental start (a mode with its own suite) —
            # clear the published sample too: this test pins the
            # wholesale + stripe path.
            r0 = await asyncio.wait_for(
                submit(c.hostport, "equiv warm", 999, params), 30)
            for m in c.scheduler.miners:
                m.rate_ewma = 1000.0
                m.win_t0, m.win_nonces = 0.0, 0
            c.scheduler.miner_plane.pool_rate = None
            r1 = await asyncio.wait_for(
                submit(c.hostport, "equiv main", 49_999, params), 60)
            ru = await asyncio.wait_for(
                submit_until(c.hostport, "equiv until", 2999, 1 << 59,
                             params), 60)
            return r0, r1, ru, c.scheduler.stats["chunks_striped"]
    return asyncio.run(scenario())


def test_e2e_bit_equivalence_knobs_on_vs_off():
    """The acceptance property: arg-min and difficulty first-hit answers
    are bit-identical with the pipeline+striping on vs off (and both
    match the host oracle); the on-leg actually striped."""
    on = _e2e_cluster_answers(True, FORCED_STRIPE)
    off = _e2e_cluster_answers(False, StripeParams(enabled=False))
    assert on[:3] == off[:3]
    assert on[0] == scan_min("equiv warm", 0, 1000)
    assert on[1] == scan_min("equiv main", 0, 50_000)
    assert on[2] == scan_until("equiv until", 0, 3000, 1 << 59)
    assert on[3] > 0 and off[3] == 0     # striping engaged only on-leg


def test_e2e_equivalence_real_jnp_searcher():
    """Same equivalence through the real jnp device tier (compiled once
    outside the wire deadline, like test_end_to_end_with_real_jax_searcher)."""
    from distributed_bitcoinminer_tpu.models import NonceSearcher

    # Precompile every signature the striped chunks can hit.
    warm = NonceSearcher("pipe jnp", batch=1 << 10)
    warm.search(0, 3000)

    factory = lambda d, b: NonceSearcher(d, batch=1 << 10)  # noqa: E731

    async def scenario():
        params = fast_params()
        async with Cluster(params) as c:
            c.scheduler.stripe = FORCED_STRIPE
            worker = MinerWorker(c.hostport, params=params,
                                 searcher_factory=factory, pipeline=True)
            await worker.join()
            c.tasks.append(asyncio.create_task(worker.run()))
            c.miners.append(worker)
            r0 = await asyncio.wait_for(
                submit(c.hostport, "pipe jnp", 999, params), 120)
            assert r0 == scan_min("pipe jnp", 0, 1000)
            # The windowed rate sampler needs RATE_WINDOW_S of wall
            # clock before publishing a rate; a sub-second warm request
            # can't fill it, so pin the EWMA (file-wide idiom) so the
            # next request stripes. On a loaded box the warm request
            # CAN outlast the window and publish a real pool rate,
            # which flips the QoS gate to a chunked incremental start
            # that never counts chunks_striped — clear the published
            # sample too: this test pins the wholesale + stripe path.
            for m in c.scheduler.miners:
                m.rate_ewma = 1000.0
                m.win_t0, m.win_nonces = 0.0, 0
            c.scheduler.miner_plane.pool_rate = None
            r1 = await asyncio.wait_for(
                submit(c.hostport, "pipe jnp", 2999, params), 120)
            assert r1 == scan_min("pipe jnp", 0, 3000)
            assert c.scheduler.stats["chunks_striped"] > 0
    asyncio.run(scenario())


# --------------------------------------------------------------- chaos leg


def test_chaos_wedge_mid_pipeline_reissues_striped_chunk():
    """A wedged miner mid-pipeline blows ONE stripe chunk's lease; the
    re-issue covers exactly that range, merges idempotently, and the
    answer stays the oracle arg-min."""
    from tests.test_chaos import ChaosCluster, tight_lease

    async def scenario():
        async with ChaosCluster(lease=tight_lease()) as c:
            c.scheduler.stripe = FORCED_STRIPE
            wedged = await c.add_miner("wedged")
            await c.add_miner("healthy")
            # Seed both rate EWMAs so the next request stripes (pinned
            # directly: the windowed rate sampler ignores sub-window
            # warm requests by design).
            r0 = await asyncio.wait_for(
                submit(c.hostport, "chaos warm", 799, c.params), 20)
            assert r0 == scan_min("chaos warm", 0, 800)
            for m in c.scheduler.miners:
                m.rate_ewma = 1000.0
            wedged.wedge()
            result = await asyncio.wait_for(
                submit(c.hostport, "chaos striped", 999, c.params), 30)
            assert result == scan_min("chaos striped", 0, 1000)
            assert c.scheduler.stats["chunks_striped"] > 0
            assert c.scheduler.stats["reissues"] >= 1
            assert c.scheduler.stats["leases_blown"] >= 1
            wedged.unwedge()
            assert await c.settle()
            assert c.scheduler.stats["results_sent"] == 2
    asyncio.run(scenario())


def test_chaos_kill_mid_pipeline_recovers_striped_chunks():
    """A miner killed mid-pipeline with several striped chunks pending:
    every unanswered stripe chunk re-executes elsewhere exactly once and
    the merge stays exact."""
    from tests.test_chaos import ChaosCluster, tight_lease

    async def scenario():
        async with ChaosCluster(lease=tight_lease()) as c:
            c.scheduler.stripe = FORCED_STRIPE
            doomed = await c.add_miner("doomed", delay=0.15)
            await c.add_miner("survivor", delay=0.01)
            r0 = await asyncio.wait_for(
                submit(c.hostport, "kill warm", 599, c.params), 20)
            assert r0 == scan_min("kill warm", 0, 600)
            pending = asyncio.create_task(
                submit(c.hostport, "kill striped", 1999, c.params))
            await asyncio.sleep(0.2)        # chunks assigned; doomed busy
            await doomed.kill()
            result = await asyncio.wait_for(pending, 30)
            assert result == scan_min("kill striped", 0, 2000)
            await doomed.restart()
            assert await c.settle()
    asyncio.run(scenario())
