"""Plane split + replica sharding tests (ISSUE 11).

Four layers:

- INTERFACE CONTRACT units: the miner plane driven standalone with stub
  callbacks, pinning the grant/complete/lease-event ordering the
  scheduler relies on (blown strictly before reissue, quarantine only
  after its triggering blow, quarantine-lift before the dispatch
  re-entry) and the tenant plane's indexed queue semantics.
- CONSISTENT-HASH stability: removing one replica moves only that
  replica's tenants (~1/N), every other key keeps its owner.
- REPLICA tier: shared ResultCache replay across replicas, kill/
  takeover re-serving exactly-once oracle-exact, and an e2e 2-replica
  run over REAL localhost LSP with real miner workers.
- DE-MELT knobs: trace sampling determinism + stock parity
  (DBM_TRACE_SAMPLE=1.0 ≡ today), batched recv parity
  (DBM_RECV_BATCH=1 ≡ stock one-message-per-await), timer-wheel
  mechanics, and the QoS ring's backlog sync.
"""

import asyncio
import time

import pytest

from distributed_bitcoinminer_tpu.apps.miner_plane import (Chunk,
                                                           MinerPlane)
from distributed_bitcoinminer_tpu.apps.replicas import HashRing, ReplicaSet
from distributed_bitcoinminer_tpu.apps.scheduler import Request, Scheduler
from distributed_bitcoinminer_tpu.apps.tenant_plane import TenantPlane
from distributed_bitcoinminer_tpu.bitcoin.hash import scan_min
from distributed_bitcoinminer_tpu.bitcoin.message import (
    Message, MsgType, new_join, new_request, new_result)
from distributed_bitcoinminer_tpu.lspnet.detnet import DetServer
from distributed_bitcoinminer_tpu.utils.config import (CacheParams,
                                                       CoalesceParams,
                                                       LeaseParams,
                                                       QosParams,
                                                       StripeParams,
                                                       VerifyParams)
from distributed_bitcoinminer_tpu.utils.metrics import NULL_TRACE, Registry
from distributed_bitcoinminer_tpu.utils.trace import sample_hit
from tests.test_scheduler_recovery import (CLIENT_X, FakeServer, MINER_A,
                                           MINER_B, join, request, result)


# ------------------------------------------------- miner-plane contract


class _PlaneRig:
    """A standalone MinerPlane with recording stubs."""

    def __init__(self, **lease_kw):
        lease_kw.setdefault("grace_s", 5.0)
        lease_kw.setdefault("floor_s", 2.0)
        lease_kw.setdefault("quarantine_after", 2)
        self.counts: dict = {}
        self.events: list = []
        self.writes: list = []
        self.inflight: dict = {}
        self.plane = MinerPlane(
            Registry(), self._count, LeaseParams(**lease_kw),
            StripeParams(enabled=False), CoalesceParams(enabled=False),
            write=lambda c, m: self.writes.append((c, m)),
            inflight=self.inflight,
            trace_get=lambda job: None,
            lease_event=self._lease_event,
            dispatch=lambda: self.events.append(("dispatch",)))

    def _count(self, name, n=1):
        self.counts[name] = self.counts.get(name, 0) + n

    def _lease_event(self, kind, chunk, conn, **info):
        self.events.append((kind, chunk.idx, conn))

    def add_request(self, job_id, n_chunks=1):
        req = Request(conn_id=99, data="x", lower=0, upper=9,
                      job_id=job_id, num_chunks=n_chunks,
                      answered=[False] * n_chunks)
        self.inflight[job_id] = req
        return req


def test_grant_writes_wire_and_stamps_lease():
    rig = _PlaneRig()
    m = rig.plane.on_join(7)
    rig.add_request(1)
    chunk = Chunk(1, "x", 0, 10)
    rig.plane.assign_chunk(m, chunk)
    assert rig.writes and rig.writes[0][0] == 7
    assert rig.writes[0][1].type == MsgType.REQUEST
    assert chunk.lease_started and chunk.deadline > 0
    assert m.pending == [chunk]


def test_blown_before_reissue_ordering():
    """The lease-event contract: ``blown`` fires strictly before the
    same chunk's ``reissue``, and the reissue's wire write lands on the
    takeover miner AFTER both events."""
    rig = _PlaneRig()
    m7 = rig.plane.on_join(7)
    rig.plane.on_join(8)
    rig.add_request(1)
    chunk = Chunk(1, "x", 0, 10)
    rig.plane.assign_chunk(m7, chunk)
    chunk.deadline = 0.0        # force expiry without sleeping
    rig.plane.check_leases()
    kinds = [e[0] for e in rig.events]
    assert kinds.index("blown") < kinds.index("reissue")
    assert rig.counts["leases_blown"] == 1
    assert rig.counts["reissues"] == 1
    # The reissue copy went to the OTHER miner, after the events fired.
    assert rig.writes[-1][0] == 8


def test_quarantine_only_after_streak_and_lift_before_dispatch():
    rig = _PlaneRig()
    m = rig.plane.on_join(7)
    rig.add_request(1)
    c0 = Chunk(1, "x", 0, 10, idx=0)
    rig.plane.assign_chunk(m, c0)
    c0.deadline = 0.0
    rig.plane.check_leases()
    assert "quarantine" not in [e[0] for e in rig.events]  # streak 1 < 2
    rig.add_request(2)
    c1 = Chunk(2, "x", 0, 10, idx=0)
    rig.plane.assign_chunk(m, c1)
    c1.deadline = 0.0
    rig.plane.check_leases()
    kinds = [e[0] for e in rig.events]
    assert m.quarantined
    # quarantine fires after (and only after) its triggering blow.
    assert kinds.index("quarantine") > \
        [i for i, k in enumerate(kinds) if k == "blown"][1]
    # COMPLETE edge: an answer lifts quarantine, and the lift event
    # precedes the dispatch re-entry it unlocks.
    rig.events.clear()
    popped = rig.plane.pop_result(7)
    assert popped is not None and popped[1] is c0
    kinds = [e[0] for e in rig.events]
    assert kinds.index("quarantine_lifted") < kinds.index("dispatch")
    assert not m.quarantined


def test_park_event_when_no_taker():
    rig = _PlaneRig()
    m = rig.plane.on_join(7)
    rig.add_request(1)
    chunk = Chunk(1, "x", 0, 10)
    rig.plane.assign_chunk(m, chunk)
    dead = rig.plane.drop_miner(7)
    rig.plane.recover(dead)     # no other miner: chunk parks
    assert ("park", 0, 7) in rig.events
    assert rig.plane.parked == [chunk]


# ---------------------------------------------- tenant-plane queue index


def _tenant_plane():
    return TenantPlane(Registry(), lambda *a, **k: None,
                       QosParams(enabled=True), LeaseParams())


def _req(conn, data="d"):
    return Request(conn_id=conn, data=data, lower=0, upper=9)


def test_queue_index_fifo_and_purge():
    tp = _tenant_plane()
    a1, b1, a2 = _req(1, "a1"), _req(2, "b1"), _req(1, "a2")
    for r in (a1, b1, a2):
        tp.enqueue(r)
    assert tp.queue == [a1, b1, a2]          # arrival order view
    assert tp.tenant_heads() == [(1, a1), (2, b1)]
    assert tp.backlog_tenants() == [1, 2]
    assert tp.pop_head() is a1
    assert tp.tenant_heads() == [(1, a2), (2, b1)]
    assert tp.purge_tenant(1) == [a2]
    assert tp.queue == [b1]
    tp.dequeue(b1)
    assert tp.queue == [] and tp.queue_len() == 0


# ------------------------------------------------------ consistent hash


def test_hash_ring_stability_under_remove():
    ring4 = HashRing([0, 1, 2, 3])
    ring3 = HashRing([0, 1, 3])
    keys = range(8000)
    moved = stayed = from2 = 0
    for k in keys:
        o4, o3 = ring4.owner(k), ring3.owner(k)
        if o4 == 2:
            from2 += 1
            assert o3 != 2
        elif o4 == o3:
            stayed += 1
        else:
            moved += 1
    # ONLY the removed replica's keys move.
    assert moved == 0
    # And its share was ~1/4 of the space.
    assert 0.12 < from2 / 8000 < 0.42


def test_hash_ring_stability_under_add():
    ring3 = HashRing([0, 1, 2])
    ring4 = HashRing([0, 1, 2, 3])
    changed = sum(1 for k in range(8000)
                  if ring3.owner(k) != ring4.owner(k))
    for k in range(8000):
        if ring3.owner(k) != ring4.owner(k):
            assert ring4.owner(k) == 3      # moves only ONTO the new one
    assert 0.12 < changed / 8000 < 0.42


# ------------------------------------------------------- replica tier


def _settle(n=6):
    async def inner():
        for _ in range(n):
            await asyncio.sleep(0)
    return inner()


async def _read_result(chan, timeout=5.0):
    async def go():
        while True:
            msg = Message.from_json(await chan.read())
            if msg.type == MsgType.RESULT:
                return msg
    return await asyncio.wait_for(go(), timeout)


def test_shared_result_cache_replays_across_replicas():
    """A result cached by ANY replica replays for a tenant hashed to
    any other: the shared tier answers with NO miners at all."""
    async def scenario():
        server = DetServer()
        rs = ReplicaSet(server, 2, lease=LeaseParams(queue_alarm_s=0.0),
                        cache=CacheParams(), qos=QosParams(enabled=False))
        run_task = asyncio.create_task(rs.run())
        rs.shared_cache.put(("m", 0, 99, 0), (123, 45))
        replies = []
        for _ in range(4):      # several conns: both ring owners hit
            chan = server.connect()
            chan.write(new_request("m", 0, 99).to_json())
            await _settle()
            replies.append(await _read_result(chan, 2.0))
        assert all((r.hash, r.nonce) == (123, 45) for r in replies)
        assert rs.stats["cache_hits"] >= 4
        run_task.cancel()
    asyncio.run(scenario())


def test_replica_kill_reserves_inflight_exactly_once():
    """Kill the replica holding an in-flight request: the takeover must
    re-serve it through a survivor, the adopted miner's stale answer
    must pop harmlessly, and the client sees EXACTLY one oracle-exact
    reply."""
    async def scenario():
        server = DetServer()
        rs = ReplicaSet(server, 2,
                        lease=LeaseParams(grace_s=30.0, floor_s=10.0,
                                          queue_alarm_s=0.0),
                        cache=CacheParams(), qos=QosParams(enabled=False))
        run_task = asyncio.create_task(rs.run())
        release = asyncio.Event()

        async def miner(chan):
            chan.write(new_join().to_json())
            while True:
                try:
                    payload = await chan.read()
                except Exception:
                    return
                msg = Message.from_json(payload)
                if msg.type != MsgType.REQUEST:
                    continue
                await release.wait()
                h, n = scan_min(msg.data, msg.lower, msg.upper)
                try:
                    chan.write(new_result(h, n).to_json())
                except Exception:
                    return

        miners = [asyncio.create_task(miner(server.connect()))
                  for _ in range(2)]
        await _settle()
        assert sorted(len(rs.replicas[r].miners) for r in rs.live) \
            == [1, 1]
        client = server.connect()
        client.write(new_request("takeover", 0, 99).to_json())
        owner = None
        for _ in range(200):
            await asyncio.sleep(0)
            owner = next((rid for rid in rs.live
                          if rs.replicas[rid]._inflight), None)
            if owner is not None:
                break
        assert owner is not None, "request never went in flight"
        rs.kill(owner)
        release.set()
        reply = await _read_result(client)
        assert (reply.hash, reply.nonce) == scan_min("takeover", 0, 100)
        # Exactly once: no second RESULT arrives.
        await asyncio.sleep(0.1)
        assert client._inbox.empty()
        # The adopter saw the dead replica's answer pop as stale/dup,
        # never as a second merge.
        assert rs.stats["results_sent"] == 1
        for t in miners + [run_task]:
            t.cancel()
    asyncio.run(scenario())


def test_two_replica_e2e_over_real_lsp():
    """End-to-end over REAL localhost LSP: a 2-replica set, two real
    miner workers (host searcher), several tenants — every reply
    oracle-exact."""
    from distributed_bitcoinminer_tpu.apps.client import submit
    from distributed_bitcoinminer_tpu.apps.miner import (HostSearcher,
                                                         MinerWorker)
    from distributed_bitcoinminer_tpu.lsp.params import Params
    from distributed_bitcoinminer_tpu.lsp.server import new_async_server

    params = Params(epoch_limit=30, epoch_millis=500, window_size=32,
                    max_backoff_interval=2)

    async def scenario():
        server = await new_async_server(0, params)
        rs = ReplicaSet(server, 2,
                        lease=LeaseParams(grace_s=60.0,
                                          queue_alarm_s=0.0),
                        cache=CacheParams(enabled=False),
                        stripe=StripeParams(enabled=False),
                        qos=QosParams(enabled=False))
        run_task = asyncio.create_task(rs.run())
        hostport = f"127.0.0.1:{server.port}"
        workers, tasks = [], []
        try:
            for _ in range(2):
                w = MinerWorker(
                    hostport, params=params,
                    searcher_factory=lambda d, b: HostSearcher(d))
                await w.join()
                tasks.append(asyncio.create_task(w.run()))
                workers.append(w)
            results = await asyncio.gather(*[
                asyncio.wait_for(
                    submit(hostport, f"rep{i}", 400 + 7 * i, params), 60)
                for i in range(4)])
            for i, got in enumerate(results):
                assert got == scan_min(f"rep{i}", 0, 401 + 7 * i)
            # Both replicas actually served work (tenants hashed to
            # both is probabilistic per conn id, but miners are sliced
            # 1/1 deterministically, so each replica had capacity).
            assert sorted(len(rs.replicas[r].miners)
                          for r in rs.live) == [1, 1]
            assert rs.stats["results_sent"] == 4
        finally:
            for t in tasks:
                t.cancel()
            for w in workers:
                await w.close()
            run_task.cancel()
            await server.close()
    asyncio.run(scenario())


def test_request_before_any_miner_completes_when_one_joins():
    """Pre-miner routing (code review): with no miners ANYWHERE the
    fallback ring is the FIRST live replica — exactly where the first
    JOIN lands (thinnest-slice tie-break) — so a tenant pinned before
    capacity exists is served the moment it appears."""
    async def scenario():
        server = DetServer()
        rs = ReplicaSet(server, 4, lease=LeaseParams(queue_alarm_s=0.0),
                        cache=CacheParams(enabled=False),
                        qos=QosParams(enabled=False))
        run_task = asyncio.create_task(rs.run())
        chan = server.connect()
        chan.write(new_request("premine", 0, 99).to_json())
        await _settle()
        assert rs.replicas[rs.live[0]].queue      # queued on live[0]

        async def miner(mchan):
            mchan.write(new_join().to_json())
            while True:
                msg = Message.from_json(await mchan.read())
                if msg.type != MsgType.REQUEST:
                    continue
                h, n = scan_min(msg.data, msg.lower, msg.upper)
                mchan.write(new_result(h, n).to_json())

        mtask = asyncio.create_task(miner(server.connect()))
        reply = await _read_result(chan, 5.0)
        assert (reply.hash, reply.nonce) == scan_min("premine", 0, 100)
        for t in (mtask, run_task):
            t.cancel()
    asyncio.run(scenario())


def test_reserve_request_bypasses_admission():
    """Takeover re-serves (code review): reserve_request charges no
    admission token and triggers no overload shed — already-admitted
    work must survive a failover even on a drained bucket."""
    server = FakeServer()
    sched = Scheduler(server, lease=LeaseParams(queue_alarm_s=0.0),
                      qos=QosParams(enabled=True, rate=0.001, burst=1.0,
                                    max_queued=1))
    # Drain tenant 10's bucket with an ordinary arrival (no miners, so
    # it queues), leaving zero tokens.
    request(sched, CLIENT_X, "adm0", 39)
    assert len(sched.queue) == 1
    # An ordinary second arrival would shed at admission...
    request(sched, CLIENT_X, "adm1", 39)
    assert sched.stats["qos_shed"] >= 1
    # ...but a takeover re-serve of the same tenant must intake.
    before = sched.stats["qos_shed"]
    sched.reserve_request(CLIENT_X, new_request("adm2", 0, 39))
    assert sched.stats["qos_shed"] == before
    assert any(r.data == "adm2" for r in sched.queue)


def test_more_replicas_than_miners_still_serves():
    """Regression (found in a live 4-replica/2-miner drive): tenants
    must route over SERVING replicas (those holding miners) — a hash
    owner with an empty miner slice would queue the request into the
    age alarm forever while capacity sat idle on its neighbors."""
    async def scenario():
        server = DetServer()
        rs = ReplicaSet(server, 4, lease=LeaseParams(queue_alarm_s=0.0),
                        cache=CacheParams(enabled=False),
                        qos=QosParams(enabled=False))
        run_task = asyncio.create_task(rs.run())

        async def miner(chan):
            chan.write(new_join().to_json())
            while True:
                msg = Message.from_json(await chan.read())
                if msg.type != MsgType.REQUEST:
                    continue
                h, n = scan_min(msg.data, msg.lower, msg.upper)
                chan.write(new_result(h, n).to_json())

        mtask = asyncio.create_task(miner(server.connect()))
        await _settle()
        replies = []
        for i in range(8):      # 8 conns: the all-live ring would have
            chan = server.connect()       # stranded ~3/4 of these
            chan.write(new_request(f"srv{i}", 0, 50 + i).to_json())
            replies.append(await _read_result(chan, 5.0))
        for i, rep in enumerate(replies):
            assert (rep.hash, rep.nonce) == scan_min(f"srv{i}", 0, 51 + i)
        for t in (mtask, run_task):
            t.cancel()
    asyncio.run(scenario())


# -------------------------------------------------------- trace sampling


def test_sample_hit_deterministic_and_calibrated():
    hits = [sample_hit(i, 0.25) for i in range(4000)]
    assert hits == [sample_hit(i, 0.25) for i in range(4000)]
    assert 0.18 < sum(hits) / 4000 < 0.32
    assert all(sample_hit(i, 1.0) for i in range(100))
    assert not any(sample_hit(i, 0.0) for i in range(100))


def test_trace_sample_zero_allocates_no_traces():
    server = FakeServer()
    sched = Scheduler(server, lease=LeaseParams(), trace_sample=0.0,
                      qos=QosParams(enabled=False),
                      verify=VerifyParams(enabled=False))
    join(sched, MINER_A)
    request(sched, CLIENT_X, "s0", 39)
    req = sched.current
    assert req.trace is NULL_TRACE
    result(sched, MINER_A, h=5, nonce=2)
    assert server.sent_to(CLIENT_X, MsgType.RESULT)      # answered fine
    assert sched.traces.items() == []                    # nothing retained
    assert sched.trace(req.job_id) is None


def test_trace_sample_one_is_stock():
    server = FakeServer()
    sched = Scheduler(server, lease=LeaseParams(), trace_sample=1.0,
                      qos=QosParams(enabled=False),
                      verify=VerifyParams(enabled=False))
    join(sched, MINER_A)
    request(sched, CLIENT_X, "s1", 39)
    job = sched.current.job_id
    result(sched, MINER_A, h=5, nonce=2)
    trace = sched.trace(job)
    assert trace is not None and trace.closed
    events = [e["event"] for e in trace.to_dict()["events"]]
    assert events[0] == "enqueue" and "reply" in events


# --------------------------------------------------------- batched recv


def test_recv_batch_parity():
    """DBM_RECV_BATCH=64 vs 1: identical replies in identical order."""
    def drive(recv_batch):
        async def scenario():
            server = DetServer()
            sched = Scheduler(server, lease=LeaseParams(
                queue_alarm_s=0.0), qos=QosParams(enabled=False),
                cache=CacheParams(enabled=False),
                recv_batch=recv_batch)
            run_task = asyncio.create_task(sched.run())
            mchan = server.connect()

            async def miner():
                mchan.write(new_join().to_json())
                while True:
                    msg = Message.from_json(await mchan.read())
                    if msg.type != MsgType.REQUEST:
                        continue
                    h, n = scan_min(msg.data, msg.lower, msg.upper)
                    mchan.write(new_result(h, n).to_json())

            mtask = asyncio.create_task(miner())
            await _settle()
            chans = []
            for i in range(6):
                chan = server.connect()
                chan.write(new_request(f"rb{i}", 0, 60 + i).to_json())
                chans.append(chan)
            out = []
            for chan in chans:
                msg = await _read_result(chan)
                out.append((msg.hash, msg.nonce))
            for t in (mtask, run_task):
                t.cancel()
            return out
        return asyncio.run(scenario())

    assert drive(1) == drive(64)


# ----------------------------------------------------------- timer wheel


def test_timer_wheel_fires_and_cancels():
    from distributed_bitcoinminer_tpu.lsp.timerwheel import TimerWheel

    async def scenario():
        wheel = TimerWheel(asyncio.get_running_loop())
        calls = []
        wheel.add(0.01, lambda: calls.append(1) is None
                  and len(calls) < 3)
        h2_calls = []
        h2 = wheel.add(0.01, lambda: h2_calls.append(1) is None)
        wheel.cancel(h2)
        await asyncio.sleep(0.15)
        assert len(calls) == 3          # self-deregistered at 3
        assert not h2_calls             # cancelled before first fire
        assert len(wheel) == 0
    asyncio.run(scenario())


def test_timer_wheel_knob_off_uses_per_conn_tasks(monkeypatch):
    monkeypatch.setenv("DBM_TIMER_WHEEL", "0")

    async def scenario():
        from distributed_bitcoinminer_tpu.lsp._engine import Conn
        from distributed_bitcoinminer_tpu.lsp.params import Params
        conn = Conn(Params(), 1, lambda raw: None, lambda p: None,
                    lambda e: None)
        assert conn._epoch_task is not None and conn._wheel is None
        conn.abort()
    asyncio.run(scenario())


# ------------------------------------------------------- QoS ring sync


def test_qos_ring_backlog_sync():
    from distributed_bitcoinminer_tpu.apps.qos import QosPlane
    plane = QosPlane(Registry())
    for t in (1, 2, 3):
        plane.tenant(t)
    plane.sync_backlog([1, 2])
    assert list(plane.ring) == [1, 2]
    plane.tenants[1].deficit = 50.0
    plane.sync_backlog([2, 3])          # 1 leaves: deficit forfeited
    assert list(plane.ring) == [2, 3]
    assert plane.tenants[1].deficit == 0.0
    plane.sync_backlog([2, 3])          # idempotent
    assert list(plane.ring) == [2, 3]
    # Idle credit never RE-ENTERS either: the pump's O(1) early exits
    # may skip the departure observation entirely, so a tenant coming
    # back from idle starts from zero regardless (code review).
    plane.tenants[1].deficit = 75.0     # banked while outside the ring
    plane.tenants[2].deficit = 30.0     # earned while INSIDE the ring
    plane.sync_backlog([1, 2, 3])
    assert list(plane.ring) == [2, 3, 1]
    assert plane.tenants[1].deficit == 0.0      # re-entry starts fresh
    assert plane.tenants[2].deficit == 30.0     # continuity retains


# ------------------------------------------------- detnet multi-server


def test_multiple_detservers_share_one_loop():
    """Replica scenarios need N transports on one loop: DetServers hold
    no loop/module-global state, conn ids are per-server (overlap is
    fine — a channel is bound to its server), and non-recording servers
    keep no capture lists."""
    async def scenario():
        s1, s2 = DetServer(), DetServer(record=False)
        a, b = s1.connect(), s2.connect()
        assert a.conn_id == b.conn_id == 1      # per-server numbering
        a.write(b"to-s1")
        b.write(b"to-s2")
        assert await s1.read() == (1, b"to-s1")
        assert await s2.read() == (1, b"to-s2")
        assert s1.read_nowait() is None
        s1.write(1, b"reply1")
        s2.write(1, b"reply2")
        assert await a.read() == b"reply1"
        assert await b.read() == b"reply2"
        # Recording is per-server: s2 kept nothing.
        assert s1._read_log and s1.writes
        assert not s2._read_log and not s2.writes and not b.sent
    asyncio.run(scenario())


# -------------------------------------------------------- load harness


def test_load_harness_smoke_completes():
    from distributed_bitcoinminer_tpu.apps.loadharness import run_load
    leg = run_load(tenants=40, replicas=2, miners=2, timeout_s=60.0)
    assert leg["completed"] == 40 and leg["shed_rate"] == 0.0
    assert leg["p99_s"] is not None and not leg.get("timed_out")
    assert leg["trace"]["sampled_traces"] > 0


def test_load_harness_sheds_over_capacity():
    from distributed_bitcoinminer_tpu.apps.loadharness import run_load
    leg = run_load(tenants=60, replicas=1, miners=2, max_queued=10,
                   timeout_s=60.0)
    # Overload shed fired and the shed tenants saw their conns die.
    assert leg["shed_tenants"] > 0
    assert leg["completed"] + leg["shed_tenants"] >= 60


# ------------------------------------- health/membership plane (ISSUE 12)


def _beat(rid, seq, inc=None, serving=True, miners=1, port=9000):
    from distributed_bitcoinminer_tpu.apps.health import Beat
    return Beat(rid=rid, incarnation=inc or f"i{rid}", seq=seq,
                port=port, serving=serving, miners=miners)


def test_beat_monitor_frozen_seq_is_death():
    """A stale blob re-read (same seq) is NOT life: only an advancing
    seq re-anchors the deadline — the SIGSTOP semantics (the frozen
    process's file keeps existing; its seq keeps not moving)."""
    from distributed_bitcoinminer_tpu.apps.health import BeatMonitor
    mon = BeatMonitor(beat_s=0.5, miss_k=3)      # window 1.5s
    assert mon.observe(_beat(0, 1), now=10.0)
    assert not mon.observe(_beat(0, 1), now=11.4)  # same seq: no refresh
    assert mon.dead(11.6) == [0]
    # An advancing seq refreshes.
    mon2 = BeatMonitor(beat_s=0.5, miss_k=3)
    mon2.observe(_beat(0, 1), now=10.0)
    mon2.observe(_beat(0, 2), now=11.4)
    assert mon2.dead(11.6) == []
    # A fresh incarnation counts as an advance even with a lower seq.
    assert mon2.observe(_beat(0, 1, inc="newinc"), now=12.0)


def test_membership_fencing_epoch_and_refused_zombie():
    """declare_dead bumps the epoch and fences the incarnation; the
    FENCED incarnation is never re-admitted (the partitioned-but-alive
    zombie), while a FRESH incarnation of the same rid is."""
    from distributed_bitcoinminer_tpu.apps.health import Membership
    m = Membership()
    assert m.admit(_beat(0, 1)) and m.admit(_beat(1, 1, inc="i1"))
    e0 = m.epoch
    assert m.declare_dead(0)
    assert m.epoch == e0 + 1
    assert m.is_fenced(0, "i0") and m.writer_fenced(0, "i0")
    assert 0 not in m.live
    # The zombie beats again: refused, epoch unchanged.
    assert not m.admit(_beat(0, 99))
    assert 0 not in m.live
    # A fresh incarnation is re-admitted at a new epoch.
    e1 = m.epoch
    assert m.admit(_beat(0, 1, inc="i0-reborn"))
    assert m.epoch == e1 + 1 and m.live[0]["incarnation"] == "i0-reborn"
    # The OLD incarnation stays fenced; the new one is not.
    assert m.is_fenced(0, "i0") and not m.is_fenced(0, "i0-reborn")
    # Round-trips through the published document.
    m2 = Membership.from_dict(m.to_dict())
    assert m2.epoch == m.epoch and m2.live == m.live
    assert m2.is_fenced(0, "i0")


def test_router_tick_detects_death_and_graceful_leave():
    from distributed_bitcoinminer_tpu.apps.health import (BeatMonitor,
                                                          RouterState,
                                                          router_tick)
    state = RouterState(BeatMonitor(beat_s=0.2, miss_k=2))  # window .4s
    assert router_tick(state, [_beat(0, 1), _beat(1, 1, inc="i1")], 0.0)
    assert sorted(state.membership.live) == [0, 1]
    # Replica 0's seq freezes; 1 keeps beating.
    assert not router_tick(state, [_beat(0, 1),
                                   _beat(1, 2, inc="i1")], 0.3)
    assert router_tick(state, [_beat(0, 1), _beat(1, 3, inc="i1")], 0.5)
    assert sorted(state.membership.live) == [1]
    assert state.membership.is_fenced(0, "i0")
    # Graceful leave: serving=False with an advancing seq fences NOW.
    assert router_tick(state, [_beat(1, 4, inc="i1", serving=False)], 0.6)
    assert state.membership.live == {}
    assert state.membership.is_fenced(1, "i1")


def test_spool_cache_write_through_ingest_and_fence_drop(tmp_path):
    """The replicated cache tier: write-through spooling, peer ingest,
    the FENCED-writer drop (a declared-dead replica's cache writes must
    not propagate — unit for the ISSUE 12 fencing satellite), and
    torn-tail-line tolerance."""
    from distributed_bitcoinminer_tpu.apps.health import Membership
    from distributed_bitcoinminer_tpu.apps.procs import SpoolResultCache
    d = str(tmp_path)
    a = SpoolResultCache(16, d, 0, "incA")
    b = SpoolResultCache(16, d, 1, "incB")
    a.put(("k", 0, 9, 0), (111, 4))
    assert a.spooled == 1
    m = Membership()
    m.admit(_beat(0, 1, inc="incA"))
    m.admit(_beat(1, 1, inc="incB"))
    assert b.ingest(m) == 1
    assert b.get(("k", 0, 9, 0)) == (111, 4)
    # Ingest is incremental: nothing new, nothing read.
    assert b.ingest(m) == 0
    # Fence replica 0: its LATER writes are dropped at ingest.
    m.declare_dead(0)
    a.put(("k2", 0, 9, 0), (222, 5))
    assert b.ingest(m) == 0 and b.dropped_fenced == 1
    assert b.get(("k2", 0, 9, 0)) is None       # miss -> recompute
    # Torn tail line: unconsumed until the newline lands, then folded.
    c = SpoolResultCache(16, d, 2, "incC")
    import json as _json
    with open(c._spool, "a", encoding="utf-8") as fh:
        fh.write(_json.dumps({"rid": 2, "inc": "incC",
                              "key": ["t", 0, 5, 0],
                              "h": 7, "n": 1})[:10])   # torn, no newline
    assert b.ingest(m) == 0
    with open(c._spool, "w", encoding="utf-8") as fh:
        fh.write(_json.dumps({"rid": 2, "inc": "incC",
                              "key": ["t", 0, 5, 0],
                              "h": 7, "n": 1}) + "\n")
    assert b.ingest(m) == 1
    assert b.get(("t", 0, 5, 0)) == (7, 1)


def test_resolve_owner_serving_rule(tmp_path):
    """The client-side ring spans SERVING replicas (live + miners in
    the live incarnation's beat); with no miners anywhere it falls back
    to the FIRST live replica — where the agent's thinnest-slice rule
    lands the first JOIN."""
    from distributed_bitcoinminer_tpu.apps.health import Membership
    from distributed_bitcoinminer_tpu.apps.procs import (
        beat_path, membership_path, resolve_owner, write_json_atomic)
    d = str(tmp_path)
    assert resolve_owner(d, "k") is None          # no membership yet
    m = Membership()
    m.admit(_beat(0, 1, inc="i0", port=7000))
    m.admit(_beat(1, 1, inc="i1", port=7001))
    write_json_atomic(membership_path(d), m.to_dict())
    write_json_atomic(beat_path(d, 0),
                      _beat(0, 5, inc="i0", miners=0, port=7000)
                      .to_dict())
    write_json_atomic(beat_path(d, 1),
                      _beat(1, 5, inc="i1", miners=0, port=7001)
                      .to_dict())
    # No miners anywhere: every key lands on the FIRST live replica.
    for key in ("a", "b", "c"):
        assert resolve_owner(d, key) == (0, "127.0.0.1:7000")
    # Only replica 1 holds miners: every key lands there.
    write_json_atomic(beat_path(d, 1),
                      _beat(1, 6, inc="i1", miners=2, port=7001)
                      .to_dict())
    for key in ("a", "b", "c"):
        assert resolve_owner(d, key) == (1, "127.0.0.1:7001")
    # A STALE incarnation's beat never vouches for the live one.
    write_json_atomic(beat_path(d, 0),
                      _beat(0, 9, inc="ghost", miners=8, port=7000)
                      .to_dict())
    for key in ("a", "b", "c"):
        assert resolve_owner(d, key) == (1, "127.0.0.1:7001")
    # Both serving: the ring splits keys across both replicas.
    write_json_atomic(beat_path(d, 0),
                      _beat(0, 10, inc="i0", miners=1, port=7000)
                      .to_dict())
    owners = {resolve_owner(d, f"key{i}")[0] for i in range(64)}
    assert owners == {0, 1}


def test_lazy_hook_seeds_existing_backlog_on_reconfigure():
    """Code review (ISSUE 12): enabling the lazy walk on a LIVE
    scheduler must seed the ring from the backlog that already exists —
    the enqueue hook only fires on future arrivals, so without the seed
    a request queued before the reconfigure would never be granted."""
    from distributed_bitcoinminer_tpu.bitcoin.message import new_request
    from tests.test_qos import FakeServer, pop_next
    server = FakeServer()
    sched = Scheduler(server, lease=LeaseParams(queue_alarm_s=0.0),
                      qos=QosParams(enabled=False),
                      verify=VerifyParams(enabled=False))
    sched._on_join(MINER_A)
    # Queue a second tenant's request behind an in-flight one (stock
    # FIFO: one in flight at a time).
    sched._on_request(CLIENT_X, new_request("infl", 0, 49))
    sched._on_request(CLIENT_X + 1, new_request("queued", 0, 49))
    assert len(sched.queue) == 1
    sched.qos = QosParams(enabled=True, lazy=True)
    assert CLIENT_X + 1 in sched.qos_plane._in_ring    # seeded
    for _ in range(4):
        pop_next(sched)
    assert len(sched.queue) == 0
    assert len(server.sent_to(CLIENT_X + 1, MsgType.RESULT)) == 1


def test_spool_rotation_and_fenced_gc(tmp_path):
    """Code review (ISSUE 12): the spool is disk-bounded — it rotates
    (old file unlinked) after ROTATE_FACTOR*size lines — and a fenced
    incarnation's leftover spools (rotated names included) are removed
    by the router's GC; ingest prunes offsets of vanished files."""
    import os
    from distributed_bitcoinminer_tpu.apps.health import Membership
    from distributed_bitcoinminer_tpu.apps.procs import (
        SpoolResultCache, gc_fenced_spools)
    d = str(tmp_path)
    a = SpoolResultCache(4, d, 0, "incA")
    a._rotate_at = 5                   # tighten the bound for the test
    first_spool = a._spool
    b = SpoolResultCache(16, d, 1, "incB")
    m = Membership()
    m.admit(_beat(0, 1, inc="incA"))
    m.admit(_beat(1, 1, inc="incB"))
    for i in range(7):
        a.put((f"k{i}", 0, 9, 0), (100 + i, i))
        b.ingest(m)
    # Rotation happened: the first spool is gone, a .1 spool exists.
    assert not os.path.exists(first_spool)
    assert a._spool.endswith(".1.spool") and os.path.exists(a._spool)
    assert a._spool_lines == 7 - 5
    # The consumer's offset entry for the unlinked file was pruned.
    assert os.path.basename(first_spool) not in b._offsets
    # Fence incarnation A: the router GC removes its remaining spools.
    m.declare_dead(0)
    assert gc_fenced_spools(d, m) == 1
    assert not any(n.startswith("cache_0_") for n in os.listdir(d))
    # B's own spool (live incarnation) survives.
    b.put(("own", 0, 9, 0), (9, 9))
    assert gc_fenced_spools(d, m) == 0
    assert any(n.startswith("cache_1_") for n in os.listdir(d))


# ----------------------------------- fence-push + sharded driver (ISSUE 13)


def test_miner_agent_owner_gone_predicate():
    """Fence-push (ISSUE 13 satellite): the agent's watcher fires when
    its owner's rid leaves the advertised ring OR returns under a fresh
    incarnation; a MISSING membership is no evidence (router restart —
    epoch detection stays the backstop)."""
    from distributed_bitcoinminer_tpu.apps.health import Membership
    from distributed_bitcoinminer_tpu.apps.procs import MinerAgent
    m = Membership()
    m.admit(_beat(0, 1, inc="i0", port=7000))
    assert not MinerAgent.owner_gone(m, 0, "i0")    # owner still live
    assert MinerAgent.owner_gone(None, 0, "i0") is False   # no evidence
    assert MinerAgent.owner_gone(m, 1, "i1")        # never admitted
    m.declare_dead(0)
    assert MinerAgent.owner_gone(m, 0, "i0")        # fenced: gone
    m.admit(_beat(0, 1, inc="i0b", port=7000))      # respawned fresh
    assert MinerAgent.owner_gone(m, 0, "i0")        # old conn is fenced
    assert not MinerAgent.owner_gone(m, 0, "i0b")   # new one is the owner


def test_adversarial_workloads_complete_and_ab_shape():
    """The ISSUE 13 adversarial generators produce the measurement
    shape detail.adapt consumes, on a small geometry: every request is
    answered or shed with its conn closed, and the adaptive leg carries
    its controllers' final state."""
    from distributed_bitcoinminer_tpu.apps.loadharness import (
        WORKLOADS, run_adversarial)
    assert set(WORKLOADS) == {"mice_stampede", "tenant_churn",
                              "elephant_convoy"}
    leg = run_adversarial("mice_stampede", adapt=False, tenants=60,
                          duration_s=0.5, timeout_s=60.0)
    assert leg["completed"] + leg["shed_requests"] >= leg["requests"]
    assert not leg.get("timed_out")
    leg = run_adversarial("tenant_churn", adapt=True, tenants=60,
                          duration_s=0.5, timeout_s=60.0)
    assert leg["completed"] + leg["shed_requests"] >= leg["requests"]
    assert "adapt_state" in leg and "admit_rate" in leg["adapt_state"]


def test_sharded_driver_merges_slices(tmp_path):
    """drive_ring_tenants is the shared unit of a (possibly sharded)
    --procs storm: with no membership published every tenant in the
    slice resolves no owner and is reported shed — the parent's merge
    accounting sees the whole slice either way."""
    import asyncio
    from distributed_bitcoinminer_tpu.apps.loadharness import \
        drive_ring_tenants
    out = asyncio.run(drive_ring_tenants(str(tmp_path), 0, 5, 2, 64,
                                         timeout_s=10.0))
    assert out["latencies"] == []
    assert sorted(out["sheds"]) == [2] * 5          # 5 tenants x 2 reqs
    assert not out["timed_out"]
