"""The driver entry points stay healthy at widths the driver itself does
not exercise (VERDICT r4 #8: n=16 — uneven per-device shapes — plus the
adversarial late-device until placement inside dryrun_multichip)."""

import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_dryrun_multichip_16_fresh_process():
    """dryrun_multichip(16) in a fresh interpreter: the device-count flag
    is process-global and conftest pins this process to 8, so the wider
    mesh needs its own process."""
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    proc = subprocess.run(
        [sys.executable, "-c",
         "from __graft_entry__ import dryrun_multichip; "
         "dryrun_multichip(16); print('ok16')"],
        cwd=_REPO, env=env, capture_output=True, text=True, timeout=840)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "ok16" in proc.stdout
